"""Trial execution engine: vmapped fits, sharded over the mesh trial axis.

This is the TPU-native replacement for the reference's entire
Kafka->scheduler->worker dispatch of per-trial sklearn fits
(``task_handler.py:185-236`` fan-out; ``worker.py:289-363`` per-trial fit +
5-fold CV). One dispatch here runs a whole *bucket* of trials:

    vmap over (K+1) split masks        — holdout fit + K CV folds
      x vmap over T trials             — hyperparameters as arrays
        sharded over mesh axis 'trials' (NamedSharding) — one slice per chip

XLA compiles the bucket once (static shapes, traced hypers) and partitions
the trial axis across chips; cross-trial aggregation (argmax of
mean_cv_score) happens on-device, so the only host traffic is the final
scalar results — replacing the reference's per-trial Kafka round trips.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.base import ModelKernel, TrialData
from ..obs import counter_inc, obs_enabled, observe
from ..ops.folds import SplitPlan
from ..utils.aot_cache import aot_jit
from .distributed import fetch as _fetch
from .distributed import prefetch_async
from .mesh import pad_to_multiple

_compiled_cache: Dict[Any, Any] = {}


def _cache_count(hit: bool) -> None:
    """In-process executable-cache accounting (obs catalog)."""
    counter_inc(
        "tpuml_executable_cache_hits_total"
        if hit
        else "tpuml_executable_cache_misses_total"
    )


class _PhaseAcc(threading.local):
    """Per-thread phase-time accumulators for the current run_trials call:
    stage (host->device uploads on cache miss) and fetch (blocking
    device->host transfers). Thread-local because coordinator job threads
    and cluster worker loops run trial batches concurrently."""

    def __init__(self):
        self.stage = 0.0
        self.fetch = 0.0


_PHASE = _PhaseAcc()


def _sds(a):
    return jax.ShapeDtypeStruct(a.shape, a.dtype)


# ---- device cost accounting -----------------------------------------------
#
# Promotes the offline bench helpers (utils/flops.py) to runtime telemetry:
# each cached executable carries its XLA cost analysis (flops, bytes
# accessed), captured ONCE at construction, and every dispatch accumulates
# it into the TrialRunResult so the executor can derive achieved-FLOP/s and
# MFU per batch. The analytical model-FLOP estimate (kernel.macs_estimate)
# is accumulated per bucket alongside — it is the MFU numerator (model
# FLOPs, comparable across implementations; see utils/flops docstring)
# while the XLA figure prices what the hardware actually did.


def _cost_capture_enabled() -> bool:
    """Capturing an executable's cost analysis costs one extra trace+lower
    at construction time (never on the dispatch hot path). Rides the master
    CS230_OBS valve; CS230_COST_ANALYSIS=0 turns just the XLA capture off
    (the free analytical accounting stays)."""
    return (
        obs_enabled()
        and os.environ.get("CS230_COST_ANALYSIS", "1") != "0"
    )


def _capture_cost(fn, example_args) -> Optional[Dict[str, float]]:
    """XLA cost analysis of ``fn`` lowered at ``example_args``:
    {"flops": ..., "bytes": ...} (either value may be absent), or None when
    capture is disabled or the backend/lowering offers no analysis. Runs at
    executable-construction time only — results are cached in
    ``_compiled_cache`` beside the executable."""
    if not _cost_capture_enabled():
        return None
    try:
        analysis = jax.jit(fn).lower(*example_args).cost_analysis()
        if isinstance(analysis, (list, tuple)):  # per-device form
            analysis = analysis[0] if analysis else {}
        out: Dict[str, float] = {}
        flops = analysis.get("flops")
        if flops is not None and float(flops) > 0:
            out["flops"] = float(flops)
        nbytes = analysis.get("bytes accessed")
        if nbytes is not None and float(nbytes) > 0:
            out["bytes"] = float(nbytes)
        return out or None
    except Exception:  # noqa: BLE001 — accounting must never fail a job
        return None


def _hbm_peak_bytes() -> Optional[int]:
    """Device-0 HBM high-water (peak_bytes_in_use); None on backends with
    no memory_stats (CPU)."""
    from ..utils.flops import device_memory_stats

    peak = device_memory_stats().get("peak_bytes_in_use")
    return int(peak) if peak is not None else None


# ---- packed single-fetch result transport ---------------------------------
#
# Every blocking device->host conversion is its own ~100 ms round trip on a
# tunneled link, paid PER LEAF of the result pytree — the whole cost floor
# of tiny jobs (BASELINE configs 1/4, GaussianNB). The trial executables
# therefore concatenate all result leaves into ONE flat byte buffer inside
# the jitted computation (bitcast, so f32/int leaves stay bit-identical)
# and the host fetches that single buffer with one jax.device_get, then
# reassembles the pytree with zero-copy numpy views.


def _packed_enabled() -> bool:
    """CS230_PACKED_FETCH=0 restores the per-leaf fetch path (debug/parity
    valve). The flag changes the executable's OUTPUT signature, so it joins
    every executable cache key via _aot_key."""
    return os.environ.get("CS230_PACKED_FETCH", "1") != "0"


@dataclasses.dataclass(frozen=True)
class _PackSpec:
    """Host-side recipe to reassemble a result pytree from one byte buffer."""

    treedef: Any
    shapes: tuple
    dtypes: tuple
    offsets: tuple
    nbytes: int


class _Packed:
    """A packed device buffer awaiting its single-transfer host fetch."""

    __slots__ = ("buf", "spec")

    def __init__(self, buf, spec: _PackSpec):
        self.buf = buf
        self.spec = spec


def _pack_spec_of(fn, example_args) -> _PackSpec:
    """Abstract-trace ``fn`` to learn its output tree; no device work."""
    out = jax.eval_shape(fn, *example_args)
    leaves, treedef = jax.tree_util.tree_flatten(out)
    shapes = tuple(tuple(int(s) for s in l.shape) for l in leaves)
    dtypes = tuple(np.dtype(l.dtype) for l in leaves)
    sizes = [
        int(np.prod(s, dtype=np.int64)) * dt.itemsize
        for s, dt in zip(shapes, dtypes)
    ]
    offs = np.concatenate([[0], np.cumsum(sizes, dtype=np.int64)])
    return _PackSpec(
        treedef, shapes, dtypes, tuple(int(o) for o in offs[:-1]), int(offs[-1])
    )


def _pack_wrap(fn):
    """Wrap a to-be-jitted trial function so its result leaves the device
    as one flat uint8 buffer (bitcast + concat traced into the executable).
    Pair with the _PackSpec from ``_pack_spec_of`` on the same example args."""

    def packed(*args):
        leaves = jax.tree_util.tree_leaves(fn(*args))
        parts = []
        for leaf in leaves:
            leaf = jnp.asarray(leaf)
            if leaf.dtype == jnp.bool_:
                leaf = leaf.astype(jnp.uint8)
            parts.append(jax.lax.bitcast_convert_type(leaf, jnp.uint8).reshape(-1))
        if not parts:
            return jnp.zeros((0,), jnp.uint8)
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    return packed


def _unpack(buf_np: np.ndarray, spec: _PackSpec):
    """Reassemble the result pytree from one fetched byte buffer (views,
    not copies — and bitwise identical to the per-leaf path)."""
    buf_np = np.ascontiguousarray(buf_np)
    leaves = []
    for off, shape, dt in zip(spec.offsets, spec.shapes, spec.dtypes):
        size = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        raw = buf_np[off : off + size]
        if dt == np.dtype(bool):
            leaves.append(raw.view(np.uint8).astype(bool).reshape(shape))
        else:
            leaves.append(raw.view(dt).reshape(shape))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def _fetch_result(out, spec: Optional[_PackSpec]):
    """One dispatch result -> (host pytree, n_blocking_fetches, bytes).

    Packed results (``spec`` given, or ``out`` already a ``_Packed``) cross
    the link as ONE buffer via a single device_get; unpacked dicts pay one
    conversion per leaf — and under a multi-process mesh go through the
    collective fetch. Each blocking fetch feeds the
    ``tpuml_executor_fetch_seconds`` histogram and the per-run phase
    accumulator (TrialRunResult.fetch_time_s)."""
    t0 = time.perf_counter()
    if isinstance(out, _Packed):
        out, spec = out.buf, out.spec
    if spec is not None:
        buf = np.asarray(jax.device_get(out))
        result = _unpack(buf, spec), 1, buf.nbytes
    else:
        host = _fetch(out)
        leaves = jax.tree_util.tree_leaves(host)
        result = host, len(leaves), sum(int(l.nbytes) for l in leaves)
    dt = time.perf_counter() - t0
    observe("tpuml_executor_fetch_seconds", dt)
    _PHASE.fetch += dt
    return result


# ---- compressed staging uploads -------------------------------------------
#
# Cold start spends seconds uploading the f32 design matrix over a ~9 MB/s
# tunneled link. CS230_STAGE_DTYPE=bf16 halves those bytes (int8 quarters
# them, with a per-column scale); the executable widens back to f32 on
# device as its first traced op. Off (f32) by default: bf16 staging moves
# scores by O(1e-3) (documented tolerance, tests/test_packed_parity.py).


def _staging_dtype() -> str:
    mode = os.environ.get("CS230_STAGE_DTYPE", "f32").lower()
    return mode if mode in ("bf16", "int8", "auto") else "f32"


#: probed host->device upload bandwidth (MB/s), measured once per process
_LINK_MBPS: Optional[float] = None


def _measured_link_mbps() -> float:
    """Host->device upload bandwidth in MB/s: ``CS230_STAGE_LINK_MBPS``
    pins it (tests, operators who know their tunnel); otherwise one 4 MiB
    ``device_put`` probe measures it (the second put — the first warms the
    transfer path so backend init doesn't read as a slow link). This is
    the ``auto`` staging policy's input: a local PCIe/host link measures
    GB/s, a tunneled TPU ~9 MB/s."""
    global _LINK_MBPS
    env = os.environ.get("CS230_STAGE_LINK_MBPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if _LINK_MBPS is None:
        try:
            probe = np.zeros((4 << 20,), np.uint8)
            jax.block_until_ready(jax.device_put(probe))
            t0 = time.perf_counter()
            jax.block_until_ready(jax.device_put(probe))
            dt = max(time.perf_counter() - t0, 1e-9)
            _LINK_MBPS = probe.nbytes / dt / 1e6
        except Exception:  # noqa: BLE001 — no backend: treat as fast/local
            _LINK_MBPS = float("inf")
    return _LINK_MBPS


def _resolve_stage_mode(mode: str) -> str:
    """Resolve the staging dtype, including the ``auto`` policy: bf16 for
    float features when the measured upload link is slower than
    ``CS230_STAGE_AUTO_MBPS`` (default 100 MB/s — an order of magnitude
    above any tunneled link, an order below any local one), f32 otherwise.
    int8 stays opt-in: its per-column quantization moves scores by ~2e-2,
    too coarse for a default."""
    if mode == "auto":
        if _stage_mode_available("bf16") != "bf16":
            return "f32"
        threshold = float(os.environ.get("CS230_STAGE_AUTO_MBPS", 100.0))
        return "bf16" if _measured_link_mbps() < threshold else "f32"
    return _stage_mode_available(mode)


def _stage_compress(X_np: np.ndarray, mode: str):
    """HOST-side compression right before the upload — the point is fewer
    bytes on the link, so the narrow form must exist before device_put."""
    X_np = np.asarray(X_np, np.float32)
    if mode == "bf16":
        import ml_dtypes  # availability pre-checked by the caller

        return {"bf16": X_np.astype(ml_dtypes.bfloat16)}
    if mode == "int8":
        scale = np.maximum(np.abs(X_np).max(axis=0), 1e-30) / 127.0
        q = np.clip(np.rint(X_np / scale), -127, 127).astype(np.int8)
        return {"q8": q, "scale": scale.astype(np.float32)}
    return X_np


def _stage_mode_available(mode: str) -> str:
    """Downgrade bf16 to f32 when ml_dtypes is missing — decided BEFORE
    the staging-cache key is formed, so a downgraded staging lands under
    the plain f32 key (no duplicate dataset copy in HBM)."""
    if mode == "bf16":
        try:
            import ml_dtypes  # noqa: F401
        except ImportError:
            return "f32"
    return mode


def _stage_decode(X):
    """Inverse of ``_stage_compress``, traced into the executable: widen
    bf16 / dequantize int8 back to the f32 matrix every kernel expects."""
    if isinstance(X, dict) and "bf16" in X:
        return X["bf16"].astype(jnp.float32)
    if isinstance(X, dict) and "q8" in X:
        return X["q8"].astype(jnp.float32) * X["scale"][None, :]
    return X


def _decode_wrap(fn):
    """Prepend the staged-X decode to a trial function's X argument (the
    one shared wrapper for the generic and fused-batched paths)."""

    def wrapped(X, y, TW, EW, hyper):
        return fn(_stage_decode(X), y, TW, EW, hyper)

    return wrapped


def _example_args(X, y, TW, EW, hyper_names, chunk):
    """Shape/dtype skeleton of one dispatch — drives the AOT export trace."""
    hyper = {
        k: jax.ShapeDtypeStruct((chunk,), jnp.float32)
        for k in (hyper_names or ["_pad"])
    }
    return (jax.tree_util.tree_map(_sds, X), _sds(y), _sds(TW), _sds(EW), hyper)


def _aot_key(kernel, static, X, n_classes, n_splits, chunk, hyper_names,
             stage_mode="f32", packed=None):
    leaves, treedef = jax.tree_util.tree_flatten(X)
    x_sig = (
        str(treedef),
        tuple((tuple(a.shape), str(a.dtype)) for a in leaves),
    )
    return (
        kernel.name,
        tuple(sorted((k, str(v)) for k, v in static.items())),
        x_sig,
        n_classes,
        n_splits,
        chunk,
        tuple(hyper_names),
        kernel.trace_salt(),
        os.environ.get("CS230_PALLAS_INTERPRET", ""),
        # transfer-layer knobs that change the executable's I/O signature:
        # packed output buffer vs per-leaf dict, and the EFFECTIVE staged-X
        # dtype of this executable (bf16/int8 stagings must never collide
        # with f32 blobs; the x_sig above carries the staged leaves' actual
        # dtype, this entry keys the decode wrapper itself). Callers pass
        # the effective mode, NOT the raw env knob — paths that force f32
        # (prepare_data/chunked/host/mesh) keep their blobs valid across
        # knob flips. ``packed`` can likewise be pinned False by callers
        # whose executable does not pack (chunk_init/chunk_step), keeping
        # their blobs valid across CS230_PACKED_FETCH flips.
        _packed_enabled() if packed is None else bool(packed),
        stage_mode,
    )


def _prepared_data(kernel, data, static_key, static):
    """Bucket-level prepare_data (tree binning etc.), cached ON the
    TrialData object so repeat jobs over a coordinator-cached dataset skip
    it. The prepare step round-trips the device (bin_data computes on
    device, ~0.11 s fetch on a tunneled link) — measured as a third of a
    tiny job's whole steady cost. Keying by (kernel, static bucket key)
    is exact: prepare_data only reads shape-determining statics, which is
    precisely what the bucket key hashes. Lifetime rides the dataset
    cache: evicting the TrialData drops the prepared forms with it."""
    cache = getattr(data, "_prepared_cache", None)
    if cache is None:
        cache = {}
        try:
            object.__setattr__(data, "_prepared_cache", cache)
        except Exception:  # exotic TrialData subclass: just don't cache
            return kernel.prepare_data(np.asarray(data.X), static)
    # trace_salt folds in the resolve-time env knobs (CS230_TREE_DEEP_N,
    # CS230_DEEP_W_FORCE, ...) that change prepare_data output without
    # changing the static bucket key — a knob flip mid-process must miss
    key = (kernel.name, static_key, kernel.trace_salt())
    if key not in cache:
        cache[key] = kernel.prepare_data(np.asarray(data.X), static)
    return cache[key]


#: distinct staged entries kept per dataset — each can be dataset-sized in
#: HBM, so a static-param sweep over many buckets must not pin one copy
#: per bucket forever (LRU; fold tensors and X share the budget)
_STAGED_CACHE_MAX = 6

#: one lock for every TrialData._device_cache — coordinator job threads
#: share DatasetCache entries, so inserts/evictions on the same OrderedDict
#: can interleave; operations under the lock are dict-op cheap
_STAGED_LOCK = threading.Lock()


def _device_sig() -> tuple:
    """Default-device identity for the staged-dataset cache key — the
    "per (dataset, device)" half of the multi-tenant staging contract."""
    try:
        d = jax.devices()[0]
        return (str(d.platform), int(d.id))
    except Exception:  # noqa: BLE001 — no backend yet
        return ("none", 0)


def _mesh_leaf_sharding_fn(mesh, data_axis, n):
    """THE row-sharding rule for dataset pytrees on a mesh, shared by the
    staging path (_staged_mesh — what gets placed) and the executable
    path (_get_compiled's in_shardings — what jit expects): leaves whose
    leading dim is the sample count shard their rows over ``data_axis``
    (2-D mesh), everything else replicates. One function so the
    staged-placement == in_shardings invariant cannot drift: a divergence
    would make every dispatch silently re-shard the full dataset."""
    replicated = NamedSharding(mesh, P())

    def leaf_sharding(leaf):
        if (
            data_axis is not None
            and hasattr(leaf, "ndim") and leaf.ndim >= 1
            and leaf.shape[0] == n
        ):
            spec = [None] * leaf.ndim
            spec[0] = data_axis
            return NamedSharding(mesh, P(*spec))
        return replicated

    return leaf_sharding


def _data_row_count(data) -> int:
    """Sample count used to recognize row-sharded leaves — one derivation
    for both users of _mesh_leaf_sharding_fn."""
    X = data.X
    return X.shape[0] if not isinstance(X, dict) else data.n_samples


def _mesh_axes_subkey(mesh) -> tuple:
    """Mesh axis spec + device identity for mesh-shaped cache subkeys:
    (((axis, size), ...), (device ids...)). The axis spec keeps the 1-D
    trial-replicated and 2-D data-sharded staged forms of one dataset
    distinct; the device ids keep two same-shaped meshes over DIFFERENT
    device subsets distinct — an entry committed to the wrong devices
    would fail the consumer jit's in_shardings, not reshard."""
    return (
        tuple((str(a), int(s)) for a, s in mesh.shape.items()),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def _staged_mesh(data, x_key, X_np, mesh, trial_axis, replicate_only=False):
    """Mesh-shaped staged dataset (docs/ARCHITECTURE.md "Elastic trial
    fabric"): ONE host->device tunnel upload per (dataset, host) — the
    plain single-device entry, shared with single-device jobs over the
    same content — then an on-device ``jax.device_put`` broadcast (1-D
    trial mesh: replicated) or reshard (2-D mesh: rows split over the
    data axis) that moves bytes over ICI instead of N independent trips
    down the tunnel. Both layers ride the multi-tenant stage cache:
    single-flight (8 concurrent mesh jobs build one copy), refcount
    pinning, and LRU eviction all apply, and the mesh entry's subkey
    carries the mesh axis spec so differently-shaped meshes coexist.

    ``replicate_only=True`` forces full replication even on a 2-D mesh —
    the chunked-fit protocol's executables expect replicated data
    (its in_shardings, _run_chunked). Falls back to the legacy
    per-dispatch ``jnp.asarray`` when the cache valve is off."""
    from ..data import stage_cache as _sc

    if not _sc.enabled():
        # legacy: leave staging/placement to jit's sharding machinery
        return jax.tree_util.tree_map(jnp.asarray, X_np)

    from .mesh import mesh_info

    n_dev, _ = mesh_info(mesh)
    data_axis = (
        None if replicate_only
        else next((a for a in mesh.shape if a != trial_axis), None)
    )
    # the shared rule: what gets placed here is exactly what
    # _get_compiled's in_shardings expect, so jit never re-shards it
    _leaf_sharding = _mesh_leaf_sharding_fn(
        mesh, data_axis, _data_row_count(data)
    )
    form = "rows" if data_axis is not None else "repl"
    mesh_key = (
        (_sc.dataset_fingerprint(data), _sc.host_signature())
        + tuple(x_key) + ("mesh", _mesh_axes_subkey(mesh), form)
    )

    def make_mesh():
        # layer 1 — the tunnel: the ordinary single-device staged entry
        # (key-identical to the single-device f32 path, so a mesh job and
        # a single-device job over one dataset share ONE upload)
        host_val = _staged_device(
            data, tuple(x_key) + ("dev",),
            lambda: jax.tree_util.tree_map(jnp.asarray, X_np),
        )
        # layer 2 — ICI: broadcast/reshard the resident copy across the
        # local mesh; device-to-device, never back through the tunnel
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, _leaf_sharding(leaf)),
            host_val,
        )

    nbytes = sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(X_np)
    )
    # replication traffic: every device beyond the source gets a full
    # copy; a row reshard moves ~one full pass of the data in total
    ici_est = nbytes * (n_dev - 1) if form == "repl" else nbytes
    t0 = time.perf_counter()
    stage_before = _PHASE.stage
    val, outcome = _sc.STAGE_CACHE.get_or_stage(
        mesh_key, make_mesh, transport="ici", ici_bytes=ici_est
    )
    if outcome != "hit":
        # the inner tunnel upload already added its own wall to the phase
        # accumulator; add only the replicate remainder so the run's
        # staging time covers both layers without double-counting
        inner = _PHASE.stage - stage_before
        _PHASE.stage += max(0.0, (time.perf_counter() - t0) - inner)
    return val


def _staged_device(data, key, make):
    """Device copies of job-invariant tensors (the dataset, fold masks).

    Default path: the process-global multi-tenant staged-dataset cache
    (data/stage_cache.py), keyed by (content fingerprint, device, entry
    subkey) with single-flight uploads and refcounted LRU eviction under
    the device-memory budget — N concurrent jobs over the same dataset
    stage it ONCE per (dataset, device). On a tunneled device,
    host->device bandwidth is the scarcest resource of all — measured
    ~9 MB/s, so re-staging a 188 MB MNIST matrix costs ~20 s PER JOB
    while the whole fused fit runs in ~2 s.

    ``CS230_STAGE_CACHE=0`` falls back to the legacy per-TrialData-object
    cache below (bit-for-bit identical staging, no cross-job sharing)."""
    from ..data import stage_cache as _sc

    if _sc.enabled():
        gkey = (_sc.dataset_fingerprint(data), _device_sig()) + tuple(key)
        t0 = time.perf_counter()
        val, outcome = _sc.STAGE_CACHE.get_or_stage(gkey, make)
        if outcome != "hit":
            dt = time.perf_counter() - t0
            if outcome == "miss":
                # only real uploads feed the histogram (hit contract);
                # "wait" time still counts as this run's staging wall
                observe("tpuml_executor_stage_seconds", dt)
            _PHASE.stage += dt
        return val
    with _STAGED_LOCK:
        cache = getattr(data, "_device_cache", None)
        if cache is None:
            cache = collections.OrderedDict()
            try:
                object.__setattr__(data, "_device_cache", cache)
            except Exception:
                cache = None
        if cache is not None and key in cache:
            cache.move_to_end(key)
            return cache[key]
    # make() outside the lock: staging can be a ~20 s host->device upload,
    # and a duplicate make() from a concurrent job thread is benign —
    # unlike a concurrent LRU eviction between insert and a re-read, which
    # would KeyError. The local `val` is returned directly so eviction of
    # this key by another thread can never fail THIS call.
    t0 = time.perf_counter()
    val = make()
    dt = time.perf_counter() - t0
    # only misses are observed: a cache hit is not a staging upload
    observe("tpuml_executor_stage_seconds", dt)
    _PHASE.stage += dt
    if cache is not None:
        with _STAGED_LOCK:
            cache[key] = val
            while len(cache) > _STAGED_CACHE_MAX:
                cache.popitem(last=False)
    return val


# overlapped device->host transfers (measured ~100 ms serial round trip
# per converted leaf on the tunneled link — the whole cost floor of tiny
# jobs, BASELINE configs 1/4): start every pending copy before the first
# blocking conversion
_prefetch_async = prefetch_async


def _call_with_prepared(fn, prepared, *args):
    """Invoke a kernel cost hook, passing the prepared-data dict to kernels
    whose estimators price it (tree kernels: grouped histograms change the
    true MAC count) while staying compatible with 3-arg estimators."""
    try:
        return fn(*args, prepared=prepared)
    except TypeError:
        return fn(*args)


#: buckets whose total analytical MACs fall below this run on the HOST XLA
#: CPU backend when the default backend is an accelerator: dispatching an
#: iris-sized fit to a (possibly tunneled) TPU costs more in round-trip
#: latency than the entire computation. This is a placement decision in the
#: spirit of the reference's size-aware scheduler (scheduler_service.py:
#: 167-191), applied at the host-vs-accelerator level.
_HOST_EXEC_MACS = float(os.environ.get("CS230_HOST_EXEC_MACS", 2e8))


def _make_batched(kernel, static, has_hyper):
    from ..obs.curves import curves_enabled

    # trial telemetry plane: kernels exposing fit_curve emit bounded
    # in-scan traces as extra result leaves (curve_*) that ride the
    # packed fetch / mesh sharding like any other output. The decision is
    # baked at trace time; kernel.trace_salt() carries the valve, so
    # every executable cache re-keys when it flips.
    capture = curves_enabled() and hasattr(kernel, "fit_curve")

    def scores_for_trial(X, y, TW, EW, hyper):
        if not has_hyper:
            hyper = {}

        def one_split(tw, ew):
            if capture:
                fitted, curve = kernel.fit_curve(X, y, tw, hyper, static)
                out = dict(kernel.evaluate(fitted, X, y, ew, static))
                for k, v in curve.items():
                    out["curve_" + k] = v
                return out
            fitted = kernel.fit(X, y, tw, hyper, static)
            return kernel.evaluate(fitted, X, y, ew, static)

        return jax.vmap(one_split)(TW, EW)

    return jax.vmap(scores_for_trial, in_axes=(None, None, None, None, 0))


@dataclasses.dataclass
class TrialRunResult:
    """Per-trial metrics in submission order, plus batch-level timing.

    ``device_best`` is the (submission-order index, mean_cv_score) winner as
    computed ON DEVICE by the collective argmax over the mesh-sharded score
    vector — present whenever the run executed sharded dispatches on a
    multi-device mesh (the BASELINE.json "argmax over ICI" path, running
    inside the production job flow, not just tests)."""

    trial_metrics: List[Dict[str, Any]]
    compile_time_s: float
    run_time_s: float
    n_dispatches: int
    device_best: Optional[tuple] = None
    #: blocking device->host result transfers performed (packed path: ONE
    #: per dispatched result buffer; per-leaf path: one per pytree leaf) —
    #: the observable the transfer-layer micro-benchmark pins
    n_host_fetches: int = 0
    #: bytes crossing the device->host boundary in those fetches
    result_bytes: int = 0
    #: wall seconds in host->device staging uploads (cache misses only)
    stage_time_s: float = 0.0
    #: wall seconds in blocking device->host result fetches
    fetch_time_s: float = 0.0
    # ---- device cost accounting (None when CS230_OBS=0 / unavailable) ----
    #: analytical model FLOPs of the whole run (2 * macs * splits * trials,
    #: summed over buckets whose kernel publishes macs_estimate) — the MFU
    #: numerator
    model_flops: Optional[float] = None
    #: XLA cost-analysis FLOPs summed over dispatches (what the hardware
    #: actually executed, padding and recompute included)
    xla_flops: Optional[float] = None
    #: XLA cost-analysis bytes accessed, summed over dispatches
    bytes_accessed: Optional[float] = None
    #: fraction of this run's buckets with a model-FLOP estimate (1.0 =
    #: model_flops prices the whole run; consumers must not read a partial
    #: sum as a total)
    flops_coverage: Optional[float] = None
    #: device-0 HBM high-water at run end (peak_bytes_in_use — MONOTONIC
    #: over the process lifetime, not per-run; the executor's in-fit
    #: sampler supplies the per-batch figure and uses this as fallback);
    #: None on CPU
    hbm_peak_bytes: Optional[int] = None


def run_trials(
    kernel: ModelKernel,
    data: TrialData,
    plan: SplitPlan,
    param_dicts: Sequence[Dict[str, Any]],
    *,
    mesh: Optional[Mesh] = None,
    trial_axis: str = "trials",
    max_trials_per_batch: int = 256,
    scoring: Optional[str] = None,
    warm_only: bool = False,
) -> TrialRunResult:
    """Run all trials (one per param dict), bucketing by static config.

    ``scoring`` is a sklearn scorer name honored by every kernel's evaluate
    (ops/metrics.py registry); None keeps the reference worker's defaults
    (accuracy / r2). It joins the static dict, so it is part of every
    executable cache key.

    ``warm_only=True`` is the prewarm path (runtime/prewarm.py): every
    bucket's executable is constructed (AOT blob deserialize or trace —
    the 2.2 s the r5 cold breakdown charges to inline AOT loading) and
    its staged tensors uploaded, but nothing is dispatched — the returned
    result carries the construction/staging timings and no metrics.

    Entries of the staged-dataset cache touched by this run are pinned
    (refcounted) for its duration so concurrent jobs' memory-pressure
    evictions can never drop a tensor out from under a dispatch.
    """
    from ..data import stage_cache as _sc

    token = _sc.STAGE_CACHE.pin_begin() if _sc.enabled() else None
    try:
        return _run_trials_impl(
            kernel, data, plan, param_dicts, mesh=mesh,
            trial_axis=trial_axis,
            max_trials_per_batch=max_trials_per_batch, scoring=scoring,
            warm_only=warm_only,
        )
    finally:
        if token is not None:
            _sc.STAGE_CACHE.pin_end(token)


def _run_trials_impl(
    kernel: ModelKernel,
    data: TrialData,
    plan: SplitPlan,
    param_dicts: Sequence[Dict[str, Any]],
    *,
    mesh: Optional[Mesh] = None,
    trial_axis: str = "trials",
    max_trials_per_batch: int = 256,
    scoring: Optional[str] = None,
    warm_only: bool = False,
) -> TrialRunResult:
    if scoring is not None:
        # fail loudly at the engine boundary, not inside a trace: every
        # entry point (executor, benchmarks, direct callers) inherits the
        # unknown-name / multiclass-binary / margin-capability checks
        from ..ops.metrics import validate_scoring

        validate_scoring(scoring, kernel.task, data.n_classes, kernel)
    n, d = data.X.shape
    results: List[Optional[Dict[str, Any]]] = [None] * len(param_dicts)
    compile_time = 0.0
    run_time = 0.0
    dispatches = 0
    n_fetches = 0
    result_bytes = 0
    # cost accounting for THIS run (valve read once: a mid-run flip must
    # not produce a half-priced result)
    acct = obs_enabled()
    model_flops = 0.0
    n_buckets = 0
    buckets_priced = 0
    xla_flops = 0.0
    xla_bytes = 0.0

    def _acc_cost(cost: Optional[Dict[str, float]]) -> None:
        # one dispatch of an executable executes its cost analysis once
        nonlocal xla_flops, xla_bytes
        if cost:
            xla_flops += cost.get("flops", 0.0)
            xla_bytes += cost.get("bytes", 0.0)
    # phase accumulators for THIS call (thread-local: concurrent jobs in
    # other threads keep their own) — read back into the TrialRunResult
    _PHASE.stage = 0.0
    _PHASE.fetch = 0.0
    # dispatches are queued without blocking and drained at the end: on a
    # remote/tunneled device each round trip costs ~0.25 s of latency, so a
    # multi-bucket job (e.g. a grid over a static param) overlaps its RPCs
    # instead of paying them serially
    pending: List[Any] = []
    # per-chunk on-device collective argmax results (multi-device mesh only):
    # (idx_scalar, score_scalar, batch_idx) — combined at drain
    pending_best: List[Any] = []
    device_best: Optional[tuple] = None
    t_first_dispatch: Optional[float] = None

    def _merge_best(idx: int, score: float):
        # sklearn's first-max rule GLOBALLY: on equal scores keep the
        # smaller submission index (chunks/buckets arrive out of global
        # submission order, so "first seen" is not enough)
        nonlocal device_best
        cur = device_best
        if cur is None or score > cur[1] or (score == cur[1] and idx < cur[0]):
            device_best = (idx, score)

    # ---- bucket trials by static (shape-determining) config ----
    buckets: Dict[Any, List[int]] = {}
    hypers: List[Dict[str, float]] = []
    for i, params in enumerate(param_dicts):
        static_key, hyper = kernel.canonicalize(params)
        hypers.append(hyper)
        buckets.setdefault(static_key, []).append(i)

    # device copies of the fold tensors are made lazily: an all-host job
    # (tiny buckets on an accelerator-default backend) must not pay any
    # accelerator transfer at all
    y_np = np.asarray(data.y)
    _dev_cache: List[Any] = []

    def _dev_args():
        if not _dev_cache:
            def make():
                return (
                    jnp.asarray(data.y),
                    jnp.asarray(plan.train_w),
                    jnp.asarray(plan.eval_w),
                )

            if plan.signature is not None:
                _dev_cache.append(
                    _staged_device(data, ("folds", plan.signature), make)
                )
            else:
                _dev_cache.append(make())
        return _dev_cache[0]

    def _to_host(out):
        nonlocal n_fetches, result_bytes
        host, nf, nb = _fetch_result(out, None)
        n_fetches += nf
        result_bytes += nb
        return host

    def _drain():
        nonlocal run_time, t_first_dispatch, n_fetches
        # overlap every pending device->host transfer before the first
        # blocking conversion (serial ~100 ms round trips otherwise)
        for bi, bs, _ in pending_best:
            _prefetch_async((bi, bs))
        for out, _ in pending:
            if isinstance(out, list):
                for og, _size in out:
                    _prefetch_async(og.buf if isinstance(og, _Packed) else og)
            elif isinstance(out, _Packed):
                _prefetch_async(out.buf)
            else:
                _prefetch_async(out)
        for bi, bs, batch_idx in pending_best:
            pos, score = int(bi), float(bs)
            n_fetches += 2  # two replicated scalars from the collective argmax
            if pos < len(batch_idx) and np.isfinite(score):
                _merge_best(batch_idx[pos], score)
        pending_best.clear()
        for out, batch_idx in pending:
            if isinstance(out, list):  # split-group dispatches: concat folds
                fetched = [(_to_host(og), size) for og, size in out]
                out = {
                    k: np.concatenate(
                        [og[k][:, :size] for og, size in fetched], axis=1
                    )
                    for k in fetched[0][0]
                }
            else:
                out = _to_host(out)
            for j, gi in enumerate(batch_idx):
                results[gi] = _postprocess(out, j, plan, kernel.task, scoring)
        pending.clear()
        if t_first_dispatch is not None:
            run_time += time.perf_counter() - t_first_dispatch
            t_first_dispatch = None

    n_dev = int(mesh.shape[trial_axis]) if mesh is not None else 1
    for static_key, idxs in buckets.items():
        static = kernel.static_from_key(static_key)
        if hasattr(kernel, "resolve_static"):
            static = kernel.resolve_static(static, n, d, data.n_classes)
        static["_n_classes"] = data.n_classes
        if scoring is not None:
            # only non-default scorers join the key: default jobs keep their
            # (already disk-cached) executables byte-identical
            static["_scoring"] = scoring

        # bucket-level data prep (e.g. feature binning for trees): computed
        # once, shared by every trial and split in the bucket — and cached
        # across jobs on the TrialData object
        if hasattr(kernel, "prepare_data"):
            X_np = _prepared_data(kernel, data, static_key, static)
        else:
            X_np = np.asarray(data.X, np.float32)

        if hasattr(kernel, "bucket_static"):
            static = kernel.bucket_static(static, [hypers[i] for i in idxs])

        # analytical model FLOPs of the whole bucket (2 * per-(trial,split)
        # MACs * splits * trials) — free to compute, covers every dispatch
        # path (generic/host/batched/chunked) the bucket takes below
        n_buckets += 1
        if acct and hasattr(kernel, "macs_estimate"):
            try:
                macs = _call_with_prepared(
                    kernel.macs_estimate, X_np, n, d, static
                )
                model_flops += (
                    2.0 * float(macs) * max(plan.n_splits, 1) * len(idxs)
                )
                buckets_priced += 1
            except Exception:  # noqa: BLE001 — estimator bug: unpriced bucket
                pass

        hyper_names = sorted(hypers[idxs[0]].keys())
        single_device = mesh is None or int(np.prod(list(mesh.shape.values()))) == 1

        # Kernels with a chunked-fit protocol (tree ensembles) split one
        # trial's fit across several bounded-time dispatches — full-depth
        # forests at any dataset size without multi-minute single RPCs. On a
        # multi-device mesh the same protocol runs with the trial axis
        # sharded across chips (state/hypers NamedSharded, data replicated),
        # so large forests keep bounded dispatches there too.
        chunk_plan = None
        if hasattr(kernel, "chunked_plan"):
            chunk_plan = _call_with_prepared(
                kernel.chunked_plan, X_np,
                static, n, d, data.n_classes, plan.n_splits,
            )

        # Host fast path decision (before any accelerator transfer): a bucket
        # whose entire work is trivial next to one device round trip runs on
        # the XLA CPU backend instead. Only kernels publishing an analytical
        # cost opt in; chunked buckets always take the device path (their
        # executables are device-platform AOT blobs).
        host_exec = (
            not chunk_plan
            and single_device
            and jax.default_backend() != "cpu"
            and hasattr(kernel, "macs_estimate")
            and _call_with_prepared(kernel.macs_estimate, X_np, n, d, static)
            * max(plan.n_splits, 1) * len(idxs) <= _HOST_EXEC_MACS
        )
        # Out-of-core row-block streaming (data/streaming.py): a bucket
        # whose staged footprint crowds the stage budget never uploads
        # the full matrix — kernels publishing a stream_scores driver
        # accumulate across double-buffered row blocks instead. Decided
        # BEFORE any X staging so the oversized single-shot upload (the
        # thing CS230_STAGE_STRICT turns into a hard error) never
        # happens. CS230_STREAM=force/off overrides the auto threshold.
        if (
            not chunk_plan
            and single_device
            and not host_exec
            and scoring is None
            and hasattr(kernel, "stream_scores")
        ):
            from ..data.streaming import should_stream, stream_mode

            x_bytes = sum(
                int(np.asarray(a).nbytes)
                for a in jax.tree_util.tree_leaves(X_np)
            )
            if (
                stream_mode() != "off"
                and kernel.stream_applicable(static, n, d)
                and should_stream(x_bytes)
            ):
                if warm_only:
                    # streamed buckets have nothing to prewarm that is
                    # worth a full block pass: their executables build
                    # lazily on the first real pass
                    continue
                # flush queued generic dispatches first — the streamed
                # bucket runs blocking and its wall must not be counted
                # inside the generic dispatch window
                _drain()
                rt, nd = _run_streamed(
                    kernel, static, X_np, y_np, hypers, idxs, results,
                    plan, hyper_names, data, max_trials_per_batch,
                )
                run_time += rt
                dispatches += nd
                continue

        # without prepare_data every bucket stages the same [n, d] matrix —
        # key by placement alone so an 8-bucket MLP grid uploads X once,
        # not 8 times (~20 s each for MNIST over the tunnel)
        x_key = (
            ("X", kernel.name, static_key, kernel.trace_salt())
            if hasattr(kernel, "prepare_data") else ("X",)
        )
        # compressed staging (CS230_STAGE_DTYPE=bf16|int8): the single-device
        # raw-matrix upload is the cold-start bill (~3.4 s of 7.4 s measured,
        # BASELINE.md r5 anatomy) — halve/quarter the bytes on the link and
        # widen back to f32 as the executable's first traced op. Kernels with
        # prepare_data stage already-compact prepared forms (binned int8)
        # and are left alone; the host fast path has no link to save.
        stage_mode = (
            _resolve_stage_mode(_staging_dtype())
            if single_device
            and not hasattr(kernel, "prepare_data")
            # chunked-protocol executables never decode (their kernels all
            # prepare_data today; this guards any future exception)
            and not chunk_plan
            else "f32"
        )
        if host_exec:
            cpu_dev = jax.local_devices(backend="cpu")[0]
            put = lambda a: jax.device_put(np.asarray(a), cpu_dev)  # noqa: E731
            X = _staged_device(
                data, x_key + ("host",),
                lambda: jax.tree_util.tree_map(put, X_np),
            )
            stage_mode = "f32"
        elif single_device:
            if stage_mode != "f32":
                X = _staged_device(
                    data, x_key + ("dev", stage_mode),
                    lambda: jax.tree_util.tree_map(
                        jnp.asarray, _stage_compress(X_np, stage_mode)
                    ),
                )
            else:
                X = _staged_device(
                    data, x_key + ("dev",),
                    lambda: jax.tree_util.tree_map(jnp.asarray, X_np),
                )
        else:
            # mesh path: stage through the tunnel ONCE per (dataset, host)
            # and broadcast/reshard over ICI (the mesh-aware stage cache;
            # legacy jit-placed staging when the cache valve is off)
            X = _staged_mesh(
                data, x_key, X_np, mesh, trial_axis,
                replicate_only=bool(chunk_plan),
            )
            stage_mode = "f32"
        if chunk_plan:
            # flush queued generic dispatches first: the chunked bucket runs
            # blocking, and its wall time must not be double-counted inside
            # the generic dispatch window
            _drain()
            y, TW, EW = _dev_args()
            ct, rt, nd, db, nf, nb = _run_chunked(
                kernel, static, X, y, TW, EW, hypers, idxs, results,
                plan, chunk_plan, hyper_names, data,
                mesh=None if single_device else mesh, trial_axis=trial_axis,
                warm_only=warm_only,
            )
            compile_time += ct
            run_time += rt
            dispatches += nd
            n_fetches += nf
            result_bytes += nb
            if db is not None:
                _merge_best(db[0], db[1])
            continue

        out_spec: Optional[_PackSpec] = None
        exec_cost: Optional[Dict[str, float]] = None
        if host_exec:
            X_d = X
            y_d = put(y_np)
            TW_d, EW_d = put(plan.train_w), put(plan.eval_w)
            chunk = min(max_trials_per_batch, len(idxs))
            cache_key = ("host",) + _aot_key(
                kernel, static, X, data.n_classes, plan.n_splits, chunk, hyper_names
            )
            fresh_compile = cache_key not in _compiled_cache
            _cache_count(not fresh_compile)
            if fresh_compile:
                raw = _make_batched(kernel, static, bool(hyper_names))
                example = _example_args(
                    X, y_np, plan.train_w, plan.eval_w, hyper_names, chunk
                )
                # cost captured on the pre-pack form: the executable's
                # priced work must not vary with the transport knob
                cost = _capture_cost(raw, example)
                spec = None
                if _packed_enabled():
                    spec = _pack_spec_of(raw, example)
                    raw = _pack_wrap(raw)
                _compiled_cache[cache_key] = (jax.jit(raw), spec, cost)
            fn, out_spec, exec_cost = _compiled_cache[cache_key]

        # Kernels with a fused batched path (e.g. the Pallas packed
        # LogisticRegression fit, models/logistic.py) take over the whole
        # chunk: one jitted call = fit scan + eval, with its own (larger)
        # chunk geometry. Single-device only — the trial mesh axis is
        # handled by the generic sharded path.
        batched_fn = None
        extra_args = None
        if (hasattr(kernel, "build_batched_fn") and single_device and not host_exec
                and scoring is None):  # fused paths score by the default metric
            Tw = getattr(kernel, "batched_trial_multiple", 128)
            cap = getattr(kernel, "batched_chunk_cap", 1024)
            bchunk = max(Tw, min(cap, pad_to_multiple(len(idxs), Tw)))
            batched_fn = kernel.build_batched_fn(
                static=static,
                n=n,
                d=d,
                n_classes=data.n_classes,
                n_splits=plan.n_splits,
                chunk=bchunk,
            )

        if batched_fn is not None:
            chunk = bchunk
            y_d, TW_d, EW_d = _dev_args()
            X_d = X
            # dispatch-invariant staged forms the kernel wants precomputed
            # (e.g. the LogReg padded bf16 design matrix and the per-split
            # Lipschitz bound): staged ONCE per (dataset, device, subkey)
            # in the multi-tenant stage cache and merged into every
            # dispatch's hyper dict — the per-dispatch jit stops paying
            # for them. Keys ride the content fingerprint + the effective
            # staged-X dtype (a bf16-staged matrix derives different
            # values than f32).
            if hasattr(kernel, "batched_staged_extras"):
                specs = kernel.batched_staged_extras(
                    static=static, n=n, d=d, n_classes=data.n_classes,
                    n_splits=plan.n_splits, fold_signature=plan.signature,
                )
                if specs:
                    ctx = {"X": X_d, "y": y_d, "TW": TW_d, "EW": EW_d,
                           "decode": _stage_decode}
                    extra_args = {}
                    for name in sorted(specs):
                        subkey, make = specs[name]
                        if subkey is None:
                            # nothing stable to key on (e.g. an unsigned
                            # fold plan): still hoisted out of the
                            # per-dispatch jit, just not cached across runs
                            extra_args[name] = make(ctx)
                        else:
                            extra_args[name] = _staged_device(
                                data,
                                ("batched_extra", kernel.name, name,
                                 stage_mode) + tuple(subkey),
                                lambda m=make: m(ctx),
                            )
            # one key for both layers: _aot_key carries everything that
            # determines the executable (incl. the interpret-mode env var,
            # which is baked into the closure at build time, and the packed/
            # staging transfer knobs)
            cache_key = ("batched",) + _aot_key(
                kernel, static, X, data.n_classes, plan.n_splits, chunk,
                hyper_names, stage_mode=stage_mode,
            )
            if extra_args:
                # the staged extras join the executable's input signature
                cache_key = cache_key + (
                    "extras",
                    tuple(
                        (k, tuple(v.shape), str(v.dtype))
                        for k, v in sorted(extra_args.items())
                    ),
                )
            fresh_compile = cache_key not in _compiled_cache
            _cache_count(not fresh_compile)
            if fresh_compile:
                raw = batched_fn
                if stage_mode != "f32":
                    # widen the compressed staged matrix before the fused
                    # kernel sees it (it expects the f32 design matrix)
                    raw = _decode_wrap(batched_fn)
                example = _example_args(X, y_np, plan.train_w, plan.eval_w,
                                        hyper_names, chunk)
                if extra_args:
                    example[4].update(
                        {k: _sds(v) for k, v in extra_args.items()}
                    )
                cost = _capture_cost(raw, example)
                spec = None
                if _packed_enabled():
                    spec = _pack_spec_of(raw, example)
                    raw = _pack_wrap(raw)
                compiled, _ = aot_jit(raw, cache_key, example)
                _compiled_cache[cache_key] = (compiled, spec, cost)
            fn, out_spec, exec_cost = _compiled_cache[cache_key]
        elif not host_exec:
            y_d, TW_d, EW_d = _dev_args()
            X_d = X
            mem_cap = _memory_chunk_cap(kernel, n, d, static, plan.n_splits, n_dev)
            chunk = min(max_trials_per_batch, mem_cap, pad_to_multiple(len(idxs), n_dev))
            chunk = max(n_dev, pad_to_multiple(chunk, n_dev))

        # split-axis chunking (same rationale as _run_chunked's): when even
        # ONE minimum-size trial batch times all folds blows the memory
        # budget — Nyström SVC's [n, m] feature matrix per split lane is
        # the motivating case — run the folds across several dispatches
        # over a fold-group-sized executable instead of OOMing the device.
        # Budgets are PER DEVICE: at chunk == n_dev each device holds one
        # trial's full fold stack, so fold memory does not divide by n_dev.
        split_groups = None
        if not host_exec and batched_fn is None:
            per_split_mb = max(
                kernel.memory_estimate_mb(n, d, static)
                if hasattr(kernel, "memory_estimate_mb") else 0.5, 0.5)
            budget_mb = 0.5 * _device_memory_mb()
            n_splits = int(plan.n_splits)
            if chunk == n_dev and per_split_mb * n_splits > budget_mb:
                sgn = max(1, min(n_splits, int(budget_mb / per_split_mb)))
                if sgn < n_splits:
                    split_groups = []
                    for s0 in range(0, n_splits, sgn):
                        size = min(sgn, n_splits - s0)
                        twg = plan.train_w[s0 : s0 + size]
                        ewg = plan.eval_w[s0 : s0 + size]
                        if size < sgn:  # pad by repeating; cols dropped later
                            twg = np.concatenate(
                                [twg, np.repeat(twg[-1:], sgn - size, 0)])
                            ewg = np.concatenate(
                                [ewg, np.repeat(ewg[-1:], sgn - size, 0)])
                        split_groups.append(
                            (jnp.asarray(twg), jnp.asarray(ewg), size))
            if split_groups is not None:
                TW_g = split_groups[0][0]
                fn, out_spec, exec_cost, fresh_compile = _get_compiled(
                    kernel, static_key, static, mesh, trial_axis, data, plan,
                    chunk, hyper_names, X, y_np,
                    np.asarray(TW_g), np.asarray(split_groups[0][1]),
                    n_splits_override=int(TW_g.shape[0]),
                    stage_mode=stage_mode,
                )
            else:
                fn, out_spec, exec_cost, fresh_compile = _get_compiled(
                    kernel, static_key, static, mesh, trial_axis, data, plan,
                    chunk, hyper_names, X, y_np, plan.train_w, plan.eval_w,
                    stage_mode=stage_mode,
                )

        if warm_only:
            # prewarm: executables constructed + tensors staged above —
            # the cold path a first trial would otherwise pay inline —
            # but nothing dispatches and no results exist
            continue

        for start in range(0, len(idxs), chunk):
            batch_idx = idxs[start : start + chunk]
            T = len(batch_idx)
            if hyper_names:
                hyper_batch = {
                    k: np.full((chunk,), hypers[batch_idx[-1]][k], np.float32)
                    for k in hyper_names
                }
                for j, gi in enumerate(batch_idx):
                    for k in hyper_names:
                        hyper_batch[k][j] = hypers[gi][k]
            else:
                hyper_batch = {"_pad": np.zeros((chunk,), np.float32)}
            to_dev = put if host_exec else jnp.asarray
            hyper_arg = {k: to_dev(v) for k, v in hyper_batch.items()}
            if extra_args:
                hyper_arg = {**hyper_arg, **extra_args}

            t0 = time.perf_counter()
            if t_first_dispatch is None:
                t_first_dispatch = t0
            if split_groups is not None:
                group_outs = []
                for gi_, (twg, ewg, size) in enumerate(split_groups):
                    out_g = fn(X_d, y_d, twg, ewg, hyper_arg)
                    dispatches += 1
                    _acc_cost(exec_cost)
                    if fresh_compile and start == 0 and gi_ == 0:
                        # attribute the XLA compile to the FIRST group only;
                        # later groups reuse the executable and their device
                        # time is steady run time, not compile
                        out_g = jax.block_until_ready(out_g)
                        compile_time += time.perf_counter() - t0
                        observe("tpuml_executor_compile_seconds",
                                time.perf_counter() - t0)
                    if out_spec is not None:
                        out_g = _Packed(out_g, out_spec)
                    group_outs.append((out_g, size))
                pending.append((group_outs, batch_idx))
                continue
            out = fn(X_d, y_d, TW_d, EW_d, hyper_arg)
            if fresh_compile and start == 0:
                # block only on a fresh executable's first dispatch so its
                # XLA compile is attributed; steady-state dispatches queue
                out = jax.block_until_ready(out)
                compile_time += time.perf_counter() - t0
                observe("tpuml_executor_compile_seconds",
                        time.perf_counter() - t0)
            if out_spec is not None:
                out = _Packed(out, out_spec)
            if mesh is not None and n_dev > 1:
                # collective argmax over the trial-sharded score vector: XLA
                # inserts the ICI all-gather/reduce; only two replicated
                # scalars come back to host per chunk
                bi, bs = _chunk_best(
                    mesh, trial_axis, chunk, int(plan.n_splits), plan.n_folds
                )(out["score"], jnp.int32(T))
                pending_best.append((bi, bs, batch_idx))
            pending.append((out, batch_idx))
            dispatches += 1
            _acc_cost(exec_cost)

    _drain()

    return TrialRunResult(
        trial_metrics=[r for r in results if r is not None],
        compile_time_s=compile_time,
        run_time_s=run_time,
        n_dispatches=dispatches,
        device_best=device_best,
        n_host_fetches=n_fetches,
        result_bytes=result_bytes,
        stage_time_s=_PHASE.stage,
        fetch_time_s=_PHASE.fetch,
        model_flops=model_flops if acct and buckets_priced else None,
        xla_flops=xla_flops if acct and xla_flops > 0 else None,
        bytes_accessed=xla_bytes if acct and xla_bytes > 0 else None,
        flops_coverage=(
            buckets_priced / n_buckets if acct and n_buckets else None
        ),
        hbm_peak_bytes=_hbm_peak_bytes() if acct else None,
    )


def fit_single(
    kernel: ModelKernel,
    data: TrialData,
    plan: SplitPlan,
    params: Dict[str, Any],
    split: int = 0,
):
    """Fit one configuration on one split's train subset (default: the
    holdout-train split) and return the fitted params pytree (host numpy).
    Used to materialize the best model artifact after aggregation
    (reference pickles every trial's model, worker.py:352-356; we refit
    only the winner), and per CV fold by the callable-scoring fallback."""
    n, d = data.X.shape
    static_key, hyper = kernel.canonicalize(params)
    static = kernel.static_from_key(static_key)
    if hasattr(kernel, "resolve_static"):
        static = kernel.resolve_static(static, n, d, data.n_classes)
    static["_n_classes"] = data.n_classes

    if hasattr(kernel, "prepare_data"):
        X = jax.tree_util.tree_map(
            jnp.asarray, _prepared_data(kernel, data, static_key, static)
        )
    else:
        X = jnp.asarray(data.X, jnp.float32)
    y = jnp.asarray(data.y)
    w = jnp.asarray(plan.train_w[split])
    hyper_arg = {k: jnp.asarray(v, jnp.float32) for k, v in hyper.items()}
    fit_key = (
        "fit_single",
        kernel.name,
        tuple(sorted((k, str(v)) for k, v in static.items())),
        data.X.shape,
        data.n_classes,
    )

    # ensemble kernels on large data: materialize the winner's trees across
    # bounded-time dispatches too (same rationale as the chunked trial path)
    chunk_plan = None
    if hasattr(kernel, "chunked_plan") and hasattr(kernel, "fit_chunk"):
        chunk_plan = _call_with_prepared(
            kernel.chunked_plan, X, static, n, d, data.n_classes, 1
        )
    if chunk_plan:
        n_chunks = int(chunk_plan["n_chunks"])
        ck = fit_key + ("chunked", n_chunks, chunk_plan["trees_per_chunk"])
        _cache_count(ck in _compiled_cache)
        if ck not in _compiled_cache:
            _compiled_cache[ck] = (
                jax.jit(lambda X, y, w, h: kernel.chunk_init(X, y, w, h, static)),
                jax.jit(
                    lambda X, y, w, h, ci, carry: kernel.fit_chunk(
                        X, y, w, h, static, ci, carry, chunk_plan
                    )
                ),
            )
        f_init, f_chunk = _compiled_cache[ck]
        carry = f_init(X, y, w, hyper_arg)
        parts = []
        for ci in range(n_chunks):
            carry, part = f_chunk(X, y, w, hyper_arg, jnp.int32(ci), carry)
            parts.append(part)  # device arrays: dispatches pipeline
        n_units = int(static.get("n_estimators", 100))
        for p in parts:
            _prefetch_async(p)
        parts = [jax.tree_util.tree_map(np.asarray, p) for p in parts]
        trees = jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0)[:n_units], *parts
        )
        fitted = kernel.assemble_artifact(trees, X, hyper_arg, static, y, w)
        return jax.tree_util.tree_map(np.asarray, fitted), static

    _cache_count(fit_key in _compiled_cache)
    if fit_key not in _compiled_cache:
        _compiled_cache[fit_key] = jax.jit(
            lambda X, y, w, h: kernel.fit(X, y, w, h, static)
        )
    fitted = _compiled_cache[fit_key](X, y, w, hyper_arg)
    return jax.tree_util.tree_map(np.asarray, fitted), static


def run_trials_callable(
    kernel: ModelKernel,
    data: TrialData,
    plan: SplitPlan,
    params_list: Sequence[Dict[str, Any]],
    scorer,
) -> List[Dict[str, Any]]:
    """Host-side fallback for CALLABLE ``scoring``: per (trial, fold) the
    kernel fits on device (fit_single — jit-cached per static bucket, so
    the accelerated fit is kept), the fitted params are exported to a real
    sklearn estimator (runtime/sklearn_export), and the user's
    ``scorer(estimator, X_eval, y_eval)`` runs on host. Slower than the
    jitted scorer registry (one export + one host call per fold) but
    correct for ANY sklearn-scorer callable — the reference client passed
    arbitrary ``scoring`` through and its worker silently dropped it
    (DistributedLibrary core.py:135-138, worker.py:320-349); here it ranks
    trials. Returns per-trial metrics dicts shaped like _postprocess's."""
    from ..runtime.sklearn_export import to_sklearn

    X_np = np.asarray(data.X)
    y_np = np.asarray(data.y)
    results: List[Dict[str, Any]] = []
    for params in params_list:
        split_scores: List[float] = []
        scorer_errors: List[str] = []
        for s in range(plan.n_splits):
            fitted, static = fit_single(kernel, data, plan, params, split=s)
            est = to_sklearn({
                "model_type": kernel.name,
                "parameters": params,
                "static": dict(static),
                "fitted_params": fitted,
            })
            keep = np.asarray(plan.eval_w[s]) > 0
            try:
                split_scores.append(float(scorer(est, X_np[keep], y_np[keep])))
            except Exception as e:  # noqa: BLE001 — a scorer bug fails THIS
                # trial (ranked last), not the whole job
                split_scores.append(float("nan"))
                scorer_errors.append(f"split {s}: {e!r}")
        metrics: Dict[str, Any] = {"scoring": "callable",
                                   "score": split_scores[0]}
        if plan.n_folds >= 2 and len(split_scores) > 1:
            metrics["cv_scores"] = split_scores[1:]
            metrics["mean_cv_score"] = float(np.mean(split_scores[1:]))
        else:
            metrics["mean_cv_score"] = split_scores[0]
        # ANY non-finite split (a holdout-only scorer failure included)
        # marks the trial diverged — a silently-NaN holdout score with a
        # finite CV mean would hide the error entirely
        if not all(np.isfinite(v) for v in split_scores):
            metrics["mean_cv_score"] = float("-inf")
            metrics["diverged"] = True
            if scorer_errors:
                metrics["scorer_error"] = "; ".join(scorer_errors)
        results.append(metrics)
    return results


def _chunk_best(mesh, trial_axis: str, chunk: int, n_splits: int, n_folds: int):
    """Cached jitted reducer: trial-sharded [chunk, n_splits] scores ->
    replicated (argmax lane, mean-CV score). The in/out sharding mismatch is
    what makes XLA emit the cross-chip collective (all-gather or reduce over
    ICI on TPU meshes). ``n_valid`` masks padding lanes; non-finite scores
    rank last, mirroring _postprocess's diverged-trial rule."""
    key = ("chunk_best", chunk, n_splits, n_folds, _mesh_signature(mesh))
    if key in _compiled_cache:
        return _compiled_cache[key]

    def reduce(score, n_valid):
        if n_folds >= 2:
            mean_cv = jnp.mean(score[:, 1:], axis=1)
        else:
            mean_cv = score[:, 0]
        lane = jnp.arange(score.shape[0])
        mean_cv = jnp.where(
            (lane < n_valid) & jnp.isfinite(mean_cv), mean_cv, -jnp.inf
        )
        i = jnp.argmax(mean_cv)  # first max: sklearn's tie rule
        return i.astype(jnp.int32), mean_cv[i]

    sharded = NamedSharding(mesh, P(trial_axis, None))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(reduce, in_shardings=(sharded, repl), out_shardings=(repl, repl))
    _compiled_cache[key] = fn
    return fn


def _device_memory_mb() -> float:
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return stats["bytes_limit"] / 1e6
    except Exception:  # noqa: BLE001
        pass
    return 8_000.0


def _memory_chunk_cap(kernel, n, d, static, n_splits, n_dev) -> int:
    """Trials per dispatch bounded by per-device HBM: each in-flight trial
    holds ~memory_estimate_mb per split concurrently under the split vmap."""
    per_trial_mb = max(kernel.memory_estimate_mb(n, d, static), 0.5) * max(n_splits, 1)
    budget_mb = 0.5 * _device_memory_mb() * max(n_dev, 1)
    return max(n_dev, int(budget_mb / per_trial_mb))


def _mesh_signature(mesh):
    """Stable executable-cache key for a Mesh: axis names/sizes + device
    ids. ``id(mesh)`` (the previous key) could serve a stale sharded
    executable if a Mesh was GC'd and a different Mesh landed on the
    recycled address (VERDICT r2 weak #6)."""
    if mesh is None:
        return None
    return (
        tuple(mesh.shape.items()),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def _get_compiled(kernel, static_key, static, mesh, trial_axis, data, plan, chunk,
                  hyper_names, X_proto=None, y=None, TW=None, EW=None,
                  n_splits_override=None, stage_mode="f32"):
    """Returns (fn, pack_spec_or_None, cost_or_None, fresh). Single-device
    executables take the packed-output form (one uint8 result buffer, see
    _pack_wrap) and carry their XLA cost analysis (captured once, at
    construction); mesh executables keep the per-leaf dict — their score
    vector feeds the on-device collective argmax and the cross-process
    collective fetch — and skip cost capture (sharded lowering would pay a
    second full trace; the analytical bucket accounting still prices
    them)."""
    has_hyper = bool(hyper_names)
    n_splits_key = n_splits_override or plan.n_splits
    # a 1-device mesh is compilation-equivalent to no mesh: drop the
    # NamedShardings so the executable is AOT-exportable and its disk key is
    # mesh-independent (single chip is the bench/measure environment)
    n_mesh_dev = int(np.prod(list(mesh.shape.values()))) if mesh is not None else 1
    if n_mesh_dev == 1:
        mesh = None
    x_sig = (
        tuple(
            (tuple(a.shape), str(a.dtype))
            for a in jax.tree_util.tree_leaves(X_proto)
        )
        if X_proto is not None else None
    )
    cache_key = (
        kernel.name,
        # trace-time env knobs (fused-step/curves/... valves) change the
        # traced program without landing in static — without the salt a
        # mid-process valve flip would serve a stale executable from this
        # in-memory cache (the disk _aot_key below already carries it)
        kernel.trace_salt(),
        tuple(sorted((k, str(v)) for k, v in static.items())),
        data.X.shape,
        x_sig,
        stage_mode,
        _packed_enabled(),
        data.n_classes,
        n_splits_key,
        chunk,
        _mesh_signature(mesh),
    )
    if cache_key in _compiled_cache:
        _cache_count(True)
        fn, spec, cost = _compiled_cache[cache_key]
        return fn, spec, cost, False
    _cache_count(False)

    batched = _make_batched(kernel, static, has_hyper)
    if stage_mode != "f32":
        # widen the compressed staged matrix to f32 before the vmapped fits
        batched = _decode_wrap(batched)

    if mesh is not None:
        replicated = NamedSharding(mesh, P())
        trial_sharded = NamedSharding(mesh, P(trial_axis))
        # 2-D mesh (trials, data): additionally shard the sample dimension of
        # the dataset arrays across the data axis — XLA inserts the psum/
        # all-gather collectives inside each trial's fit (batch parallelism
        # within a trial, trial parallelism across the other axis)
        data_axis = next((a for a in mesh.shape if a != trial_axis), None)
        if data_axis is not None and X_proto is not None:
            # shared with _staged_mesh: the staged placement and these
            # in_shardings must agree or every dispatch re-shards
            leaf_sharding = _mesh_leaf_sharding_fn(
                mesh, data_axis, _data_row_count(data)
            )
            X_shardings = jax.tree_util.tree_map(leaf_sharding, X_proto)
            y_sh = NamedSharding(mesh, P(data_axis))
            w_sh = NamedSharding(mesh, P(None, data_axis))
            fn = jax.jit(
                batched,
                in_shardings=(X_shardings, y_sh, w_sh, w_sh, trial_sharded),
                out_shardings=trial_sharded,
            )
        else:
            fn = jax.jit(
                batched,
                in_shardings=(replicated, replicated, replicated, replicated, trial_sharded),
                out_shardings=trial_sharded,
            )
        spec = None
        cost = None
    else:
        X_ex = X_proto if X_proto is not None else jax.ShapeDtypeStruct(
            data.X.shape, jnp.float32
        )
        example = _example_args(X_ex, y, TW, EW, hyper_names, chunk)
        disk_key = ("generic",) + _aot_key(
            kernel, static, X_ex, data.n_classes, n_splits_key, chunk,
            hyper_names, stage_mode=stage_mode,
        )
        cost = _capture_cost(batched, example)
        spec = None
        if _packed_enabled():
            spec = _pack_spec_of(batched, example)
            batched = _pack_wrap(batched)
        fn, _ = aot_jit(batched, disk_key, example)
    _compiled_cache[cache_key] = (fn, spec, cost)
    return fn, spec, cost, True


def _run_chunked(
    kernel, static, X, y, TW, EW, hypers, idxs, results,
    plan: SplitPlan, chunk_plan: Dict[str, Any], hyper_names, data,
    mesh: Optional[Mesh] = None, trial_axis: str = "trials",
    warm_only: bool = False,
):
    """Run one bucket through the kernel's chunked-fit protocol.

    init -> n_chunks x step -> eval, all vmapped over (trials, splits); the
    cross-dispatch state is the kernel's accumulator pytree (e.g. summed
    per-tree predictions for a forest). Dispatches are NOT synchronized
    between steps — they pipeline on the device queue; only eval's output is
    fetched (packed into one byte buffer on the single-device path, so the
    whole bucket's scores cross the link as ONE transfer). With ``mesh``,
    the trial axis of hypers and state is NamedSharded across devices (data
    replicated) so each chip carries its trial slice through every chunk.
    Returns (compile_time, run_time, n_dispatches, device_best,
    n_host_fetches, result_bytes) — device_best is the collective-argmax
    winner (submission-order trial index, score) on multi-device meshes
    with an unsplit fold stack, else None.
    """
    n_chunks = int(chunk_plan["n_chunks"])
    n_dev = int(np.prod(list(mesh.shape.values()))) if mesh is not None else 1

    from ..obs.curves import curve_points, curves_enabled

    # sampled-chunk curve stride; 0 = capture off (single-chunk plans
    # have no intermediate prefix to evaluate)
    curve_stride = (
        max(1, -(-n_chunks // curve_points()))
        if curves_enabled() and n_chunks > 1 and not warm_only
        else 0
    )

    def _h(hyper):
        return hyper if hyper_names else {}

    def init_b(X, y, TW, EW, hyper):
        return jax.vmap(
            lambda tw: kernel.chunk_init(X, y, tw, _h(hyper), static)
        )(TW)

    def step_b(X, y, TW, EW, hyper, ci, state):
        return jax.vmap(
            lambda tw, st: kernel.chunk_step(
                X, y, tw, _h(hyper), static, ci, st, chunk_plan
            )
        )(TW, state)

    def eval_b(X, y, TW, EW, hyper, state):
        return jax.vmap(
            lambda ew, st: kernel.chunk_eval(X, y, ew, _h(hyper), static, st)
        )(EW, state)

    vinit = jax.vmap(init_b, in_axes=(None, None, None, None, 0))
    vstep = jax.vmap(step_b, in_axes=(None, None, None, None, 0, None, 0))
    veval = jax.vmap(eval_b, in_axes=(None, None, None, None, 0, 0))

    # trial-chunk size: bounded by BOTH the cross-dispatch state memory and
    # the kernel's per-trial working-set estimate (histogram buffers etc. —
    # the same cap the non-chunked path consults)
    state_mb = 4.0 * data.n_samples * max(data.n_classes, 1) * plan.n_splits / 1e6
    mem_cap = _memory_chunk_cap(kernel, data.n_samples, data.n_features, static,
                                plan.n_splits, n_dev)
    chunk = max(1, min(len(idxs), mem_cap,
                       int(0.25 * n_dev * _device_memory_mb() / max(state_mb, 1.0)),
                       64 * n_dev))
    chunk = max(n_dev, pad_to_multiple(chunk, n_dev))

    # split-axis chunking: the per-trial working set is multiplied by
    # n_splits inside the split vmap, so when even ONE trial's splits blow
    # the budget (deep/wide trees at large n), run the folds across several
    # dispatches instead of dispatching past HBM
    n_splits = int(plan.n_splits)
    sg = n_splits
    per_split_mb = max(kernel.memory_estimate_mb(
        data.n_samples, data.n_features, static), 0.5)
    budget_mb = 0.5 * _device_memory_mb()
    if chunk == 1 and per_split_mb * n_splits > budget_mb:
        sg = max(1, min(n_splits, int(budget_mb / per_split_mb)))

    split_groups = []
    for s0 in range(0, n_splits, sg):
        size = min(sg, n_splits - s0)
        twg, ewg = TW[s0 : s0 + size], EW[s0 : s0 + size]
        if size < sg:  # pad by repeating a fold; padded cols dropped below
            twg = jnp.concatenate([twg, jnp.repeat(twg[-1:], sg - size, 0)])
            ewg = jnp.concatenate([ewg, jnp.repeat(ewg[-1:], sg - size, 0)])
        split_groups.append((twg, ewg, size))
    TW_ex, EW_ex = split_groups[0][0], split_groups[0][1]

    # packed=False: init/step executables never pack (their state stays on
    # device), so their disk blobs must survive CS230_PACKED_FETCH flips;
    # only chunk_eval's key (below) carries the live flag
    base_key_parts = _aot_key(
        kernel, static, X, data.n_classes, sg, chunk, hyper_names,
        packed=False,
    ) + (n_chunks, chunk_plan.get("trees_per_chunk"))
    cache_tag = ("chunked",) + base_key_parts + (_packed_enabled(),) + (
        (_mesh_signature(mesh),) if mesh is not None else ()
    )
    compile_time = 0.0
    run_time = 0.0
    dispatches = 0
    n_fetches = 0
    result_bytes = 0
    device_best = None
    fresh = cache_tag not in _compiled_cache
    _cache_count(not fresh)
    if fresh:
        # compile_time counts executable construction (trace or AOT
        # deserialize) only — the first batch's wall time is real chunked
        # compute and is NOT compile (an earlier version attributed it,
        # inflating the metric even on full AOT-cache hits). XLA compiles of
        # freshly traced executables still land in the first batch's
        # run_time; the persistent compile cache keeps that small.
        t_build = time.perf_counter()
        hyper_ex = {
            k: jax.ShapeDtypeStruct((chunk,), jnp.float32)
            for k in (hyper_names or ["_pad"])
        }
        if mesh is not None:
            # sharded chunked protocol: trial axis (hypers, state, outputs)
            # split across the mesh, dataset/fold masks replicated. Mesh
            # executables are process-local — no AOT export.
            repl = NamedSharding(mesh, P())
            tsh = NamedSharding(mesh, P(trial_axis))
            X_sh = jax.tree_util.tree_map(lambda _: repl, X)
            h_sh = {k: tsh for k in hyper_ex}
            state_ex = jax.eval_shape(vinit, X, y, TW_ex, EW_ex, hyper_ex)
            st_sh = jax.tree_util.tree_map(lambda _: tsh, state_ex)
            out_ex = jax.eval_shape(veval, X, y, TW_ex, EW_ex, hyper_ex, state_ex)
            fi = jax.jit(
                vinit,
                in_shardings=(X_sh, repl, repl, repl, h_sh),
                out_shardings=st_sh,
            )
            fs = jax.jit(
                vstep,
                in_shardings=(X_sh, repl, repl, repl, h_sh, repl, st_sh),
                out_shardings=st_sh,
            )
            fe = jax.jit(
                veval,
                in_shardings=(X_sh, repl, repl, repl, h_sh, st_sh),
                out_shardings=jax.tree_util.tree_map(lambda _: tsh, out_ex),
            )
            fe_spec = None
        else:
            Xe = jax.tree_util.tree_map(_sds, X)
            args_ie = (Xe, _sds(y), _sds(TW_ex), _sds(EW_ex), hyper_ex)
            fi, _ = aot_jit(vinit, ("chunk_init",) + base_key_parts, args_ie)
            state_ex = jax.eval_shape(vinit, X, y, TW_ex, EW_ex, hyper_ex)
            args_e = args_ie + (jax.tree_util.tree_map(_sds, state_ex),)
            fs, _ = aot_jit(
                vstep,
                ("chunk_step",) + base_key_parts,
                args_ie + (jax.ShapeDtypeStruct((), jnp.int32),)
                + (jax.tree_util.tree_map(_sds, state_ex),),
            )
            # only eval's output crosses to host: pack it (init/step state
            # stays device-resident across the pipelined dispatches)
            ev = veval
            fe_spec = None
            if _packed_enabled():
                fe_spec = _pack_spec_of(veval, args_e)
                ev = _pack_wrap(veval)
            fe, _ = aot_jit(
                ev,
                ("chunk_eval",) + base_key_parts + (_packed_enabled(),),
                args_e,
            )
        _compiled_cache[cache_tag] = (fi, fs, fe, fe_spec)
        compile_time += time.perf_counter() - t_build
        observe("tpuml_executor_compile_seconds", compile_time)
    fi, fs, fe, fe_spec = _compiled_cache[cache_tag]

    if warm_only:
        # prewarm: the init/step/eval executables are constructed (AOT
        # deserialize or trace) and the staged tensors uploaded; nothing
        # dispatches
        return compile_time, 0.0, 0, None, 0, 0

    for start in range(0, len(idxs), chunk):
        batch_idx = idxs[start : start + chunk]
        if hyper_names:
            hyper_arg = {
                k: jnp.asarray(
                    [hypers[gi][k] for gi in batch_idx]
                    + [hypers[batch_idx[-1]][k]] * (chunk - len(batch_idx)),
                    jnp.float32,
                )
                for k in hyper_names
            }
        else:
            hyper_arg = {"_pad": jnp.zeros((chunk,), jnp.float32)}

        t0 = time.perf_counter()
        group_outs = []
        group_curves = []
        for twg, ewg, size in split_groups:
            state = fi(X, y, twg, ewg, hyper_arg)
            mids = []
            for ci in range(n_chunks):
                state = fs(X, y, twg, ewg, hyper_arg, jnp.int32(ci), state)
                if (
                    curve_stride
                    and (ci + 1) % curve_stride == 0
                    and ci < n_chunks - 1
                ):
                    # trial telemetry plane: score-vs-chunk curve via
                    # strided extra eval dispatches on the existing fe
                    # executable (the accumulator protocol makes every
                    # prefix a valid model) — the tree kernels themselves
                    # are untouched. eval is O(n*k) against the chunk's
                    # O(n*k*trees) build, so the sampled extra evals stay
                    # inside the curve overhead gate.
                    mids.append(fe(X, y, twg, ewg, hyper_arg, state))
            group_outs.append((fe(X, y, twg, ewg, hyper_arg, state), size))
            group_curves.append(mids)
            dispatches += len(mids)
        if mesh is not None and len(split_groups) == 1:
            # collective argmax on the trial-sharded eval output (see
            # run_trials' generic path); split-group runs skip it — their
            # fold means span executables
            bi, bs = _chunk_best(mesh, trial_axis, chunk, sg, plan.n_folds)(
                group_outs[0][0]["score"], jnp.int32(len(batch_idx))
            )
            pos, score = int(bi), float(bs)
            n_fetches += 2
            if pos < len(batch_idx) and np.isfinite(score) and (
                device_best is None or score > device_best[1]
            ):
                device_best = (batch_idx[pos], score)
        for og, _size in group_outs:
            _prefetch_async(og)
        fetched = []
        for og, size in group_outs:
            host, nf, nb = _fetch_result(og, fe_spec)
            n_fetches += nf
            result_bytes += nb
            fetched.append((host, size))
        group_outs = fetched
        mids_host = []
        for mids in group_curves:
            row = []
            for og in mids:
                host, nf, nb = _fetch_result(og, fe_spec)
                n_fetches += nf
                result_bytes += nb
                row.append(host)
            mids_host.append(row)
        out = {
            k: np.concatenate([og[k][:, :size] for og, size in group_outs], axis=1)
            for k in group_outs[0][0]
        }
        if curve_stride:
            cs = [
                np.stack(
                    [m["score"][:, :size] for m in row]
                    + [host["score"][:, :size]],
                    axis=-1,
                )
                for (host, size), row in zip(group_outs, mids_host)
            ]
            out["curve_score"] = np.concatenate(cs, axis=1)
            shape2 = out["score"].shape[:2]
            out["curve_stride"] = np.full(shape2, float(curve_stride), np.float32)
            out["curve_steps"] = np.full(shape2, float(n_chunks), np.float32)
        run_time += time.perf_counter() - t0
        dispatches += (2 + n_chunks) * len(split_groups)

        for j, gi in enumerate(batch_idx):
            results[gi] = _postprocess(
                out, j, plan, kernel.task, static.get("_scoring")
            )

    return compile_time, run_time, dispatches, device_best, n_fetches, result_bytes


def _run_streamed(
    kernel, static, X_np, y_np, hypers, idxs, results,
    plan: SplitPlan, hyper_names, data, max_trials_per_batch: int,
):
    """Run one bucket through the kernel's out-of-core streaming driver.

    The full design matrix never stages: ``kernel.stream_form`` names the
    blockable host array, ``data/streaming.py`` tiles it into row blocks
    staged (double-buffered) through the multi-tenant cache, and
    ``kernel.stream_scores`` accumulates partial gradients/histograms
    across blocks — scores match the single-shot path (bitwise for
    integer tree stats, f32-summation-order for float gradients;
    tests/test_streaming.py pins both). The padded fold tensors are
    ordinary staged entries (three small keys, so the strict budget
    judges each alone); the block cache keys carry
    ``host_signature()`` + the kernel's trace_salt + the staged form.

    Returns ``(run_time, n_dispatches)``; the consumer's blocked
    block-wait time lands in ``_PHASE.stage`` like any other staging
    wall (the hidden share is devprof's ``stream`` phase).
    """
    from ..data import stage_cache as _sc
    from ..data.streaming import (
        RowBlockStreamer, array_block_source, plan_blocks,
    )

    blockable, form_salt = kernel.stream_form(X_np, static)
    n = int(blockable.shape[0])
    row_bytes = int(blockable.nbytes // max(n, 1))
    bplan = plan_blocks(n, row_bytes)
    # prepare_data kernels stream already-compact prepared forms (binned
    # int codes) — the f32-cast compressor would corrupt them; raw-matrix
    # kernels reuse the CS230_STAGE_DTYPE link compression per block
    stage_mode = (
        "f32" if hasattr(kernel, "prepare_data")
        else _resolve_stage_mode(_staging_dtype())
    )
    if stage_mode == "f32":
        def to_device(blk):
            return jnp.asarray(blk)
    else:
        def to_device(blk):
            return jax.tree_util.tree_map(
                jnp.asarray, _stage_compress(blk, stage_mode)
            )

    base_key = (
        _sc.dataset_fingerprint(data), _sc.host_signature(), "block",
        kernel.name, kernel.trace_salt(), tuple(form_salt), stage_mode,
        bplan.rows,
    )
    streamer = RowBlockStreamer(
        base_key, array_block_source(blockable, bplan), to_device, bplan,
        row_shape=tuple(blockable.shape[1:]),
    )

    n_pad = bplan.n_pad
    pad = n_pad - n

    def _pad_y():
        yv = np.asarray(y_np)
        return jnp.asarray(np.concatenate([yv, np.zeros((pad,), yv.dtype)]))

    def _pad_w(W):
        W = np.asarray(W, np.float32)
        return jnp.asarray(
            np.concatenate([W, np.zeros((W.shape[0], pad), np.float32)], 1)
        )

    if plan.signature is not None:
        y_d = _staged_device(
            data, ("stream_folds", plan.signature, n_pad, "y"), _pad_y
        )
        TW_d = _staged_device(
            data, ("stream_folds", plan.signature, n_pad, "tw"),
            lambda: _pad_w(plan.train_w),
        )
        EW_d = _staged_device(
            data, ("stream_folds", plan.signature, n_pad, "ew"),
            lambda: _pad_w(plan.eval_w),
        )
    else:
        y_d, TW_d, EW_d = _pad_y(), _pad_w(plan.train_w), _pad_w(plan.eval_w)

    run_time = 0.0
    dispatches = 0
    chunk = min(max_trials_per_batch, len(idxs))
    for start in range(0, len(idxs), chunk):
        batch_idx = idxs[start : start + chunk]
        if hyper_names:
            hyper_batch = {
                k: np.asarray(
                    [hypers[gi][k] for gi in batch_idx]
                    + [hypers[batch_idx[-1]][k]] * (chunk - len(batch_idx)),
                    np.float32,
                )
                for k in hyper_names
            }
        else:
            hyper_batch = {"_pad": np.zeros((chunk,), np.float32)}
        t0 = time.perf_counter()
        wait0 = streamer.stats["wait_s"]
        blocks0 = streamer.stats["blocks"]
        score = np.asarray(
            kernel.stream_scores(
                streamer, y_d, TW_d, EW_d, hyper_batch, static, n
            )
        )
        wall = time.perf_counter() - t0
        wait = streamer.stats["wait_s"] - wait0
        _PHASE.stage += wait
        run_time += max(wall - wait, 0.0)
        dispatches += streamer.stats["blocks"] - blocks0
        out = {"score": score}
        for j, gi in enumerate(batch_idx):
            results[gi] = _postprocess(out, j, plan, kernel.task, None)
    return run_time, dispatches


def _postprocess(out: Dict[str, np.ndarray], j: int, plan: SplitPlan, task: str,
                 scoring: Optional[str] = None) -> Dict[str, Any]:
    """Split 0 = holdout test metrics; splits 1..K = CV fold scores.
    mean_cv_score is the trial-ranking key (reference task_handler.py:254-263).
    With a custom ``scoring``, the holdout score is reported under the scorer
    name instead of the default accuracy/r2_score keys."""
    metrics: Dict[str, Any] = {}
    score = float(out["score"][j, 0])
    if scoring is not None:
        metrics[scoring] = score
        metrics["scoring"] = scoring
    elif task == "classification":
        metrics["accuracy"] = score
    elif task == "transform":
        metrics["score"] = score
    else:
        metrics["r2_score"] = score
    if task == "regression" and "mse" in out:
        metrics["mse"] = float(out["mse"][j, 0])
    if plan.n_folds >= 2:
        cv = out["score"][j, 1:]
        metrics["cv_scores"] = [float(v) for v in cv]
        metrics["mean_cv_score"] = float(np.mean(cv))
    else:
        metrics["mean_cv_score"] = score
    # a diverged trial (NaN/inf score from a pathological hyper combo) must
    # rank last, not poison the sort — Python sorted() with NaN is undefined
    if not np.isfinite(metrics["mean_cv_score"]):
        metrics["mean_cv_score"] = float("-inf")
        metrics["diverged"] = True
    channels = {
        k[len("curve_"):]: out[k][j]
        for k in out
        if k.startswith("curve_") and k not in ("curve_stride", "curve_steps")
    }
    if channels:
        from ..obs.curves import build_curve_record

        # stride/steps ride as per-(trial, split) leaves purely so they
        # share the score transport; they are bucket-constant
        stride = int(np.asarray(out["curve_stride"])[j].flat[0])
        steps = int(np.asarray(out["curve_steps"])[j].flat[0])
        metrics["curve"] = build_curve_record(
            channels, stride, steps, tail=np.asarray(out["score"][j]).reshape(-1)
        )
    return metrics
