from .mesh import trial_mesh, local_device_count
from .trial_map import TrialRunResult, run_trials, fit_single

__all__ = [
    "trial_mesh",
    "local_device_count",
    "TrialRunResult",
    "run_trials",
    "fit_single",
]
