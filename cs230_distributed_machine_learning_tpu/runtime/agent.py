"""Worker agent: a per-host executor process on the DCN control plane.

Capability parity with the reference worker's lifecycle
(``aws-prod/worker/worker.py:90-286``): on start, register with the
coordinator over REST (retry loop -> worker_id); heartbeat in a daemon
thread; consume the keyed task stream; run trial batches on the local
mesh; report results and metrics; unsubscribe on shutdown so queued tasks
requeue gracefully. Where the reference worker consumed a keyed Kafka
topic, the agent long-polls ``GET /next_tasks/<wid>`` — the coordinator
holds its keyed queue (runtime/cluster.py register_remote) — so no broker
exists anywhere.

Multi-host TPU deployment model (SURVEY.md §5.8): one agent per TPU-VM
host, each owning its host's chips as a local mesh. Datasets resolve
through a fetch-on-miss cache (data/datasets.FetchingDatasetCache): local
staged copies first, then ``GET /dataset/<id>`` from the coordinator over
DCN — the replacement for the reference's shared EFS volume
(docker-compose.yml:92-94), with arrays living in HBM across trials. For
pod-slice SPMD *within* a job — chips spread over hosts acting as ONE
mesh — launch with ``--distributed`` on every host of the slice: process 0
keeps the whole control plane and every process executes the sharded trial
batches in lockstep (:func:`run_distributed`;
parallel/distributed.py has the broadcast/fetch collectives).
"""

from __future__ import annotations

import collections
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

from ..obs import TRACE_HEADER, Tracer, counter_inc, obs_enabled, span, use_tracer
from ..utils.config import get_config
from ..utils.logging import get_logger
from ..utils.serialization import json_safe
from .executor import DeviceLostError, LocalExecutor

logger = get_logger("tpuml.agent")

#: agent exit status for an unrecoverable backend fault — supervisors treat
#: any non-zero exit as restartable, but this one is self-diagnosing in logs
DEVICE_LOST_EXIT_CODE = 13


def _make_executor(url: str, executor_id: str, mesh, max_batch) -> LocalExecutor:
    """Executor wired the agent way: fetch-on-miss dataset cache so
    coordinator-staged (kaggle/HF/preprocessed) datasets reach this host
    over DCN — the shared-volume replacement (VERDICT r1 #4)."""
    from ..data.datasets import FetchingDatasetCache

    executor = LocalExecutor(
        executor_id=executor_id, mesh=mesh, cache=FetchingDatasetCache(url)
    )
    if max_batch:
        executor.max_trials_per_batch = max_batch
    return executor


def _exit_for_restart(context: str) -> None:
    """Fail-fast containment for a poisoned device backend: exit non-zero
    so a supervisor (runtime/supervisor.py, compose/systemd restart policy)
    replaces the process with a fresh backend. Pulled tasks stay in the
    worker's coordinator-side queue and requeue via the dead-worker sweep."""
    logger.exception("%s; exiting for restart", context)
    import os

    os._exit(DEVICE_LOST_EXIT_CODE)


class WorkerAgent:
    def __init__(
        self,
        coordinator_url: str,
        *,
        mesh=None,
        mem_capacity_mb: Optional[float] = None,
        poll_timeout_s: float = 5.0,
        max_batch: Optional[int] = None,
        register_retries: int = 10,
        register_backoff_s: float = 5.0,
        result_buffer: Optional[int] = None,
    ):
        self.url = coordinator_url.rstrip("/")
        self.poll_timeout_s = poll_timeout_s
        self._stop = threading.Event()
        # ---- reconnecting edge (docs/ROBUSTNESS.md "Coordinator
        # recovery"): a coordinator outage must not lose finished work or
        # strand this agent. Results that fail to post are parked in a
        # bounded local buffer (CS230_AGENT_BUFFER, default 256 — oldest
        # dropped beyond it) and flushed after reconnection; a 404 from
        # /next_tasks (the restarted coordinator lost the worker registry)
        # triggers a re-register under a fresh worker id; transient poll
        # errors back off exponentially with jitter so a reviving
        # coordinator is not stampeded by its whole fleet at once.
        self._mem_capacity_mb = mem_capacity_mb
        self._register_retries = register_retries
        self._register_backoff_s = register_backoff_s
        if result_buffer is None:
            result_buffer = int(os.environ.get("CS230_AGENT_BUFFER", "256") or 256)
        self._buffer_max = max(int(result_buffer), 0)
        self._result_buffer: collections.deque = collections.deque()
        self._buffer_lock = threading.Lock()
        self._reconnect_lock = threading.Lock()
        self._poll_failures = 0
        #: cancel list of the most recent successful poll (see _poll_tasks)
        self._last_cancels: List[Dict[str, Any]] = []
        #: prewarm hints shipped in the /subscribe response (the runtime
        #: predictor's hot families bound to recent job shapes); warmed in
        #: the background by start() so the first placed trial finds a
        #: loaded executable + staged dataset instead of the inline cold
        #: path (runtime/prewarm.py; CS230_PREWARM=0 disables)
        self._prewarm_hints: List[Dict[str, Any]] = []
        self._prewarm = None
        #: this host's mesh slice: reported at /subscribe so the
        #: placement engine prices trial batches per slice, and re-used
        #: on every re-registration
        self._mesh = mesh
        self.worker_id = self._register(mem_capacity_mb, register_retries, register_backoff_s)
        self.executor = _make_executor(self.url, self.worker_id, mesh, max_batch)
        self._threads: List[threading.Thread] = []
        # spans recorded in THIS process (executor.batch + phases) go into a
        # private tracer and ship to the coordinator after each batch
        # (POST /trace_spans/<wid>), so one job's timeline stitches across
        # the process boundary. journal=False: the coordinator journals on
        # ingest — double-writing locally would split the record.
        self._tracer = Tracer(pending=True, journal=False)

    # ---------------- lifecycle ----------------

    def _mesh_report(self) -> Dict[str, Any]:
        """The /subscribe mesh-slice report: how many devices this
        worker's batches shard across. Only an EXPLICIT mesh widens the
        report — a meshless agent's executor dispatches single-device, so
        pricing it wider would mispack it. Shares mesh_info with the
        in-process registration path (cluster.add_executor) so local and
        remote workers report identically."""
        from ..parallel.mesh import mesh_info

        n_devices, mesh_shape = mesh_info(self._mesh)
        report: Dict[str, Any] = {"n_devices": n_devices}
        if mesh_shape is not None:
            report["mesh_shape"] = mesh_shape
        return report

    def _register(self, mem_capacity_mb, retries: int, backoff_s: float) -> str:
        import requests

        last_err: Optional[Exception] = None
        for attempt in range(retries):
            try:
                resp = requests.post(
                    f"{self.url}/subscribe",
                    json={"mem_capacity_mb": mem_capacity_mb,
                          **self._mesh_report()},
                    timeout=10,
                )
                resp.raise_for_status()
                body = resp.json()
                wid = body["worker_id"]
                self._prewarm_hints = body.get("prewarm") or []
                logger.info(
                    "Registered with coordinator as %s (%d prewarm hints)",
                    wid, len(self._prewarm_hints),
                )
                return wid
            except Exception as e:  # noqa: BLE001
                last_err = e
                logger.warning("Registration attempt %d failed: %s", attempt + 1, e)
                time.sleep(backoff_s)
        raise ConnectionError(f"Could not register with {self.url}: {last_err}")

    def start(self) -> None:
        from .prewarm import PrewarmWorker, enabled as prewarm_enabled

        if prewarm_enabled() and self._prewarm_hints:
            # background AOT prewarm: bounded, yields to real batches
            # (executor.busy), single-process agents only — SPMD slices
            # skip it (run_distributed never calls start(); a rank-local
            # warm dispatch would desync the lockstep collectives)
            self._prewarm = PrewarmWorker(self.executor, self._prewarm_hints)
            self._prewarm.start()
        for target in (self._run_loop, self._heartbeat_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, unsubscribe: bool = True) -> None:
        self._stop.set()
        if self._prewarm is not None:
            self._prewarm.stop()
        if self._result_buffer:
            # last-chance drain: finished work outlives the agent when the
            # coordinator is reachable (best-effort, first failure stops)
            self._flush_results()
        if unsubscribe:
            try:
                import requests

                requests.post(f"{self.url}/unsubscribe/{self.worker_id}", timeout=10)
            except Exception:  # noqa: BLE001
                logger.exception("Unsubscribe failed")
        for t in self._threads:
            t.join(timeout=self.poll_timeout_s + 2)

    def run_forever(self) -> None:
        self.start()
        try:
            while not self._stop.wait(1.0):
                pass
        except KeyboardInterrupt:
            self.stop()

    # ---------------- loops ----------------

    def _heartbeat_loop(self) -> None:
        import requests

        interval = get_config().scheduler.heartbeat_interval_s
        while not self._stop.wait(interval):
            try:
                requests.post(f"{self.url}/heartbeat/{self.worker_id}", timeout=10)
            except Exception:  # noqa: BLE001
                logger.warning("Heartbeat to %s failed", self.url)

    def _poll_tasks(self) -> List[Dict[str, Any]]:
        """One long-poll for this worker's keyed queue; [] on timeout or
        transient DCN error. A 404 means the coordinator restarted and
        lost the worker registry — re-register instead of polling a dead
        id forever; other errors back off with jittered exponential
        delays (docs/ROBUSTNESS.md "Reconnecting edges")."""
        import requests

        try:
            resp = requests.get(
                f"{self.url}/next_tasks/{self.worker_id}",
                params={
                    "max": self.executor.max_trials_per_batch,
                    "timeout": self.poll_timeout_s,
                },
                timeout=self.poll_timeout_s + 10,
            )
            if resp.status_code == 404:
                logger.warning(
                    "Coordinator no longer knows worker %s (restart?); "
                    "re-registering", self.worker_id,
                )
                self._resubscribe()
                return []
            resp.raise_for_status()
            body = resp.json()
            tasks = body.get("tasks", [])
            # cooperative-cancel list (docs/SEARCH.md): feed the executor
            # so pruned-mid-flight attempts stop at the next batch
            # boundary; kept for run_distributed to broadcast so every
            # SPMD rank filters the same set (lockstep contract)
            self._last_cancels = body.get("cancel") or []
            if self._last_cancels:
                self.executor.cancel(self._last_cancels)
        except Exception:  # noqa: BLE001
            self._poll_failures += 1
            backoff = min(
                10.0, 0.5 * 2 ** min(self._poll_failures - 1, 5)
            ) * (0.5 + random.random())
            logger.warning(
                "Task poll failed (%d consecutive); backing off %.2fs",
                self._poll_failures, backoff,
            )
            self._stop.wait(backoff)
            return []
        self._poll_failures = 0
        if self._result_buffer:
            # the control plane answered: drain results parked during the
            # outage before executing anything new
            self._flush_results()
        return tasks

    # ---------------- reconnecting edge ----------------

    def _resubscribe(self) -> bool:
        """Re-register with a restarted coordinator (fresh worker id),
        then flush the local result buffer under it. Best-effort: a
        coordinator that vanished again simply leaves the next poll to
        retry."""
        with self._reconnect_lock:
            old = self.worker_id
            try:
                wid = self._register(
                    self._mem_capacity_mb,
                    self._register_retries,
                    self._register_backoff_s,
                )
            except ConnectionError:
                logger.error(
                    "Re-registration with %s failed; will retry on the "
                    "next poll", self.url,
                )
                return False
            self.worker_id = wid
            self.executor.executor_id = wid
            self._poll_failures = 0
            counter_inc("tpuml_agent_reconnects_total")
            logger.info(
                "Re-registered after coordinator restart: %s -> %s", old, wid
            )
        self._flush_results()
        return True

    def _buffer_result(self, stid: str, payload: Dict[str, Any]) -> None:
        with self._buffer_lock:
            if self._buffer_max <= 0:
                counter_inc("tpuml_agent_results_dropped_total")
                return
            while len(self._result_buffer) >= self._buffer_max:
                dropped_stid, _ = self._result_buffer.popleft()
                counter_inc("tpuml_agent_results_dropped_total")
                logger.warning(
                    "Result buffer full (%d); dropping oldest result %s "
                    "(its subtask will be re-run by the coordinator's "
                    "recovery/lease machinery)",
                    self._buffer_max, dropped_stid,
                )
            self._result_buffer.append((stid, payload))
        counter_inc("tpuml_agent_results_buffered_total")
        logger.warning(
            "Result post failed for %s; buffered locally (%d pending)",
            stid, len(self._result_buffer),
        )

    def _flush_results(self) -> None:
        """Post buffered results in order; stop at the first failure (the
        coordinator went away again — keep the rest parked)."""
        import requests

        while True:
            with self._buffer_lock:
                if not self._result_buffer:
                    return
                stid, payload = self._result_buffer.popleft()
            try:
                resp = requests.post(
                    f"{self.url}/task_result/{self.worker_id}",
                    json=payload,
                    timeout=30,
                )
                if (
                    400 <= resp.status_code < 500
                    and resp.status_code != 404
                ):
                    # permanently rejected (bad payload, coordinator
                    # without a cluster): drop it rather than wedge the
                    # whole buffer behind one poison entry — the subtask
                    # re-runs via the recovery/lease machinery. 404 is
                    # NOT permanent: the worker id went stale again, and
                    # the next poll's re-register owns that.
                    counter_inc("tpuml_agent_results_dropped_total")
                    logger.error(
                        "Buffered result %s permanently rejected (%d); "
                        "dropping it", stid, resp.status_code,
                    )
                    continue
                resp.raise_for_status()
                logger.info("Flushed buffered result for %s", stid)
            except Exception:  # noqa: BLE001 — transient: keep the buffer
                with self._buffer_lock:
                    self._result_buffer.appendleft((stid, payload))
                logger.warning(
                    "Buffered-result flush failed at %s; %d still parked",
                    stid, len(self._result_buffer),
                )
                return

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            t_poll = time.time()
            tasks = self._poll_tasks()
            if not tasks:
                continue
            tid = next((t.get("trace_id") for t in tasks if t.get("trace_id")), None)
            if tid and obs_enabled():
                # back-dated span over the long-poll that delivered the batch
                with span("agent.poll", trace_id=tid, parent_id=None,
                          tracer=self._tracer, worker=self.worker_id,
                          n_tasks=len(tasks)) as sp:
                    sp.start = t_poll
            try:
                with use_tracer(self._tracer):
                    self.executor.run_subtasks(
                        tasks,
                        on_result=self._post_result,
                        on_metrics=self._post_metrics,
                    )
            except DeviceLostError:
                _exit_for_restart(
                    f"Agent {self.worker_id} lost its device backend"
                )
            finally:
                self._ship_spans()

    def _ship_spans(self) -> None:
        """Ship locally-recorded spans to the coordinator's tracer
        (POST /trace_spans/<wid>, X-Trace-Id on the request) — the
        return leg of the trace-header propagation contract. Best-effort:
        a lost batch of spans degrades the timeline, never the job."""
        spans = self._tracer.drain()
        if not spans:
            return
        import requests

        try:
            requests.post(
                f"{self.url}/trace_spans/{self.worker_id}",
                json={"spans": json_safe(spans)},
                headers={TRACE_HEADER: spans[0].get("trace_id", "")},
                timeout=10,
            )
        except Exception:  # noqa: BLE001
            logger.warning("Span shipping failed (%d spans dropped)", len(spans))

    def _post_result(self, stid: str, status: str, result: Optional[Dict[str, Any]]) -> None:
        import requests

        from ..obs import process_token

        # obs_pid rides the wire only (popped at ingest): the
        # coordinator's push_result counts subtask outcomes for REMOTE
        # processes and must skip an agent sharing its own process,
        # whose executor already counted into the shared registry
        payload = {**json_safe(result), "obs_pid": process_token()}
        try:
            resp = requests.post(
                f"{self.url}/task_result/{self.worker_id}",
                json=payload,
                timeout=30,
            )
            resp.raise_for_status()
        except Exception:  # noqa: BLE001
            # coordinator outage: park the finished work locally — it is
            # flushed after the next successful poll / re-registration
            # instead of being lost (at-least-once, deduped at ingest)
            self._buffer_result(stid, payload)

    def _post_metrics(self, msg: Dict[str, Any]) -> None:
        import requests

        try:
            requests.post(
                f"{self.url}/task_metrics/{self.worker_id}",
                json=json_safe(msg),
                timeout=30,
            )
        except Exception:  # noqa: BLE001
            logger.exception("Metrics post failed")


def _prefetch_agree(executor, tasks) -> List[str]:
    """Pre-collective dataset staging with cross-process agreement.

    The per-rank nondeterminism hazard in SPMD lockstep is the dataset
    fetch (DCN HTTP): if it failed on only SOME ranks mid-batch, those
    ranks would skip the batch's collectives while the others entered them
    — a slice-wide hang. So every rank prefetches each dataset BEFORE the
    sharded region, then all ranks allgather success flags and agree on
    the same bad-dataset set. Returns dataset_ids that failed anywhere;
    tasks on them must be failed host-side (no collectives) on every rank.
    """
    import numpy as np

    from jax.experimental import multihost_utils

    from ..models.registry import get_kernel

    wanted: Dict[str, str] = {}  # dataset_id -> model_type (first seen)
    for st in tasks:
        wanted.setdefault(st["dataset_id"], st["model_type"])
    # signature per dataset, not just a success bit: a rank whose DCN fetch
    # fell back to a stale local copy would report ok yet stage different-
    # shaped arrays — mismatched executables across the slice. (rows, cols)
    # agreement catches the version split; (0, 0) marks outright failure.
    sig = np.zeros((len(wanted), 2), np.int64)
    for i, (did, model_type) in enumerate(wanted.items()):
        try:
            data = executor.cache.get(did, get_kernel(model_type).task)
            sig[i] = data.X.shape[:2]
        except Exception:  # noqa: BLE001 — the zero signature carries it
            logger.exception("Prefetch failed for dataset %r", did)
    all_sig = np.asarray(multihost_utils.process_allgather(sig))
    if all_sig.ndim == 2:  # single process
        all_sig = all_sig[None, :, :]
    bad = []
    for i, did in enumerate(wanted):
        rank_sigs = all_sig[:, i, :]
        if (rank_sigs == 0).all(axis=1).any() or len(
            {tuple(s) for s in rank_sigs}
        ) > 1:
            bad.append(did)
    return bad


def _slice_watchdog(url: str, slice_id: str, rank: int, n_proc: int) -> None:
    """Per-rank SPMD slice liveness (daemon thread on EVERY rank).

    A SIGKILLed sibling leaves survivors blocked inside a collective —
    process 0's REST worker heartbeats are a separate daemon thread that
    KEEPS running, so the coordinator would never mark the slice dead and
    its pulled tasks would hang forever. Each rank therefore heartbeats
    ``POST /slice_heartbeat/<slice>/<rank>`` and checks the siblings' ages;
    a sibling stale past the scheduler's ``dead_after_s`` (or absent after
    a startup grace) kills THIS rank too (non-zero exit) — process 0's
    death stops the worker heartbeats, the coordinator's dead-worker sweep
    requeues the pulled tasks onto surviving workers, and the restart
    policy relaunches the whole slice (one jax.distributed runtime cannot
    be rejoined by a lone respawned rank; see run_distributed docstring).
    Reference analog: dead-worker requeue, scheduler_service.py:218-247 —
    extended to the fleet mode where the workers ARE one SPMD program."""
    import requests

    cfg = get_config().scheduler
    interval = cfg.heartbeat_interval_s
    dead_after = max(cfg.dead_after_s, 2 * interval)
    grace_until = time.time() + 6 * dead_after
    # a sibling ABSENT from the table (vs stale) must persist missing for
    # dead_after before it counts as dead: a coordinator restart wipes the
    # in-memory slice table, and killing every healthy rank of every slice
    # over a routine coordinator bounce would turn one restart into a
    # fleet-wide requeue storm
    missing_since: Dict[int, float] = {}
    while True:
        try:
            requests.post(
                f"{url}/slice_heartbeat/{slice_id}/{rank}", timeout=10
            )
            resp = requests.get(f"{url}/slice_status/{slice_id}", timeout=10)
            ages = {
                int(r): float(a)
                for r, a in resp.json().get("ranks", {}).items()
            }
        except Exception:  # noqa: BLE001 — coordinator unreachable: the
            # generic worker-heartbeat path owns that failure mode
            time.sleep(interval)
            continue
        now = time.time()
        for sib in range(n_proc):
            if sib == rank:
                continue
            age = ages.get(sib)
            if age is None:
                if now <= grace_until:
                    continue
                first = missing_since.setdefault(sib, now)
                if now - first <= dead_after:
                    continue
            else:
                missing_since.pop(sib, None)
                if age <= dead_after:
                    continue
            logger.error(
                "SPMD slice %s: rank %d lost sibling rank %d "
                "(age %s, threshold %.1fs); exiting for slice restart",
                slice_id, rank, sib, age, dead_after,
            )
            import os

            os._exit(DEVICE_LOST_EXIT_CODE)
        time.sleep(interval)


def run_distributed(
    url: str,
    *,
    mem_capacity_mb: Optional[float] = None,
    max_batch: Optional[int] = None,
    poll_timeout_s: float = 5.0,
) -> None:
    """SPMD agent fleet over one multi-process mesh (pod-slice mode).

    Call after :func:`parallel.distributed.init_distributed`. Process 0
    owns the whole DCN control plane — it registers ONE worker with the
    coordinator, heartbeats, long-polls tasks, and reports results — while
    every process (0 included) executes each trial batch over the global
    mesh built from ``jax.devices()``. Task batches reach the non-primary
    processes via a host-level broadcast, so all processes enter the same
    sharded executables in lockstep (the SPMD contract); results are
    assembled collectively inside the trial engine and only process 0
    posts them. This is the capability analog of the reference's 4-worker
    fleet (docker-compose.yml:133-199) rebuilt for hardware where the
    workers ARE one machine: a v5e-16+ slice whose chips span hosts.

    Shutdown/restart semantics: SIGINT/SIGTERM on process 0 broadcasts a
    stop message so every rank exits cleanly. A fatal backend fault on any
    rank exits THAT process non-zero; the peers' next collective then
    errors (dead peer) and they exit too — restart policy must relaunch
    the WHOLE slice (one ``jax.distributed`` runtime cannot be rejoined by
    a lone respawned rank). See deploy/tpu_vm_fleet.md.
    """
    import jax

    from ..parallel.distributed import broadcast_json, is_primary
    from ..parallel.mesh import trial_mesh

    mesh = trial_mesh()  # ALL devices: jax.devices() is global post-init
    n_proc = jax.process_count()
    logger.info(
        "Distributed agent: process %d/%d, %d global devices (%d local)",
        jax.process_index(), n_proc, len(jax.devices()),
        len(jax.local_devices()),
    )

    if n_proc > 1:
        # slice id agreed via one host-level broadcast, then every rank
        # watches its siblings through the coordinator (slice watchdog:
        # a dead rank must take the slice down so pulled tasks requeue)
        import uuid

        sid_msg = broadcast_json(
            {"slice_id": uuid.uuid4().hex[:12]} if is_primary() else None
        )
        threading.Thread(
            target=_slice_watchdog,
            args=(url.rstrip("/"), sid_msg["slice_id"],
                  jax.process_index(), n_proc),
            daemon=True,
        ).start()

    agent: Optional[WorkerAgent] = None
    if is_primary():
        try:
            agent = WorkerAgent(
                url,
                mesh=mesh,
                mem_capacity_mb=mem_capacity_mb,
                poll_timeout_s=poll_timeout_s,
                max_batch=max_batch,
            )
        except Exception:
            # non-primaries are already waiting at their first broadcast:
            # release them before propagating, or they hang forever
            logger.exception("Primary registration failed; stopping slice")
            broadcast_json({"tasks": [], "stop": True})
            raise
        # SIGINT/SIGTERM -> graceful slice stop: flag it here, and the loop
        # below broadcasts {"stop": true} at the next rendezvous so every
        # rank exits instead of blocking in a collective
        import signal

        def _on_signal(signum, frame):
            agent._stop.set()

        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                signal.signal(sig, _on_signal)
            except ValueError:  # non-main thread (tests): skip
                pass
        hb = threading.Thread(target=agent._heartbeat_loop, daemon=True)
        hb.start()
        executor = agent.executor
        post_result, post_metrics = agent._post_result, agent._post_metrics
    else:
        executor = _make_executor(
            url, f"spmd-rank{jax.process_index()}", mesh, max_batch
        )
        post_result = post_metrics = lambda *a, **k: None

    try:
        while True:
            if is_primary():
                stop = agent._stop.is_set()
                msg = {"tasks": [] if stop else agent._poll_tasks(),
                       "stop": stop,
                       # cancels broadcast with the tasks: every rank must
                       # filter the SAME set or the lockstep collectives
                       # desync (agent._poll_tasks already applied them to
                       # the primary's shared executor)
                       "cancel": agent._last_cancels}
            else:
                msg = None
            msg = broadcast_json(msg)  # lockstep rendezvous, every iteration
            if msg["stop"]:
                break
            if msg.get("cancel") and agent is None:
                executor.cancel(msg["cancel"])
            tasks = msg["tasks"]
            if not tasks:
                continue
            bad = _prefetch_agree(executor, tasks)
            if bad:
                # agreed-on unfetchable datasets: fail those tasks without
                # entering any collective (identical branch on every rank)
                failed = [t for t in tasks if t["dataset_id"] in bad]
                tasks = [t for t in tasks if t["dataset_id"] not in bad]
                for st in failed:
                    post_result(
                        st["subtask_id"],
                        "failed",
                        {
                            "subtask_id": st["subtask_id"],
                            "job_id": st.get("job_id"),
                            "model_type": st["model_type"],
                            "parameters": st["parameters"],
                            "status": "failed",
                            "error": f"dataset {st['dataset_id']!r} "
                                     "unavailable on the slice",
                        },
                    )
            if not tasks:
                continue
            try:
                if agent is not None:
                    # primary: route spans into the agent's tracer and ship
                    # them after the batch (non-primaries record nothing —
                    # their work is the same lockstep program)
                    with use_tracer(agent._tracer):
                        executor.run_subtasks(
                            tasks, on_result=post_result,
                            on_metrics=post_metrics,
                        )
                    agent._ship_spans()
                else:
                    executor.run_subtasks(
                        tasks, on_result=post_result, on_metrics=post_metrics
                    )
            except DeviceLostError:
                _exit_for_restart(
                    f"SPMD rank {jax.process_index()} lost its backend"
                )
    except KeyboardInterrupt:
        if agent is not None:
            agent._stop.set()
    finally:
        if agent is not None:
            agent.stop()


def main() -> None:
    """CLI: ``python -m cs230_distributed_machine_learning_tpu.runtime.agent
    --url http://coordinator:5001`` (one per TPU-VM host).

    Pod-slice SPMD (chips spanning hosts acting as one mesh): add
    ``--distributed`` on EVERY host of the slice. On TPU VMs the topology
    flags are optional (inferred from TPU metadata); on CPU test fleets
    pass ``--coordinator-address host:port --num-processes N
    --process-id i`` (and optionally ``--local-devices K`` for K virtual
    devices per process)."""
    import argparse

    parser = argparse.ArgumentParser(description="tpuml worker agent")
    parser.add_argument("--url", required=True, help="coordinator base URL")
    parser.add_argument("--mem-mb", type=float, default=None)
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument("--distributed", action="store_true",
                        help="join a jax.distributed multi-process mesh")
    parser.add_argument("--coordinator-address", default=None,
                        help="jax.distributed rendezvous host:port "
                             "(NOT the REST url; optional on TPU VMs)")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--local-devices", type=int, default=None,
                        help="virtual device count per process (CPU testing)")
    args = parser.parse_args()
    if args.distributed:
        from ..parallel.distributed import init_distributed

        init_distributed(
            args.coordinator_address,
            args.num_processes,
            args.process_id,
            local_device_count=args.local_devices,
        )
        run_distributed(
            args.url, mem_capacity_mb=args.mem_mb, max_batch=args.max_batch
        )
        return
    agent = WorkerAgent(args.url, mem_capacity_mb=args.mem_mb, max_batch=args.max_batch)
    agent.run_forever()


if __name__ == "__main__":
    main()
