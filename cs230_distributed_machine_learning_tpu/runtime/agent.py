"""Worker agent: a per-host executor process on the DCN control plane.

Capability parity with the reference worker's lifecycle
(``aws-prod/worker/worker.py:90-286``): on start, register with the
coordinator over REST (retry loop -> worker_id); heartbeat in a daemon
thread; consume the keyed task stream; run trial batches on the local
mesh; report results and metrics; unsubscribe on shutdown so queued tasks
requeue gracefully. Where the reference worker consumed a keyed Kafka
topic, the agent long-polls ``GET /next_tasks/<wid>`` — the coordinator
holds its keyed queue (runtime/cluster.py register_remote) — so no broker
exists anywhere.

Multi-host TPU deployment model (SURVEY.md §5.8): one agent per TPU-VM
host, each owning its host's chips as a local mesh. Datasets resolve
through a fetch-on-miss cache (data/datasets.FetchingDatasetCache): local
staged copies first, then ``GET /dataset/<id>`` from the coordinator over
DCN — the replacement for the reference's shared EFS volume
(docker-compose.yml:92-94), with arrays living in HBM across trials. For
pod-slice SPMD *within* a job, the agent can be launched under
``jax.distributed.initialize`` so its mesh spans hosts; the control plane
here is orthogonal to that data plane.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.config import get_config
from ..utils.logging import get_logger
from ..utils.serialization import json_safe
from .executor import DeviceLostError, LocalExecutor

logger = get_logger("tpuml.agent")

#: agent exit status for an unrecoverable backend fault — supervisors treat
#: any non-zero exit as restartable, but this one is self-diagnosing in logs
DEVICE_LOST_EXIT_CODE = 13


class WorkerAgent:
    def __init__(
        self,
        coordinator_url: str,
        *,
        mesh=None,
        mem_capacity_mb: Optional[float] = None,
        poll_timeout_s: float = 5.0,
        max_batch: Optional[int] = None,
        register_retries: int = 10,
        register_backoff_s: float = 5.0,
    ):
        from ..data.datasets import FetchingDatasetCache

        self.url = coordinator_url.rstrip("/")
        self.poll_timeout_s = poll_timeout_s
        self._stop = threading.Event()
        self.worker_id = self._register(mem_capacity_mb, register_retries, register_backoff_s)
        # fetch-on-miss dataset cache: coordinator-staged (kaggle/HF/
        # preprocessed) datasets reach this host over DCN — the shared-volume
        # replacement (VERDICT r1 #4)
        self.executor = LocalExecutor(
            executor_id=self.worker_id,
            mesh=mesh,
            cache=FetchingDatasetCache(self.url),
        )
        if max_batch:
            self.executor.max_trials_per_batch = max_batch
        self._threads: List[threading.Thread] = []

    # ---------------- lifecycle ----------------

    def _register(self, mem_capacity_mb, retries: int, backoff_s: float) -> str:
        import requests

        last_err: Optional[Exception] = None
        for attempt in range(retries):
            try:
                resp = requests.post(
                    f"{self.url}/subscribe",
                    json={"mem_capacity_mb": mem_capacity_mb},
                    timeout=10,
                )
                resp.raise_for_status()
                wid = resp.json()["worker_id"]
                logger.info("Registered with coordinator as %s", wid)
                return wid
            except Exception as e:  # noqa: BLE001
                last_err = e
                logger.warning("Registration attempt %d failed: %s", attempt + 1, e)
                time.sleep(backoff_s)
        raise ConnectionError(f"Could not register with {self.url}: {last_err}")

    def start(self) -> None:
        for target in (self._run_loop, self._heartbeat_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, unsubscribe: bool = True) -> None:
        self._stop.set()
        if unsubscribe:
            try:
                import requests

                requests.post(f"{self.url}/unsubscribe/{self.worker_id}", timeout=10)
            except Exception:  # noqa: BLE001
                logger.exception("Unsubscribe failed")
        for t in self._threads:
            t.join(timeout=self.poll_timeout_s + 2)

    def run_forever(self) -> None:
        self.start()
        try:
            while not self._stop.wait(1.0):
                pass
        except KeyboardInterrupt:
            self.stop()

    # ---------------- loops ----------------

    def _heartbeat_loop(self) -> None:
        import requests

        interval = get_config().scheduler.heartbeat_interval_s
        while not self._stop.wait(interval):
            try:
                requests.post(f"{self.url}/heartbeat/{self.worker_id}", timeout=10)
            except Exception:  # noqa: BLE001
                logger.warning("Heartbeat to %s failed", self.url)

    def _run_loop(self) -> None:
        import requests

        while not self._stop.is_set():
            try:
                resp = requests.get(
                    f"{self.url}/next_tasks/{self.worker_id}",
                    params={
                        "max": self.executor.max_trials_per_batch,
                        "timeout": self.poll_timeout_s,
                    },
                    timeout=self.poll_timeout_s + 10,
                )
                resp.raise_for_status()
                tasks: List[Dict[str, Any]] = resp.json().get("tasks", [])
            except Exception:  # noqa: BLE001
                logger.exception("Task poll failed; backing off")
                time.sleep(1.0)
                continue
            if not tasks:
                continue
            try:
                self.executor.run_subtasks(
                    tasks,
                    on_result=self._post_result,
                    on_metrics=self._post_metrics,
                )
            except DeviceLostError:
                # fail-fast containment: this process's backend is poisoned —
                # exit non-zero so a supervisor (runtime/supervisor.py, compose
                # restart policy) replaces the process with a fresh backend.
                # Pulled tasks stay in this worker's coordinator-side queue and
                # requeue via the dead-worker sweep.
                logger.exception(
                    "Agent %s lost its device backend; exiting for restart",
                    self.worker_id,
                )
                import os

                os._exit(DEVICE_LOST_EXIT_CODE)

    def _post_result(self, stid: str, status: str, result: Optional[Dict[str, Any]]) -> None:
        import requests

        try:
            requests.post(
                f"{self.url}/task_result/{self.worker_id}",
                json=json_safe(result),
                timeout=30,
            )
        except Exception:  # noqa: BLE001
            logger.exception("Result post failed for %s", stid)

    def _post_metrics(self, msg: Dict[str, Any]) -> None:
        import requests

        try:
            requests.post(
                f"{self.url}/task_metrics/{self.worker_id}",
                json=json_safe(msg),
                timeout=30,
            )
        except Exception:  # noqa: BLE001
            logger.exception("Metrics post failed")


def main() -> None:
    """CLI: ``python -m cs230_distributed_machine_learning_tpu.runtime.agent
    --url http://coordinator:5001`` (one per TPU-VM host)."""
    import argparse

    parser = argparse.ArgumentParser(description="tpuml worker agent")
    parser.add_argument("--url", required=True, help="coordinator base URL")
    parser.add_argument("--mem-mb", type=float, default=None)
    parser.add_argument("--max-batch", type=int, default=None)
    args = parser.parse_args()
    agent = WorkerAgent(args.url, mem_capacity_mb=args.mem_mb, max_batch=args.max_batch)
    agent.run_forever()


if __name__ == "__main__":
    main()
