"""Background AOT prewarm: warm hot executables before the first trial.

The r5 cold-start breakdown charges 2.2 s of every fresh worker's first
trial to AOT executable loading and 3.4 s to the staging upload — pure
data-plane latency paid INLINE, while the worker sat idle between
register and first placement. This module moves that work into the idle
window: when a worker registers, the coordinator ships prewarm *hints*
(the runtime predictor's hot model families, each bound to the dataset /
parameter shape of a recent job — ``Coordinator.prewarm_hints``), and the
agent runs a :class:`PrewarmWorker` thread that warms one hint at a time
via ``LocalExecutor.prewarm_hint``:

- ``construct`` mode (default): build every bucket executable (AOT blob
  deserialize or trace) and upload the staged tensors — the two measured
  cold costs — without dispatching anything
  (``trial_map.run_trials(warm_only=True)``).
- ``execute`` mode (``CS230_PREWARM=execute``): additionally dispatch the
  warmed bucket once with the hinted parameters and discard the result,
  so the first real trial also skips the first-dispatch XLA compile.

The worker **yields to real work**: before each hint it waits while the
executor has live batches in flight, and it never warms the same
(family, dataset, geometry) twice. ``CS230_PREWARM=0`` disables the
whole path (parity valve: registration and the first trial behave
exactly as before this layer existed).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional

from ..obs import counter_inc, record_event
from ..utils import aot_cache
from ..utils.logging import get_logger

logger = get_logger("tpuml.prewarm")


def prewarm_mode() -> str:
    """``off`` (CS230_PREWARM=0), ``construct`` (default), or
    ``execute``."""
    raw = os.environ.get("CS230_PREWARM", "1").strip().lower()
    if raw in ("0", "off", "false"):
        return "off"
    if raw == "execute":
        return "execute"
    return "construct"


def enabled() -> bool:
    return prewarm_mode() != "off"


def max_hints() -> int:
    """Hints warmed per registration (``CS230_PREWARM_MAX_HINTS``,
    default 3) — bounds background device time on a busy fleet."""
    try:
        return max(int(os.environ.get("CS230_PREWARM_MAX_HINTS", 3)), 0)
    except ValueError:
        return 3


class PrewarmWorker:
    """Bounded background warmer over a list of coordinator hints.

    ``is_busy`` is polled before each hint; while it returns True the
    worker sleeps (``yield_poll_s``) instead of competing with live
    batches for the device. Defaults to the executor's in-flight batch
    flag (``LocalExecutor.busy``)."""

    def __init__(
        self,
        executor,
        hints: List[Dict[str, Any]],
        *,
        is_busy: Optional[Callable[[], bool]] = None,
        mode: Optional[str] = None,
        yield_poll_s: float = 0.05,
        limit: Optional[int] = None,
    ):
        self.executor = executor
        self.hints = list(hints)[: (limit if limit is not None else max_hints())]
        self.mode = mode or prewarm_mode()
        self.yield_poll_s = yield_poll_s
        self._is_busy = is_busy or (
            lambda: bool(getattr(executor, "busy", False))
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: (family, dataset, geometry) keys already warmed — a family is
        #: never compiled twice by this worker (pinned in tests)
        self._warmed: set = set()
        #: per-hint warm summaries, in completion order
        self.results: List[Dict[str, Any]] = []
        self.done = threading.Event()

    @staticmethod
    def _hint_key(hint: Dict[str, Any]) -> tuple:
        return (
            hint.get("model_type"),
            hint.get("dataset_id"),
            int(hint.get("n_trials") or 1),
            repr(sorted((hint.get("parameters") or {}).items())),
            repr(sorted(
                (k, str(v)) for k, v in (hint.get("train_params") or {}).items()
            )),
        )

    def start(self) -> None:
        if self._thread is not None or self.mode == "off" or not self.hints:
            self.done.set()
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def join(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def _run(self) -> None:
        try:
            inventory = aot_cache.generation_inventory()
            if inventory["n_blobs"]:
                logger.info(
                    "Prewarm: %d AOT blobs (%.1f MB) on disk for this "
                    "generation",
                    inventory["n_blobs"], inventory["bytes"] / 1e6,
                )
            for hint in self.hints:
                if self._stop.is_set():
                    break
                # yield to real placements: a live batch always wins the
                # device; prewarm resumes when the executor idles
                while self._is_busy() and not self._stop.is_set():
                    self._stop.wait(self.yield_poll_s)
                if self._stop.is_set():
                    break
                key = self._hint_key(hint)
                if key in self._warmed:
                    counter_inc(
                        "tpuml_prewarm_skipped_total", reason="duplicate"
                    )
                    continue
                self._warmed.add(key)
                family = str(hint.get("model_type"))
                try:
                    summary = self.executor.prewarm_hint(hint, mode=self.mode)
                except Exception:  # noqa: BLE001 — a bad hint must never
                    # hurt the worker it was meant to help
                    logger.exception("Prewarm failed for family %s", family)
                    counter_inc("tpuml_prewarm_skipped_total", reason="error")
                    continue
                counter_inc("tpuml_prewarm_warmed_total", model=family)
                record_event("prewarm.warm", **summary)
                logger.info(
                    "Prewarmed %s on %s (%s: compile %.2fs, stage %.2fs)",
                    family, hint.get("dataset_id"), summary.get("mode"),
                    summary.get("compile_s") or 0.0,
                    summary.get("stage_s") or 0.0,
                )
                self.results.append(summary)
        finally:
            self.done.set()
