"""Stateless API front end for the sharded control plane.

The thin half of the front/core split (docs/ARCHITECTURE.md "Sharded
control plane"): this process holds NO job state — every request is
routed to a coordinator shard using only the ids already in the URL
(runtime/sharding.py):

- session routes (``/train/<sid>``, ``/train_status/<sid>``,
  ``/check_status/<sid>/<jid>``, ...) route by ``shard_of(session_id)``;
  ``/create_session`` MINTS the session id here, so the hash and the
  owning shard agree by construction;
- job-only routes (``/trace/<jid>``, ``/cost/<jid>``, ``/explain/...``)
  route by the ``s<k>-`` stamp the owning shard minted into the job id
  (unstamped ids fall back to a scatter probe);
- worker-plane routes (``/subscribe``, ``/next_tasks/<wid>``,
  ``/task_result/<wid>``, ...) route by the same stamp in the worker id;
  ``/subscribe`` assigns the worker to a shard (body ``{"shard": k}``
  pins it, else round-robin) — the shard's engine mints the stamped id;
- fleet-wide concerns aggregate over every shard: ``/healthz`` (worst
  status wins), ``/readyz`` (ready only when EVERY shard is), ``/jobs``
  / ``/workers`` / ``/queues`` (merged), ``/metrics/prom`` (one
  exposition with a ``shard`` label injected per series),
  ``/metrics/history`` (scatter-merge by series, shard-labeled),
  ``/events`` (seq-ordered merge paged by PER-SHARD cursors),
  ``/alerts`` (union of every shard's rule states, shard-stamped), and
  ``/autoscale`` (fleet-summed capacity signals with per-shard bodies)
  — the fleet health plane, docs/OBSERVABILITY.md.

Because no state lives here, any number of front ends can run against
the same shard fleet, restart freely, and serve any client: a job
submitted through one front end is visible and streamable through every
other (pinned in tests/test_sharding.py). A shard that is down answers
as 503 + Retry-After — the same overload contract clients already retry
through (docs/ROBUSTNESS.md) — so a killed shard's takeover process
slots back in with no front-end restart.

Run: ``tpuml-frontend --port 5000 --shards http://h1:5001,http://h2:5001``
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..obs import counter_inc, render_prometheus
from ..obs.tracing import (
    PARENT_HEADER,
    TRACE_HEADER,
    TRACER,
    _enabled as _obs_enabled,
    new_span_id,
    new_trace_id,
)
from ..utils.logging import get_logger
from ..utils.serialization import json_safe
from .sharding import ForwardingCache, id_shard, shard_of

logger = get_logger("tpuml.frontend")

#: URL prefixes routed by the session id in the first path argument
_SESSION_ROUTES = {
    "download_data", "check_data", "preprocess", "train", "train_status",
    "check_status", "download_model",
}
#: routed by the worker-id stamp in the first path argument
_WORKER_ROUTES = {
    "unsubscribe", "heartbeat", "next_tasks", "task_result", "task_metrics",
    "trace_spans",
}
#: routed by the job-id stamp (scatter probe for unstamped ids); "trace"
#: also covers /trace/<jid>/export — the stamp is still parts[1]
_JOB_ROUTES = {"trace", "cost", "explain", "critical_path", "curves"}
#: response headers forwarded from the shard to the client
_FWD_HEADERS = (
    "Content-Type", "Retry-After", "X-Trace-Id", "X-Dataset-Kind",
    "Content-Disposition",
)


#: request bodies at or above this size are streamed to the owning shard
#: chunk-wise instead of being buffered whole in the front end (zero-copy
#: proxying for large dataset uploads; small bodies keep the simple path)
_STREAM_BODY_MIN = 256 * 1024


class _BodyStream:
    """File-like view over the WSGI input stream with a declared length.

    ``requests``/urllib3 stream a ``read()``-able body upstream in fixed
    chunks, and the ``len`` attribute makes them send a Content-Length
    header instead of chunked transfer-encoding (which the shards' dev
    server would reject) — so a large ``/train`` or ``/download_data``
    body crosses the front end hop-by-hop in 8 KB chunks, never fully
    resident, instead of being re-read into memory per hop."""

    def __init__(self, stream, length: int):
        self._stream = stream
        self.len = int(length)

    def read(self, n: int = -1) -> bytes:
        return self._stream.read(n)


def _inject_shard_label(body: str, shard: int) -> List[str]:
    """Rewrite one shard's Prometheus exposition so every series carries
    a ``shard=<k>`` label — the merge that keeps identical series from N
    shards distinct in one scrape. Comment/metadata lines pass through
    (the caller dedups them)."""
    out = []
    for line in body.splitlines():
        if not line.strip() or line.startswith("#"):
            out.append(line)
            continue
        name, _, rest = line.partition(" ")
        if "{" in name:
            fam, _, labels = name.partition("{")
            out.append(f'{fam}{{shard="{shard}",{labels} {rest}')
        else:
            out.append(f'{name}{{shard="{shard}"}} {rest}')
    return out


def create_frontend_app(shard_urls: List[str]):
    import requests
    from werkzeug.wrappers import Request, Response

    urls = [u.rstrip("/") for u in shard_urls]
    if not urls:
        raise ValueError("frontend needs at least one shard URL")
    n_shards = len(urls)

    # pooled connections sized for hundreds of concurrent client threads
    # fanning into a handful of shards
    session = requests.Session()
    adapter = requests.adapters.HTTPAdapter(
        pool_connections=max(2 * n_shards, 4), pool_maxsize=256
    )
    session.mount("http://", adapter)
    session.mount("https://", adapter)

    #: round-robin cursor for /subscribe shard assignment
    _rr = itertools.count()

    #: migrated-job redirect cache (docs/ROBUSTNESS.md "Shard
    #: rebalancing"): a donor's 409 forwarding stamp is remembered here
    #: so subsequent requests for the job proxy straight to the new
    #: owner instead of paying the probe-then-redirect round trip
    fwd_cache = ForwardingCache()

    # one shared pool for every fan-out route (/healthz, /jobs,
    # /metrics/prom, ...): these are POLLED endpoints, and spawning +
    # joining n_shards fresh threads per hit would put constant thread
    # churn on exactly the liveness paths
    from concurrent.futures import ThreadPoolExecutor

    fan_pool = ThreadPoolExecutor(
        max_workers=max(2 * n_shards, 4),
        thread_name_prefix="tpuml-fe-fan",
    )

    def _json(data, status=200, headers=None):
        return Response(
            json.dumps(json_safe(data)), status=status,
            mimetype="application/json", headers=headers,
        )

    def _shard_down(k: int) -> Response:
        # same contract as an overloaded/recovering coordinator: clients
        # (MLTaskManager, agents) already retry 503 + Retry-After, so a
        # dead shard's takeover window looks like a brief overload
        return _json(
            {"status": "error", "reason": "shard_unavailable", "shard": k,
             "retry_after_s": 2.0},
            status=503, headers={"Retry-After": "2"},
        )

    def _upstream(request, k: int, path: str, *, body: Optional[bytes] = None,
                  stream: bool = False, timeout: Tuple[float, float] = (10, 910)):
        headers = {}
        for h in ("Content-Type", "X-Trace-Id"):
            v = request.headers.get(h)
            if v:
                headers[h] = v
        # the fleet's first hop is traced (frontend.proxy, see app()):
        # forward the — possibly front-end-minted — trace id plus the
        # proxy span's id, so the shard's http.<endpoint> span nests
        # under it instead of surfacing as a second trace root
        ctx = getattr(request, "tpuml_trace", None)
        if ctx is not None:
            headers[TRACE_HEADER] = ctx[0]
            headers[PARENT_HEADER] = ctx[1]
        request.tpuml_shard = k
        if body is not None:
            data = body
        else:
            cl = request.content_length
            if (
                cl and cl >= _STREAM_BODY_MIN
                and request.method in ("POST", "PUT")
            ):
                # zero-copy: relay the body chunk-wise from the client
                # socket to the shard socket (see _BodyStream)
                data = _BodyStream(request.stream, cl)
            else:
                data = request.get_data()
        return session.request(
            request.method,
            f"{urls[k]}{path}",
            params=request.query_string.decode() or None,
            data=data,
            headers=headers,
            stream=stream,
            timeout=timeout,
        )

    def _relay(upstream, stream: bool = False) -> Response:
        headers = {
            h: upstream.headers[h] for h in _FWD_HEADERS
            if h in upstream.headers
        }
        if not stream:
            body = upstream.content
            upstream.close()
            return Response(
                body, status=upstream.status_code, headers=headers
            )

        def _body():
            # unbuffered relay: read1 hands over whatever bytes the shard
            # already flushed (an SSE event) instead of blocking until a
            # full buffer accumulates — the same time-to-first-event
            # hazard the coordinator's padding prologue defeats must not
            # be reintroduced by this hop. read(1) is the (slow, correct)
            # fallback for urllib3 builds without read1.
            raw = upstream.raw
            read1 = getattr(raw, "read1", None)
            try:
                if read1 is not None:
                    while True:
                        chunk = read1(65536)
                        if not chunk:
                            return
                        yield chunk
                else:
                    while True:
                        b = raw.read(1)
                        if not b:
                            return
                        yield b
            finally:
                upstream.close()

        return Response(
            _body(), status=upstream.status_code, headers=headers,
            direct_passthrough=True,
        )

    def _proxy(request, k: int, path: str, *, body: Optional[bytes] = None,
               stream: bool = False, job_id: Optional[str] = None) -> Response:
        # migrated-job fast path: a cached forwarding stamp overrides the
        # hash/stamp route — the donor would only answer 409 moved anyway
        if job_id is not None:
            cached = fwd_cache.get(job_id)
            if cached is not None and 0 <= cached < n_shards:
                k = cached
        try:
            upstream = _upstream(request, k, path, body=body, stream=stream)
        except requests.RequestException:
            return _shard_down(k)
        if job_id is not None and upstream.status_code == 409:
            # the forwarding stamp (server.py _moved): learn the move,
            # then re-proxy ONCE to the new owner. Bodies on these routes
            # are small (werkzeug caches get_data), so the resend is safe.
            try:
                moved = upstream.json()
            except ValueError:
                moved = None
            if isinstance(moved, dict) and moved.get("status") == "moved":
                upstream.close()
                try:
                    dest = int(moved.get("migrated_to"))
                except (TypeError, ValueError):
                    dest = -1
                if 0 <= dest < n_shards and dest != k:
                    fwd_cache.put(str(moved.get("job_id") or job_id), dest)
                    counter_inc("tpuml_frontend_forwarded_total")
                    try:
                        upstream = _upstream(
                            request, dest, path, body=body, stream=stream
                        )
                    except requests.RequestException:
                        return _shard_down(dest)
                    return _relay(upstream, stream=stream)
                return _json(moved, status=409)
        return _relay(upstream, stream=stream)

    def _fan_json(request, path: str) -> Dict[int, Any]:
        """GET ``path`` on every shard CONCURRENTLY; {shard: parsed body}
        for the ones that answered (HTTP errors/outages are simply
        absent). Concurrency matters: a sequential loop would let one
        hung shard stall every aggregate route (/healthz, /jobs,
        /metrics/prom, the /readyz fleet gate) by its full timeout."""
        qs = request.query_string.decode() or None

        def _one(k: int):
            try:
                r = session.get(
                    f"{urls[k]}{path}", params=qs, timeout=10
                )
                return k, (r.json() if r.ok else None)
            except requests.RequestException:
                return k, None

        results = list(fan_pool.map(_one, range(n_shards)))
        return {k: body for k, body in results if body is not None}

    def _scatter_first(request, path: str, stream: bool = False) -> Response:
        """Try every shard in order; first non-404 answer wins (job-stamp
        fallback for unstamped ids, and /dataset, which any shard sharing
        the storage root can serve)."""
        last: Optional[Response] = None
        for k in range(n_shards):
            try:
                upstream = _upstream(request, k, path, stream=stream)
            except requests.RequestException:
                last = _shard_down(k)
                continue
            if upstream.status_code == 404:
                upstream.close()
                continue
            return _relay(upstream, stream=stream)
        return last if last is not None else _json(
            {"status": "error", "message": "not found on any shard"},
            status=404,
        )

    # ---------------- fleet-wide aggregates ----------------

    def _home(request):
        return _json({
            "service": "tpuml-frontend",
            "n_shards": n_shards,
            "shards": urls,
            "note": "stateless front end: session routes hash on "
                    "session_id, job/worker routes follow the s<k>- id "
                    "stamp; /healthz, /jobs, /workers, /queues and "
                    "/metrics/prom aggregate over every shard",
        })

    def _health(request):
        shards = _fan_json(request, "/health")
        degraded = [
            k for k in range(n_shards)
            if shards.get(k, {}).get("status") != "ok"
        ]
        return _json({
            "status": "ok" if not degraded else "degraded",
            "n_shards": n_shards,
            "shards_unhealthy": degraded,
        })

    def _readyz(request):
        shards = _fan_json(request, "/readyz")
        ready = [k for k in shards if shards[k].get("status") == "ready"]
        if len(ready) == n_shards:
            return _json({"status": "ready", "n_shards": n_shards})
        return _json(
            {"status": "recovering", "n_shards": n_shards,
             "shards_ready": sorted(ready)},
            status=503, headers={"Retry-After": "2"},
        )

    def _healthz(request):
        shards = _fan_json(request, "/healthz")
        status = "ok"
        if len(shards) < n_shards or any(
            s.get("status") != "ok" for s in shards.values()
        ):
            status = "degraded"
        return _json({
            "status": status,
            "n_shards": n_shards,
            "shards_down": [k for k in range(n_shards) if k not in shards],
            "n_workers": sum(
                int(s.get("n_workers") or 0) for s in shards.values()
            ),
            "shards": shards,
        })

    def _jobs(request):
        merged: List[Dict[str, Any]] = []
        for body in _fan_json(request, "/jobs").values():
            if isinstance(body, list):
                merged.extend(body)
        merged.sort(key=lambda j: j.get("created_at") or 0, reverse=True)
        return _json(merged)

    def _merge_dicts(request, path: str):
        merged: Dict[str, Any] = {}
        for body in _fan_json(request, path).values():
            if isinstance(body, dict):
                merged.update(body)  # worker ids are shard-stamped: unique
        return _json(merged)

    def _metrics_prom(request):
        def _scrape(k: int):
            try:
                r = session.get(f"{urls[k]}/metrics/prom", timeout=10)
                r.raise_for_status()
                return k, r.text
            except requests.RequestException:
                return k, None

        bodies = list(fan_pool.map(_scrape, range(n_shards)))
        # the front end's OWN registry (tpuml_frontend_forwarded_total,
        # ...) lives in this process, invisible to every shard scrape —
        # appended under shard="frontend" so the fleet exposition is
        # still one scrape
        bodies.append(("frontend", render_prometheus()))
        lines: List[str] = []
        seen_meta = set()
        for k, text in bodies:
            if text is None:
                continue
            for line in _inject_shard_label(text, k):
                if line.startswith("#"):
                    if line in seen_meta:
                        continue
                    seen_meta.add(line)
                lines.append(line)
        return Response(
            "\n".join(lines) + "\n",
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )

    def _dashboard(request):
        from .server import _DASHBOARD_HTML

        # same self-contained page: every endpoint it polls exists here
        # (aggregated), and job-stamped /trace//cost route to the owner
        return Response(_DASHBOARD_HTML, mimetype="text/html")

    def _scatter_dict(request, path: str):
        return _json({"shards": _fan_json(request, path)})

    # dashboard-compatible aggregates: the /dashboard JS polls these
    # expecting the COORDINATOR's response shapes, so the front end must
    # merge into the same shapes (not the raw {"shards": ...} scatter)

    def _events(request):
        """Fleet event feed: seq-ordered merge with PER-SHARD cursors.

        Per-shard seqs collide (every recorder counts from 1), so one
        fleet-wide ``last_seq`` cannot page this feed. Instead ``?since=``
        accepts either a plain int (applied to every shard — the
        single-coordinator contract, so direct-mode pollers keep working)
        or the JSON cursor map a previous response returned
        (``{"0": 41, "1": 17}``); the response carries ``cursors`` (the
        map) and ``cursor`` (its compact JSON encoding, ready to pass
        back url-encoded). Events merge sorted by (seq, shard) — a
        deterministic interleave in which each shard's events stay in
        its own seq order — truncated to ``?limit=`` from the OLDEST end,
        so repeated cursor polls walk forward without ever duplicating
        or skipping a (shard, seq) pair across page boundaries (pinned
        in tests/test_frontend_aggregation.py)."""
        def _int(v, default):
            try:
                return int(v)
            except (TypeError, ValueError):
                return default

        limit = max(_int(request.args.get("limit"), 1000), 1)
        cursors = {k: 0 for k in range(n_shards)}
        since_raw = request.args.get("since") or ""
        if since_raw:
            parsed = None
            try:
                parsed = json.loads(since_raw)
            except ValueError:
                pass
            if isinstance(parsed, dict):
                for k, v in parsed.items():
                    kk = _int(k, -1)
                    if 0 <= kk < n_shards:
                        cursors[kk] = _int(v, 0)
            else:
                base = _int(since_raw, 0)
                cursors = {k: base for k in range(n_shards)}

        def _one(k: int):
            try:
                r = session.get(
                    f"{urls[k]}/events",
                    params={"since": cursors[k], "limit": limit},
                    timeout=10,
                )
                return k, (r.json() if r.ok else None)
            except requests.RequestException:
                return k, None

        merged: List[Dict[str, Any]] = []
        for k, body in fan_pool.map(_one, range(n_shards)):
            for e in (body or {}).get("events") or []:
                e["shard"] = k
                merged.append(e)
        merged.sort(
            key=lambda e: (int(e.get("seq") or 0), int(e.get("shard") or 0))
        )
        merged = merged[:limit]
        # advance each shard's cursor to its newest RETURNED seq; the
        # sort/truncate keeps a per-shard seq prefix, so max == last
        out_cursors = dict(cursors)
        for e in merged:
            k = e["shard"]
            out_cursors[k] = max(out_cursors[k], int(e.get("seq") or 0))
        cursor_map = {str(k): v for k, v in sorted(out_cursors.items())}
        return _json({
            "events": merged,
            "n_events": len(merged),
            "cursors": cursor_map,
            "cursor": json.dumps(cursor_map, separators=(",", ":")),
            # legacy field: per-shard seqs collide, use `cursor` to page
            "last_seq": 0,
        })

    def _alerts(request):
        """Fleet alert view: the union of every shard's rule states,
        each entry stamped with its shard (the same rule can fire on one
        shard and be quiet on another — attribution is the point)."""
        shards = _fan_json(request, request.path)
        merged: List[Dict[str, Any]] = []
        for k in sorted(shards):
            for a in (shards[k] or {}).get("alerts") or []:
                a = dict(a)
                a["shard"] = k
                merged.append(a)
        merged.sort(key=lambda a: (a.get("rule") or "", a.get("shard") or 0))
        firing = [
            {"rule": a["rule"], "shard": a["shard"]}
            for a in merged if a.get("state") == "firing"
        ]
        return _json({
            "status": "firing" if firing else "ok",
            "n_firing": len(firing),
            "firing": firing,
            "alerts": merged,
            "n_shards": n_shards,
            "shards_down": [k for k in range(n_shards) if k not in shards],
        })

    def _autoscale(request):
        """Fleet capacity view: desired/live workers SUM across shards
        (each shard owns its worker pool, so fleet capacity is the sum),
        desired_shards is the MAX of the per-shard recommendations (each
        shard sizes the whole fleet from its own saturation — the most
        pressured shard's view wins), with the per-shard bodies attached
        for attribution. Also names WHICH shard is hot: the per-shard
        ``shard_pressure`` map, the argmax (``hot_shard``) and the
        max/mean ``imbalance_ratio`` — the external autoscaler's skew
        signal (a high ratio with low fleet totals means rebalance, not
        scale-out; docs/ROBUSTNESS.md "Shard rebalancing")."""
        shards = _fan_json(request, request.path)
        bodies = {k: (shards[k] or {}) for k in shards}
        pressures: Dict[int, float] = {}
        for k, b in bodies.items():
            sp = (b.get("signals") or {}).get("shard_pressure")
            if sp is not None:
                pressures[k] = float(sp)
        hot_shard = (
            max(pressures, key=lambda k: pressures[k]) if pressures else None
        )
        mean_p = (
            sum(pressures.values()) / len(pressures) if pressures else 0.0
        )
        imbalance = (
            round(max(pressures.values()) / mean_p, 4)
            if pressures and mean_p > 1e-9 else None
        )
        return _json({
            "desired_workers": sum(
                int(b.get("desired_workers") or 0) for b in bodies.values()
            ),
            "live_workers": sum(
                int(b.get("live_workers") or 0) for b in bodies.values()
            ),
            "desired_shards": max(
                [int(b.get("desired_shards") or 0) for b in bodies.values()]
                + [0]
            ),
            "shard_pressure": {str(k): v for k, v in sorted(pressures.items())},
            "hot_shard": hot_shard,
            "imbalance_ratio": imbalance,
            "n_shards": n_shards,
            "shards_down": [k for k in range(n_shards) if k not in shards],
            "shards": bodies,
        })

    def _steal_candidates(request):
        """Fleet steal surface: scatter /steal_candidates over every
        shard and merge, each candidate stamped with its donor shard —
        the discovery feed an idle shard's work-stealing loop (or an
        operator) reads to find pullable queued work."""
        shards = _fan_json(request, request.path)
        merged: List[Dict[str, Any]] = []
        pressures: Dict[str, Any] = {}
        for k in sorted(shards):
            body = shards[k] or {}
            pressures[str(k)] = body.get("shard_pressure")
            for c in body.get("candidates") or []:
                c = dict(c)
                c["shard"] = k
                merged.append(c)
        return _json({
            "candidates": merged,
            "n_candidates": len(merged),
            "shard_pressure": pressures,
            "n_shards": n_shards,
            "shards_down": [k for k in range(n_shards) if k not in shards],
        })

    def _metrics_history(request):
        shards = _fan_json(request, request.path)
        if not request.args.get("name"):
            names = sorted({
                n for body in shards.values()
                for n in (body or {}).get("names") or []
            })
            return _json({"names": names})
        series: List[Dict[str, Any]] = []
        for k, body in shards.items():
            for s in (body or {}).get("series") or []:
                s["labels"] = {**(s.get("labels") or {}), "shard": str(k)}
                series.append(s)
        return _json({
            "name": request.args.get("name"),
            "since": float(request.args.get("since", 0) or 0),
            "series": series,
        })

    def _supervisor(request):
        merged = []
        for body in _fan_json(request, request.path).values():
            if isinstance(body, list):
                merged.extend(body)
        return _json(merged)

    # ---------------- the router ----------------

    _cors = {
        "Access-Control-Allow-Origin": "*",
        "Access-Control-Allow-Headers": "Content-Type, Authorization",
        "Access-Control-Allow-Methods": "GET, POST, OPTIONS",
    }

    def _route(request) -> Response:
        parts = [p for p in request.path.split("/") if p]
        if not parts:
            return _home(request)
        head = parts[0]

        if head == "create_session":
            # mint the session id HERE so shard_of(sid) and the owning
            # shard agree by construction (client-supplied ids are
            # ignored — honoring them would allow session fixation /
            # cross-client sharing); forward any QoS priority
            body = request.get_json(force=True, silent=True) or {}
            sid = str(uuid.uuid4())
            k = shard_of(sid, n_shards)
            fwd = {"session_id": sid}
            if body.get("priority") is not None:
                fwd["priority"] = body["priority"]
            return _proxy(
                request, k, "/create_session",
                body=json.dumps(fwd).encode(),
            )

        if head in _SESSION_ROUTES and len(parts) >= 2:
            k = shard_of(parts[1], n_shards)
            # job routes follow a migrated job's forwarding stamp: the
            # job id is parts[2] on <sid>/<jid> routes, and in the POST
            # body on /train_status (an SSE resume of a moved job)
            job_id = None
            if head in ("check_status", "download_model") and len(parts) >= 3:
                job_id = parts[2]
            elif head == "train_status":
                jbody = request.get_json(force=True, silent=True) or {}
                job_id = jbody.get("job_id") or None
            return _proxy(
                request, k, request.path, stream=(head == "train_status"),
                job_id=job_id,
            )
        if head == "metrics" and len(parts) == 3 and parts[1] not in (
            "prom", "history"
        ):
            return _proxy(
                request, shard_of(parts[1], n_shards), request.path,
                job_id=parts[2],
            )

        if head in _WORKER_ROUTES and len(parts) >= 2:
            k = id_shard(parts[1])
            if k is None or k >= n_shards:
                return _json(
                    {"status": "error",
                     "message": f"worker id {parts[1]!r} carries no valid "
                                "shard stamp"},
                    status=404,
                )
            return _proxy(request, k, request.path)
        if head == "subscribe":
            body = request.get_json(force=True, silent=True) or {}
            pinned = body.pop("shard", None)
            if pinned is None:
                k = next(_rr) % n_shards
            else:
                # an explicit pin is a placement intent: reject anything
                # unroutable instead of silently wrapping modulo N
                try:
                    k = int(pinned)
                except (TypeError, ValueError):
                    k = -1
                if not 0 <= k < n_shards:
                    return _json(
                        {"status": "error",
                         "message": f"shard {pinned!r} not in "
                                    f"[0, {n_shards})"},
                        status=400,
                    )
            return _proxy(
                request, k, "/subscribe", body=json.dumps(body).encode()
            )

        if head in _JOB_ROUTES and len(parts) >= 2:
            k = id_shard(parts[1])
            if k is not None and k < n_shards:
                # cache consult only: a migrated job keeps its donor
                # stamp, but the recorder/trace state lives wherever the
                # job actually ran last
                return _proxy(request, k, request.path, job_id=parts[1])
            return _scatter_first(request, request.path)

        if head == "dataset" and len(parts) == 2:
            return _scatter_first(request, request.path, stream=True)
        if head in ("slice_heartbeat", "slice_status") and len(parts) >= 2:
            return _proxy(
                request, shard_of(parts[1], n_shards), request.path
            )

        if head == "health":
            return _health(request)
        if head == "livez":
            return _json({"status": "ok"})
        if head == "readyz":
            return _readyz(request)
        if head == "healthz":
            return _healthz(request)
        if head == "jobs":
            return _jobs(request)
        if head in ("workers", "queues"):
            return _merge_dicts(request, request.path)
        if head == "metrics" and len(parts) == 2 and parts[1] == "prom":
            return _metrics_prom(request)
        if head == "dashboard":
            return _dashboard(request)
        if head == "events":
            return _events(request)
        if head == "alerts":
            return _alerts(request)
        if head == "autoscale":
            return _autoscale(request)
        if head == "steal_candidates":
            return _steal_candidates(request)
        if head == "supervisor":
            return _supervisor(request)
        if head == "metrics" and len(parts) == 2 and parts[1] == "history":
            return _metrics_history(request)
        if head == "predictor":
            # no fleet-wide calibration registry exists: expose the
            # per-shard bodies keyed by shard index
            return _scatter_dict(request, request.path)

        return _json(
            {"status": "error", "message": "not found"}, status=404
        )

    def _ship_span(k: int, span: Dict[str, Any]) -> None:
        """Stitch the proxy span into the owning shard's tracer
        (POST /trace_spans, the same return leg remote agents use) —
        best-effort: a lost span degrades the fleet view, never the
        request."""
        try:
            session.post(
                f"{urls[k]}/trace_spans/frontend",
                json={"spans": [json_safe(span)]},
                timeout=5,
            )
        except requests.RequestException:
            logger.debug("frontend.proxy span shipping to shard %d failed", k)

    @Request.application
    def app(request):
        if request.method == "OPTIONS":
            return Response(status=204, headers=_cors)
        # frontend.proxy span — the fleet's first hop, previously the
        # trace blind spot: the trace id is MINTED here when the client
        # sent none, so every relayed request is traced from first
        # contact. /trace_spans relays are exempt (they are the span
        # TRANSPORT — a meta-span per shipped batch would pollute every
        # job trace it carries).
        head = request.path.split("/")[1] if "/" in request.path else ""
        inbound_tid = request.headers.get(TRACE_HEADER)
        traced = _obs_enabled() and head != "trace_spans"
        trace_id = inbound_tid
        span_id = None
        t0 = time.time()
        if traced:
            trace_id = inbound_tid or new_trace_id()
            span_id = new_span_id()
            request.tpuml_trace = (trace_id, span_id)
        try:
            resp = _route(request)
        except Exception as e:  # noqa: BLE001 — a routing bug must answer
            logger.exception("Frontend routing failed for %s", request.path)
            resp = _json(
                {"status": "error", "message": str(e)}, status=500
            )
        resp.headers.extend(_cors)
        if trace_id:
            resp.headers[TRACE_HEADER] = trace_id
        shard = getattr(request, "tpuml_shard", None)
        # record only client-traced or single-shard-relayed requests:
        # local aggregates polled untraced (/jobs, /events, the
        # dashboard's 2 s tick) must not churn the trace ring with
        # one-span garbage traces
        if traced and (inbound_tid or shard is not None):
            proxy_span = {
                "trace_id": trace_id,
                "span_id": span_id,
                "parent_id": None,
                "name": "frontend.proxy",
                "start": t0,
                "end": time.time(),
                "attrs": {
                    "route": head or "/",
                    "path": request.path,
                    "method": request.method,
                    "status": resp.status_code,
                    "shard": shard,
                    "minted": inbound_tid is None,
                },
                "process": f"frontend:{os.getpid()}",
            }
            # local ring + the front end's own spans.jsonl journal ...
            TRACER.record(proxy_span)
            # ... and stitched into the owning shard's fleet view
            if shard is not None:
                fan_pool.submit(_ship_span, shard, proxy_span)
        return resp

    app.shard_urls = urls
    return app


def serve(shard_urls: List[str], host: str = "0.0.0.0", port: int = 5000):
    from werkzeug.serving import run_simple

    run_simple(host, port, create_frontend_app(shard_urls), threaded=True)


def main() -> None:
    """``tpuml-frontend`` console entry point: serve the stateless front
    end of a sharded control plane (docs/ARCHITECTURE.md)."""
    import argparse

    parser = argparse.ArgumentParser(description="tpuml API front end")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=5000)
    parser.add_argument(
        "--shards", required=True,
        help="comma-separated coordinator-shard base URLs, in shard order "
             "(index in this list == shard id)",
    )
    args = parser.parse_args()
    serve(
        [u for u in args.shards.split(",") if u.strip()],
        host=args.host, port=args.port,
    )


if __name__ == "__main__":
    main()
