"""Online-learned trial-runtime predictor.

Capability parity with the reference scheduler's ``RuntimePredictor``
(``aws-prod/scheduler/scheduler_service.py:40-84``): a
GradientBoostingRegressor over 7 features [algo id hash, n_rows, n_cols,
mem%, cpu%, metric value, size_mb], joblib-persisted across restarts,
cold-started with a dummy fit, refit every ``refit_batch`` observed
samples, with per-algorithm multipliers from config. Here the observations
come from executor device timings instead of Kafka ``metrics`` messages,
and a trial batch's predicted runtime feeds the placement score the same
way the reference's did.

Beyond the reference: **calibration telemetry**. Since the fault-tolerance
layer (docs/ROBUSTNESS.md) derives lease deadlines, reclaim decisions,
speculation triggers, and (via the placement score) breaker exposure from
these estimates, a drifting predictor now causes false lease reclaims
that silently burn retry budgets. ``record_calibration`` keeps bounded
per-model-family predicted-vs-actual error windows (fed by the
scheduler's observe path with the EXACT estimate that drove the placement
— algo multiplier included), publishes them as
``tpuml_predictor_abs_rel_error{model=}`` /
``tpuml_predictor_calibration_ratio{model=}``, and
``calibration_report()`` backs ``GET /predictor/calibration``
(docs/OBSERVABILITY.md "Predictor calibration").
"""

from __future__ import annotations

import collections
import os
import statistics
import threading
from typing import Any, Dict, Optional

import numpy as np

from ..obs import gauge_set, observe
from ..utils.config import get_config
from ..utils.logging import get_logger

logger = get_logger("tpuml.predictor")


class RuntimePredictor:
    N_FEATURES = 7

    #: per-model-family calibration window: the last N (predicted, actual)
    #: pairs back the error percentiles in calibration_report()
    CALIB_WINDOW = 256
    #: EWMA smoothing for the per-family predicted/actual ratio gauge
    CALIB_EMA_ALPHA = 0.2

    #: recent-family window: the last N observed model families back
    #: ``hot_families()`` — the prewarm hint ranking (a family the fleet
    #: has been running is the one whose cold AOT load the NEXT worker
    #: to register should pay in the background, not inline)
    HOT_WINDOW = 512

    #: replay-buffer depth: every refit trains on the last N observations,
    #: not just the latest 10-sample batch. The reference refit on each
    #: batch alone (scheduler_service.py:72-84), so its model FORGOT all
    #: earlier workloads every 10 samples — prediction error plateaued
    #: instead of shrinking as observations accumulated (VERDICT weak #7).
    REPLAY_SIZE = 200

    def __init__(
        self,
        model_path: Optional[str] = None,
        refit_batch: Optional[int] = None,
        algo_weights: Optional[Dict[str, float]] = None,
        replay_size: Optional[int] = None,
    ):
        cfg = get_config()
        self.model_path = model_path or cfg.storage.runtime_model_path
        self.refit_batch = refit_batch or cfg.scheduler.predictor_refit_batch
        self.algo_weights = dict(algo_weights or cfg.scheduler.algo_weights)
        self._lock = threading.Lock()
        #: observations since the last refit — a counter only; the
        #: observations themselves live in the replay buffer
        self._pending = 0
        self._history: collections.deque = collections.deque(
            maxlen=int(replay_size or self.REPLAY_SIZE)
        )
        #: last HOT_WINDOW observed model families (most recent last)
        self._family_recent: collections.deque = collections.deque(
            maxlen=self.HOT_WINDOW
        )
        #: model family -> deque[(predicted_s, actual_s)] (CALIB_WINDOW)
        self._calib: Dict[str, collections.deque] = {}
        #: model family -> EWMA of predicted/actual
        self._calib_ratio: Dict[str, float] = {}
        self._model = self._load_or_init()

    # ---------------- features ----------------

    @staticmethod
    def features(task: Dict[str, Any]) -> np.ndarray:
        algo = task.get("model_type", "")
        meta = task.get("metadata") or {}
        return np.asarray(
            [
                hash(algo) % 1000,
                float(meta.get("n_rows", 0) or 0),
                float(meta.get("n_cols", 0) or 0),
                float(task.get("mem_percent_avg", 0) or 0),
                float(task.get("cpu_percent_avg", 0) or 0),
                float(task.get("metric_value", 0) or 0),
                float(meta.get("size_mb", 0) or 0),
            ],
            dtype=np.float64,
        )

    # ---------------- predict / observe ----------------

    @staticmethod
    def resource_fraction(obj: Dict[str, Any]) -> float:
        """Rung budget as a fraction of the full trial budget, for
        adaptive-search dispatches (docs/SEARCH.md). Task specs carry an
        ``asha`` block {resource, max_resource}; executor metrics messages
        carry the precomputed ``asha_resource_fraction``. Exhaustive-search
        work prices at 1.0 (unchanged behavior)."""
        a = obj.get("asha")
        if isinstance(a, dict):
            r = a.get("resource")
            big = a.get("max_resource")
            if isinstance(r, (int, float)) and isinstance(big, (int, float)) and big > 0:
                return min(max(float(r) / float(big), 0.01), 1.0)
        f = obj.get("asha_resource_fraction")
        if isinstance(f, (int, float)) and f > 0:
            return min(max(float(f), 0.01), 1.0)
        return 1.0

    def predict(self, task: Dict[str, Any]) -> float:
        feats = self.features(task)[None, :]
        with self._lock:
            est = float(self._model.predict(feats)[0])
        est = max(est, 1e-3)
        mult = self.algo_weights.get(task.get("model_type", ""), 1.0)
        # rungs are priced by their resource so placement scores and lease
        # deadlines reflect the SMALL budget actually dispatched — a rung-0
        # probe must not be leased (or load-accounted) like a full trial
        return est * mult * self.resource_fraction(task)

    def observe(self, task: Dict[str, Any], actual_runtime_s: float) -> None:
        # normalize rung observations back to full-budget-equivalent cost
        # so the model learns ONE consistent target regardless of which
        # rung reported; predict() re-applies the dispatch's fraction
        actual_runtime_s = float(actual_runtime_s) / self.resource_fraction(
            task
        )
        feats = self.features(task)
        # executor metrics messages carry the family as "algo" (reference
        # schema); synthetic/test feedback uses "model_type"
        family = task.get("model_type") or task.get("algo")
        with self._lock:
            if family and "_family_recent" in self.__dict__:
                self._family_recent.append(str(family))
            self._history.append((feats, float(actual_runtime_s)))
            self._pending += 1
            if self._pending < self.refit_batch:
                return
            self._pending = 0
            replay = list(self._history)
        self._refit(replay)

    def hot_families(self, top_n: int = 5) -> list:
        """Model families ranked by recent observation frequency — the
        prewarm hint ordering (docs/ARCHITECTURE.md "Data-plane caching
        and prewarm"). Empty for stub predictors constructed without
        ``RuntimePredictor.__init__`` and before any observation."""
        if "_family_recent" not in self.__dict__:
            return []
        with self._lock:
            counts = collections.Counter(self._family_recent)
        return [family for family, _ in counts.most_common(top_n)]

    # ---------------- calibration ----------------

    def record_calibration(
        self, model_type: Optional[str], predicted_s: float, actual_s: float
    ) -> None:
        """Record one predicted-vs-actual pair for ``model_type``. Called
        by the scheduler's metrics-feedback path with the estimate that
        actually drove the placement (and thus the lease deadline), so the
        report measures the predictor AS USED, not a recomputation."""
        if not (predicted_s > 0 and actual_s > 0):
            return
        if "_calib" not in self.__dict__:
            # a stub subclass constructed without RuntimePredictor.__init__
            # (deterministic test predictors) carries no calibration state
            return
        family = str(model_type or "unknown")
        ratio = predicted_s / actual_s
        with self._lock:
            window = self._calib.get(family)
            if window is None:
                window = collections.deque(maxlen=self.CALIB_WINDOW)
                self._calib[family] = window
            window.append((float(predicted_s), float(actual_s)))
            a = self.CALIB_EMA_ALPHA
            prev = self._calib_ratio.get(family)
            ewma = ratio if prev is None else (1 - a) * prev + a * ratio
            self._calib_ratio[family] = ewma
        observe(
            "tpuml_predictor_abs_rel_error",
            abs(predicted_s - actual_s) / actual_s,
            model=family,
        )
        gauge_set("tpuml_predictor_calibration_ratio", ewma, model=family)

    def calibration_report(self) -> Dict[str, Dict[str, Any]]:
        """Per-model-family calibration stats over the bounded window —
        the ``GET /predictor/calibration`` body. ``ratio`` figures are
        predicted/actual (1.0 = calibrated; < 1 underestimates, which
        tightens leases toward false reclaims); ``abs_rel_error`` is
        |predicted - actual| / actual."""
        if "_calib" not in self.__dict__:
            # stub subclass without RuntimePredictor.__init__ (see
            # record_calibration): no state, empty report
            return {}
        with self._lock:
            windows = {f: list(w) for f, w in self._calib.items()}
            ewmas = dict(self._calib_ratio)
        report: Dict[str, Dict[str, Any]] = {}
        for family, pairs in sorted(windows.items()):
            ratios = sorted(p / a for p, a in pairs)
            errors = sorted(abs(p - a) / a for p, a in pairs)
            last_p, last_a = pairs[-1]
            report[family] = {
                "n": len(pairs),
                "ratio_ewma": ewmas.get(family),
                "ratio_median": statistics.median(ratios),
                "abs_rel_error_mean": statistics.fmean(errors),
                "abs_rel_error_p90": errors[
                    min(int(0.9 * len(errors)), len(errors) - 1)
                ],
                "last_predicted_s": last_p,
                "last_actual_s": last_a,
            }
        return report

    def _refit(self, batch) -> None:
        from sklearn.ensemble import GradientBoostingRegressor

        X = np.stack([f for f, _ in batch])
        y = np.asarray([t for _, t in batch])
        with self._lock:
            # GBRT has no partial_fit, so each refit trains from scratch —
            # but on the bounded replay buffer (last REPLAY_SIZE
            # observations), not just the triggering batch: accuracy
            # improves as observations accumulate instead of resetting to
            # a 10-sample model every refit cycle
            model = GradientBoostingRegressor(random_state=0)
            try:
                model.fit(X, y)
                self._model = model
                self._persist()
            except Exception:  # noqa: BLE001
                logger.exception("Runtime-predictor refit failed; keeping old model")

    # ---------------- persistence ----------------

    def _load_or_init(self):
        from sklearn.ensemble import GradientBoostingRegressor

        if self.model_path and os.path.exists(self.model_path):
            try:
                import joblib

                return joblib.load(self.model_path)
            except Exception:  # noqa: BLE001
                logger.exception("Failed to load runtime model; cold-starting")
        model = GradientBoostingRegressor(random_state=0)
        # cold-start dummy fit so predict() works before observations arrive
        Xd = np.zeros((2, self.N_FEATURES))
        model.fit(Xd, np.asarray([1.0, 1.0]))
        return model

    def _persist(self) -> None:
        if not self.model_path:
            return
        try:
            import joblib

            os.makedirs(os.path.dirname(self.model_path), exist_ok=True)
            joblib.dump(self._model, self.model_path)
        except Exception:  # noqa: BLE001
            logger.exception("Failed to persist runtime model")
