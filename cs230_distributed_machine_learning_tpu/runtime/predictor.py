"""Online-learned trial-runtime predictor.

Capability parity with the reference scheduler's ``RuntimePredictor``
(``aws-prod/scheduler/scheduler_service.py:40-84``): a
GradientBoostingRegressor over 7 features [algo id hash, n_rows, n_cols,
mem%, cpu%, metric value, size_mb], joblib-persisted across restarts,
cold-started with a dummy fit, refit every ``refit_batch`` observed
samples, with per-algorithm multipliers from config. Here the observations
come from executor device timings instead of Kafka ``metrics`` messages,
and a trial batch's predicted runtime feeds the placement score the same
way the reference's did.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Dict, Optional

import numpy as np

from ..utils.config import get_config
from ..utils.logging import get_logger

logger = get_logger("tpuml.predictor")


class RuntimePredictor:
    N_FEATURES = 7

    #: replay-buffer depth: every refit trains on the last N observations,
    #: not just the latest 10-sample batch. The reference refit on each
    #: batch alone (scheduler_service.py:72-84), so its model FORGOT all
    #: earlier workloads every 10 samples — prediction error plateaued
    #: instead of shrinking as observations accumulated (VERDICT weak #7).
    REPLAY_SIZE = 200

    def __init__(
        self,
        model_path: Optional[str] = None,
        refit_batch: Optional[int] = None,
        algo_weights: Optional[Dict[str, float]] = None,
        replay_size: Optional[int] = None,
    ):
        cfg = get_config()
        self.model_path = model_path or cfg.storage.runtime_model_path
        self.refit_batch = refit_batch or cfg.scheduler.predictor_refit_batch
        self.algo_weights = dict(algo_weights or cfg.scheduler.algo_weights)
        self._lock = threading.Lock()
        #: observations since the last refit — a counter only; the
        #: observations themselves live in the replay buffer
        self._pending = 0
        self._history: collections.deque = collections.deque(
            maxlen=int(replay_size or self.REPLAY_SIZE)
        )
        self._model = self._load_or_init()

    # ---------------- features ----------------

    @staticmethod
    def features(task: Dict[str, Any]) -> np.ndarray:
        algo = task.get("model_type", "")
        meta = task.get("metadata") or {}
        return np.asarray(
            [
                hash(algo) % 1000,
                float(meta.get("n_rows", 0) or 0),
                float(meta.get("n_cols", 0) or 0),
                float(task.get("mem_percent_avg", 0) or 0),
                float(task.get("cpu_percent_avg", 0) or 0),
                float(task.get("metric_value", 0) or 0),
                float(meta.get("size_mb", 0) or 0),
            ],
            dtype=np.float64,
        )

    # ---------------- predict / observe ----------------

    def predict(self, task: Dict[str, Any]) -> float:
        feats = self.features(task)[None, :]
        with self._lock:
            est = float(self._model.predict(feats)[0])
        est = max(est, 1e-3)
        mult = self.algo_weights.get(task.get("model_type", ""), 1.0)
        return est * mult

    def observe(self, task: Dict[str, Any], actual_runtime_s: float) -> None:
        feats = self.features(task)
        with self._lock:
            self._history.append((feats, float(actual_runtime_s)))
            self._pending += 1
            if self._pending < self.refit_batch:
                return
            self._pending = 0
            replay = list(self._history)
        self._refit(replay)

    def _refit(self, batch) -> None:
        from sklearn.ensemble import GradientBoostingRegressor

        X = np.stack([f for f, _ in batch])
        y = np.asarray([t for _, t in batch])
        with self._lock:
            # GBRT has no partial_fit, so each refit trains from scratch —
            # but on the bounded replay buffer (last REPLAY_SIZE
            # observations), not just the triggering batch: accuracy
            # improves as observations accumulate instead of resetting to
            # a 10-sample model every refit cycle
            model = GradientBoostingRegressor(random_state=0)
            try:
                model.fit(X, y)
                self._model = model
                self._persist()
            except Exception:  # noqa: BLE001
                logger.exception("Runtime-predictor refit failed; keeping old model")

    # ---------------- persistence ----------------

    def _load_or_init(self):
        from sklearn.ensemble import GradientBoostingRegressor

        if self.model_path and os.path.exists(self.model_path):
            try:
                import joblib

                return joblib.load(self.model_path)
            except Exception:  # noqa: BLE001
                logger.exception("Failed to load runtime model; cold-starting")
        model = GradientBoostingRegressor(random_state=0)
        # cold-start dummy fit so predict() works before observations arrive
        Xd = np.zeros((2, self.N_FEATURES))
        model.fit(Xd, np.asarray([1.0, 1.0]))
        return model

    def _persist(self) -> None:
        if not self.model_path:
            return
        try:
            import joblib

            os.makedirs(os.path.dirname(self.model_path), exist_ok=True)
            joblib.dump(self._model, self.model_path)
        except Exception:  # noqa: BLE001
            logger.exception("Failed to persist runtime model")
