"""Adaptive search: ASHA / Hyperband rung controller (docs/SEARCH.md).

The exhaustive Grid/Randomized fan-out runs every trial to its full budget
— at fleet scale most of those device-seconds are spent on trials that were
visibly doomed after a fraction of the budget. This module adds
asynchronous successive halving (ASHA, Li et al. 2020) and Hyperband
(Li et al. 2018) as first-class job types on top of the primitives the
runtime already owns:

- a trial's **resource** is its iteration budget (solver iterations for
  LogReg/MLP/SVM, boosting rounds / tree count for the ensembles), carried
  in the subtask's parameters, so a rung dispatch rides the vmapped trial
  engine unchanged;
- each **rung** is one dispatch of the trial at that rung's resource; the
  completion result (and the executor's per-batch metrics message) carries
  the intermediate validation score at the rung boundary;
- **promotion is asynchronous**: a trial promotes the moment it is in the
  top 1/eta of its rung's *reported* peers — no rung barrier. A promotion
  re-enqueues the trial as a fresh attempt with the eta-times-larger
  budget (optionally warm-started from its own lower-rung weights via the
  artifact plumbing, see ``warm_from`` below);
- **pruning is terminal but non-failure**: a trial that can never be
  promoted (its rank among reported peers already exceeds the rung's
  promotion quota, or the rung closed without promoting it) finalizes as
  the new ``pruned`` subtask status. Prune decisions for in-flight
  attempts ride the cooperative-cancel path: the coordinator synthesizes
  the terminal ``pruned`` result immediately (so liveness never depends on
  the worker) AND marks the attempt cancelled — the agent's next poll
  response carries the cancel list and the executor stops the trial at the
  next batch boundary instead of burning the rest of its budget. A dead or
  ignoring worker is already handled by the lease reclaim: the requeued
  copy is dropped by the ledger's ``is_done`` check.

The controller is **deterministic**: feeding the same reports in the same
order reproduces the same promotions/prunes, which is how a SIGKILLed
coordinator resumes rung state from the journal's replayed rung history
without double-promoting (``SearchJobDriver.resume``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..obs import counter_inc, record_event
from ..utils.logging import get_logger

logger = get_logger("tpuml.search")

#: model family -> the parameter that IS the trial's resource budget.
#: Families without an iterative budget (KNN, NaiveBayes, plain linear
#: solves) cannot be early-stopped meaningfully and are rejected at
#: expansion time with a clear error.
RESOURCE_PARAMS: Dict[str, str] = {
    "LogisticRegression": "max_iter",
    "MLPClassifier": "max_iter",
    "MLPRegressor": "max_iter",
    "SVC": "max_iter",
    "LinearSVC": "max_iter",
    "SGDClassifier": "max_iter",
    "GradientBoostingClassifier": "n_estimators",
    "GradientBoostingRegressor": "n_estimators",
    "RandomForestClassifier": "n_estimators",
    "RandomForestRegressor": "n_estimators",
    "ExtraTreesClassifier": "n_estimators",
    "ExtraTreesRegressor": "n_estimators",
}

#: fallback full budget per resource param when neither the asha config
#: nor the base estimator pins one
_DEFAULT_MAX_RESOURCE = {"max_iter": 100, "n_estimators": 100}


def resource_param_for(model_type: str) -> str:
    param = RESOURCE_PARAMS.get(model_type)
    if param is None:
        raise ValueError(
            f"adaptive search needs an iterative resource budget, which "
            f"{model_type!r} does not expose; supported families: "
            f"{sorted(RESOURCE_PARAMS)}"
        )
    return param


def asha_schedule(min_resource: int, max_resource: int, eta: int) -> List[int]:
    """Geometric rung ladder [r, r*eta, ...] ending exactly at
    ``max_resource``. ``min_resource >= max_resource`` degenerates to a
    single rung at the full budget (== exhaustive search, nothing pruned
    before the full budget is spent)."""
    min_resource = max(int(min_resource), 1)
    max_resource = max(int(max_resource), 1)
    if min_resource >= max_resource:
        return [max_resource]
    ladder = [min_resource]
    while ladder[-1] * eta < max_resource:
        ladder.append(ladder[-1] * eta)
    if len(ladder) > 1 and max_resource < ladder[-1] * math.sqrt(eta):
        # a final step smaller than sqrt(eta) buys almost no halving
        # power but costs a full extra dispatch round — fold it into the
        # last geometric rung instead (e.g. [10, 30, 90, 100] -> [10, 30, 100])
        ladder[-1] = max_resource
    else:
        ladder.append(max_resource)
    return ladder


def hyperband_brackets(
    max_resource: int,
    eta: int = 3,
    max_brackets: Optional[int] = None,
    n_trials: Optional[int] = None,
) -> List[Dict[str, int]]:
    """Standard Hyperband bracket allocation (Li et al. 2018, Alg. 1):
    ``s_max + 1`` brackets trading off exploration (many trials, tiny
    starting budget) against exploitation (few trials, full budget).
    ``max_brackets`` keeps only the most-exploratory N brackets;
    ``n_trials`` rescales the per-bracket trial counts so the total equals
    the caller's budget (floored at 1 per bracket)."""
    eta = max(int(eta), 2)
    max_resource = max(int(max_resource), 1)
    s_max = int(math.floor(math.log(max_resource) / math.log(eta)))
    budget = (s_max + 1) * max_resource
    out = []
    for s in range(s_max, -1, -1):
        n = int(math.ceil(budget / max_resource * (eta ** s) / (s + 1)))
        r = max(1, int(max_resource * (eta ** -s)))
        out.append({"bracket": s, "n_trials": n, "min_resource": r})
    if max_brackets is not None and max_brackets > 0:
        out = out[:max_brackets]
    if n_trials is not None and n_trials > 0:
        total = sum(b["n_trials"] for b in out)
        for b in out:
            b["n_trials"] = max(1, round(b["n_trials"] * n_trials / total))
    return out


@dataclasses.dataclass
class Rung:
    index: int
    resource: int
    #: theoretical max entrants (rung 0: the bracket's n; k>0: the rung
    #: below's promotion quota). The early-prune rank test uses it as the
    #: never-exceedable promotion bound — safe even when failures shrink
    #: the real entrant count below capacity.
    capacity: int
    entered: set = dataclasses.field(default_factory=set)
    #: trial -> score, in report (seq) order — the tie-break order
    reported: Dict[str, float] = dataclasses.field(default_factory=dict)
    promoted: set = dataclasses.field(default_factory=set)
    #: decided at this rung without promotion (pruned or failed)
    removed: set = dataclasses.field(default_factory=set)


class AshaController:
    """Per-bracket asynchronous successive-halving state machine.

    ``on_report`` is **idempotent** — a duplicate (trial, rung) report, a
    report for a decided trial, or a stale lower-rung report after a
    promotion all return no decisions — which is what makes the journal
    replay and the at-least-once result ingest safe to feed directly.
    """

    def __init__(
        self,
        trial_ids: Iterable[str],
        *,
        min_resource: int,
        max_resource: int,
        eta: int = 3,
        bracket: int = 0,
        stop_score: Optional[float] = None,
    ):
        self.eta = max(int(eta), 2)
        self.bracket = bracket
        self.stop_score = stop_score
        self.max_resource = max(int(max_resource), 1)
        resources = asha_schedule(min_resource, self.max_resource, self.eta)
        ids = list(trial_ids)
        self.rungs: List[Rung] = []
        cap = len(ids)
        for k, r in enumerate(resources):
            self.rungs.append(Rung(index=k, resource=r, capacity=max(cap, 1)))
            cap = max(1, cap // self.eta)
        self.rungs[0].entered = set(ids)
        #: trial -> terminal outcome ("completed" | "pruned" | "failed")
        self.decided: Dict[str, str] = {}
        #: trial -> highest rung index entered
        self.trial_rung: Dict[str, int] = {tid: 0 for tid in ids}
        self.stopped = False

    # ---------------- rung math ----------------

    @property
    def top(self) -> int:
        return len(self.rungs) - 1

    def _max_promotions(self, k: int) -> int:
        """Hard bound on promotions out of rung k: the rung above's
        capacity. A reported trial ranked below it can never promote."""
        return 0 if k >= self.top else self.rungs[k + 1].capacity

    def _ranked(self, rung: Rung) -> List[str]:
        """Reported trials by score desc; ties resolve first-reported-first
        (dict insertion order), so replaying the same report order
        reproduces the same ranking."""
        order = {tid: i for i, tid in enumerate(rung.reported)}
        return sorted(
            rung.reported,
            key=lambda tid: (-rung.reported[tid], order[tid]),
        )

    def _closed(self, k: int) -> bool:
        """True when no further trial can ever ENTER rung k."""
        if k == 0:
            return True
        below = self.rungs[k - 1]
        return below.entered <= (below.promoted | below.removed)

    # ---------------- reports ----------------

    def on_report(
        self, trial_id: str, rung_idx: int, score: Optional[float]
    ) -> List[Dict[str, Any]]:
        """Feed one rung-boundary score; returns the decisions it caused —
        possibly about OTHER trials (a report can fill a quota, unlock a
        peer's promotion, or doom paused peers)."""
        if self.stopped or trial_id in self.decided:
            return []
        if rung_idx != self.trial_rung.get(trial_id):
            return []  # stale (superseded rung) or foreign report
        if rung_idx < 0 or rung_idx > self.top:
            return []
        rung = self.rungs[rung_idx]
        if trial_id in rung.reported or trial_id not in rung.entered:
            return []  # duplicate delivery / never scheduled here
        if not isinstance(score, (int, float)) or score != score:
            return self.on_trial_failed(trial_id)
        rung.reported[trial_id] = float(score)
        decisions: List[Dict[str, Any]] = []
        if self.stop_score is not None and score >= self.stop_score:
            return self._stop(trial_id, rung_idx, score)
        if rung_idx == self.top:
            self.decided[trial_id] = "completed"
            decisions.append(
                self._decision("complete", trial_id, rung_idx, score=score)
            )
        self._sweep(rung_idx, decisions)
        return decisions

    def on_trial_failed(self, trial_id: str) -> List[Dict[str, Any]]:
        """A rung execution failed terminally (quarantine): the trial
        leaves the ladder; its rung may now close for the survivors."""
        if trial_id in self.decided:
            return []
        self.decided[trial_id] = "failed"
        k = self.trial_rung.get(trial_id, 0)
        rung = self.rungs[k]
        rung.removed.add(trial_id)
        rung.reported.pop(trial_id, None)
        decisions: List[Dict[str, Any]] = []
        self._sweep(k, decisions)
        return decisions

    def _stop(self, trial_id, rung_idx, score) -> List[Dict[str, Any]]:
        """``stop_score`` reached: the winner completes where it stands and
        every other undecided trial is pruned (in-flight attempts are
        cancelled cooperatively by the driver)."""
        self.stopped = True
        self.decided[trial_id] = "completed"
        decisions = [
            self._decision(
                "complete", trial_id, rung_idx, score=score, reason="stop_score"
            )
        ]
        for tid in list(self.trial_rung):
            if tid in self.decided:
                continue
            self.decided[tid] = "pruned"
            k = self.trial_rung[tid]
            self.rungs[k].removed.add(tid)
            decisions.append(
                self._decision(
                    "prune", tid, k,
                    score=self.rungs[k].reported.get(tid),
                    reason="stop_score",
                )
            )
        return decisions

    # ---------------- promotion / prune sweep ----------------

    def _sweep(self, start: int, decisions: List[Dict[str, Any]]) -> None:
        """Re-evaluate rungs ``start``..top: async promotions up to
        floor(reported/eta), terminal prunes for trials that can never be
        promoted, and closure resolution (a fully-reported closed rung
        promotes at least its best survivor and prunes the rest). Closure
        cascades upward — resolving rung k can close rung k+1."""
        for k in range(start, self.top):
            rung = self.rungs[k]
            max_prom = self._max_promotions(k)
            # async promotion: top-1/eta of *reported* peers, no barrier
            quota = min(len(rung.reported) // self.eta, max_prom)
            closed = self._closed(k)
            fully_reported = closed and not (
                rung.entered - rung.removed - set(rung.reported)
            )
            if fully_reported and rung.reported:
                # rung closed with every survivor reported: promote at
                # least one so the ladder always delivers a trial to the
                # full budget, even when floor(n/eta) is 0 (max_prom is
                # >= 1 for every non-top rung by capacity construction)
                quota = min(max(quota, 1), max_prom)
            ranked = self._ranked(rung)
            active = [t for t in ranked if t not in self.decided]
            for tid in active:
                if len(rung.promoted) >= quota:
                    break
                if tid in rung.promoted:
                    continue
                self._promote(tid, k, decisions)
            # terminal prune: rank among reported only ever worsens and
            # max_prom is a hard bound — outside it means never promotable
            for pos, tid in enumerate(ranked):
                if tid in self.decided or tid in rung.promoted:
                    continue
                doomed = pos >= max_prom
                if doomed or (
                    fully_reported and len(rung.promoted) >= quota
                ):
                    self.decided[tid] = "pruned"
                    rung.removed.add(tid)
                    decisions.append(
                        self._decision(
                            "prune", tid, k, score=rung.reported[tid],
                            reason="outranked" if doomed else "rung_closed",
                        )
                    )
        # top rung has no promotions; nothing to sweep there

    def _promote(self, tid: str, k: int, decisions: List[Dict[str, Any]]) -> None:
        rung = self.rungs[k]
        nxt = self.rungs[k + 1]
        rung.promoted.add(tid)
        nxt.entered.add(tid)
        self.trial_rung[tid] = k + 1
        decisions.append(
            self._decision(
                "promote", tid, k, score=rung.reported.get(tid),
                to_rung=k + 1, to_resource=nxt.resource,
            )
        )

    def _decision(self, action, tid, rung_idx, score=None, **extra):
        rung = self.rungs[rung_idx]
        return {
            "action": action,
            "trial_id": tid,
            "bracket": self.bracket,
            "rung": rung_idx,
            "resource": rung.resource,
            "score": score,
            "peers": len(rung.reported),
            **extra,
        }

    # ---------------- queries ----------------

    def force_decide(self, trial_id: str, outcome: str) -> List[Dict[str, Any]]:
        """Adopt a terminal outcome the journal already committed (e.g. a
        ``pruned`` result for a cancelled attempt, whose triggering report
        never had a score to replay). First-wins: a trial the replay
        already decided is untouched. Pruned/failed trials leave their
        rung so closure math proceeds for the survivors."""
        if trial_id in self.decided or trial_id not in self.trial_rung:
            return []
        self.decided[trial_id] = outcome
        k = self.trial_rung[trial_id]
        if outcome in ("pruned", "failed", "diverged"):
            self.rungs[k].removed.add(trial_id)
            self.rungs[k].reported.pop(trial_id, None)
        decisions: List[Dict[str, Any]] = []
        self._sweep(k, decisions)
        return decisions

    def is_complete(self) -> bool:
        return all(tid in self.decided for tid in self.trial_rung)

    def pending_rungs(self) -> Dict[str, Tuple[int, int]]:
        """trial -> (rung index, resource) for every undecided trial whose
        current rung has no report yet — exactly the dispatches a resumed
        coordinator must (re-)issue."""
        out = {}
        for tid, k in self.trial_rung.items():
            if tid in self.decided:
                continue
            if tid not in self.rungs[k].reported:
                out[tid] = (k, self.rungs[k].resource)
        return out

    def summary(self) -> Dict[str, Any]:
        return {
            "bracket": self.bracket,
            "eta": self.eta,
            "max_resource": self.max_resource,
            "stopped": self.stopped,
            "rungs": [
                {
                    "rung": r.index,
                    "resource": r.resource,
                    "entered": len(r.entered),
                    "reported": len(r.reported),
                    "promoted": len(r.promoted),
                    "pruned": len(
                        [t for t in r.removed if self.decided.get(t) == "pruned"]
                    ),
                }
                for r in self.rungs
            ],
            "completed": sum(
                1 for v in self.decided.values() if v == "completed"
            ),
            "pruned": sum(1 for v in self.decided.values() if v == "pruned"),
            "failed": sum(1 for v in self.decided.values() if v == "failed"),
            # numerical-health watchdog terminals (docs/OBSERVABILITY.md
            # "Trial telemetry plane") — non-failure, like pruned
            "diverged": sum(
                1 for v in self.decided.values() if v == "diverged"
            ),
            "n_trials": len(self.trial_rung),
        }


class MultiBracketController:
    """Hyperband: independent ASHA brackets, one controller each; the
    trial's spec carries its bracket id. Complete when every bracket is."""

    def __init__(self, brackets: Dict[int, AshaController],
                 trial_bracket: Dict[str, int]):
        self.brackets = brackets
        self.trial_bracket = trial_bracket

    def _ctrl(self, trial_id: str) -> Optional[AshaController]:
        b = self.trial_bracket.get(trial_id)
        return self.brackets.get(b) if b is not None else None

    def on_report(self, trial_id, rung_idx, score):
        ctrl = self._ctrl(trial_id)
        return ctrl.on_report(trial_id, rung_idx, score) if ctrl else []

    def on_trial_failed(self, trial_id):
        ctrl = self._ctrl(trial_id)
        return ctrl.on_trial_failed(trial_id) if ctrl else []

    def force_decide(self, trial_id, outcome):
        ctrl = self._ctrl(trial_id)
        return ctrl.force_decide(trial_id, outcome) if ctrl else []

    def is_complete(self):
        return all(c.is_complete() for c in self.brackets.values())

    def pending_rungs(self):
        out = {}
        for c in self.brackets.values():
            out.update(c.pending_rungs())
        return out

    @property
    def decided(self):
        merged: Dict[str, str] = {}
        for c in self.brackets.values():
            merged.update(c.decided)
        return merged

    @property
    def trial_rung(self):
        merged: Dict[str, int] = {}
        for c in self.brackets.values():
            merged.update(c.trial_rung)
        return merged

    def rung_resource(self, trial_id: str, rung_idx: int) -> int:
        ctrl = self._ctrl(trial_id)
        return ctrl.rungs[rung_idx].resource if ctrl else 0

    def summary(self):
        per = [c.summary() for _, c in sorted(self.brackets.items())]
        return {
            "brackets": per,
            "completed": sum(s["completed"] for s in per),
            "pruned": sum(s["pruned"] for s in per),
            "failed": sum(s["failed"] for s in per),
            "diverged": sum(s.get("diverged", 0) for s in per),
            "n_trials": sum(s["n_trials"] for s in per),
        }


# ---------------- trial planning (subtask expansion) ----------------


def plan_trials(model_details: Dict[str, Any]) -> List[Tuple[Dict[str, Any], Dict[str, Any]]]:
    """Expand an asha/hyperband job into (param combo, asha block) pairs —
    the ``create_subtasks`` input. The asha block is the spec's rung-state
    stamp: {rung, resource, min_resource, max_resource, eta, bracket,
    resource_param, stop_score?}. The resource param is controller-owned:
    a sampled value for it is dropped from the combo."""
    from sklearn.model_selection import ParameterGrid, ParameterSampler

    model_type = model_details["model_type"]
    search_type = model_details.get("search_type")
    cfg = dict(model_details.get("asha") or {})
    resource_param = cfg.get("resource_param") or resource_param_for(model_type)
    base = dict(model_details.get("base_estimator_params") or {})
    eta = max(int(cfg.get("eta", 3)), 2)
    max_resource = int(
        cfg.get("max_resource")
        or base.get(resource_param)
        or _DEFAULT_MAX_RESOURCE.get(resource_param, 100)
    )
    min_resource = int(cfg.get("min_resource") or max(1, max_resource // eta ** 2))
    stop_score = cfg.get("stop_score")

    def _draw(n: Optional[int]) -> List[Dict[str, Any]]:
        """``n`` trial configurations; None = the caller set no n_iter —
        sample the distribution default (16) or run the FULL grid (a
        param_grid must never be silently truncated: exhaustive
        GridSearchCV runs every combo, and so does asha over a grid)."""
        dists = model_details.get("param_distributions")
        if dists:
            return list(
                ParameterSampler(
                    dists, n_iter=int(n or 16),
                    random_state=model_details.get("random_state"),
                )
            )
        grid = model_details.get("param_grid") or {}
        combos = list(ParameterGrid(grid)) if grid else [{}]
        if n is not None and 0 < n < len(combos):
            return combos[:n]
        return combos

    def _block(rung0_resource: int, bracket: int) -> Dict[str, Any]:
        block = {
            "rung": 0,
            "resource": int(rung0_resource),
            "min_resource": int(rung0_resource),
            "max_resource": max_resource,
            "eta": eta,
            "bracket": bracket,
            "resource_param": resource_param,
        }
        if stop_score is not None:
            block["stop_score"] = float(stop_score)
        return block

    out: List[Tuple[Dict[str, Any], Dict[str, Any]]] = []
    if search_type == "hyperband":
        brackets = hyperband_brackets(
            max_resource, eta,
            max_brackets=cfg.get("max_brackets"),
            n_trials=model_details.get("n_iter"),
        )
        total = sum(b["n_trials"] for b in brackets)
        combos = _draw(total)
        i = 0
        for b in brackets:
            for _ in range(b["n_trials"]):
                combo = dict(combos[i % len(combos)])
                i += 1
                combo.pop(resource_param, None)
                out.append((combo, _block(b["min_resource"], b["bracket"])))
    else:  # asha: one bracket
        n_iter = model_details.get("n_iter")
        for combo in _draw(int(n_iter) if n_iter else None):
            combo = dict(combo)
            combo.pop(resource_param, None)
            out.append((combo, _block(min_resource, 0)))
    return out


def build_controller(specs: List[Dict[str, Any]]) -> MultiBracketController:
    """Rebuild the bracket controllers from the subtask specs' asha blocks
    (works for fresh jobs and journal-replayed ones alike — the blocks are
    journaled with the specs)."""
    by_bracket: Dict[int, List[Dict[str, Any]]] = {}
    for st in specs:
        a = st.get("asha") or {}
        by_bracket.setdefault(int(a.get("bracket", 0)), []).append(st)
    brackets: Dict[int, AshaController] = {}
    trial_bracket: Dict[str, int] = {}
    for b, sts in by_bracket.items():
        a0 = sts[0].get("asha") or {}
        brackets[b] = AshaController(
            [st["subtask_id"] for st in sts],
            min_resource=int(a0.get("min_resource", 1)),
            max_resource=int(a0.get("max_resource", 100)),
            eta=int(a0.get("eta", 3)),
            bracket=b,
            stop_score=a0.get("stop_score"),
        )
        for st in sts:
            trial_bracket[st["subtask_id"]] = b
    return MultiBracketController(brackets, trial_bracket)


# ---------------- coordinator-facing driver ----------------


@dataclasses.dataclass
class Step:
    """The dispatch-side effect of one ingested report: terminal results
    to finalize, intermediate (promoted) results to store, fresh rung
    dispatches to enqueue, and in-flight attempts to cancel."""

    finished: List[Tuple[str, str, Dict[str, Any]]] = dataclasses.field(
        default_factory=list
    )
    promoted: List[Tuple[str, Dict[str, Any]]] = dataclasses.field(
        default_factory=list
    )
    new_tasks: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    cancels: List[Dict[str, Any]] = dataclasses.field(default_factory=list)


class SearchJobDriver:
    """Bridges the rung controller to the coordinator's result loop.

    All ``handle_*`` entry points are idempotent: the controller dedups
    reports, ``_issued`` guards duplicate rung dispatches, and
    ``_finalized`` guards duplicate terminal emissions — so the same
    report may arrive via the metrics feed AND the result ingest (or be
    replayed from the journal) without double-promoting.
    """

    def __init__(self, specs: List[Dict[str, Any]]):
        self.specs = {st["subtask_id"]: st for st in specs}
        self.controller = build_controller(specs)
        self.job_id = specs[0].get("job_id") if specs else None
        self._seq = 0
        #: trial -> highest rung index a dispatch was issued for
        self._issued: Dict[str, int] = {tid: 0 for tid in self.specs}
        self._finalized: set = set()
        #: trial -> sum of resources of completed rung dispatches
        self._spent: Dict[str, int] = {}
        #: (trial, rung) pairs already absorbed into the spent accounting
        self._counted: set = set()
        #: trial -> last REAL result seen (any rung) — synthesized
        #: terminals merge over it so pruned/paused trials keep their
        #: measured metrics instead of a bare stub
        self._last_result: Dict[str, Dict[str, Any]] = {}
        #: trial -> (training_time_s, resource) of the last completed rung
        self._last_time: Dict[str, Tuple[float, int]] = {}

    # ---------------- dispatch specs ----------------

    def _stamp(self, spec: Dict[str, Any], rung: int, resource: int,
               warm_from: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        task = dict(spec)
        a = dict(task.get("asha") or {})
        a.update(rung=rung, resource=int(resource))
        if warm_from is not None:
            a["warm_from"] = warm_from
        elif "warm_from" in a:
            a.pop("warm_from")
        task["asha"] = a
        params = dict(task.get("parameters") or {})
        params[a["resource_param"]] = int(resource)
        task["parameters"] = params
        tp = dict(task.get("train_params") or {})
        tp["rung"] = rung
        tp["resource"] = int(resource)
        task["train_params"] = tp
        self.specs[task["subtask_id"]] = task
        return task

    def pending_tasks(self) -> List[Dict[str, Any]]:
        """Rung dispatches currently owed: for a fresh job, every trial's
        rung 0; after ``resume``, exactly the unreported current rungs."""
        tasks = []
        for tid, (rung, resource) in sorted(
            self.controller.pending_rungs().items()
        ):
            self._issued[tid] = rung
            warm = self.specs[tid].get("asha", {}).get("warm_from")
            tasks.append(self._stamp(self.specs[tid], rung, resource,
                                     warm_from=warm))
        return tasks

    def done(self) -> bool:
        return self.controller.is_complete()

    def summary(self) -> Dict[str, Any]:
        return self.controller.summary()

    # ---------------- resume (journal replay) ----------------

    def resume(self, job_record: Dict[str, Any]) -> None:
        """Rebuild rung state from the journaled rung history. Reports are
        re-fed in their original global ``seq`` order; the controller's
        determinism reproduces every promotion/prune, so nothing is
        promoted twice and ``pending_tasks`` yields only the dispatches
        still owed."""
        entries = []
        for stid, sub in (job_record.get("subtasks") or {}).items():
            for h in sub.get("rung_history") or []:
                # only REAL execution reports re-feed the controller;
                # synthesized terminal entries carry no ``report`` flag
                # (their outcome is adopted via force_decide below)
                if h.get("report") or h.get("failed"):
                    entries.append((h.get("seq", 0), stid, h))
        replayed = 0
        for seq, stid, h in sorted(entries, key=lambda e: e[0]):
            self._seq = max(self._seq, int(seq))
            if h.get("failed"):
                self.controller.on_trial_failed(stid)
                continue
            rung = int(h.get("rung", 0))
            self.controller.on_report(stid, rung, h.get("score"))
            if (stid, rung) not in self._counted:
                self._counted.add((stid, rung))
                self._spent[stid] = self._spent.get(stid, 0) + int(
                    h.get("resource", 0)
                )
            replayed += 1
        # terminal results already journaled must stay final even if the
        # controller would re-derive them differently (first-wins). The
        # force covers trials whose terminal state had no replayable
        # report — e.g. a ``pruned`` write for a cancelled attempt.
        from .store import SUBTASK_TERMINAL_STATUSES

        for stid, sub in (job_record.get("subtasks") or {}).items():
            status = sub.get("status")
            if status in SUBTASK_TERMINAL_STATUSES:
                self._finalized.add(stid)
                self.controller.force_decide(stid, status)
        if replayed:
            logger.info(
                "Search job %s resumed: %d rung reports replayed, "
                "%d trials decided, %d dispatches pending",
                self.job_id, replayed, len(self.controller.decided),
                len(self.controller.pending_rungs()),
            )
            record_event(
                "rung.resume", job_id=self.job_id, replayed=replayed,
                decided=len(self.controller.decided),
                pending=len(self.controller.pending_rungs()),
            )

    def resume_step(self) -> Step:
        """Terminal states the replayed controller derived whose store
        writes were lost in the crash (decided but no journaled terminal
        result): synthesize them now so the resumed job can finalize
        without waiting on reports that will never come."""
        step = Step()
        for tid, outcome in sorted(self.controller.decided.items()):
            if tid in self._finalized:
                continue
            self._finalized.add(tid)
            ctrl = self.controller._ctrl(tid)
            k = self.controller.trial_rung.get(tid, 0)
            score = None
            if ctrl is not None:
                score = ctrl.rungs[min(k, ctrl.top)].reported.get(tid)
            res = self._synth_result(
                tid, outcome,
                {"reason": "replay", "rung": k, "score": score},
            )
            step.finished.append((tid, outcome, res))
        return step

    # ---------------- report ingest ----------------

    def handle_result(self, stid: str, result: Dict[str, Any]) -> Step:
        """A completed rung dispatch reported its validation score."""
        a = dict(result.get("asha") or self.specs[stid].get("asha") or {})
        rung = int(a.get("rung", self._issued.get(stid, 0)))
        score = result.get("mean_cv_score")
        if not isinstance(score, (int, float)) or score != score:
            # a completed result with no usable score cannot climb the
            # ladder — treat it like a terminal execution failure
            return self.handle_quarantine(stid, result)
        score = self._curve_adjusted_score(result, float(score))
        tt = result.get("training_time")
        resource = int(a.get("resource", 0))
        self._last_result[stid] = result
        ctrl = self.controller._ctrl(stid)

        def _in_reported() -> bool:
            return (
                ctrl is not None
                and 0 <= rung <= ctrl.top
                and stid in ctrl.rungs[rung].reported
            )

        before = _in_reported()
        decisions = self.controller.on_report(stid, rung, score)
        absorbed = _in_reported() and not before
        if not absorbed:
            # duplicate delivery, a stale-rung zombie (pre-crash attempt),
            # or an already-decided trial: nothing to journal — writing it
            # would replay as a report the live controller never consumed
            return self._apply(decisions, reporting=None)
        self._counted.add((stid, rung))
        self._spent[stid] = self._spent.get(stid, 0) + resource
        if isinstance(tt, (int, float)) and resource > 0:
            self._last_time[stid] = (float(tt), resource)
        self._seq += 1
        # ``report: True`` marks a REAL execution report — exactly the
        # entries ``resume`` re-feeds (synthesized terminals carry none)
        a.update(score=score, seq=self._seq, report=True)
        result["asha"] = a
        return self._apply(decisions, reporting=(stid, result))

    def handle_metrics(self, msg: Dict[str, Any]) -> Step:
        """Rung-boundary score off a per-batch metrics message — the early
        feed (``Coordinator.on_metrics``). Deliberately restricted to the
        one decision that cannot wait for the result ingest: a
        ``stop_score`` hit, whose cancels must reach still-running batches
        NOW. Every other rung decision rides the result ingest so the
        journaled report order (the replay order) is exactly the order the
        controller consumed — the determinism the no-double-promotion
        guarantee rests on."""
        stid = msg.get("subtask_id")
        score = msg.get("intermediate_score")
        if stid not in self.specs or score is None:
            return Step()
        ctrl = self.controller._ctrl(stid)
        if (
            ctrl is None
            or ctrl.stop_score is None
            or not isinstance(score, (int, float))
            or score < ctrl.stop_score
        ):
            return Step()
        decisions = self.controller.on_report(
            stid, int(msg.get("rung", 0)), score
        )
        return self._apply(decisions, reporting=None)

    def handle_pruned_result(self, stid: str, result: Dict[str, Any]) -> Step:
        """A worker posted the terminal ``pruned`` result for a cancelled
        attempt. Usually the coordinator already synthesized the terminal
        state (the cancel was advisory) — then this is a duplicate and
        yields nothing."""
        if stid in self._finalized or stid not in self.specs:
            return Step()
        # a cancel the coordinator never decided (e.g. a stale executor
        # cancel entry surviving a restart) — adopt the worker's terminal
        # state through force_decide so the trial also LEAVES its rung
        # (closure math for the surviving peers must keep moving)
        decisions = self.controller.force_decide(stid, "pruned")
        step = self._apply(decisions, reporting=None)
        if stid not in self._finalized:
            self._finalized.add(stid)
            step.finished.append((stid, "pruned", result))
        return step

    def _curve_adjusted_score(
        self, result: Dict[str, Any], score: float
    ) -> float:
        """Curve-aware rung decisions (docs/SEARCH.md), opt-in via
        ``CS230_ASHA_CURVE=1``: tilt the reported score by the learning
        curve's last-k slope so a still-improving trial outranks a
        plateaued peer with the same boundary score. The tilt is bounded
        (±5% of |score|) and ADDITIVE, so ranking stays stable and the
        adjusted value is what gets journaled — replay re-feeds the same
        number and reproduces the same promotions."""
        import os

        if os.environ.get("CS230_ASHA_CURVE") != "1":
            return score
        curve = result.get("curve")
        if not isinstance(curve, dict):
            return score
        from ..obs.curves import last_k_slope

        rows, sign = None, 1.0
        if isinstance(curve.get("loss"), list) and curve["loss"]:
            rows, sign = curve["loss"], -1.0  # falling loss = improving
        elif isinstance(curve.get("score"), list) and curve["score"]:
            rows, sign = curve["score"], 1.0
        if not rows:
            return score
        tilts = []
        for row in rows:
            slope = last_k_slope(row)
            if slope is None:
                continue
            finite = [v for v in row if isinstance(v, (int, float))]
            ref = max(abs(finite[-1]), 1e-12) if finite else 1.0
            tilts.append(sign * slope / ref)
        if not tilts:
            return score
        tilt = max(-0.05, min(0.05, sum(tilts) / len(tilts)))
        return score + abs(score) * tilt

    def handle_diverged(
        self,
        stid: str,
        curve: Dict[str, Any],
        result: Optional[Dict[str, Any]] = None,
    ) -> Step:
        """Numerical-health watchdog verdict (docs/OBSERVABILITY.md
        "Trial telemetry plane"): the trial's learning curve went
        non-finite, or its tail blew past ``curve_divergence_factor`` ×
        its early-trace median. The trial leaves the ladder under the
        NON-failure terminal ``diverged`` — never quarantine, numerics
        (a bad hyperparameter draw) killed it, not infrastructure — and
        never climbs to a higher rung, which is where the device-second
        savings come from. ``result`` is the delivering rung result when
        the curve rode a completed result (nothing left to cancel);
        None when it rode the early metrics feed, in which case the
        attempt is still burning budget and gets a cooperative cancel
        (PR-12 path: the executor drops it at the next batch boundary)."""
        if stid in self._finalized or stid not in self.specs:
            return Step()
        rung = int(self.controller.trial_rung.get(stid, 0))
        if result is not None:
            self._last_result[stid] = dict(result)
            a0 = dict(result.get("asha") or {})
            tt = result.get("training_time")
            resource = int(a0.get("resource", 0) or 0)
            if isinstance(tt, (int, float)) and resource > 0:
                self._last_time[stid] = (float(tt), resource)
                if (stid, rung) not in self._counted:
                    self._counted.add((stid, rung))
                    self._spent[stid] = self._spent.get(stid, 0) + resource
        decisions = self.controller.force_decide(stid, "diverged")
        step = self._apply(decisions, reporting=None)
        self._finalized.add(stid)
        counter_inc("tpuml_trials_diverged_total")
        saved = self._device_seconds_saved(stid)
        if saved is not None and saved > 0:
            counter_inc(
                "tpuml_device_seconds_saved_total", saved, reason="diverge"
            )
        record_event(
            "trial.diverge", job_id=self.job_id, subtask_id=stid,
            rung=rung, nonfinite=bool((curve or {}).get("nonfinite")),
            device_seconds_saved=round(saved, 6) if saved else None,
        )
        if result is None:
            spec = self.specs[stid]
            attempt = int(spec.get("attempt") or 0)
            counter_inc("tpuml_trials_cancelled_total")
            record_event(
                "trial.cancel", job_id=self.job_id, subtask_id=stid,
                attempt=attempt, rung=rung, reason="diverged",
            )
            step.cancels.append(
                {"subtask_id": stid, "attempt": attempt,
                 "job_id": self.job_id}
            )
        res = self._synth_result(
            stid, "diverged",
            {"rung": rung, "reason": "diverged", "score": None},
        )
        step.finished.append((stid, "diverged", res))
        return step

    def handle_quarantine(self, stid: str, result: Dict[str, Any]) -> Step:
        """The retry layer gave up on a rung execution: the trial leaves
        the ladder as failed; its rung may close for the survivors."""
        decisions = self.controller.on_trial_failed(stid)
        self._seq += 1
        a = dict(result.get("asha") or self.specs[stid].get("asha") or {})
        a.update(failed=True, seq=self._seq)
        result["asha"] = a
        step = self._apply(decisions, reporting=None)
        if stid not in self._finalized:
            self._finalized.add(stid)
            step.finished.append((stid, "failed", result))
        return step

    # ---------------- decision application ----------------

    def _apply(
        self,
        decisions: List[Dict[str, Any]],
        reporting: Optional[Tuple[str, Dict[str, Any]]] = None,
    ) -> Step:
        step = Step()
        rep_stid, rep_result = reporting if reporting else (None, None)
        rep_handled = False
        for d in decisions:
            tid = d["trial_id"]
            if d["action"] == "promote":
                self._on_promote(d, step)
                if tid == rep_stid:
                    rep_handled = True
                    step.promoted.append((tid, rep_result))
            elif d["action"] == "prune":
                self._on_prune(d, step, rep_result if tid == rep_stid else None)
                if tid == rep_stid:
                    rep_handled = True
            elif d["action"] == "complete":
                if tid in self._finalized:
                    continue
                self._finalized.add(tid)
                res = rep_result if tid == rep_stid else self._synth_result(
                    tid, "completed", d
                )
                if tid == rep_stid:
                    rep_handled = True
                step.finished.append((tid, "completed", res))
        if rep_stid is not None and not rep_handled:
            # reported but paused (awaiting async promotion): store the
            # intermediate score, no terminal transition
            if rep_stid not in self._finalized:
                step.promoted.append((rep_stid, rep_result))
        return step

    def _on_promote(self, d: Dict[str, Any], step: Step) -> None:
        tid = d["trial_id"]
        counter_inc("tpuml_trials_promoted_total")
        record_event(
            "rung.promote", job_id=self.job_id, subtask_id=tid,
            rung=d["rung"], to_rung=d["to_rung"], resource=d["resource"],
            to_resource=d["to_resource"], score=d.get("score"),
            peers=d.get("peers"), bracket=d.get("bracket"),
        )
        if self._issued.get(tid, -1) >= d["to_rung"]:
            return  # dispatch already out (resume / duplicate feed)
        self._issued[tid] = d["to_rung"]
        # warm-start handoff (docs/SEARCH.md "Warm start"): the promoted
        # dispatch points at its own lower-rung fit so executors that can
        # inject weights skip the already-paid iterations; the artifact
        # plumbing (runtime/artifacts.py) is the serialization format
        warm = {
            "subtask_id": tid,
            "rung": d["rung"],
            "resource": d["resource"],
        }
        step.new_tasks.append(
            self._stamp(self.specs[tid], d["to_rung"], d["to_resource"],
                        warm_from=warm)
        )

    def _on_prune(self, d: Dict[str, Any], step: Step,
                  rep_result: Optional[Dict[str, Any]]) -> None:
        tid = d["trial_id"]
        if tid in self._finalized:
            return
        self._finalized.add(tid)
        counter_inc("tpuml_trials_pruned_total")
        saved = self._device_seconds_saved(tid)
        if saved is not None and saved > 0:
            counter_inc(
                "tpuml_device_seconds_saved_total", saved, reason="prune"
            )
        record_event(
            "rung.prune", job_id=self.job_id, subtask_id=tid,
            rung=d["rung"], resource=d["resource"], score=d.get("score"),
            peers=d.get("peers"), bracket=d.get("bracket"),
            reason=d.get("reason"),
            device_seconds_saved=round(saved, 6) if saved else None,
        )
        if rep_result is not None:
            res = dict(rep_result)
            res["status"] = "pruned"
            res["pruned"] = True
            res["prune_reason"] = d.get("reason")
        else:
            res = self._synth_result(tid, "pruned", d)
            # the trial may have an attempt in flight (stop_score, or a
            # straggler retry): cancel it cooperatively so the worker
            # stops at the next batch boundary instead of finishing the
            # doomed budget
            if self._issued.get(tid, 0) == self.controller.trial_rung.get(
                tid, 0
            ) and tid not in self._reported_current(tid):
                spec = self.specs[tid]
                attempt = int(spec.get("attempt") or 0)
                counter_inc("tpuml_trials_cancelled_total")
                record_event(
                    "trial.cancel", job_id=self.job_id, subtask_id=tid,
                    attempt=attempt, rung=d["rung"], reason=d.get("reason"),
                )
                step.cancels.append(
                    {"subtask_id": tid, "attempt": attempt,
                     "job_id": self.job_id}
                )
        step.finished.append((tid, "pruned", res))

    def _reported_current(self, tid: str) -> set:
        ctrl = self.controller._ctrl(tid)
        if ctrl is None:
            return set()
        k = ctrl.trial_rung.get(tid, 0)
        return set(ctrl.rungs[min(k, ctrl.top)].reported)

    def _device_seconds_saved(self, tid: str) -> Optional[float]:
        """Estimated device-seconds NOT spent because this trial stops
        short of the full budget, priced from its own measured per-unit
        cost (hardware-grounded, not a predictor guess)."""
        last = self._last_time.get(tid)
        a = self.specs[tid].get("asha") or {}
        max_r = int(a.get("max_resource", 0))
        spent = self._spent.get(tid, 0)
        if last is None or max_r <= spent:
            return None
        tt, r = last
        return (tt / max(r, 1)) * (max_r - spent)

    def _synth_result(self, tid: str, status: str, d: Dict[str, Any]) -> Dict[str, Any]:
        spec = self.specs[tid]
        self._seq += 1
        base = dict(self._last_result.get(tid) or {})
        score = d.get("score")
        if score is None:
            score = base.get("mean_cv_score")
        base.update({
            "subtask_id": tid,
            "job_id": spec.get("job_id"),
            "model_type": spec.get("model_type"),
            "parameters": spec.get("parameters"),
            "search_params": spec.get("search_params"),
            "status": status,
            "mean_cv_score": score,
            "attempt": int(spec.get("attempt") or 0),
            "asha": {
                **(spec.get("asha") or {}),
                "rung": d.get("rung"),
                "score": score,
                "seq": self._seq,
            },
        })
        if status == "pruned":
            base["pruned"] = True
            base["prune_reason"] = d.get("reason")
        elif status == "diverged":
            # watchdog terminal: flagged so the ranking/predictor paths
            # can skip it (its last measured score is numerically suspect)
            base["diverged"] = True
            base["diverge_reason"] = d.get("reason", "diverged")
        return base
