"""Search-space expansion: job -> per-trial subtasks.

Semantics parity with the reference's ``create_subtasks``
(``aws-prod/master/task_handler.py:156-252``):

- GridSearchCV  -> one subtask per ``sklearn.model_selection.ParameterGrid``
  combination, in ParameterGrid iteration order;
- RandomizedSearchCV -> ``ParameterSampler(param_distributions, n_iter,
  random_state)`` draws — using sklearn's own sampler so the drawn
  configurations (and hence ``best_params_``) are bit-identical to what
  sklearn itself would try;
- plain estimator -> a single subtask with ``base_estimator_params``.

Beyond the reference: ``search_type="asha" | "hyperband"`` expands an
adaptive-search job (docs/SEARCH.md). Each trial starts at its bracket's
rung 0 with the small resource budget in its parameters AND
``train_params`` ({rung, resource}); the spec's ``asha`` block carries the
full rung state the controller (runtime/search.py) promotes/prunes
against. Promotions later re-stamp the same subtask id with the larger
budget as a fresh attempt.

Subtask ids follow the reference's ``<job_id>-subtask-<i>`` scheme.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

#: model_details.search_type values owned by the adaptive-search
#: controller (runtime/search.py) rather than exhaustive fan-out
ADAPTIVE_SEARCH_TYPES = ("asha", "hyperband")


def create_subtasks(
    job_id: str,
    session_id: str,
    dataset_id: str,
    model_details: Dict[str, Any],
    train_params: Dict[str, Any],
) -> List[Dict[str, Any]]:
    from sklearn.model_selection import ParameterGrid, ParameterSampler

    model_type = model_details["model_type"]
    search_type = model_details.get("search_type")
    base_params = dict(model_details.get("base_estimator_params") or {})
    asha_blocks: List[Optional[Dict[str, Any]]]

    if search_type in ADAPTIVE_SEARCH_TYPES:
        from .search import plan_trials

        planned = plan_trials(model_details)
        combos = [combo for combo, _ in planned]
        asha_blocks = [block for _, block in planned]
    elif search_type == "GridSearchCV":
        grid = model_details.get("param_grid") or {}
        combos = list(ParameterGrid(grid))
        asha_blocks = [None] * len(combos)
    elif search_type == "RandomizedSearchCV":
        dists = model_details.get("param_distributions") or {}
        n_iter = int(model_details.get("n_iter", 10))
        random_state = model_details.get("random_state")
        combos = list(ParameterSampler(dists, n_iter=n_iter, random_state=random_state))
        asha_blocks = [None] * len(combos)
    else:
        combos = [{}]
        asha_blocks = [None]

    cv_params = dict(model_details.get("cv_params") or {})
    subtasks = []
    for i, combo in enumerate(combos):
        params = {**base_params, **combo}
        st = {
            "subtask_id": f"{job_id}-subtask-{i}",
            "job_id": job_id,
            "session_id": session_id,
            "dataset_id": dataset_id,
            "model_type": model_type,
            "parameters": params,
            "search_params": combo,
            "train_params": {**train_params, **cv_params},
            # fault-tolerance bookkeeping (docs/ROBUSTNESS.md): the
            # attempt id stamps every dispatched copy; reclaims and
            # retries bump it through the AttemptLedger. Journals from
            # before this field replay fine — readers default to 0.
            "attempt": 0,
        }
        block = asha_blocks[i]
        if block is not None:
            st["asha"] = dict(block)
            st["parameters"] = {
                **params, block["resource_param"]: block["resource"]
            }
            st["train_params"] = {
                **st["train_params"],
                "rung": block["rung"],
                "resource": block["resource"],
            }
        subtasks.append(st)
    return subtasks
