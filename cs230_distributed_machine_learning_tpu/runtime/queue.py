"""In-process topic bus: the Kafka replacement.

The reference moves every control/feedback message through four Kafka topics
(``tasks``/``train``/``result``/``metrics`` — ``docker-compose.yml:56``) with
worker routing via message keys. On a TPU pod the control plane lives in one
coordinator process per host, so the bus is a thread-safe in-process pub-sub:
``publish(topic, msg)`` fans out to every subscriber queue. Keyed routing
(scheduler -> one worker) is just a per-executor subscriber with a filter,
mirroring the reference's key==worker_id consumption (``worker.py:185-186``)
without broker round-trips. The same interface is what a DCN-backed
implementation plugs into for multi-host (runtime/agent.py).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, List, Optional


class TopicBus:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: Dict[str, List["Subscription"]] = {}

    def subscribe(
        self, topic: str, key_filter: Optional[Callable[[Any], bool]] = None
    ) -> "Subscription":
        sub = Subscription(self, topic, key_filter)
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)
        return sub

    def unsubscribe(self, sub: "Subscription") -> None:
        with self._lock:
            subs = self._subs.get(sub.topic, [])
            if sub in subs:
                subs.remove(sub)

    def publish(self, topic: str, message: Any, key: Any = None) -> int:
        delivered = 0
        with self._lock:
            subs = list(self._subs.get(topic, []))
        for sub in subs:
            if sub.key_filter is None or sub.key_filter(key):
                sub._q.put((key, message))
                delivered += 1
        return delivered

    def depth(self, topic: str) -> int:
        """Undelivered messages parked on the topic's subscriber queues —
        an overload signal (`GET /healthz` bus_depths): a deep `train`
        backlog means placements are outrunning the executors."""
        with self._lock:
            subs = list(self._subs.get(topic, []))
        return sum(len(s) for s in subs)

    def depths(self) -> Dict[str, int]:
        # one lock hold: a consistent cross-topic snapshot, not N+1
        # acquisitions contending with the publish path
        with self._lock:
            return {
                t: sum(len(s) for s in subs)
                for t, subs in self._subs.items()
            }


class Subscription:
    def __init__(self, bus: TopicBus, topic: str, key_filter) -> None:
        self._bus = bus
        self.topic = topic
        self.key_filter = key_filter
        self._q: "queue.Queue" = queue.Queue()

    def get(self, timeout: Optional[float] = None):
        """Returns (key, message); raises queue.Empty on timeout."""
        return self._q.get(timeout=timeout)

    def get_nowait(self):
        return self._q.get_nowait()

    def close(self) -> None:
        self._bus.unsubscribe(self)

    def __len__(self) -> int:
        return self._q.qsize()
