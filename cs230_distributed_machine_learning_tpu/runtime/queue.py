"""In-process topic bus: the Kafka replacement.

The reference moves every control/feedback message through four Kafka topics
(``tasks``/``train``/``result``/``metrics`` — ``docker-compose.yml:56``) with
worker routing via message keys. On a TPU pod the control plane lives in one
coordinator process per host, so the bus is a thread-safe in-process pub-sub:
``publish(topic, msg)`` fans out to every subscriber queue. Keyed routing
(scheduler -> one worker) is just a per-executor subscriber with a filter,
mirroring the reference's key==worker_id consumption (``worker.py:185-186``)
without broker round-trips. The same interface is what a DCN-backed
implementation plugs into for multi-host (runtime/agent.py).
"""

from __future__ import annotations

import heapq
import itertools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class TopicBus:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: Dict[str, List["Subscription"]] = {}

    def subscribe(
        self,
        topic: str,
        key_filter: Optional[Callable[[Any], bool]] = None,
        priority: bool = False,
        aging_s: Optional[float] = None,
    ) -> "Subscription":
        """``priority=True`` makes this subscription a QoS lane consumer
        (docs/ARCHITECTURE.md "QoS priority lanes"): delivery order is by
        the message's ``priority`` field (higher first; dict messages
        only, default lane 0), FIFO within a lane. The dispatch-side
        subscriptions (task ingress, per-worker train queues) opt in so a
        heavy tenant's backlog cannot starve a higher-priority session;
        result/metrics subscriptions stay plain FIFO.

        Strict priority alone starves: under a sustained high-lane flood
        a lane-0 message would wait forever. Priority subscriptions
        therefore age — a waiting message is promoted one lane per
        ``aging_s`` seconds of queue age (default: the ``qos_aging_s``
        scheduler config knob; <= 0 restores pure strict priority), so
        bounded starvation is the contract, not unbounded."""
        sub = Subscription(
            self, topic, key_filter, priority=priority, aging_s=aging_s
        )
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)
        return sub

    def unsubscribe(self, sub: "Subscription") -> None:
        with self._lock:
            subs = self._subs.get(sub.topic, [])
            if sub in subs:
                subs.remove(sub)

    def publish(self, topic: str, message: Any, key: Any = None) -> int:
        delivered = 0
        with self._lock:
            subs = list(self._subs.get(topic, []))
        for sub in subs:
            if sub.key_filter is None or sub.key_filter(key):
                sub._put(key, message)
                delivered += 1
        return delivered

    def depth(self, topic: str) -> int:
        """Undelivered messages parked on the topic's subscriber queues —
        an overload signal (`GET /healthz` bus_depths): a deep `train`
        backlog means placements are outrunning the executors."""
        with self._lock:
            subs = list(self._subs.get(topic, []))
        return sum(len(s) for s in subs)

    def depths(self) -> Dict[str, int]:
        # one lock hold: a consistent cross-topic snapshot, not N+1
        # acquisitions contending with the publish path
        with self._lock:
            return {
                t: sum(len(s) for s in subs)
                for t, subs in self._subs.items()
            }


class Subscription:
    def __init__(
        self, bus: TopicBus, topic: str, key_filter,
        priority: bool = False, aging_s: Optional[float] = None,
    ) -> None:
        self._bus = bus
        self.topic = topic
        self.key_filter = key_filter
        self._priority = priority
        if priority and aging_s is None:
            from ..utils.config import get_config

            aging_s = get_config().scheduler.qos_aging_s
        self._aging_s = float(aging_s or 0.0)
        #: throttle stamp for the lazy promotion sweep
        self._last_promote = 0.0
        #: tie-break sequence: FIFO within a priority lane (PriorityQueue
        #: would otherwise compare the message dicts and raise)
        self._seq = itertools.count()
        self._q: "queue.Queue" = (
            queue.PriorityQueue() if priority else queue.Queue()
        )

    @staticmethod
    def _message_priority(message: Any) -> int:
        if isinstance(message, dict):
            try:
                return int(message.get("priority") or 0)
            except (TypeError, ValueError):
                return 0
        return 0

    def _put(self, key: Any, message: Any) -> None:
        if self._priority:
            prio = self._message_priority(message)
            # entry: (-effective_lane, seq, enqueue_ts, base_lane, key,
            # message) — the consumer-facing get()s slice the last two
            self._q.put(
                (-prio, next(self._seq), time.time(), prio, key, message)
            )
        else:
            self._q.put((key, message))

    def _promote_aged(self) -> None:
        """QoS lane aging: raise a waiting entry's effective lane by one
        per ``aging_s`` seconds of queue age, so a sustained high-lane
        flood cannot starve low lanes forever (bounded starvation:
        worst-case wait ~= lane_gap x aging_s). Runs lazily at consume
        time, throttled — order only matters when entries are waiting,
        and every get() re-checks."""
        if not self._priority or self._aging_s <= 0:
            return
        now = time.time()
        if now - self._last_promote < min(1.0, self._aging_s / 4):
            return
        self._last_promote = now
        q = self._q
        with q.mutex:
            heap = q.queue
            changed = False
            for i, (neg_lane, seq, ts, base, key, msg) in enumerate(heap):
                eff = base + int((now - ts) // self._aging_s)
                if eff > -neg_lane:
                    heap[i] = (-eff, seq, ts, base, key, msg)
                    changed = True
            if changed:
                heapq.heapify(heap)

    def get(self, timeout: Optional[float] = None):
        """Returns (key, message); raises queue.Empty on timeout."""
        self._promote_aged()
        item = self._q.get(timeout=timeout)
        return item[-2:] if self._priority else item

    def get_nowait(self):
        self._promote_aged()
        item = self._q.get_nowait()
        return item[-2:] if self._priority else item

    def close(self) -> None:
        self._bus.unsubscribe(self)

    def __len__(self) -> int:
        return self._q.qsize()
