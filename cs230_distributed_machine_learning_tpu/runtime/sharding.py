"""Control-plane sharding: stable routing ids + per-shard config carving.

The single-coordinator control plane tops out around 8 jobs/s on the dev
box (benchmarks/loadtest_single_shard.json) because one Python process
owns every session, job, placement, and SSE stream. The sharded topology
(docs/ARCHITECTURE.md "Sharded control plane") splits that into:

- N **coordinator shards** — full Coordinator+ClusterRuntime processes,
  each owning the sessions that hash to it, its own ``JobStore`` journal
  (``<journal_dir>/shard-<k>``), its own placement engine, and its own
  worker partition;
- any number of stateless **front ends** (runtime/frontend.py) that route
  requests to shards using only the ids in the URL — no lookup table, no
  shared state, so front ends scale horizontally and restart freely.

Three id conventions make stateless routing possible:

- ``shard_of(session_id, n)`` — a stable content hash (sha1, NOT Python's
  salted ``hash()``) of the session id. Every front end, in every process,
  forever, maps a session to the same shard. Sessions are minted BY the
  front end so the hash and the owning shard agree by construction.
- **job ids carry a shard stamp**: the owning shard prefixes every job id
  with ``s<k>-`` (``stamp_job_id``), so job-only routes (``/trace/<jid>``,
  ``/cost/<jid>``, ``/explain/<jid>``) route without knowing the session.
  Client-minted job ids (idempotent resubmits) are stamped the same
  deterministic way, so the dedupe contract survives sharding.
- **worker ids carry the same stamp**: a shard's placement engine mints
  ``s<k>-worker-<n>`` ids, so every worker-plane route
  (``/next_tasks/<wid>``, ``/task_result/<wid>``, ...) routes by prefix.

Uuid4-style ids can never be mistaken for stamps (a uuid's first dash is
at position 8; the stamp's is at position 3), so unstamped single-shard
deployments parse as "no shard" and behave exactly as before.
"""

from __future__ import annotations

import hashlib
import re
import threading
import time
from typing import Optional

#: stamp grammar shared by job and worker ids: ``s<2-digit shard>-<rest>``
#: — two digits exactly, hence the MAX_SHARDS=100 bound (a 3-digit index
#: would mint ids the parser, and therefore every front end, rejects)
_STAMP_RE = re.compile(r"^s(\d{2})-")

#: hard bound implied by the 2-digit stamp grammar; enforced at mint
#: time and by the launch surfaces (server --num-shards, ShardFleet)
MAX_SHARDS = 100


def shard_of(session_id: str, n_shards: int) -> int:
    """Stable shard index for a session id. sha1-based so the mapping is
    identical across processes and Python restarts (``hash()`` is salted
    per process and would scatter a session over the fleet)."""
    if n_shards <= 1:
        return 0
    digest = hashlib.sha1(str(session_id).encode()).digest()
    return int.from_bytes(digest[:8], "big") % n_shards


def stamp_job_id(shard_id: int, job_id: str) -> str:
    """Prefix a job id with its OWNING shard. Deterministic — the same
    client-minted id always stamps to the same canonical id, so
    duplicate submits dedupe across retries exactly as unsharded ones —
    and idempotent only for this shard's own stamp: a client-minted id
    that happens to carry a FOREIGN-looking stamp (``s07-retrain`` as an
    idempotency key submitted to shard 2) is wrapped again, because
    passing it through would bind the job to a shard that never stored
    it and job-only routes would 404 instead of scatter-probing."""
    if not 0 <= int(shard_id) < MAX_SHARDS:
        raise ValueError(
            f"shard_id {shard_id} outside the stamp grammar "
            f"[0, {MAX_SHARDS})"
        )
    if id_shard(job_id) == int(shard_id):
        return job_id
    return f"s{shard_id:02d}-{job_id}"


def id_shard(stamped_id: str) -> Optional[int]:
    """Shard index carried by a stamped job/worker id, or None for
    unstamped (single-shard / client-minted) ids."""
    m = _STAMP_RE.match(str(stamped_id))
    return int(m.group(1)) if m else None


def worker_prefix(shard_id: int) -> str:
    """Worker-id prefix a shard's placement engine mints under, so every
    worker route is front-end-routable by the same stamp grammar."""
    if not 0 <= int(shard_id) < MAX_SHARDS:
        raise ValueError(
            f"shard_id {shard_id} outside the stamp grammar "
            f"[0, {MAX_SHARDS})"
        )
    return f"s{shard_id:02d}-"


def _carve(cap: int, n_shards: int) -> int:
    """One shard's share of a global admission cap: floor division so
    the shares sum to AT MOST the global cap (caps are upper bounds —
    rejecting a touch early under hash imbalance is the safe side;
    ceil would over-admit up to N-1 jobs past the configured total).
    Floored at 1 because 0 means "cap disabled" in the admission logic —
    so a cap smaller than the shard count admits up to N (one per
    shard), the closest enforceable bound."""
    return max(cap // n_shards, 1)


def shard_service_config(cfg, n_shards: int):
    """Per-shard copy of a FrameworkConfig with the GLOBAL admission caps
    carved into per-shard shares (``_carve``: floor, min 1), so the
    fleet-wide accepted load stays bounded by the configured totals (not
    cap x N — pinned in tests/test_sharding.py). The per-SESSION cap is
    untouched — a session lives entirely on one shard."""
    if n_shards <= 1:
        return cfg
    svc = cfg.service
    updates = {}
    if svc.max_inflight_jobs > 0:
        updates["max_inflight_jobs"] = _carve(
            svc.max_inflight_jobs, n_shards
        )
    if svc.admission_queue_watermark > 0:
        updates["admission_queue_watermark"] = _carve(
            svc.admission_queue_watermark, n_shards
        )
    if not updates:
        return cfg
    return cfg.merged({"service": updates})


class ForwardingCache:
    """Bounded-TTL job→shard redirect cache for migrated jobs.

    When a job migrates (docs/ROBUSTNESS.md "Shard rebalancing") the
    donor shard answers its job routes with ``409 {"status": "moved",
    "migrated_to": k}`` — the forwarding stamp. Without a cache every
    request for a migrated job pays a probe-then-redirect round trip;
    with it the front end proxies straight to the new owner until the
    entry expires. TTL-bounded (not permanent) because a stamp can go
    stale — the job may migrate again, or the fleet may be redeployed
    with a different shard count — and a bounded re-probe beats serving
    a wrong shard forever. Entry count is bounded so a scan over many
    dead job ids cannot grow front-end memory without limit."""

    def __init__(self, ttl_s: float = 300.0, max_entries: int = 4096):
        self.ttl_s = float(ttl_s)
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._entries: dict = {}  # job_id -> (shard, expires_at)

    def get(self, job_id: str) -> Optional[int]:
        """Cached destination shard for a job id, or None (unknown or
        expired — expired entries are dropped on read)."""
        with self._lock:
            hit = self._entries.get(job_id)
            if hit is None:
                return None
            shard, expires = hit
            if time.time() >= expires:
                self._entries.pop(job_id, None)
                return None
            return shard

    def put(self, job_id: str, shard: int) -> None:
        with self._lock:
            if job_id not in self._entries and len(self._entries) >= self.max_entries:
                now = time.time()
                expired = [j for j, (_, exp) in self._entries.items() if now >= exp]
                for j in expired:
                    self._entries.pop(j, None)
                if len(self._entries) >= self.max_entries:
                    # still full: evict the soonest-to-expire entry —
                    # O(n), but only on the overflow path
                    oldest = min(self._entries, key=lambda j: self._entries[j][1])
                    self._entries.pop(oldest, None)
            self._entries[job_id] = (int(shard), time.time() + self.ttl_s)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
