"""Attempt ledger: shared fault-tolerance accounting for subtask attempts.

The fault-tolerance layer (docs/ROBUSTNESS.md) runs the same subtask more
than once — lease reclaims off hung workers, bounded retries after
failures, speculative backup copies — so somebody has to own the facts
that make re-execution safe:

- the **attempt counter**: a monotonically increasing id stamped into
  every dispatched copy of a subtask. Result ingest dedups on it (a
  FAILED report from a superseded attempt must not burn retry budget) and
  the coordinator journals it (``JobStore.record_attempt``) so a replayed
  coordinator resumes with budgets intact.
- the **failure budget**: how many executions of this subtask ended in a
  terminal failure or an expired lease. At ``retry_max_attempts`` the
  subtask is quarantined instead of retried.
- **excluded-worker memory**: a subtask is never retried on the worker
  that just failed it or sat on its lease (mirroring excluded_runner
  semantics from self-hosted runner pools). Placement treats the list as
  a preference, not a hard gate — liveness beats affinity when only
  excluded workers remain.
- the **device-loss correlation**: a subtask that has killed
  ``poison_kill_threshold`` worker backends is poisoned and quarantined
  without further retries, so one bad trial cannot chew through the pool.

The ledger is shared by the :class:`~.scheduler.PlacementEngine` (lease
reclaims, dead-worker requeues, speculation) and the coordinator's
result-collection loop (failure retries, quarantine) via the owning
:class:`~.cluster.ClusterRuntime`. All methods are thread-safe; the
``on_attempt`` hook (installed by the coordinator) fires OUTSIDE the
internal lock so it may take the job-store lock freely.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional

from ..obs import record_event
from ..utils.logging import get_logger

logger = get_logger("tpuml.faults")

#: hook signature: (task_dict, AttemptEntry snapshot, reason) -> None
AttemptHook = Callable[[Dict[str, Any], "AttemptEntry", str], None]


@dataclasses.dataclass
class AttemptEntry:
    """Per-subtask fault accounting (see module docstring)."""

    subtask_id: str
    #: highest attempt id issued (0 = the initial dispatch)
    attempt: int = 0
    #: executions that ended in a terminal failure or a reclaimed lease
    failures: int = 0
    #: worker backends this subtask's executions have killed (DeviceLost)
    device_losses: int = 0
    #: workers that failed/hung this subtask — avoided on later attempts
    excluded: List[str] = dataclasses.field(default_factory=list)
    #: a speculative duplicate has been launched (at most one per subtask)
    speculated: bool = False
    #: a terminal result was accepted; later copies are dropped, not re-run
    done: bool = False


class AttemptLedger:
    def __init__(self, on_attempt: Optional[AttemptHook] = None):
        self._lock = threading.Lock()
        self._entries: Dict[str, AttemptEntry] = {}
        #: journaling hook, installed by the coordinator (store binding)
        self.on_attempt = on_attempt

    # ---------------- internals ----------------

    def _entry_locked(self, subtask_id: str, attempt: int = 0) -> AttemptEntry:
        e = self._entries.get(subtask_id)
        if e is None:
            e = AttemptEntry(subtask_id=subtask_id, attempt=int(attempt or 0))
            self._entries[subtask_id] = e
        return e

    @staticmethod
    def _snapshot(e: AttemptEntry) -> AttemptEntry:
        return dataclasses.replace(e, excluded=list(e.excluded))

    # ---------------- lifecycle ----------------

    def seed(self, spec: Dict[str, Any]) -> AttemptEntry:
        """Adopt a subtask spec (possibly replayed from a journal). Specs
        from journals that predate the attempt schema carry none of the
        fields — every read defaults to a zeroed budget."""
        stid = spec["subtask_id"]
        with self._lock:
            e = self._entry_locked(stid, spec.get("attempt", 0))
            e.attempt = max(e.attempt, int(spec.get("attempt", 0) or 0))
            e.failures = max(e.failures, int(spec.get("failures", 0) or 0))
            for w in spec.get("excluded_workers") or []:
                if w not in e.excluded:
                    e.excluded.append(w)
            return self._snapshot(e)

    def forget(self, subtask_ids) -> None:
        """Drop entries for a finished job (bounds the ledger's size)."""
        with self._lock:
            for stid in subtask_ids:
                self._entries.pop(stid, None)

    # ---------------- attempts ----------------

    def next_attempt(
        self,
        task: Dict[str, Any],
        exclude_worker: Optional[str] = None,
        reason: str = "retry",
        speculative: bool = False,
    ) -> AttemptEntry:
        """Issue the next attempt id for ``task`` and stamp it in place
        (``attempt``, ``excluded_workers``, and ``speculative`` when set).
        Fires the ``on_attempt`` journal hook."""
        stid = task["subtask_id"]
        with self._lock:
            e = self._entry_locked(stid, task.get("attempt", 0))
            e.attempt = max(e.attempt, int(task.get("attempt", 0) or 0)) + 1
            if exclude_worker and exclude_worker not in e.excluded:
                e.excluded.append(exclude_worker)
            if speculative:
                e.speculated = True
            task["attempt"] = e.attempt
            task["excluded_workers"] = list(e.excluded)
            if speculative:
                task["speculative"] = True
            snap = self._snapshot(e)
        # flight-recorder breadcrumb for EVERY re-dispatch stamp — lease
        # reclaims, failure retries, dead-worker requeues, speculation —
        # since every path funnels through here (docs/OBSERVABILITY.md
        # "Flight recorder")
        record_event(
            "attempt",
            job_id=task.get("job_id"), subtask_id=stid,
            attempt=snap.attempt, reason=reason,
            excluded_worker=exclude_worker, failures=snap.failures,
            excluded=list(snap.excluded), speculative=bool(speculative),
        )
        hook = self.on_attempt
        if hook is not None:
            try:
                hook(task, snap, reason)
            except Exception:  # noqa: BLE001 — journaling must not kill dispatch
                logger.exception("Attempt journal hook failed for %s", stid)
        return snap

    def record_failure(
        self, subtask_id: str, worker_id: Optional[str] = None
    ) -> AttemptEntry:
        """Count one failed execution against the subtask's budget and
        remember the worker it failed on."""
        with self._lock:
            e = self._entry_locked(subtask_id)
            e.failures += 1
            if worker_id and worker_id not in e.excluded:
                e.excluded.append(worker_id)
            return self._snapshot(e)

    def note_device_loss(self, subtask_id: str) -> int:
        """Count one killed worker backend against the subtask; returns the
        new kill count (the poison correlation input)."""
        with self._lock:
            e = self._entry_locked(subtask_id)
            e.device_losses += 1
            return e.device_losses

    # ---------------- queries ----------------

    def get(self, subtask_id: str) -> Optional[AttemptEntry]:
        with self._lock:
            e = self._entries.get(subtask_id)
            return self._snapshot(e) if e is not None else None

    def is_stale(self, subtask_id: str, attempt: int) -> bool:
        """True when ``attempt`` has been superseded by a newer one — its
        failure must not consume budget (the newer attempt owns the
        outcome now)."""
        with self._lock:
            e = self._entries.get(subtask_id)
            return e is not None and int(attempt or 0) < e.attempt

    def mark_done(self, subtask_id: str) -> None:
        """A terminal result was accepted: later lease expiries/requeues of
        surviving duplicate copies release bookkeeping without re-running."""
        with self._lock:
            self._entry_locked(subtask_id).done = True

    def is_done(self, subtask_id: str) -> bool:
        with self._lock:
            e = self._entries.get(subtask_id)
            return e is not None and e.done

    def was_speculated(self, subtask_id: str) -> bool:
        with self._lock:
            e = self._entries.get(subtask_id)
            return e is not None and e.speculated
