"""Shard-fleet process launcher: N coordinator shards + M front ends.

The operational glue of the sharded control plane (docs/ARCHITECTURE.md
"Sharded control plane"), shared by the load-test harness
(benchmarks/loadtest.py), the CI sharded smoke (deploy/ci.sh), and the
shard-kill chaos drill (tests/test_chaos.py). Each shard is a REAL
subprocess — its own interpreter, its own GIL, its own journal under
``<storage_root>/journal/shard-<k>`` — because sharding only buys
throughput across processes. Front ends are subprocesses too (they carry
the proxy CPU cost the benchmark must charge honestly).

``restart_shard(k)`` relaunches a (killed) shard on the SAME port and
journal directory — the hot-standby takeover path: journal replay +
``resume_inflight`` finish the dead process's jobs
(docs/ROBUSTNESS.md "Shard takeover").
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ShardFleet:
    def __init__(
        self,
        n_shards: int,
        *,
        storage_root: str,
        n_frontends: int = 1,
        local_executors: int = 1,
        journal: bool = True,
        env: Optional[Dict[str, str]] = None,
        log_dir: Optional[str] = None,
        host: str = "127.0.0.1",
    ):
        from .sharding import MAX_SHARDS

        self.n_shards = int(n_shards)
        if not 1 <= self.n_shards <= MAX_SHARDS:
            raise ValueError(
                f"n_shards must be in [1, {MAX_SHARDS}] (id stamp grammar)"
            )
        self.host = host
        self.local_executors = int(local_executors)
        self.journal = journal
        self.storage_root = storage_root
        self.log_dir = log_dir or storage_root
        os.makedirs(self.log_dir, exist_ok=True)
        # child processes must import the package no matter where the
        # PARENT runs from (an uninstalled checkout driven from a scratch
        # cwd): prepend the package's own root to PYTHONPATH
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        self.env = {
            **os.environ,
            "TPUML_STORAGE__ROOT": storage_root,
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
            "PYTHONPATH": pkg_root + (
                os.pathsep + os.environ["PYTHONPATH"]
                if os.environ.get("PYTHONPATH") else ""
            ),
            **(env or {}),
        }
        self.shard_ports = [free_port() for _ in range(self.n_shards)]
        self.frontend_ports = [free_port() for _ in range(int(n_frontends))]
        self.shard_procs: List[Optional[subprocess.Popen]] = [
            None
        ] * self.n_shards
        self.frontend_procs: List[subprocess.Popen] = []

    # ---------------- addresses ----------------

    @property
    def shard_urls(self) -> List[str]:
        return [f"http://{self.host}:{p}" for p in self.shard_ports]

    @property
    def frontend_urls(self) -> List[str]:
        return [f"http://{self.host}:{p}" for p in self.frontend_ports]

    # ---------------- lifecycle ----------------

    def _log(self, name: str):
        return open(os.path.join(self.log_dir, f"{name}.log"), "ab")

    def start_shard(self, k: int) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m",
            "cs230_distributed_machine_learning_tpu.runtime.server",
            "--host", self.host, "--port", str(self.shard_ports[k]),
            "--shard-index", str(k), "--num-shards", str(self.n_shards),
            "--local-executors", str(self.local_executors),
            # peer directory for cross-shard rebalancing: ports are
            # allocated in __init__ (stable across restart_shard), so
            # the list is correct even before peers are up. Inert unless
            # service.rebalance_enabled is set in the fleet env.
            "--peers", ",".join(self.shard_urls),
        ]
        if self.journal:
            cmd.append("--journal")
        proc = subprocess.Popen(
            cmd, env=self.env,
            stdout=self._log(f"shard-{k}"), stderr=subprocess.STDOUT,
        )
        self.shard_procs[k] = proc
        return proc

    def start(self, timeout_s: float = 300.0) -> "ShardFleet":
        for k in range(self.n_shards):
            self.start_shard(k)
        shard_list = ",".join(self.shard_urls)
        for i, port in enumerate(self.frontend_ports):
            self.frontend_procs.append(subprocess.Popen(
                [
                    sys.executable, "-m",
                    "cs230_distributed_machine_learning_tpu.runtime.frontend",
                    "--host", self.host, "--port", str(port),
                    "--shards", shard_list,
                ],
                env=self.env,
                stdout=self._log(f"frontend-{i}"), stderr=subprocess.STDOUT,
            ))
        self.wait_ready(timeout_s)
        return self

    def wait_ready(self, timeout_s: float = 300.0) -> None:
        import requests

        deadline = time.time() + timeout_s
        # front ends are ready exactly when every shard is (their /readyz
        # aggregates), so gating on them gates on the whole fleet
        for url in self.frontend_urls or self.shard_urls:
            while True:
                try:
                    if requests.get(f"{url}/readyz", timeout=2).status_code == 200:
                        break
                except Exception:  # noqa: BLE001 — still booting
                    pass
                if time.time() > deadline:
                    raise TimeoutError(f"fleet at {url} never became ready")
                time.sleep(0.3)

    def kill_shard(self, k: int, sig: int = signal.SIGKILL) -> None:
        proc = self.shard_procs[k]
        if proc is not None:
            proc.send_signal(sig)
            proc.wait(timeout=30)

    def restart_shard(self, k: int, timeout_s: float = 300.0) -> None:
        """Hot-standby takeover: a fresh process on the dead shard's port
        and journal dir; returns once its /readyz (journal replayed,
        in-flight jobs re-queued) answers 200."""
        import requests

        self.start_shard(k)
        url = self.shard_urls[k]
        deadline = time.time() + timeout_s
        while True:
            try:
                if requests.get(f"{url}/readyz", timeout=2).status_code == 200:
                    return
            except Exception:  # noqa: BLE001
                pass
            if time.time() > deadline:
                raise TimeoutError(f"shard {k} never recovered at {url}")
            time.sleep(0.3)

    def stop(self) -> None:
        procs = [p for p in self.shard_procs if p is not None]
        procs += self.frontend_procs
        for p in procs:
            try:
                p.send_signal(signal.SIGKILL)
            except Exception:  # noqa: BLE001 — already dead
                pass
        for p in procs:
            try:
                p.wait(timeout=30)
            except Exception:  # noqa: BLE001
                pass

    def __enter__(self) -> "ShardFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
