from .coordinator import Coordinator
from .store import JobStore

__all__ = ["Coordinator", "JobStore"]
