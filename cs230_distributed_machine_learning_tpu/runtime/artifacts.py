"""Model artifact store: save/load fitted kernels, and export to sklearn.

Parity target: the reference pickles each fitted sklearn estimator to
``./models/<subtask_id>_model.pkl`` and serves the best one via
``/download_model`` (``worker.py:352-356``, ``master.py:270-291``). Here the
artifact is a plain dict of numpy arrays + config (no arbitrary-code
pickle), written with ``pickle`` for wire parity but loadable into either
our kernels (``predict_with_artifact``) or a real state-injected sklearn
estimator for EVERY model family (``to_sklearn``, runtime/sklearn_export.py)
for users migrating off the reference.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

from ..utils.config import get_config


def artifact_path(subtask_id: str, models_dir: Optional[str] = None) -> str:
    models_dir = models_dir or get_config().storage.models_dir
    os.makedirs(models_dir, exist_ok=True)
    return os.path.join(models_dir, f"{subtask_id}_model.pkl")


def save_artifact(subtask_id: str, artifact: Dict[str, Any], models_dir: Optional[str] = None) -> str:
    path = artifact_path(subtask_id, models_dir)
    with open(path, "wb") as f:
        pickle.dump(artifact, f)
    return path


def load_artifact(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return pickle.load(f)


def predict_with_artifact(artifact: Dict[str, Any], X):
    """Run inference with a stored artifact using the owning kernel."""
    from ..models.registry import get_kernel

    kernel = get_kernel(artifact["model_type"])
    import jax.numpy as jnp

    return kernel.predict(
        jnp_tree(artifact["fitted_params"]), jnp.asarray(X), artifact["static"]
    )


def jnp_tree(tree):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, tree)


def to_sklearn(artifact: Dict[str, Any]):
    """Construct the equivalent fitted sklearn estimator (state injection;
    see runtime/sklearn_export.py for the per-family contracts)."""
    from .sklearn_export import to_sklearn as _to_sklearn

    return _to_sklearn(artifact)
