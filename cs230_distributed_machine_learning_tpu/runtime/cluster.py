"""Cluster runtime: scheduler-mediated dispatch to a pool of executors.

This is the process topology of the reference system — master -> Kafka
``tasks`` -> scheduler -> Kafka ``train`` (keyed by worker) -> workers ->
``result``/``metrics`` back (SURVEY.md §1) — collapsed onto the in-process
TopicBus with the same message flow and the same failure semantics:

  coordinator.submit -> bus:"tasks" -> PlacementEngine.place ->
  bus:"train"(key=worker_id) -> ExecutorWorker loop -> run on mesh ->
  bus:"result" (coordinator collects), bus:"metrics" (engine feedback)

Executors heartbeat the engine; killing one (crash simulation) triggers the
dead-worker sweep and requeue onto survivors, mirroring the reference's
elastic recovery (scheduler_service.py:205-247). A worker drains its queue
and hands the whole batch to the vmapped trial engine — scheduling stays
dynamic at worker granularity while execution stays SPMD within a batch
(the two-level resolution of SURVEY.md §7's "scheduling vs SPMD tension").
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional

from ..obs import (
    counter_inc,
    gauge_set,
    observe,
    process_token,
    record_batch_device_seconds,
    record_event,
)
from ..utils.config import get_config
from ..utils.logging import get_logger
from .executor import DeviceLostError, LocalExecutor
from .faults import AttemptLedger
from .queue import TopicBus
from .scheduler import TOPIC_TASKS, TOPIC_TRAIN, PlacementEngine
from .store import SUBTASK_TERMINAL_STATUSES

logger = get_logger("tpuml.cluster")

TOPIC_RESULT = "result"
TOPIC_METRICS = "metrics"


class ExecutorWorker:
    """Reference-worker lifecycle (worker.py:90-286) around a mesh executor:
    subscribe -> heartbeat thread -> keyed consume loop -> emit result+metrics."""

    def __init__(self, cluster: "ClusterRuntime", executor: LocalExecutor, worker_id: str):
        self.cluster = cluster
        self.executor = executor
        self.worker_id = worker_id
        self._stop = threading.Event()
        # priority=True: the worker drains its keyed queue highest QoS
        # lane first (docs/ARCHITECTURE.md "QoS priority lanes")
        self._sub = cluster.bus.subscribe(
            TOPIC_TRAIN, key_filter=lambda k: k == worker_id, priority=True
        )
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        for target in (self._run_loop, self._heartbeat_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, graceful: bool = True) -> None:
        self._stop.set()
        self._sub.close()
        if graceful:
            self.cluster.engine.unsubscribe(self.worker_id)

    def kill(self) -> None:
        """Crash simulation: loops stop, no unsubscribe — the engine only
        finds out via missed heartbeats."""
        self._stop.set()
        self._sub.close()

    # ---------------- loops ----------------

    def _heartbeat_loop(self) -> None:
        interval = get_config().scheduler.heartbeat_interval_s
        while not self._stop.wait(interval):
            self.cluster.engine.heartbeat(self.worker_id)

    def _run_loop(self) -> None:
        max_batch = self.executor.max_trials_per_batch
        while not self._stop.is_set():
            try:
                _, first = self._sub.get(timeout=0.2)
            except _queue.Empty:
                continue
            batch = [first]
            while len(batch) < max_batch:
                try:
                    batch.append(self._sub.get_nowait()[1])
                except _queue.Empty:
                    break
            if self._stop.is_set():
                # crash between dequeue and execution: tasks are lost here and
                # recovered by the dead-worker requeue (at-least-once)
                return
            def on_result(stid, status, result):
                # in-process workers bypass push_result, so the engine's
                # per-worker failure accounting hooks here. worker_id rides
                # the result so the coordinator's retry path can exclude
                # the failing worker; a failed attempt emits no metrics
                # message, so the engine's books are released here instead.
                result = {**(result or {}), "worker_id": self.worker_id}
                failed = status == "failed"
                self.cluster.engine.record_outcome(self.worker_id, not failed)
                if failed or status == "pruned":
                    # neither emits a timed metrics message: release the
                    # engine's books here (pruned = cooperative cancel,
                    # docs/SEARCH.md — a non-failure terminal)
                    self.cluster.engine.release_task(self.worker_id, stid)
                self.cluster.bus.publish(TOPIC_RESULT, result, key=stid)

            try:
                self.executor.run_subtasks(
                    batch,
                    on_result=on_result,
                    on_metrics=lambda msg: self.cluster.bus.publish(
                        TOPIC_METRICS, {**msg, "worker_id": self.worker_id}, key=msg.get("subtask_id")
                    ),
                )
            except DeviceLostError:
                # containment: this worker's backend is gone for good — leave
                # the pool like a crashed worker (no unsubscribe), so the
                # dead-worker sweep requeues its queued tasks onto survivors.
                # The engine's queue still holds this batch (metrics feedback
                # never fired), so nothing is lost. If this was the last
                # executor, the job surfaces the stall via the coordinator's
                # progress-aware timeout.
                logger.exception(
                    "Worker %s lost its device backend; leaving the pool",
                    self.worker_id,
                )
                # poison correlation first: a subtask on its Nth killed
                # backend must be quarantined, not requeued to kill N+1
                self.cluster.note_device_loss(self.worker_id, batch)
                self.cluster.kill_executor(self.worker_id)
                return
            except Exception:  # noqa: BLE001
                logger.exception("Worker %s batch execution failed", self.worker_id)


class ClusterRuntime:
    def __init__(self, *, cache=None, predictor=None, shard_id=None):
        self.bus = TopicBus()
        #: shared attempt/exclusion/poison accounting: the engine bumps it
        #: on lease reclaims/requeues/speculation, the coordinator on
        #: failure retries; one ledger keeps attempt ids monotonic
        self.ledger = AttemptLedger()
        #: shard identity (sharded control plane, runtime/sharding.py):
        #: stamps minted worker ids so front ends route worker-plane
        #: traffic statelessly; None = the unsharded single-coordinator
        #: topology, ids unchanged
        self.shard_id = shard_id
        prefix = ""
        if shard_id is not None:
            from .sharding import worker_prefix

            prefix = worker_prefix(int(shard_id))
        self.engine = PlacementEngine(
            bus=self.bus, predictor=predictor, ledger=self.ledger,
            worker_prefix=prefix,
        )
        self.engine.on_evict = self._on_worker_evicted
        self.cache = cache
        self.workers: Dict[str, ExecutorWorker] = {}
        self._remote_subs: Dict[str, Any] = {}
        #: cooperative-cancel registry (docs/SEARCH.md): subtask_id ->
        #: {subtask_id, attempt, job_id}. Served on every /next_tasks
        #: long-poll (the agents' cancel list) and pushed straight into
        #: in-process workers' executors; entries clear when the
        #: subtask's terminal result lands or its job's loop ends.
        self._cancel_lock = threading.Lock()
        self._cancels: Dict[str, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        for target in (self._ingress_loop, self._metrics_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        self.engine.start_monitor()

    # ---------------- executor pool ----------------

    def add_executor(
        self, mesh=None, mem_capacity_mb: Optional[float] = None, executor: Optional[LocalExecutor] = None
    ) -> str:
        from ..parallel.mesh import mesh_info

        if mesh is None and executor is not None:
            mesh = executor.mesh
        n_devices, mesh_shape = mesh_info(mesh)
        wid = self.engine.subscribe(
            mem_capacity_mb=mem_capacity_mb,
            n_devices=n_devices, mesh_shape=mesh_shape,
        )
        executor = executor or LocalExecutor(executor_id=wid, mesh=mesh, cache=self.cache)
        executor.executor_id = wid
        worker = ExecutorWorker(self, executor, wid)
        self.workers[wid] = worker
        worker.start()
        return wid

    def remove_executor(self, worker_id: str, graceful: bool = True) -> None:
        worker = self.workers.pop(worker_id, None)
        if worker is not None:
            worker.stop(graceful=graceful)

    def kill_executor(self, worker_id: str) -> None:
        """Fault injection: crash a worker without unsubscribe."""
        worker = self.workers.pop(worker_id, None)
        if worker is not None:
            worker.kill()

    def _on_worker_evicted(self, worker_id: str) -> None:
        """Breaker eviction teardown: stop the in-process worker threads
        and/or close the remote long-poll subscription — the engine already
        removed the WorkerState and requeues the tasks."""
        worker = self.workers.pop(worker_id, None)
        if worker is not None:
            worker.kill()
        sub = self._remote_subs.pop(worker_id, None)
        if sub is not None:
            sub.close()

    def note_device_loss(self, worker_id: str, tasks: List[Dict[str, Any]]) -> None:
        """Correlate a backend loss with the subtasks that rode the dying
        batch. A subtask that has now killed ``poison_kill_threshold``
        worker backends is poisoned: release it from the dying worker's
        queue (so the dead-worker sweep does NOT requeue it to kill a
        third) and publish a synthetic failed result the coordinator
        quarantines on ingest. Below the threshold, nothing happens here —
        the task stays queued for the normal sweep requeue."""
        threshold = get_config().scheduler.poison_kill_threshold
        for task in tasks:
            stid = task.get("subtask_id")
            if not stid:
                continue
            kills = self.ledger.note_device_loss(stid)
            if kills < threshold:
                continue
            logger.error(
                "Subtask %s killed %d worker backends; poisoning it instead "
                "of requeueing", stid, kills,
            )
            record_event(
                "poison", job_id=task.get("job_id"), subtask_id=stid,
                worker_id=worker_id,
                attempt=int(task.get("attempt") or 0),
                device_losses=kills, threshold=threshold,
            )
            self.engine.release_task(worker_id, stid)
            self.bus.publish(
                TOPIC_RESULT,
                {
                    "subtask_id": stid,
                    "job_id": task.get("job_id"),
                    "model_type": task.get("model_type"),
                    "parameters": task.get("parameters"),
                    "status": "failed",
                    "error": f"subtask killed {kills} worker backends "
                             "(device loss correlation)",
                    "error_kind": "device_lost",
                    "attempt": int(task.get("attempt") or 0),
                    "worker_id": worker_id,
                },
                key=stid,
            )

    # ---------------- cooperative cancel (docs/SEARCH.md) ----------------

    def cancel_subtask(
        self, subtask_id: str, attempt: int = 0,
        job_id: Optional[str] = None,
    ) -> None:
        """Mark a subtask's current attempt cancelled. Remote agents pick
        it up from their next poll's ``cancel`` list; in-process workers'
        executors are updated immediately. The executor stops the trial at
        the next batch boundary and posts a terminal ``pruned`` result; a
        dead/ignoring worker is covered by the lease reclaim + the
        ledger's ``is_done`` requeue drop."""
        entry = {
            "subtask_id": subtask_id,
            "attempt": int(attempt or 0),
            "job_id": job_id,
        }
        with self._cancel_lock:
            self._cancels[subtask_id] = entry
        counter_inc("tpuml_cancels_issued_total")
        for worker in list(self.workers.values()):
            worker.executor.cancel([entry])

    def cancel_list(self) -> List[Dict[str, Any]]:
        with self._cancel_lock:
            return list(self._cancels.values())

    def clear_cancels(self, subtask_ids) -> None:
        with self._cancel_lock:
            for stid in subtask_ids:
                self._cancels.pop(stid, None)

    # ---------------- remote agents (DCN control plane) ----------------
    # A remote WorkerAgent (runtime/agent.py) on another host registers here
    # over REST and long-polls its keyed train queue — the HTTP analog of the
    # reference worker's /subscribe + keyed Kafka consumption
    # (worker.py:90-112, 185-186).

    def register_remote(
        self,
        mem_capacity_mb: Optional[float] = None,
        n_devices: Optional[int] = None,
        mesh_shape: Optional[Dict[str, int]] = None,
    ) -> str:
        wid = self.engine.subscribe(
            mem_capacity_mb=mem_capacity_mb,
            n_devices=n_devices, mesh_shape=mesh_shape,
        )
        self._remote_subs[wid] = self.bus.subscribe(
            TOPIC_TRAIN, key_filter=lambda k, w=wid: k == w, priority=True
        )
        return wid

    def unregister_remote(self, worker_id: str) -> None:
        sub = self._remote_subs.pop(worker_id, None)
        if sub is not None:
            sub.close()
        self.engine.unsubscribe(worker_id)

    def pull_tasks(self, worker_id: str, max_n: int = 64, timeout_s: float = 10.0) -> List[Dict[str, Any]]:
        """Long-poll the worker's train queue: blocks up to timeout for the
        first task, then drains without blocking."""
        sub = self._remote_subs.get(worker_id)
        if sub is None:
            raise KeyError(f"Unknown remote worker {worker_id}")
        counter_inc("tpuml_agent_polls_total")
        tasks: List[Dict[str, Any]] = []
        try:
            tasks.append(sub.get(timeout=timeout_s)[1])
        except _queue.Empty:
            return tasks
        while len(tasks) < max_n:
            try:
                tasks.append(sub.get_nowait()[1])
            except _queue.Empty:
                break
        if tasks:
            counter_inc("tpuml_agent_tasks_pulled_total", len(tasks))
        return tasks

    def push_result(self, worker_id: str, result: Dict[str, Any]) -> None:
        counter_inc("tpuml_agent_acks_total")
        result = dict(result or {})
        # wire-only dedup stamp (agent._post_result): popped so it never
        # reaches the job store / client-visible results
        src_pid = result.pop("obs_pid", None)
        ok = result.get("status") != "failed"
        result.setdefault("worker_id", worker_id)
        if worker_id not in self.engine.workers:
            # a worker this coordinator never registered — typically an
            # agent flushing its local result buffer across a coordinator
            # restart, still posting under the pre-crash worker id
            # (docs/ROBUSTNESS.md "Coordinator recovery"). The result IS
            # ingested (at-least-once; the job-side attempt dedup owns
            # duplicates) — only the per-worker books are unknown.
            counter_inc("tpuml_agent_orphan_results_total")
            record_event(
                "result.orphan", job_id=result.get("job_id"),
                subtask_id=result.get("subtask_id"), worker_id=worker_id,
                attempt=int(result.get("attempt") or 0),
            )
        self.engine.record_outcome(worker_id, ok)
        if result.get("status") in ("failed", "pruned", "diverged"):
            # failed attempts emit no metrics message, and a pruned (or
            # watchdog-diverged) attempt's release message may race the
            # result: release the engine's books (queue entry, load,
            # lease) here (idempotent — release_task no-ops once the
            # books are clear)
            self.engine.release_task(worker_id, result.get("subtask_id"))
        if result.get("status") in SUBTASK_TERMINAL_STATUSES:
            self.clear_cancels([result.get("subtask_id")])
        # count the outcome coordinator-side so /metrics/prom sees subtasks
        # executed in other processes — but not twice for an agent sharing
        # THIS process (its executor already counted into the shared
        # registry; same contract as push_metrics' obs_pid skip)
        if src_pid != process_token():
            counter_inc(
                "tpuml_subtasks_completed_total"
                if ok
                else "tpuml_subtasks_failed_total"
            )
        self.bus.publish(TOPIC_RESULT, result, key=result.get("subtask_id"))

    def push_metrics(self, worker_id: str, msg: Dict[str, Any]) -> None:
        # remote executor phase timers + cost figures -> the coordinator's
        # registry. Agents' registries live in their own processes with no
        # exposition endpoint, so the batch totals ride the metrics message
        # instead; batch_primary marks exactly one message per batch, and
        # obs_pid marks which process already observed it locally — an
        # agent sharing THIS process (the test topology) is skipped here,
        # so nothing double-observes into the shared registry
        # (docs/OBSERVABILITY.md; pinned by tests/test_cost_health.py).
        if msg.get("batch_primary") and msg.get("obs_pid") != process_token():
            for field, metric in (
                ("batch_compile_s", "tpuml_executor_compile_seconds"),
                ("batch_stage_s", "tpuml_executor_stage_seconds"),
                ("batch_dispatch_s", "tpuml_executor_dispatch_seconds"),
                ("batch_fetch_s", "tpuml_executor_fetch_seconds"),
            ):
                v = msg.get(field)
                if isinstance(v, (int, float)):
                    observe(metric, float(v))
            # device-time attribution for remote batches: the same phase
            # totals feed tpuml_executor_device_seconds_total{phase=}, so
            # one scrape attributes the whole fleet's device time
            phase = {
                f: msg.get(f)
                for f in ("batch_compile_s", "batch_stage_s",
                          "batch_dispatch_s", "batch_fetch_s")
            }
            if all(isinstance(v, (int, float)) for v in phase.values()):
                record_batch_device_seconds(
                    phase["batch_compile_s"], phase["batch_stage_s"],
                    phase["batch_dispatch_s"], phase["batch_fetch_s"],
                )
            algo = str(msg.get("algo") or "unknown")
            flops = msg.get("batch_model_flops")
            if flops is None:
                flops = msg.get("batch_xla_flops")
            if isinstance(flops, (int, float)):
                counter_inc(
                    "tpuml_executor_flops_total", float(flops), model=algo
                )
            nbytes = msg.get("batch_bytes_accessed")
            if isinstance(nbytes, (int, float)):
                counter_inc(
                    "tpuml_executor_bytes_total", float(nbytes), model=algo
                )
            mfu_v = msg.get("batch_mfu")
            if isinstance(mfu_v, (int, float)):
                gauge_set("tpuml_executor_mfu", float(mfu_v), model=algo)
        self.bus.publish(
            TOPIC_METRICS, {**msg, "worker_id": worker_id}, key=msg.get("subtask_id")
        )

    # ---------------- job submission ----------------

    def submit(self, subtasks: List[Dict[str, Any]], metadata: Optional[Dict[str, Any]] = None) -> None:
        for st in subtasks:
            task = dict(st)
            if metadata:
                task["metadata"] = metadata
            task["mem_estimate_mb"] = self._mem_estimate(task)
            self.bus.publish(TOPIC_TASKS, task)

    @staticmethod
    def _mem_estimate(task: Dict[str, Any]) -> float:
        try:
            from ..models.registry import get_kernel

            meta = task.get("metadata") or {}
            kernel = get_kernel(task["model_type"])
            return kernel.memory_estimate_mb(
                int(meta.get("n_rows", 1000) or 1000),
                int(meta.get("n_cols", 10) or 10),
                {},
            )
        except Exception:  # noqa: BLE001
            return 1.0

    # ---------------- internal loops ----------------

    def _ingress_loop(self) -> None:
        # priority=True: under a placement backlog, higher-QoS sessions'
        # subtasks reach the engine first (retries/requeues keep the
        # priority their spec was stamped with, so the lane survives the
        # whole retry-budget machinery)
        sub = self.bus.subscribe(TOPIC_TASKS, priority=True)
        while not self._stop.is_set():
            try:
                _, task = sub.get(timeout=0.2)
            except _queue.Empty:
                continue
            wid = self.engine.place(task)
            if wid is None:
                # no executors yet: park and retry
                time.sleep(0.1)
                self.bus.publish(TOPIC_TASKS, task)

    def _metrics_loop(self) -> None:
        sub = self.bus.subscribe(TOPIC_METRICS)
        while not self._stop.is_set():
            try:
                _, msg = sub.get(timeout=0.2)
            except _queue.Empty:
                continue
            try:
                self.engine.on_metrics(msg)
            except Exception:  # noqa: BLE001
                logger.exception("Metrics feedback failed")

    def shutdown(self) -> None:
        for wid in list(self.workers):
            self.remove_executor(wid)
        self._stop.set()
        self.engine.stop_monitor()
