"""In-memory, journaled job/session state store.

Replaces the reference's Redis instance and key schema
(``aws-prod/master/redis_util.py:44-74``: ``active_sessions`` set,
``active_sessions:<sid>:jobs:<jid>`` hash with total/completed/status,
per-subtask JSON blobs, metadata hashes) with a coordinator-local store:
plain dicts guarded by one lock, plus an append-only JSONL journal so a
restarted coordinator can resume job state (a capability the reference
lacks — SURVEY.md §5.4).

Status semantics preserved from the reference (``task_handler.py:71-123``):
``status`` is "pending" until the first subtask completes, then a
percentage string, then "completed"; failed subtasks count toward
completion (fixing the reference's stuck-job bug at ``task_handler.py:91``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ..utils.serialization import json_safe

#: job statuses past which no further transitions happen.
#: ``completed_with_failures`` is the quarantine contract
#: (docs/ROBUSTNESS.md): the job finished with partial results plus a
#: structured ``failed_subtasks`` report instead of stalling on a
#: poisoned subtask.
TERMINAL_STATUSES = ("completed", "failed", "completed_with_failures")

#: per-SUBTASK terminal statuses. ``pruned`` is the adaptive-search
#: contract (docs/SEARCH.md): a non-failure terminal state for a trial the
#: rung controller stopped early — it counts toward job completion like
#: ``completed`` but never toward the failure report. ``diverged`` is the
#: numerical-health watchdog's verdict (docs/OBSERVABILITY.md "Trial
#: telemetry plane"): the trial's learning curve went non-finite or blew
#: past the divergence threshold — terminal like ``pruned``, never a
#: failure and never quarantine.
SUBTASK_TERMINAL_STATUSES = ("completed", "failed", "pruned", "diverged")


def _final_status(result) -> str:
    """Derive the terminal job status from a finalize payload."""
    result = result or {}
    if result.get("status") == "failed":
        return "failed"
    if result.get("failed_subtasks"):
        return "completed_with_failures"
    return "completed"


class JobStore:
    def __init__(self, journal_dir: Optional[str] = None):
        self._lock = threading.RLock()
        self._sessions: Dict[str, Dict[str, Any]] = {}
        self._done_events: Dict[tuple, threading.Event] = {}
        self._journal_path = None
        #: replay forensics, read by the coordinator's recovery metrics
        #: (tpuml_recovery_replayed_ops_total{op=}) and GET /healthz
        self.replay_ops: Dict[str, int] = {}
        self.replay_skipped = 0
        self.replay_seconds = 0.0
        #: highest journaled mesh generation (elastic trial fabric,
        #: docs/ARCHITECTURE.md): replayed at boot so a recovered
        #: coordinator's placement engine resumes its generation counter
        #: monotonically instead of restarting at 0
        self.mesh_generation = 0
        #: forwarding stamps (docs/ROBUSTNESS.md "Shard rebalancing"):
        #: job_id -> destination shard for jobs this store migrated OUT.
        #: The donor keeps the record but stops resuming/serving it —
        #: job routes answer 409 moved so front ends redirect.
        self._migrated: Dict[str, int] = {}
        #: job ids adopted from a donor shard (migrate_in). These keep
        #: the DONOR's stamp, so canonical_job_id must pass them through
        #: instead of re-wrapping into an id this shard never stored.
        self._adopted: set = set()
        #: donor-side steal tombstones: subtask_id -> grant info for
        #: queued subtasks handed to a thief shard. While a tombstone is
        #: live the donor never re-dispatches the subtask; the entry is
        #: cleared by the subtask's next result (any status) or reclaimed
        #: after ``steal_lease_s`` if the thief went dark.
        self.steal_tombstones: Dict[str, Dict[str, Any]] = {}
        #: ``curve`` journal entries seen during replay, drained once by
        #: the coordinator into its CurveStore (trial telemetry plane,
        #: docs/OBSERVABILITY.md) so /curves history survives a restart
        self._replayed_curves: List[Dict[str, Any]] = []
        if journal_dir:
            os.makedirs(journal_dir, exist_ok=True)
            self._journal_path = os.path.join(journal_dir, "jobs.jsonl")
            self._replay()

    # ---------------- sessions ----------------

    def create_session(
        self,
        session_id: Optional[str] = None,
        priority: int = 0,
    ) -> str:
        """Create (or idempotently re-create) a session. ``session_id`` is
        accepted from the caller so a sharded front end can mint the id
        itself and route by ``shard_of(session_id)`` (runtime/sharding.py).
        ``priority`` is the session's QoS lane (docs/ARCHITECTURE.md
        "QoS priority lanes"): higher dispatches first; jobs inherit it
        unless their payload overrides."""
        sid = session_id or str(uuid.uuid4())
        with self._lock:
            self._sessions.setdefault(
                sid,
                {"created_at": time.time(), "jobs": {},
                 "priority": int(priority)},
            )
        self._journal(
            {"op": "create_session", "sid": sid, "priority": int(priority)}
        )
        return sid

    def session_priority(self, sid: str) -> int:
        with self._lock:
            sess = self._sessions.get(sid) or {}
            return int(sess.get("priority", 0) or 0)

    def has_session(self, sid: str) -> bool:
        with self._lock:
            return sid in self._sessions

    def sessions(self) -> List[str]:
        with self._lock:
            return list(self._sessions)

    def jobs_overview(self) -> List[Dict[str, Any]]:
        """Flat per-job summaries across all sessions — the observability
        feed for the dashboard (the reference exposed queue/topic state
        only through kafka-ui, docker-compose.yml:69-84; here job state IS
        the queue state)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for sid, sess in self._sessions.items():
                for jid, job in sess["jobs"].items():
                    payload = job.get("payload") or {}
                    out.append(
                        {
                            "session_id": sid,
                            "job_id": jid,
                            "status": job.get("status"),
                            "model_type": (payload.get("model_details") or {}).get(
                                "model_type"
                            ),
                            "dataset_id": payload.get("dataset_id"),
                            "total_subtasks": job.get("total_subtasks"),
                            "completed_subtasks": job.get("completed_subtasks"),
                            "failed_subtasks": job.get("failed_subtasks"),
                            "pruned_subtasks": job.get("pruned_subtasks", 0),
                            "diverged_subtasks": job.get(
                                "diverged_subtasks", 0
                            ),
                            "created_at": job.get("created_at"),
                            "completion_time": job.get("completion_time"),
                            # rebalancing provenance: where the job went
                            # (donor view) / came from (recipient view)
                            "migrated_to": job.get("migrated_to"),
                            "migrated_from": job.get("migrated_from"),
                        }
                    )
        out.sort(key=lambda j: j.get("created_at") or 0, reverse=True)
        return out

    def hint_shape(self, sid: str, job_id: str) -> Dict[str, Any]:
        """Lightweight prewarm-hint extract for one job: the FIRST
        subtask's parameters, the payload's scalar train_params, and the
        subtask count — without the full-job deep copy ``get_job`` pays
        (``prewarm_hints`` runs on every ``/subscribe``, and a long-lived
        coordinator holds thousand-subtask jobs whose specs/results must
        not be serialized under the store lock per registration).
        Raises KeyError for unknown ids."""
        with self._lock:
            job = self._require_job(sid, job_id)
            subtasks = job.get("subtasks") or {}
            first = next(iter(subtasks.values()), None)
            params = ((first or {}).get("spec") or {}).get("parameters") or {}
            train_params = (job.get("payload") or {}).get("train_params") or {}
            out = {
                # specs/payload were json_safe'd at create_job, so the
                # round trip is safe — and it only serializes ONE param
                # dict, not the job
                "parameters": json.loads(json.dumps(params)),
                "train_params": {
                    k: v for k, v in train_params.items()
                    if isinstance(v, (str, int, float, bool, type(None)))
                },
                "n_trials": int(job.get("total_subtasks") or 1),
            }
        return out

    # ---------------- jobs ----------------

    def create_job(
        self,
        sid: str,
        job_id: str,
        payload: Dict[str, Any],
        subtasks: List[Dict[str, Any]],
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        record = {
            "job_id": job_id,
            "payload": json_safe(payload),
            "created_at": time.time(),
            "total_subtasks": len(subtasks),
            "completed_subtasks": 0,
            "failed_subtasks": 0,
            "pruned_subtasks": 0,
            "diverged_subtasks": 0,
            "status": "pending",
            "subtasks": {
                st["subtask_id"]: {"spec": json_safe(st), "status": "pending", "result": None}
                for st in subtasks
            },
            "metadata": json_safe(metadata or {}),
            "result": None,
        }
        with self._lock:
            self._require_session(sid)["jobs"][job_id] = record
        self._journal({"op": "create_job", "sid": sid, "record": record})

    def update_subtask(
        self,
        sid: str,
        job_id: str,
        subtask_id: str,
        status: str,
        result: Optional[Dict[str, Any]] = None,
    ) -> None:
        with self._lock:
            job = self._require_job(sid, job_id)
            sub = job["subtasks"][subtask_id]
            self._apply_subtask_update(job, sub, status, json_safe(result))
            # any delivered result retires a steal tombstone: the grant
            # is settled (terminal) or back in the donor's retry path
            self.steal_tombstones.pop(subtask_id, None)
        self._journal(
            {
                "op": "update_subtask",
                "sid": sid,
                "jid": job_id,
                "stid": subtask_id,
                "status": status,
                "attempt": int((result or {}).get("attempt") or 0),
                "result": json_safe(result),
            }
        )

    @staticmethod
    def _apply_subtask_update(
        job: Dict[str, Any],
        sub: Dict[str, Any],
        status: str,
        result: Optional[Dict[str, Any]],
    ) -> None:
        """One subtask transition, shared by the live path and journal
        replay so both count identically. Terminal statuses (completed /
        failed / pruned) count once toward completion; ``promoted`` (an
        adaptive-search rung boundary, docs/SEARCH.md) stores the
        intermediate result without counting. Any result carrying an
        ``asha`` block is appended to the subtask's ``rung_history`` — the
        record a restarted coordinator rebuilds rung state from."""
        prev = sub["status"]
        sub["status"] = status
        if result is not None:
            sub["result"] = result
            # the attempt that delivered the accepted result — the
            # result-ack half of the at-least-once contract: a replayed
            # coordinator knows which attempt is already delivered
            sub["attempt"] = int((result or {}).get("attempt") or 0)
            if result.get("asha"):
                sub.setdefault("rung_history", []).append(
                    dict(result["asha"])
                )
        if (
            status in SUBTASK_TERMINAL_STATUSES
            and prev not in SUBTASK_TERMINAL_STATUSES
        ):
            if status == "completed":
                job["completed_subtasks"] += 1
            elif status == "pruned":
                job["pruned_subtasks"] = job.get("pruned_subtasks", 0) + 1
            elif status == "diverged":
                job["diverged_subtasks"] = (
                    job.get("diverged_subtasks", 0) + 1
                )
            else:
                job["failed_subtasks"] += 1
        done = (
            job["completed_subtasks"]
            + job["failed_subtasks"]
            + job.get("pruned_subtasks", 0)
            + job.get("diverged_subtasks", 0)
        )
        total = job["total_subtasks"]
        if done < total:
            job["status"] = f"{100.0 * done / total:.1f}%"

    def record_attempt(
        self,
        sid: str,
        job_id: str,
        subtask_id: str,
        attempt: int,
        failures: int = 0,
        excluded: Optional[List[str]] = None,
    ) -> None:
        """Journal a subtask attempt issue (lease reclaim, failure retry,
        requeue, speculation) into the spec, so a replayed coordinator
        resumes with retry budgets and excluded-worker memory intact
        instead of resetting every subtask to a fresh budget."""
        with self._lock:
            job = self._require_job(sid, job_id)
            spec = job["subtasks"][subtask_id]["spec"]
            spec["attempt"] = int(attempt)
            spec["failures"] = int(failures)
            spec["excluded_workers"] = list(excluded or [])
        self._journal(
            {
                "op": "subtask_attempt",
                "sid": sid,
                "jid": job_id,
                "stid": subtask_id,
                "attempt": int(attempt),
                "failures": int(failures),
                "excluded": list(excluded or []),
            }
        )

    def record_placement(
        self,
        sid: str,
        job_id: str,
        subtask_id: str,
        worker_id: str,
        attempt: int = 0,
        lease_deadline: Optional[float] = None,
    ) -> None:
        """Journal a placement (and its lease grant, when leases are on)
        into the spec. A replayed coordinator can then tell dispatched
        in-flight subtasks (bump the attempt before re-queueing, so a
        zombie worker's late FAILED report is stale by construction) from
        never-dispatched ones, instead of re-issuing attempt 0 blind."""
        with self._lock:
            job = self._require_job(sid, job_id)
            spec = job["subtasks"][subtask_id]["spec"]
            spec["placed_worker"] = worker_id
            spec["placed_attempt"] = int(attempt or 0)
            if lease_deadline is not None:
                spec["lease_deadline"] = float(lease_deadline)
        self._journal(
            {
                "op": "place",
                "sid": sid,
                "jid": job_id,
                "stid": subtask_id,
                "worker": worker_id,
                "attempt": int(attempt or 0),
                "lease_deadline": lease_deadline,
            }
        )

    def record_curve(
        self,
        sid: str,
        job_id: str,
        subtask_id: str,
        curve: Dict[str, Any],
        rung: int = 0,
        attempt: int = 0,
        diverged: bool = False,
    ) -> None:
        """Journal a rung-boundary learning curve (docs/OBSERVABILITY.md
        "Trial telemetry plane"). The coordinator's CurveStore is
        in-memory only; journaling each ingested curve lets a restarted
        coordinator re-serve ``GET /curves`` history instead of starting
        blank. Replayed entries land in ``replayed_curves`` for the
        coordinator to drain at boot (``drain_replayed_curves``)."""
        self._journal(
            {
                "op": "curve",
                "sid": sid,
                "jid": job_id,
                "stid": subtask_id,
                "rung": int(rung or 0),
                "attempt": int(attempt or 0),
                "diverged": bool(diverged),
                "curve": json_safe(curve),
            }
        )

    def drain_replayed_curves(self) -> List[Dict[str, Any]]:
        """Hand replayed ``curve`` entries to the caller exactly once —
        the boot-time bridge from journal replay into the coordinator's
        CurveStore."""
        with self._lock:
            out = self._replayed_curves
            self._replayed_curves = []
        return out

    def record_mesh_generation(
        self, generation: int, reason: Optional[str] = None
    ) -> None:
        """Journal a mesh-generation bump (worker join/death/evict —
        the elastic fabric's reshard marker) so recovery replays the
        fleet topology history instead of resetting the counter."""
        with self._lock:
            self.mesh_generation = max(
                self.mesh_generation, int(generation or 0)
            )
        self._journal(
            {
                "op": "mesh_gen",
                "generation": int(generation or 0),
                "reason": reason,
            }
        )

    def has_job(self, sid: str, job_id: str) -> bool:
        with self._lock:
            sess = self._sessions.get(sid)
            return bool(sess and job_id in sess["jobs"])

    # ---------------- cross-shard rebalancing ----------------
    # (docs/ROBUSTNESS.md "Shard rebalancing") — the journal is the
    # migration transport: ``migrate_in`` lands the full job record on
    # the recipient BEFORE the donor stamps ``migrate_out``, so a crash
    # between the two leaves at most a duplicated (deduped) owner, never
    # a lost job.

    def migrated_to(self, job_id: str) -> Optional[int]:
        """Destination shard for a job this store migrated away, or
        None for jobs still owned here (the forwarding stamp)."""
        return self._migrated.get(job_id)

    def record_migrate_out(self, sid: str, job_id: str, dest_shard: int) -> None:
        """Stamp a job as migrated to ``dest_shard``. The record stays
        (job routes need it to answer 409 moved) but the job leaves
        ``unfinished_jobs``/``unfinished_counts`` — a restarted donor
        must not resume a job it gave away."""
        with self._lock:
            job = self._require_job(sid, job_id)
            job["migrated_to"] = int(dest_shard)
            self._migrated[job_id] = int(dest_shard)
            # a waiter blocked in wait_job must not hang on a job that
            # left this shard; it re-reads status and sees the move
            event = self._done_events.pop((sid, job_id), None)
        self._journal(
            {"op": "migrate_out", "sid": sid, "jid": job_id,
             "dest": int(dest_shard)}
        )
        if event is not None:
            event.set()

    def import_job(
        self,
        sid: str,
        record: Dict[str, Any],
        source_shard: Optional[int] = None,
    ) -> None:
        """Install a full job record exported by a donor shard. The
        journal entry carries the whole record (like ``create_job``) so
        a recipient crash after the import replays into the identical
        adopted state."""
        record = json_safe(record)
        record["migrated_from"] = source_shard
        # a record can never arrive still wearing the donor's own
        # forwarding stamp, but strip defensively: this shard OWNS it now
        record.pop("migrated_to", None)
        with self._lock:
            self._require_session(sid)["jobs"][record["job_id"]] = record
            self._adopted.add(record["job_id"])
        self._journal(
            {"op": "migrate_in", "sid": sid, "record": record,
             "source_shard": source_shard}
        )

    def is_adopted_job(self, job_id: str) -> bool:
        """True for ids this store adopted via ``import_job`` — they wear
        the DONOR's shard stamp and must not be re-canonicalized."""
        return job_id in self._adopted

    def record_steal(
        self,
        sid: str,
        job_id: str,
        subtask_id: str,
        thief_shard: int,
        attempt: int,
    ) -> None:
        """Tombstone a queued subtask granted to a thief shard. The
        journaled attempt is the FENCED attempt the thief executes —
        replay restores the tombstone (with a fresh lease clock, the
        conservative side) so a restarted donor still won't double-run
        the subtask inside the lease window."""
        with self._lock:
            self.steal_tombstones[subtask_id] = {
                "sid": sid, "jid": job_id, "thief": int(thief_shard),
                "attempt": int(attempt), "ts": time.time(),
            }
        self._journal(
            {"op": "steal", "sid": sid, "jid": job_id, "stid": subtask_id,
             "thief": int(thief_shard), "attempt": int(attempt)}
        )

    def clear_steal(self, subtask_id: str) -> None:
        """Drop a steal tombstone (result arrived, or lease reclaimed).
        Not journaled: the matching ``update_subtask``/``subtask_attempt``
        entry already encodes the outcome, and a replayed tombstone for a
        terminal subtask is cleared by the update's replay."""
        if not self.steal_tombstones:
            return
        with self._lock:
            self.steal_tombstones.pop(subtask_id, None)

    def lookup_specs(self, subtask_ids) -> Dict[str, Dict[str, Any]]:
        """Resolve live (non-terminal, non-migrated) subtask ids to
        ``{session_id, job_id, spec, metadata}`` copies in one lock pass
        — the steal-grant path's bridge from the placement engine's
        id-only queue snapshot back to dispatchable task dicts."""
        wanted = set(subtask_ids)
        out: Dict[str, Dict[str, Any]] = {}
        if not wanted:
            return out
        with self._lock:
            for sid, sess in self._sessions.items():
                for jid, job in sess["jobs"].items():
                    if job.get("migrated_to") is not None:
                        continue
                    if job["status"] in TERMINAL_STATUSES:
                        continue
                    for stid in wanted & set(job["subtasks"]):
                        sub = job["subtasks"][stid]
                        if sub["status"] in SUBTASK_TERMINAL_STATUSES:
                            continue
                        out[stid] = {
                            "session_id": sid,
                            "job_id": jid,
                            "spec": json.loads(json.dumps(sub["spec"])),
                            "metadata": json.loads(
                                json.dumps(job.get("metadata") or {})
                            ),
                        }
        return out

    def unfinished_counts(self) -> Dict[str, Any]:
        """Admission-control inputs in one lock hold: unfinished job count
        (global + per session) and the total PENDING subtasks across those
        jobs — the queue-depth watermark input (docs/ROBUSTNESS.md
        "Admission control")."""
        per_session: Dict[str, int] = {}
        jobs = 0
        pending = 0
        with self._lock:
            for sid, sess in self._sessions.items():
                for job in sess["jobs"].values():
                    if job["status"] in TERMINAL_STATUSES:
                        continue
                    # migrated-away jobs are the destination shard's
                    # load now — counting them here would double-charge
                    # the fleet's admission caps
                    if job.get("migrated_to") is not None:
                        continue
                    jobs += 1
                    per_session[sid] = per_session.get(sid, 0) + 1
                    done = (
                        job["completed_subtasks"]
                        + job["failed_subtasks"]
                        + job.get("pruned_subtasks", 0)
                        + job.get("diverged_subtasks", 0)
                    )
                    pending += max(int(job["total_subtasks"]) - done, 0)
        return {
            "jobs": jobs,
            "per_session": per_session,
            "pending_subtasks": pending,
        }

    def finalize_job(self, sid: str, job_id: str, result: Dict[str, Any]) -> None:
        status = _final_status(result)
        with self._lock:
            job = self._require_job(sid, job_id)
            job["result"] = json_safe(result)
            job["status"] = status
            job["completion_time"] = time.time()
            # pop, don't keep: late waiters short-circuit on the status check
            # in wait_job, and pruning here bounds the dict's size
            event = self._done_events.pop((sid, job_id), None)
            completion_time = job["completion_time"]
        try:
            self._journal(
                {
                    "op": "finalize_job",
                    "sid": sid,
                    "jid": job_id,
                    "result": json_safe(result),
                    "completion_time": completion_time,
                }
            )
        finally:
            if event is not None:
                event.set()

    def wait_job(self, sid: str, job_id: str, timeout: Optional[float] = None) -> bool:
        """Block until the job is finalized (completed or failed). Event-driven
        — the in-process replacement for the reference client's 1 s Redis
        poll loop (core.py:180-199); returns False on timeout."""
        with self._lock:
            job = self._require_job(sid, job_id)
            if job["status"] in TERMINAL_STATUSES:
                return True
            if job.get("migrated_to") is not None:
                # the job will never finalize HERE: the waiter re-reads
                # status and follows the forwarding stamp
                return True
            event = self._done_events.setdefault((sid, job_id), threading.Event())
        return event.wait(timeout)

    def get_job(self, sid: str, job_id: str) -> Dict[str, Any]:
        with self._lock:
            return json.loads(json.dumps(self._require_job(sid, job_id)))

    def job_progress(self, sid: str, job_id: str) -> Dict[str, Any]:
        with self._lock:
            job = self._require_job(sid, job_id)
            pruned = job.get("pruned_subtasks", 0)
            diverged = job.get("diverged_subtasks", 0)
            done = (
                job["completed_subtasks"] + job["failed_subtasks"]
                + pruned + diverged
            )
            out = {
                # the CANONICAL (shard-stamped) id rides every progress/SSE
                # event, so a client that submitted under a client-minted
                # id learns the routable id from the stream itself
                "job_id": job.get("job_id", job_id),
                "job_status": job["status"],
                "tasks_completed": done,
                "tasks_pending": job["total_subtasks"] - done,
                # degradation surfaced mid-stream AND in the final event:
                # increments the moment a subtask is QUARANTINED (retries
                # in flight are not terminal and do not count), final
                # under completed_with_failures (docs/ROBUSTNESS.md)
                "tasks_failed": job["failed_subtasks"],
                # adaptive search (docs/SEARCH.md): trials the rung
                # controller stopped early — non-failure terminals that
                # ride the SSE stream so clients can show rung progress
                "tasks_pruned": pruned,
                # numerical-health watchdog (docs/OBSERVABILITY.md "Trial
                # telemetry plane"): trials terminated because their
                # learning curve went non-finite or blew past the
                # divergence threshold — non-failure terminals, streamed
                # like tasks_pruned
                "tasks_diverged": diverged,
                "total_subtasks": job["total_subtasks"],
                "job_result": job["result"]
                if job["status"] in TERMINAL_STATUSES
                else None,
            }
            if job.get("search") is not None:
                out["search"] = json.loads(json.dumps(job["search"]))
            return out

    def set_search_state(
        self, sid: str, job_id: str, summary: Dict[str, Any]
    ) -> None:
        """Attach the live rung-state summary (AshaController.summary) to
        the job for progress/SSE readers. Derived state — rebuilt from
        ``rung_history`` on replay — so it is deliberately NOT journaled."""
        with self._lock:
            self._require_job(sid, job_id)["search"] = json_safe(summary)

    def unfinished_jobs(self) -> List[tuple]:
        """(sid, job_id) of jobs not yet finalized — after a journal replay
        these are the in-flight jobs a restarted coordinator must resume.
        Jobs wearing a ``migrated_to`` forwarding stamp are excluded —
        the destination shard owns them, and a restarted donor resuming
        one would race the owner with duplicate attempts."""
        with self._lock:
            return [
                (sid, jid)
                for sid, sess in self._sessions.items()
                for jid, job in sess["jobs"].items()
                if job["status"] not in TERMINAL_STATUSES
                and job.get("migrated_to") is None
            ]

    def subtask_results(self, sid: str, job_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            job = self._require_job(sid, job_id)
            return [
                json.loads(json.dumps(sub["result"]))
                for sub in job["subtasks"].values()
                if sub["result"] is not None
            ]

    # ---------------- internals ----------------

    def _require_session(self, sid: str) -> Dict[str, Any]:
        if sid not in self._sessions:
            raise KeyError(f"Invalid session id: {sid}")
        return self._sessions[sid]

    def _require_job(self, sid: str, job_id: str) -> Dict[str, Any]:
        jobs = self._require_session(sid)["jobs"]
        if job_id not in jobs:
            raise KeyError(f"Invalid job id: {job_id}")
        return jobs[job_id]

    def _journal(self, entry: Dict[str, Any]) -> None:
        if not self._journal_path:
            return
        with self._lock:
            with open(self._journal_path, "a") as f:
                f.write(json.dumps(json_safe(entry)) + "\n")

    def _replay(self) -> None:
        if not (self._journal_path and os.path.exists(self._journal_path)):
            return
        t0 = time.time()
        ends_with_newline = True
        with open(self._journal_path) as f:
            for line in f:
                ends_with_newline = line.endswith("\n")
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    # a torn write (the process died mid-append) or bitrot:
                    # skip the line — losing ONE op beats losing the store
                    self.replay_skipped += 1
                    continue
                if self._apply_entry(e):
                    op = str(e.get("op"))
                    self.replay_ops[op] = self.replay_ops.get(op, 0) + 1
                else:
                    self.replay_skipped += 1
        if not ends_with_newline:
            # torn-tail repair: the journal died mid-line. Terminate the
            # torn line NOW so the next append starts clean — otherwise the
            # first post-recovery op would concatenate onto the torn bytes
            # and BOTH would be lost at the next replay (pinned in
            # tests/test_durability.py).
            try:
                with self._lock:
                    with open(self._journal_path, "a") as f:
                        f.write("\n")
            except OSError:
                pass
        self.replay_seconds = time.time() - t0

    def _apply_entry(self, e: Dict[str, Any]) -> bool:
        """Apply one journal entry to in-memory state; False when the entry
        is unknown or references state the (possibly truncated) journal
        never created. Every branch is total — replay NEVER raises, no
        matter where a crash truncated the journal (the crash-point fuzz
        test in tests/test_durability.py cuts at every op boundary)."""
        op = e.get("op")
        try:
            if op == "create_session":
                self._sessions.setdefault(
                    e["sid"],
                    {"created_at": time.time(), "jobs": {},
                     # pre-QoS journals have no priority field: lane 0
                     "priority": int(e.get("priority", 0) or 0)},
                )
            elif op == "create_job":
                self._sessions.setdefault(
                    e["sid"], {"created_at": time.time(), "jobs": {}}
                )["jobs"][e["record"]["job_id"]] = e["record"]
            elif op == "update_subtask":
                job = self._sessions[e["sid"]]["jobs"][e["jid"]]
                # journals from before the adaptive-search layer have no
                # pruned counter — seed it so the shared transition logic
                # (and its done arithmetic) is total on old records
                job.setdefault("pruned_subtasks", 0)
                job.setdefault("diverged_subtasks", 0)
                sub = job["subtasks"][e["stid"]]
                self._apply_subtask_update(
                    job, sub, e["status"], e.get("result")
                )
                # mirror the live path: a replayed result retires any
                # earlier-journaled steal tombstone for the subtask
                self.steal_tombstones.pop(e["stid"], None)
            elif op == "subtask_attempt":
                # fault-tolerance bookkeeping (docs/ROBUSTNESS.md):
                # restore retry budgets / excluded-worker memory into
                # the spec. Journals that predate the attempt schema
                # simply have no such ops — every reader of the fields
                # defaults to a zeroed budget (.get(..., 0)), the same
                # fallback style as completion_time below.
                job = self._sessions[e["sid"]]["jobs"][e["jid"]]
                spec = job["subtasks"][e["stid"]]["spec"]
                spec["attempt"] = int(e.get("attempt", 0) or 0)
                spec["failures"] = int(e.get("failures", 0) or 0)
                spec["excluded_workers"] = list(e.get("excluded") or [])
            elif op == "place":
                job = self._sessions[e["sid"]]["jobs"][e["jid"]]
                spec = job["subtasks"][e["stid"]]["spec"]
                spec["placed_worker"] = e.get("worker")
                spec["placed_attempt"] = int(e.get("attempt", 0) or 0)
                if e.get("lease_deadline") is not None:
                    spec["lease_deadline"] = float(e["lease_deadline"])
            elif op == "mesh_gen":
                # elastic-fabric reshard marker: keep the highest seen
                # (bumps are monotonic; a truncated tail just resumes
                # from an earlier generation, still monotonic)
                self.mesh_generation = max(
                    self.mesh_generation, int(e.get("generation", 0) or 0)
                )
            elif op == "migrate_out":
                # forwarding stamp: the job left this shard. Restore the
                # stamp AND the lookup index so a restarted donor serves
                # 409 moved instead of resuming a job it gave away.
                job = self._sessions[e["sid"]]["jobs"][e["jid"]]
                job["migrated_to"] = int(e.get("dest", 0) or 0)
                self._migrated[e["jid"]] = int(e.get("dest", 0) or 0)
            elif op == "migrate_in":
                # adopted job: the entry carries the donor's full record
                # (same shape as create_job), so replay reinstalls the
                # identical state resume_inflight adopts from
                self._sessions.setdefault(
                    e["sid"], {"created_at": time.time(), "jobs": {},
                               "priority": 0}
                )["jobs"][e["record"]["job_id"]] = e["record"]
                self._adopted.add(e["record"]["job_id"])
            elif op == "steal":
                # restore the donor-side tombstone with a FRESH lease
                # clock (conservative: the thief gets a full lease after
                # a donor restart before the subtask is reclaimed)
                self.steal_tombstones[e["stid"]] = {
                    "sid": e["sid"], "jid": e["jid"],
                    "thief": int(e.get("thief", 0) or 0),
                    "attempt": int(e.get("attempt", 0) or 0),
                    "ts": time.time(),
                }
            elif op == "curve":
                # trial telemetry plane: restore /curves history. Guard
                # on the job existing — a truncated journal may carry a
                # curve for a job whose create_job entry was torn away
                if e["jid"] not in self._sessions[e["sid"]]["jobs"]:
                    return False
                if not isinstance(e.get("curve"), dict):
                    return False
                self._replayed_curves.append(
                    {
                        "sid": e["sid"],
                        "jid": e["jid"],
                        "stid": e["stid"],
                        "rung": int(e.get("rung", 0) or 0),
                        "attempt": int(e.get("attempt", 0) or 0),
                        "diverged": bool(e.get("diverged")),
                        "curve": e["curve"],
                    }
                )
            elif op == "finalize_job":
                job = self._sessions[e["sid"]]["jobs"][e["jid"]]
                job["result"] = e["result"]
                job["status"] = _final_status(e["result"])
                # older journals predate the field: fall back to
                # the entry's absence rather than losing the job
                if e.get("completion_time") is not None:
                    job["completion_time"] = e["completion_time"]
            else:
                return False
        except (KeyError, TypeError, ValueError):
            return False
        return True
