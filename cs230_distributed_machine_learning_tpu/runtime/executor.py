"""Trial executor: runs subtask batches on the local device mesh.

The TPU-native replacement for the reference worker process
(``aws-prod/worker/worker.py:156-363``): where a reference worker consumes
one Kafka message, re-reads the CSV, and runs one sklearn fit on CPU, an
executor here receives a *list* of subtasks, groups them by model family,
and dispatches them to the vmapped/sharded trial engine
(parallel/trial_map.py) — all trials of a batch fit in parallel across the
mesh. Per-subtask results and metrics messages keep the reference's wire
schema (``worker.py:233-254``) so the feedback consumers (store, placement
engine's runtime predictor) are drop-in.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..data.datasets import DatasetCache
from ..models.registry import get_kernel
from ..obs import (
    counter_inc,
    gauge_set,
    obs_enabled,
    observe,
    process_token,
    record_batch_device_seconds,
    record_phase,
    span,
)
from ..ops.folds import build_split_plan
from ..parallel.trial_map import fit_single, run_trials
from ..utils.config import get_config
from ..utils.flops import mfu as _mfu
from ..utils.logging import get_logger

logger = get_logger("tpuml.executor")

ResultCallback = Callable[[str, str, Optional[Dict[str, Any]]], None]
MetricsCallback = Callable[[Dict[str, Any]], None]


def record_hbm_gauges() -> None:
    """Refresh ``tpuml_device_hbm_bytes{kind=used|peak|limit}`` from the
    local device's memory_stats. Backends without stats (CPU) write
    nothing — the family stays at its registered zero. Called after every
    executed batch and at /metrics/prom scrape time."""
    if not obs_enabled():
        return
    from ..utils.flops import device_memory_stats

    stats = device_memory_stats()
    for kind, key in (
        ("used", "bytes_in_use"),
        ("peak", "peak_bytes_in_use"),
        ("limit", "bytes_limit"),
    ):
        v = stats.get(key)
        if v is not None:
            gauge_set("tpuml_device_hbm_bytes", float(v), kind=kind)


class ResourceSampler:
    """Background CPU/mem sampling at a fixed cadence DURING a fit.

    The reference samples psutil every 0.5 s in a thread while the sklearn
    fit runs and reports the averages (worker.py:201-221, 240-241); those
    averages are two of the runtime predictor's 7 features, so a single
    instantaneous snapshot (the round-2 form) fed it near-noise. Also
    tracks device-memory stats (peak bytes in use across samples) — the
    accelerator-side resource signal psutil can't see.
    """

    def __init__(self, interval_s: float = 0.5):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cpu: List[float] = []
        self._mem: List[float] = []
        self._dev_peak_mb: Optional[float] = None

    def _sample_device(self) -> None:
        # max over CURRENT bytes_in_use samples: this fit's observed peak.
        # (peak_bytes_in_use is monotonic over the backend's lifetime — it
        # would report the largest batch ever, not this one)
        from ..utils.flops import device_memory_stats

        used = device_memory_stats().get("bytes_in_use")
        if used is not None:
            mb = used / 1e6
            if self._dev_peak_mb is None or mb > self._dev_peak_mb:
                self._dev_peak_mb = mb

    def _loop(self) -> None:
        try:
            import psutil
        except ImportError:
            return
        psutil.cpu_percent(interval=None)  # prime the delta-based counter
        while not self._stop.wait(self.interval_s):
            self._cpu.append(psutil.cpu_percent(interval=None))
            self._mem.append(psutil.virtual_memory().percent)
            self._sample_device()

    def __enter__(self) -> "ResourceSampler":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1)
        self._sample_device()  # at least one device reading even on fast fits

    def averages(self) -> Dict[str, Optional[float]]:
        """Averaged samples; falls back to one instantaneous reading when
        the fit finished inside the first sampling interval."""
        cpu = mem = None
        if self._cpu:
            cpu = float(sum(self._cpu) / len(self._cpu))
            mem = float(sum(self._mem) / len(self._mem))
        else:
            try:
                import psutil

                cpu = psutil.cpu_percent(interval=None)
                mem = psutil.virtual_memory().percent
            except ImportError:
                pass
        return {
            "cpu_percent_avg": cpu,
            "mem_percent_avg": mem,
            "device_peak_mem_mb": self._dev_peak_mb,
        }


class LocalExecutor:
    """Executes trial batches on the local mesh. ``executor_id`` plays the
    role of the reference's worker_id (assigned at /subscribe,
    scheduler_service.py:157-165)."""

    def __init__(
        self,
        executor_id: str = "exec-0",
        *,
        mesh=None,
        cache: Optional[DatasetCache] = None,
        max_trials_per_batch: Optional[int] = None,
        fault_injector: Optional["FaultInjector"] = None,
    ):
        from ..utils.jax_setup import setup_jax

        setup_jax()
        cfg = get_config()
        self.executor_id = executor_id
        self.mesh = mesh
        self.cache = cache or DatasetCache()
        self.max_trials_per_batch = max_trials_per_batch or cfg.execution.max_trials_per_batch
        self.trial_axis = cfg.execution.trial_axis
        self.fault_injector = fault_injector
        self.enable_profiler = cfg.execution.enable_profiler
        self.profiler_dir = cfg.execution.profiler_dir
        #: live run_subtasks calls — the prewarm worker's yield signal
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        #: cooperative-cancel set (docs/SEARCH.md): subtask_id -> highest
        #: cancelled attempt. Fed by the coordinator (poll response
        #: ``cancel`` list / in-process push); consumed at the next batch
        #: boundary — the matching trials are dropped from the batch and
        #: posted as terminal ``pruned`` results instead of running
        self._cancel_lock = threading.Lock()
        self._cancelled: Dict[str, int] = {}

    @property
    def busy(self) -> bool:
        """True while at least one subtask batch is executing. The
        background prewarm worker (runtime/prewarm.py) polls this and
        yields the device to real placements."""
        return self._inflight > 0

    def cancel(self, items) -> None:
        """Mark attempts cancelled (the cooperative-cancel contract,
        docs/SEARCH.md). ``items``: dicts with ``subtask_id`` (+ optional
        ``attempt``). Matching trials still queued or batched stop at the
        next batch boundary and post a terminal ``pruned`` result; a
        trial already inside a fused device dispatch finishes that
        dispatch (the rung) — cancellation is between batches, never a
        mid-kernel abort."""
        with self._cancel_lock:
            for item in items or []:
                stid = item.get("subtask_id") if isinstance(item, dict) else item
                if not stid:
                    continue
                attempt = (
                    int(item.get("attempt") or 0)
                    if isinstance(item, dict)
                    else 0
                )
                self._cancelled[stid] = max(
                    self._cancelled.get(stid, 0), attempt
                )
            # bound the set: entries for subtasks this executor never sees
            # (the cancel list is fleet-broadcast) must not accumulate for
            # the process lifetime — active cancels re-arrive on every
            # poll, so evicting the oldest is safe
            while len(self._cancelled) > 4096:
                self._cancelled.pop(next(iter(self._cancelled)))

    def _take_cancelled(self, subtasks, idxs):
        """Split a group into (live, cancelled) index lists; cancelled
        entries are consumed from the set (a later duplicate delivery of
        the same subtask re-arrives via the next poll's cancel list). A
        task stamped with a HIGHER attempt than the cancel is NOT
        cancelled — a legitimately re-issued attempt (post-restart
        re-dispatch) must survive a stale entry."""
        with self._cancel_lock:
            if not self._cancelled:
                return idxs, []
            live, cancelled = [], []
            for gi in idxs:
                st = subtasks[gi]
                stid = st["subtask_id"]
                marked = self._cancelled.get(stid)
                if marked is not None and int(st.get("attempt") or 0) <= marked:
                    cancelled.append(gi)
                    self._cancelled.pop(stid, None)
                else:
                    live.append(gi)
        return live, cancelled

    def _post_pruned(self, st, results, gi, on_result, on_metrics) -> None:
        """Terminal ``pruned`` result for a cancelled attempt: the trial
        never (re-)runs. The paired metrics message carries no timing and
        ``cancelled: true`` so the scheduler releases the worker's books
        WITHOUT feeding the runtime predictor or its calibration windows
        (runtime/scheduler.on_metrics)."""
        result = {
            "subtask_id": st["subtask_id"],
            "job_id": st.get("job_id"),
            "model_type": st.get("model_type"),
            "parameters": st.get("parameters"),
            "status": "pruned",
            "pruned": True,
            "prune_reason": "cancelled",
            "attempt": int(st.get("attempt") or 0),
        }
        if st.get("asha"):
            result["asha"] = dict(st["asha"])
        results[gi] = result
        counter_inc("tpuml_subtasks_pruned_total")
        logger.info(
            "Cancelled subtask %s pruned at the batch boundary",
            st["subtask_id"],
        )
        if on_result:
            on_result(st["subtask_id"], "pruned", result)
        if on_metrics:
            on_metrics({
                "worker_id": self.executor_id,
                "subtask_id": st["subtask_id"],
                "status": "PRUNED",
                "cancelled": True,
                "algo": st.get("model_type"),
                "obs_pid": process_token(),
            })

    def run_subtasks(
        self,
        subtasks: List[Dict[str, Any]],
        *,
        on_result: Optional[ResultCallback] = None,
        on_metrics: Optional[MetricsCallback] = None,
    ) -> List[Dict[str, Any]]:
        """Run subtasks grouped by (dataset, model_type); returns results in
        input order. Callbacks fire per subtask as batches complete."""
        with self._inflight_lock:
            self._inflight += 1
        try:
            return self._run_subtasks(
                subtasks, on_result=on_result, on_metrics=on_metrics
            )
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _run_subtasks(
        self,
        subtasks: List[Dict[str, Any]],
        *,
        on_result: Optional[ResultCallback] = None,
        on_metrics: Optional[MetricsCallback] = None,
    ) -> List[Dict[str, Any]]:
        results: List[Optional[Dict[str, Any]]] = [None] * len(subtasks)
        groups: Dict[Any, List[int]] = {}
        for i, st in enumerate(subtasks):
            groups.setdefault((st["dataset_id"], st["model_type"]), []).append(i)

        for (dataset_id, model_type), idxs in groups.items():
            # cooperative cancel, checked at every batch boundary: trials
            # the coordinator pruned mid-flight are dropped here and
            # posted as terminal ``pruned`` results instead of burning
            # the rest of their budget (docs/SEARCH.md)
            idxs, cancelled = self._take_cancelled(subtasks, idxs)
            for gi in cancelled:
                self._post_pruned(
                    subtasks[gi], results, gi, on_result, on_metrics
                )
            if not idxs:
                continue
            received_at = time.time()
            # the batch rides the submitting job's trace (trace_id stamped
            # into each subtask spec by the coordinator); direct callers
            # (benchmarks) carry none — then no span is opened at all
            tid = next(
                (
                    subtasks[i].get("trace_id")
                    for i in idxs
                    if subtasks[i].get("trace_id")
                ),
                None,
            )
            batch_cm = (
                span(
                    "executor.batch",
                    trace_id=tid,
                    worker=self.executor_id,
                    model_type=model_type,
                    dataset_id=dataset_id,
                    n_subtasks=len(idxs),
                )
                if tid
                else contextlib.nullcontext(None)
            )
            try:
                with batch_cm as batch_sp:
                    self._run_group(
                        subtasks, idxs, dataset_id, model_type, received_at,
                        results, on_result, on_metrics, batch_sp,
                    )
            except Exception as e:  # noqa: BLE001 — task-level failure semantics
                if _is_device_fatal(e):
                    # a poisoned backend fails every later dispatch in this
                    # process: do NOT publish per-task failures (the owner
                    # keeps the tasks queued, so the dead-worker sweep can
                    # requeue them onto live executors) — escalate instead
                    raise DeviceLostError(
                        f"device backend lost on {self.executor_id}: {e}"
                    ) from e
                logger.exception("Batch failed for %s/%s", dataset_id, model_type)
                for gi in idxs:
                    st = subtasks[gi]
                    result = {
                        "subtask_id": st["subtask_id"],
                        "job_id": st.get("job_id"),
                        "model_type": model_type,
                        "parameters": st["parameters"],
                        "status": "failed",
                        "error": str(e),
                        # attempt-id stamp: the coordinator's retry/dedup
                        # path must know WHICH attempt failed — a stale
                        # attempt's failure must not consume retry budget
                        "attempt": int(st.get("attempt") or 0),
                    }
                    if st.get("speculative"):
                        result["speculative"] = True
                    results[gi] = result
                    counter_inc("tpuml_subtasks_failed_total")
                    if on_result:
                        on_result(st["subtask_id"], "failed", result)
        return results  # type: ignore[return-value]

    def _run_group(
        self, subtasks, idxs, dataset_id, model_type, received_at,
        results, on_result, on_metrics, batch_sp,
    ) -> None:
        """Execute one (dataset, model_type) group on the trial engine and
        emit per-subtask results/metrics. ``batch_sp`` is the enclosing
        ``executor.batch`` span handle (or None): the engine's phase timers
        — compile / stage-upload / dispatch / packed fetch, the numbers
        PR 1 measured ad-hoc — are attached to it as synthesized child
        spans laid out sequentially from batch start."""
        if self.fault_injector is not None:
            self.fault_injector.before_batch(self.executor_id, model_type)
        kernel = get_kernel(model_type)
        data = self.cache.get(dataset_id, kernel.task)
        tp = subtasks[idxs[0]].get("train_params", {}) or {}
        scoring = _normalize_scoring(
            tp.get("scoring"), kernel.task, data.n_classes, kernel
        )
        plan = build_split_plan(
            data.y if kernel.task == "regression" else _np(data.y),
            task=kernel.task,
            n_folds=_coerce_cv(tp.get("cv")),
            test_size=float(tp.get("test_size", get_config().execution.default_test_size)),
            random_state=tp.get("random_state", 42),
        )
        started_at = time.time()
        profiler_cm = self._profiler_cm(model_type)
        with profiler_cm, ResourceSampler() as sampler:
            if callable(scoring) and not isinstance(scoring, str):
                # host-side fallback: device fits per fold, sklearn
                # export, user scorer on host (trial_map docstring)
                from ..parallel.trial_map import (
                    TrialRunResult,
                    run_trials_callable,
                )

                t0 = time.time()
                metrics_list = run_trials_callable(
                    kernel, data, plan,
                    [subtasks[i]["parameters"] for i in idxs],
                    scoring,
                )
                run = TrialRunResult(
                    trial_metrics=metrics_list,
                    compile_time_s=0.0,
                    run_time_s=time.time() - t0,
                    n_dispatches=len(idxs) * plan.n_splits,
                )
            else:
                run = run_trials(
                    kernel,
                    data,
                    plan,
                    [subtasks[i]["parameters"] for i in idxs],
                    mesh=self.mesh,
                    trial_axis=self.trial_axis,
                    max_trials_per_batch=self.max_trials_per_batch,
                    scoring=scoring,
                )
        finished_at = time.time()
        if self.fault_injector is not None and self.fault_injector.drop_batch_results(
            self.executor_id
        ):
            # silent-worker chaos: the batch RAN (compute burned) but no
            # result/metrics message ever leaves this executor — the lease
            # layer must recover the subtasks (docs/ROBUSTNESS.md)
            logger.warning(
                "FaultInjector: dropping results of a %d-trial %s batch on %s",
                len(idxs), model_type, self.executor_id,
            )
            return
        observe("tpuml_executor_dispatch_seconds", run.run_time_s)
        # device-time attribution (obs/devprof.py): the same phase totals
        # the synthesized trace children carry, accumulated into the
        # tpuml_executor_device_seconds_total{phase=} counter
        record_batch_device_seconds(
            run.compile_time_s, run.stage_time_s,
            run.run_time_s, run.fetch_time_s,
        )
        resources = sampler.averages()
        batch_cost = self._record_batch_cost(
            run, model_type, dataset_id, len(idxs), resources
        )
        self._record_batch_phases(batch_sp, run, started_at, batch_cost)
        per_trial_time = run.run_time_s / max(len(idxs), 1)
        # winner-by-ICI-collective: run_trials' on-device argmax over
        # the mesh-sharded scores (multi-device only). The marked
        # result lets the coordinator select the winner from the
        # device reduction instead of a host sort.
        device_best_pos = (
            run.device_best[0] if run.device_best is not None else None
        )
        for j, gi in enumerate(idxs):
            st = subtasks[gi]
            result = {
                "subtask_id": st["subtask_id"],
                "job_id": st.get("job_id"),
                "model_type": model_type,
                "parameters": st["parameters"],
                "search_params": st.get("search_params"),
                "training_time": per_trial_time,
                "status": "completed",
                # attempt-id stamp for result-ingest dedup under retries
                # and speculative duplicates (docs/ROBUSTNESS.md)
                "attempt": int(st.get("attempt") or 0),
                **run.trial_metrics[j],
            }
            if st.get("speculative"):
                result["speculative"] = True
            if st.get("asha"):
                # rung stamp echoed so the coordinator's rung controller
                # can attribute the score without a spec lookup race
                result["asha"] = dict(st["asha"])
            if device_best_pos == j:
                result["device_argmax"] = True
            if j == 0 and batch_cost is not None:
                # the batch's cost record rides exactly ONE result (the
                # primary) into the job store, where GET /cost/<job_id>
                # aggregates it — stamping every result would overcount
                result["batch_cost"] = batch_cost
            results[gi] = result
            counter_inc("tpuml_subtasks_completed_total")
            if on_result:
                on_result(st["subtask_id"], "completed", result)
            if on_metrics:
                on_metrics(
                    self._metrics_message(
                        st, received_at, started_at, finished_at,
                        model_type, resources, run=run,
                        batch_size=len(idxs), primary=(j == 0),
                        batch_cost=batch_cost,
                        score=run.trial_metrics[j].get("mean_cv_score"),
                        curve=run.trial_metrics[j].get("curve"),
                    )
                )

    def _record_batch_cost(
        self, run, model_type: str, dataset_id: str, batch_size: int,
        resources: Optional[Dict[str, Any]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Device cost accounting for one executed batch: feed the
        ``tpuml_executor_flops_total`` / ``_bytes_total`` / ``_mfu`` /
        ``tpuml_device_hbm_bytes`` families and build the per-batch cost
        record that rides the primary result into the job store (the
        ``GET /cost/<job_id>`` input). Returns None when CS230_OBS=0 —
        the valve disables cost accounting end to end."""
        if not obs_enabled():
            return None
        n_devices = 1
        if self.mesh is not None:
            import numpy as np

            n_devices = int(np.prod(list(self.mesh.shape.values())))
        flops = run.model_flops if run.model_flops is not None else run.xla_flops
        # MFU only from a COMPLETE model-FLOP sum (a partially priced run
        # must report null, not an understated figure — flops_coverage
        # contract, trial_map), over the peak of EVERY participating
        # device (whole-mesh FLOPs over one chip's peak would read Nx)
        mfu_val = (
            _mfu(run.model_flops, run.run_time_s, n_devices=n_devices)
            if run.flops_coverage == 1.0
            else None
        )
        if flops is not None:
            counter_inc("tpuml_executor_flops_total", flops, model=model_type)
        if run.bytes_accessed is not None:
            counter_inc(
                "tpuml_executor_bytes_total", run.bytes_accessed,
                model=model_type,
            )
        if mfu_val is not None:
            gauge_set("tpuml_executor_mfu", mfu_val, model=model_type)
        record_hbm_gauges()
        # per-batch HBM: the sampler's max over bytes_in_use DURING this
        # fit (memory_stats' peak_bytes_in_use is monotonic over the
        # process lifetime — it would pin every later batch to the
        # largest batch ever; run.hbm_peak_bytes keeps that lifetime
        # high-water as the fallback when the sampler saw nothing)
        dev_peak_mb = (resources or {}).get("device_peak_mem_mb")
        hbm_peak = (
            int(dev_peak_mb * 1e6)
            if dev_peak_mb is not None
            else run.hbm_peak_bytes
        )
        return {
            "model_type": model_type,
            "dataset_id": dataset_id,
            "n_subtasks": batch_size,
            "n_devices": n_devices,
            "device_seconds": run.run_time_s,
            "model_flops": run.model_flops,
            "xla_flops": run.xla_flops,
            "bytes_accessed": run.bytes_accessed,
            "flops_coverage": run.flops_coverage,
            "mfu": mfu_val,
            "hbm_peak_bytes": hbm_peak,
        }

    @staticmethod
    def _record_batch_phases(
        batch_sp, run, started_at: float,
        batch_cost: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Attach the trial engine's measured phase totals to the batch
        span as synthesized children. Phases are laid out sequentially from
        batch start (real execution overlaps stage/dispatch/fetch — the
        durations are exact, the offsets indicative; attrs carry
        ``synthesized: true``)."""
        if batch_sp is None or getattr(batch_sp, "span_id", None) is None:
            return
        batch_sp.attrs.update(
            n_dispatches=run.n_dispatches,
            n_host_fetches=run.n_host_fetches,
            result_bytes=run.result_bytes,
            compile_time_s=round(run.compile_time_s, 6),
            run_time_s=round(run.run_time_s, 6),
        )
        if batch_cost is not None:
            # cost attrs join the span so trace timelines price themselves
            batch_sp.attrs.update(
                {
                    k: batch_cost[k]
                    for k in ("model_flops", "xla_flops", "bytes_accessed",
                              "mfu", "hbm_peak_bytes")
                    if batch_cost.get(k) is not None
                }
            )
        t = record_phase(
            batch_sp, "executor.compile", run.compile_time_s, start=started_at
        )
        t = record_phase(batch_sp, "executor.stage", run.stage_time_s, start=t)
        dispatch_s = max(run.run_time_s - run.fetch_time_s, 0.0)
        t = record_phase(batch_sp, "executor.dispatch", dispatch_s, start=t,
                         n_dispatches=run.n_dispatches)
        record_phase(batch_sp, "executor.fetch", run.fetch_time_s, start=t,
                     n_host_fetches=run.n_host_fetches,
                     result_bytes=run.result_bytes)

    def prewarm_hint(
        self, hint: Dict[str, Any], mode: str = "construct"
    ) -> Dict[str, Any]:
        """Warm one coordinator prewarm hint: resolve the dataset (which
        fetches + parses it on a cold agent and stages it into the
        multi-tenant device cache), then construct every bucket executable
        the hinted job shape would use (``run_trials(warm_only=True)`` —
        AOT blob deserialize or trace, the inline cold cost this kills).
        ``mode="execute"`` additionally dispatches the warmed bucket once
        with the hinted parameters and discards the result, so the first
        real trial also finds a finished XLA compile.

        Hint schema (Coordinator.prewarm_hints): ``{model_type,
        dataset_id, parameters, n_trials, train_params}`` — ``n_trials``
        matters because the trial-chunk geometry is part of every
        executable cache key; warming the wrong chunk warms nothing.
        It is capped at THIS executor's ``max_trials_per_batch``: a
        scheduled worker never sees more trials per batch than its
        long-poll cap (agent._poll_tasks passes exactly this value), so
        the full-batch geometry — what a saturated queue delivers cold —
        is the shape worth warming, and a bigger hinted job would warm a
        chunk size no delivered batch ever has. String ``scoring``
        survives into the warm (it is part of the executable key);
        callable scoring cannot arrive here (REST-serialized hints)."""
        kernel = get_kernel(hint["model_type"])
        data = self.cache.get(hint["dataset_id"], kernel.task)
        tp = dict(hint.get("train_params") or {})
        scoring = tp.get("scoring")
        scoring = _normalize_scoring(
            scoring if isinstance(scoring, str) else None,
            kernel.task, data.n_classes, kernel,
        )
        plan = build_split_plan(
            data.y if kernel.task == "regression" else _np(data.y),
            task=kernel.task,
            n_folds=_coerce_cv(tp.get("cv")),
            test_size=float(
                tp.get("test_size", get_config().execution.default_test_size)
            ),
            random_state=tp.get("random_state", 42),
        )
        n_trials = max(
            1, min(int(hint.get("n_trials") or 1), self.max_trials_per_batch)
        )
        params = dict(hint.get("parameters") or {})
        run = run_trials(
            kernel,
            data,
            plan,
            [params] * n_trials,
            mesh=self.mesh,
            trial_axis=self.trial_axis,
            max_trials_per_batch=self.max_trials_per_batch,
            scoring=scoring,
            warm_only=(mode != "execute"),
        )
        return {
            "model_type": hint["model_type"],
            "dataset_id": hint["dataset_id"],
            "n_trials": n_trials,
            "mode": mode,
            "compile_s": round(run.compile_time_s, 6),
            "stage_s": round(run.stage_time_s, 6),
            "run_s": round(run.run_time_s, 6),
            "n_dispatches": run.n_dispatches,
        }

    def fit_artifact(self, subtask: Dict[str, Any]) -> Dict[str, Any]:
        """Refit one configuration on the holdout-train split and return a
        serializable artifact dict (see runtime/artifacts.py)."""
        kernel = get_kernel(subtask["model_type"])
        data = self.cache.get(subtask["dataset_id"], kernel.task)
        tp = subtask.get("train_params", {}) or {}
        plan = build_split_plan(
            _np(data.y),
            task=kernel.task,
            n_folds=0,
            test_size=float(tp.get("test_size", get_config().execution.default_test_size)),
            random_state=tp.get("random_state", 42),
        )
        fitted, static = fit_single(kernel, data, plan, subtask["parameters"])
        return {
            "model_type": subtask["model_type"],
            "parameters": subtask["parameters"],
            "static": {k: v for k, v in static.items()},
            "fitted_params": fitted,
        }

    def _metrics_message(self, st, received_at, started_at, finished_at,
                         algo, resources=None, run=None, batch_size=1,
                         primary=False, batch_cost=None, score=None,
                         curve=None):
        """Reference metrics schema (worker.py:233-243): CPU/mem averaged
        over the fit by the 0.5 s-cadence ResourceSampler (the predictor's
        feature inputs), plus device peak-memory — the accelerator signal
        the reference had no analog for — and the batch's host<->device
        transfer accounting (dispatches / blocking fetches / result bytes),
        the observability for the packed single-fetch transport."""
        resources = resources or {}
        msg = {
            "worker_id": self.executor_id,
            "subtask_id": st["subtask_id"],
            "status": "DONE",
            "received_at": received_at,
            "started_at": started_at,
            "finished_at": finished_at,
            "cpu_percent_avg": resources.get("cpu_percent_avg"),
            "mem_percent_avg": resources.get("mem_percent_avg"),
            "device_peak_mem_mb": resources.get("device_peak_mem_mb"),
            "algo": algo,
            # the process (host:pid) that ALREADY observed this batch's
            # phase/cost metrics into its local registry — the
            # coordinator's ingest (cluster.push_metrics) skips
            # re-observing when the message originated in its own process
            # (the in-process-agent test topology would otherwise
            # double-observe; docs/OBSERVABILITY.md)
            "obs_pid": process_token(),
        }
        a = st.get("asha")
        if a:
            # rung boundary (docs/SEARCH.md): the intermediate validation
            # score + rung/resource ride the metrics message so the
            # coordinator's on_metrics can feed the rung controller before
            # the result lands, and the scheduler's predictor feed can
            # normalize the rung's wall time by its resource fraction
            msg["rung"] = int(a.get("rung", 0))
            msg["resource"] = int(a.get("resource", 0))
            msg["intermediate_score"] = score
            big = a.get("max_resource")
            if isinstance(big, (int, float)) and big > 0:
                msg["asha_resource_fraction"] = min(
                    max(float(a.get("resource", 0)) / float(big), 0.01), 1.0
                )
        if run is not None:
            # batch_-prefixed: these are totals for the WHOLE run_trials
            # batch this subtask rode in (every subtask of the batch
            # carries the same numbers — summing them per job would
            # overcount by the batch size; divide by batch_n_subtasks or
            # dedupe on them instead). ``batch_primary`` marks exactly one
            # message per batch — the dedup handle consumers (e.g. the
            # coordinator's remote-metrics ingest, cluster.push_metrics)
            # key batch-level observations on.
            msg["batch_n_subtasks"] = batch_size
            msg["batch_n_dispatches"] = run.n_dispatches
            msg["batch_device_fetches"] = run.n_host_fetches
            msg["batch_result_bytes"] = run.result_bytes
            msg["batch_primary"] = bool(primary)
            msg["batch_compile_s"] = run.compile_time_s
            msg["batch_stage_s"] = run.stage_time_s
            msg["batch_dispatch_s"] = run.run_time_s
            msg["batch_fetch_s"] = run.fetch_time_s
        if batch_cost is not None:
            # remote agents have no exposition endpoint: the batch's cost
            # figures ride the metrics message so the coordinator's ingest
            # can count them fleet-wide (same dedup contract as the phase
            # timers: batch_primary + obs_pid)
            msg["batch_model_flops"] = batch_cost.get("model_flops")
            msg["batch_xla_flops"] = batch_cost.get("xla_flops")
            msg["batch_bytes_accessed"] = batch_cost.get("bytes_accessed")
            msg["batch_mfu"] = batch_cost.get("mfu")
            msg["batch_hbm_peak_bytes"] = batch_cost.get("hbm_peak_bytes")
        if curve is not None:
            # trial telemetry plane: the per-trial convergence trace rides
            # the metrics message so the coordinator can ingest (and
            # watchdog) it live, before the result settles. Per-SUBTASK —
            # no batch dedup needed; the curve store dedups re-delivery
            # through the result transport on (subtask, rung, attempt).
            msg["curve"] = curve
            msg["attempt"] = int(st.get("attempt") or 0)
        return msg


    def _profiler_cm(self, tag: str):
        """jax.profiler trace around a trial batch (replaces the reference's
        psutil sampler as the deep-inspection path, SURVEY.md §5.1)."""
        import contextlib

        if not self.enable_profiler:
            return contextlib.nullcontext()
        import os

        import jax

        trace_dir = os.path.join(self.profiler_dir, f"{self.executor_id}-{tag}")
        return jax.profiler.trace(trace_dir)


class DeviceLostError(RuntimeError):
    """The executor's accelerator backend is poisoned (e.g. an UNAVAILABLE
    RPC fault on a TPU chip): every later dispatch in this process will
    fail, so the owning worker must leave the pool instead of emitting
    per-task failures. Containment per runtime mode:

    - remote agent (runtime/agent.py): exits the process — the scheduler's
      dead-worker sweep requeues its tasks, and a supervisor/compose
      restart policy brings a fresh process (and backend) back.
    - in-process worker (runtime/cluster.py): kills itself without
      unsubscribe, so its tasks requeue onto surviving executors.
    """


#: substrings marking an unrecoverable backend fault (vs a per-batch error
#: like RESOURCE_EXHAUSTED/INVALID_ARGUMENT, which stays task-level)
_FATAL_MARKERS = (
    "UNAVAILABLE",
    "DATA_LOSS",
    "device is in an invalid state",
    "backend has been poisoned",
    "lost connection to the device",
)


def _is_multiprocess() -> bool:
    """True only inside a live multi-process (slice) runtime — the context
    where a broad network-error marker really does mean the collective is
    dead for every later dispatch."""
    try:
        import jax

        return jax.process_count() > 1
    except Exception:  # noqa: BLE001 — no backend yet: not a slice
        return False


def _is_device_fatal(e: BaseException) -> bool:
    msg = f"{type(e).__name__}: {e}"
    if isinstance(e, DeviceLostError):
        return True
    # a backend that never came up (e.g. two processes contending for one
    # chip) fails every batch this process will ever run — process-fatal
    if "Unable to initialize backend" in msg:
        return True
    # cross-process collective failure (a slice sibling died mid-program:
    # gloo on CPU fleets, ICI/barrier errors on TPU slices): every later
    # sharded dispatch on this rank fails too, and publishing per-task
    # FAILED results would make the sibling's crash terminal for the job —
    # escalate so the tasks stay queued for the dead-worker requeue
    # (tests/test_chaos_spmd.py pins this path). The broad network markers
    # ("heartbeat", "Connection reset by peer") only escalate under a
    # multi-process slice: on a single-process executor a transient
    # network hiccup on a tunneled device whose message happens to contain
    # them fails ONE batch, not the whole agent (ADVICE r5 #3). The
    # collective-specific prefixes stay unconditional — a gloo/coordination
    # error cannot occur outside a collective runtime.
    if "JaxRuntimeError" in msg or "XlaRuntimeError" in msg:
        if any(m in msg for m in ("Gloo ", "coordination service")):
            return True
        if any(
            m in msg
            for m in (
                "Connection reset by peer",
                "Connection closed by peer",
                "heartbeat",
            )
        ) and _is_multiprocess():
            return True
    if "XlaRuntimeError" not in msg and "DeviceLost" not in msg:
        return False
    return any(m in msg for m in _FATAL_MARKERS)


class FaultInjector:
    """Test/chaos hooks (SURVEY.md §5.3: 'add real fault injection hooks'):
    delay a host's batches, fail N batches (task-level), drop the results
    of N batches silently (``drop_results`` — the compute runs but no
    result or metrics message leaves the executor: the silent/hung-worker
    scenario the lease layer recovers), or poison the device backend
    (process-level) — immediately or after N healthy batches
    (``device_lost_after``, the kill-mid-job chaos scenario).
    ``only_worker=`` scopes every mode to one executor id, so a shared
    injector can target a single worker deterministically."""

    def __init__(self, delay_s: float = 0.0, fail_batches: int = 0,
                 device_lost: bool = False,
                 device_lost_after: Optional[int] = None,
                 drop_results: int = 0,
                 only_worker: Optional[str] = None):
        self.delay_s = delay_s
        self.fail_batches = fail_batches
        self.device_lost = device_lost
        self.device_lost_after = device_lost_after
        self.drop_results = drop_results
        self.only_worker = only_worker
        self._batches_seen = 0

    def _targets(self, executor_id: str) -> bool:
        return self.only_worker is None or executor_id == self.only_worker

    def before_batch(self, executor_id: str, model_type: str) -> None:
        if not self._targets(executor_id):
            return
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        if self.device_lost or (
            self.device_lost_after is not None
            and self._batches_seen >= self.device_lost_after
        ):
            raise DeviceLostError(
                f"fault injection: simulated backend loss on {executor_id}"
            )
        if self.fail_batches > 0:
            self.fail_batches -= 1
            raise RuntimeError(f"fault injection: simulated batch failure on {executor_id}")
        self._batches_seen += 1  # only batches that passed injection count

    def drop_batch_results(self, executor_id: str) -> bool:
        """True when this batch's results/metrics must be silently dropped
        (consumes one ``drop_results`` budget unit). Called by the executor
        after the batch ran, before any emission."""
        if not self._targets(executor_id):
            return False
        if self.drop_results > 0:
            self.drop_results -= 1
            return True
        return False


def _np(y):
    import numpy as np

    return np.asarray(y)


def _normalize_scoring(scoring, task: str, n_classes: int = 0, kernel=None):
    """Validate a job's ``scoring`` and collapse the task defaults to None
    (so default jobs keep their cached executables). The reference worker
    silently dropped custom scoring (worker.py:320-349); here an unsupported
    scorer fails the batch with a clear error instead — including the cases
    sklearn itself rejects (binary-average scorers on multiclass targets)
    and the one it can't know about (margin scorers on kernels with no
    decision margin)."""
    from ..ops.metrics import validate_scoring

    if scoring is None:
        return None
    if task != "transform" and scoring == (
        "accuracy" if task == "classification" else "r2"
    ):
        return None
    validate_scoring(scoring, task, n_classes, kernel)
    return scoring


def _coerce_cv(cv) -> int:
    """Accept the cv forms sklearn search wrappers take: None (default 5),
    an int, or a CV splitter object (use its fold count; fold *assignment*
    still follows our default splitters)."""
    if cv is None:
        return get_config().execution.default_cv_folds
    if isinstance(cv, (int, float)):
        return int(cv)
    if hasattr(cv, "get_n_splits"):
        return int(cv.get_n_splits())
    try:
        return int(cv)
    except (TypeError, ValueError):
        return get_config().execution.default_cv_folds
