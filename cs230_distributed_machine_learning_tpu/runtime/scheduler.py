"""Placement engine: learned-runtime, load/memory/speed-aware scheduling.

Capability parity with the reference scheduler service
(``aws-prod/scheduler/scheduler_service.py``), re-homed from Kafka-keyed
containers to mesh executors:

- ``WorkerState`` (scheduler_service.py:91-104): queued-runtime load,
  memory load vs capacity, EMA speed factor, heartbeat stamp, task queue.
- placement (scheduler_service.py:167-191): eligible = fits in memory
  (fallback: all, with a warning); score = effective_finish_time +
  est_runtime / max(speed, 1e-3); pick min.
- feedback (scheduler_service.py:295-351): on a metrics message, decrement
  load/memory, update ``speed_factor = clamp(0.2..5, 0.8*old +
  0.2*(est/actual))``, feed the runtime predictor.
- failure detection (scheduler_service.py:205-247): periodic sweep marks
  workers dead after ``dead_after_s`` of heartbeat silence and requeues
  their queued tasks onto survivors; ``unsubscribe`` does the same
  gracefully (scheduler.py:120-139). Elastic join assigns monotonically
  increasing ids (scheduler_service.py:157-165).

The engine is transport-agnostic: it consumes/produces on the in-process
TopicBus (runtime/queue.py) locally, and the same message schema rides DCN
RPC for multi-host agents (runtime/agent.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..obs import counter_inc, gauge_set, observe, span
from ..utils.config import get_config
from ..utils.logging import get_logger
from .predictor import RuntimePredictor

logger = get_logger("tpuml.scheduler")

TOPIC_TASKS = "tasks"
TOPIC_TRAIN = "train"


@dataclasses.dataclass
class WorkerState:
    worker_id: str
    mem_capacity_mb: float
    load_seconds: float = 0.0
    mem_load_mb: float = 0.0
    speed_factor: float = 1.0
    last_heartbeat: float = dataclasses.field(default_factory=time.time)
    tasks_queue: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # per-task bookkeeping for feedback decrements
    task_est: Dict[str, float] = dataclasses.field(default_factory=dict)
    task_mem: Dict[str, float] = dataclasses.field(default_factory=dict)
    alive: bool = True

    def effective_finish_time(self) -> float:
        return self.load_seconds / max(self.speed_factor, 1e-3)


class PlacementEngine:
    def __init__(self, bus=None, predictor: Optional[RuntimePredictor] = None):
        cfg = get_config().scheduler
        self.cfg = cfg
        self.bus = bus
        self.predictor = predictor or RuntimePredictor()
        self._lock = threading.RLock()
        self.workers: Dict[str, WorkerState] = {}
        self._next_id = 0
        self._stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None

    # ---------------- registry (subscribe/heartbeat/unsubscribe) ----------------

    def subscribe(self, mem_capacity_mb: Optional[float] = None, worker_id: Optional[str] = None) -> str:
        with self._lock:
            if worker_id is None:
                worker_id = f"worker-{self._next_id}"
                self._next_id += 1
            self.workers[worker_id] = WorkerState(
                worker_id=worker_id,
                mem_capacity_mb=mem_capacity_mb or self.cfg.default_mem_capacity_mb,
            )
            logger.info("Worker %s subscribed", worker_id)
            gauge_set("tpuml_workers_alive", len(self.workers))
            return worker_id

    def unsubscribe(self, worker_id: str) -> List[Dict[str, Any]]:
        """Remove a worker; requeue its queued tasks. Returns the requeued tasks."""
        with self._lock:
            state = self.workers.pop(worker_id, None)
            gauge_set("tpuml_workers_alive", len(self.workers))
        if state is None:
            return []
        logger.info("Worker %s unsubscribed; requeueing %d tasks", worker_id, len(state.tasks_queue))
        return self._requeue(state.tasks_queue)

    def heartbeat(self, worker_id: str) -> bool:
        with self._lock:
            state = self.workers.get(worker_id)
            if state is None:
                return False
            state.last_heartbeat = time.time()
            return True

    def worker_snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                wid: {
                    "load_seconds": w.load_seconds,
                    "mem_load_mb": w.mem_load_mb,
                    "mem_capacity_mb": w.mem_capacity_mb,
                    "speed_factor": w.speed_factor,
                    "last_heartbeat": w.last_heartbeat,
                    "queue_depth": len(w.tasks_queue),
                }
                for wid, w in self.workers.items()
            }

    def queue_snapshot(self) -> Dict[str, List[str]]:
        with self._lock:
            return {
                wid: [t.get("subtask_id", "?") for t in w.tasks_queue]
                for wid, w in self.workers.items()
            }

    # ---------------- placement ----------------

    def place(self, task: Dict[str, Any]) -> Optional[str]:
        """Choose a worker for a task, update its load, and (when a bus is
        wired) publish to the train topic keyed by worker id. Returns the
        worker id, or None if no workers exist. The decision latency feeds
        the ``tpuml_scheduler_placement_seconds`` histogram and, when the
        task carries a trace id, a ``schedule.place`` span."""
        t_place = time.perf_counter()
        est = self.predictor.predict(task)
        mem_mb = float(task.get("mem_estimate_mb", 1.0))
        with self._lock:
            if not self.workers:
                return None
            eligible = [
                w
                for w in self.workers.values()
                if w.mem_load_mb + mem_mb <= w.mem_capacity_mb
            ]
            if not eligible:
                logger.warning(
                    "No worker fits task %s (%.0f MB); falling back to all",
                    task.get("subtask_id"),
                    mem_mb,
                )
                eligible = list(self.workers.values())
            best = min(
                eligible,
                key=lambda w: w.effective_finish_time() + est / max(w.speed_factor, 1e-3),
            )
            best.load_seconds += est
            best.mem_load_mb += mem_mb
            best.tasks_queue.append(task)
            stid = task.get("subtask_id")
            best.task_est[stid] = est
            best.task_mem[stid] = mem_mb
            wid = best.worker_id
        elapsed = time.perf_counter() - t_place
        observe("tpuml_scheduler_placement_seconds", elapsed)
        counter_inc("tpuml_subtasks_dispatched_total")
        tid = task.get("trace_id")
        if tid:
            # the decision already ran: back-date the span over it
            with span("schedule.place", trace_id=tid, parent_id=None,
                      subtask_id=stid, worker=wid, est_runtime_s=est) as sp:
                sp.start = time.time() - elapsed
        if self.bus is not None:
            self.bus.publish(TOPIC_TRAIN, task, key=wid)
        return wid

    # ---------------- feedback ----------------

    def on_metrics(self, msg: Dict[str, Any]) -> None:
        """Consume a worker metrics message (schema: worker.py:233-243)."""
        wid = msg.get("worker_id")
        stid = msg.get("subtask_id")
        started = msg.get("started_at")
        finished = msg.get("finished_at")
        actual = None
        if started is not None and finished is not None:
            actual = max(float(finished) - float(started), 1e-3)
        with self._lock:
            w = self.workers.get(wid)
            if w is None:
                return
            est = w.task_est.pop(stid, 0.0)
            mem = w.task_mem.pop(stid, 0.0)
            w.load_seconds = max(0.0, w.load_seconds - est)
            w.mem_load_mb = max(0.0, w.mem_load_mb - mem)
            w.tasks_queue = [t for t in w.tasks_queue if t.get("subtask_id") != stid]
            if actual is not None and est > 0:
                ratio = est / actual
                w.speed_factor = min(
                    self.cfg.speed_factor_max,
                    max(
                        self.cfg.speed_factor_min,
                        (1 - self.cfg.speed_ema_alpha) * w.speed_factor
                        + self.cfg.speed_ema_alpha * ratio,
                    ),
                )
        if actual is not None:
            self.predictor.observe(msg, actual)

    # ---------------- failure detection ----------------

    def start_monitor(self) -> None:
        if self._monitor_thread is not None:
            return
        self._stop.clear()
        self._monitor_thread = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor_thread.start()

    def stop_monitor(self) -> None:
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=2)
            self._monitor_thread = None

    def sweep(self) -> List[str]:
        """One failure-detection pass; returns ids of workers declared dead."""
        now = time.time()
        dead: List[WorkerState] = []
        with self._lock:
            for wid, w in list(self.workers.items()):
                if now - w.last_heartbeat > self.cfg.dead_after_s:
                    dead.append(self.workers.pop(wid))
            if dead:
                gauge_set("tpuml_workers_alive", len(self.workers))
        for w in dead:
            logger.warning(
                "Worker %s dead (no heartbeat for >%ss); requeueing %d tasks",
                w.worker_id,
                self.cfg.dead_after_s,
                len(w.tasks_queue),
            )
            self._requeue(w.tasks_queue)
        return [w.worker_id for w in dead]

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.cfg.sweep_interval_s):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001
                logger.exception("Heartbeat sweep failed")

    def _requeue(self, tasks: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        requeued = []
        for task in tasks:
            counter_inc("tpuml_subtasks_requeued_total")
            wid = self.place(task)
            if wid is None:
                logger.error(
                    "No surviving worker for %s; task dropped back to tasks topic",
                    task.get("subtask_id"),
                )
                if self.bus is not None:
                    self.bus.publish(TOPIC_TASKS, task)
            else:
                requeued.append(task)
        return requeued
