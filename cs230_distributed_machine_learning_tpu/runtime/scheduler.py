"""Placement engine: learned-runtime, load/memory/speed-aware scheduling.

Capability parity with the reference scheduler service
(``aws-prod/scheduler/scheduler_service.py``), re-homed from Kafka-keyed
containers to mesh executors:

- ``WorkerState`` (scheduler_service.py:91-104): queued-runtime load,
  memory load vs capacity, EMA speed factor, heartbeat stamp, task queue.
- placement (scheduler_service.py:167-191): eligible = fits in memory
  (fallback: all, with a warning); score = effective_finish_time +
  est_runtime / max(speed, 1e-3); pick min.
- feedback (scheduler_service.py:295-351): on a metrics message, decrement
  load/memory, update ``speed_factor = clamp(0.2..5, 0.8*old +
  0.2*(est/actual))``, feed the runtime predictor.
- failure detection (scheduler_service.py:205-247): periodic sweep marks
  workers dead after ``dead_after_s`` of heartbeat silence and requeues
  their queued tasks onto survivors; ``unsubscribe`` does the same
  gracefully (scheduler.py:120-139). Elastic join assigns monotonically
  increasing ids (scheduler_service.py:157-165).

Beyond the reference, the fault-tolerance layer (docs/ROBUSTNESS.md):

- **leases**: every placed subtask carries a deadline derived from the
  runtime predictor's estimate (x ``lease_factor``, floored); the sweep
  reclaims expired leases from LIVE but hung workers — the strictly
  stronger form of the dead-worker detection above.
- **speculative execution**: an in-flight subtask whose age exceeds the
  peer-median batch EWMA x ``straggler_factor`` gets ONE duplicate on an
  idle worker (Dean & Ghemawat's backup tasks); the coordinator's
  result-ingest dedups by attempt id, first terminal result wins.
- **circuit breaker**: a worker whose windowed failure ratio trips
  ``breaker_failure_ratio`` is demoted to half-open (probe tasks only —
  at most one in flight) and evicted after ``breaker_max_trips`` trips,
  upgrading the advisory straggler penalty into an enforced state
  machine.
All re-executions are accounted through the shared
:class:`~.faults.AttemptLedger` so attempt ids stay monotonic and
journaled.

The engine is transport-agnostic: it consumes/produces on the in-process
TopicBus (runtime/queue.py) locally, and the same message schema rides DCN
RPC for multi-host agents (runtime/agent.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..obs import (
    counter_inc,
    gauge_set,
    obs_enabled,
    observe,
    record_event,
    refresh_route_p99,
    span,
    timeseries_sample,
)
from ..utils.config import get_config
from ..utils.logging import get_logger
from .faults import AttemptLedger
from .predictor import RuntimePredictor

logger = get_logger("tpuml.scheduler")

TOPIC_TASKS = "tasks"
TOPIC_TRAIN = "train"
#: same name as cluster.TOPIC_RESULT — the sweep publishes synthetic
#: failed results here when a subtask exhausts its lease budget
TOPIC_RESULT = "result"


@dataclasses.dataclass
class WorkerState:
    worker_id: str
    mem_capacity_mb: float
    #: devices in this worker's mesh slice (reported at /subscribe) — the
    #: predictor-aware packing divisor: a trial batch parallelizes across
    #: the slice, so an N-device worker drains its queue ~N x faster and
    #: its placement score prices estimates per slice, not per process
    n_devices: int = 1
    #: mesh axis spec of the slice ({axis: size}), advisory/observability
    mesh_shape: Optional[Dict[str, int]] = None
    load_seconds: float = 0.0
    mem_load_mb: float = 0.0
    speed_factor: float = 1.0
    last_heartbeat: float = dataclasses.field(default_factory=time.time)
    tasks_queue: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # per-task bookkeeping for feedback decrements
    task_est: Dict[str, float] = dataclasses.field(default_factory=dict)
    task_mem: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: per-task lease deadline (absolute time); expired leases on a LIVE
    #: worker are reclaimed by the sweep (docs/ROBUSTNESS.md)
    task_lease: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: per-task placement timestamp — the speculation age signal
    task_placed_at: Dict[str, float] = dataclasses.field(default_factory=dict)
    alive: bool = True
    # ---- circuit breaker (closed -> half_open -> evicted) ----
    breaker_state: str = "closed"
    breaker_trips: int = 0
    #: outcome window since the last breaker transition
    window_ok: int = 0
    window_failed: int = 0
    # ---- health telemetry (docs/OBSERVABILITY.md "Worker health") ----
    #: EWMA of this worker's batch wall time (None until the first batch)
    ewma_batch_s: Optional[float] = None
    #: batches absorbed into the EWMA (the straggler-guard denominator:
    #: outcomes arrive per SUBTASK, so counting them would let one cold
    #: multi-subtask batch satisfy the min-batches guard)
    n_batches: int = 0
    #: subtask outcomes reported for this worker
    n_completed: int = 0
    n_failed: int = 0

    def effective_finish_time(self) -> float:
        return self.load_seconds / max(self.speed_factor, 1e-3)

    def slice_est(self, est: float) -> float:
        """Price an estimate per mesh slice: the trial engine shards a
        batch's trial axis across the worker's devices, so wall time
        divides by the slice width (the speed_factor EWMA then corrects
        whatever the ideal-scaling assumption gets wrong)."""
        return est / max(int(self.n_devices or 1), 1)

    def n_outcomes(self) -> int:
        return self.n_completed + self.n_failed

    def failure_ratio(self) -> float:
        total = self.n_outcomes()
        return self.n_failed / total if total else 0.0


class PlacementEngine:
    def __init__(
        self,
        bus=None,
        predictor: Optional[RuntimePredictor] = None,
        ledger: Optional[AttemptLedger] = None,
        worker_prefix: str = "",
    ):
        cfg = get_config().scheduler
        self.cfg = cfg
        self.bus = bus
        #: minted worker ids are ``<prefix>worker-<n>``; a coordinator
        #: shard sets its shard stamp here (runtime/sharding.worker_prefix)
        #: so front ends can route worker-plane requests statelessly
        self.worker_prefix = worker_prefix
        self.predictor = predictor or RuntimePredictor()
        #: attempt/exclusion/poison accounting, shared with the coordinator
        #: when a ClusterRuntime wires both to one ledger
        self.ledger = ledger if ledger is not None else AttemptLedger()
        #: called with a worker id the breaker evicted — the cluster hooks
        #: this to tear down the in-process worker / remote subscription
        self.on_evict: Optional[Callable[[str], None]] = None
        #: called AFTER a placement with (task, worker_id, lease_deadline)
        #: — the coordinator hooks this to journal placements + lease
        #: grants so a restarted process can tell dispatched in-flight
        #: subtasks from never-dispatched ones (docs/ROBUSTNESS.md
        #: "Coordinator recovery")
        self.on_place: Optional[
            Callable[[Dict[str, Any], str, Optional[float]], None]
        ] = None
        #: overload probe installed by the coordinator (admission control):
        #: True while the fleet is shedding optional work — speculation
        #: skips its launches first, before admission starts rejecting
        self.shed_check: Optional[Callable[[], bool]] = None
        #: elastic-fabric mesh generation (docs/ARCHITECTURE.md "Elastic
        #: trial fabric"): bumped whenever the fleet's device topology
        #: changes (worker join / death / eviction / unsubscribe). Every
        #: placement stamps the task with the current generation; the
        #: coordinator journals bumps (``on_mesh_change``) so recovery
        #: replays the generation instead of restarting at 0.
        self.mesh_generation = 0
        #: called with (generation, reason, snapshot) after each bump —
        #: the coordinator hooks this to journal the reshard
        self.on_mesh_change: Optional[
            Callable[[int, str, Dict[str, Any]], None]
        ] = None
        #: called at the end of every sweep, after the health/route-p99
        #: refresh and the time-series sample — the coordinator hooks its
        #: fleet-health tick here (capacity signals + alert evaluation,
        #: docs/OBSERVABILITY.md "Fleet health plane")
        self.on_sweep_end: Optional[Callable[[], None]] = None
        self._lock = threading.RLock()
        self.workers: Dict[str, WorkerState] = {}
        self._next_id = 0
        self._stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        #: workers currently flagged as stragglers (transition logging)
        self._flagged: set = set()

    # ---------------- registry (subscribe/heartbeat/unsubscribe) ----------------

    def subscribe(
        self,
        mem_capacity_mb: Optional[float] = None,
        worker_id: Optional[str] = None,
        n_devices: Optional[int] = None,
        mesh_shape: Optional[Dict[str, int]] = None,
    ) -> str:
        with self._lock:
            if worker_id is None:
                worker_id = f"{self.worker_prefix}worker-{self._next_id}"
                self._next_id += 1
            self.workers[worker_id] = WorkerState(
                worker_id=worker_id,
                mem_capacity_mb=mem_capacity_mb or self.cfg.default_mem_capacity_mb,
                n_devices=max(int(n_devices or 1), 1),
                mesh_shape=(
                    {str(k): int(v) for k, v in mesh_shape.items()}
                    if mesh_shape else None
                ),
            )
            logger.info(
                "Worker %s subscribed (%d-device slice)",
                worker_id, self.workers[worker_id].n_devices,
            )
            gauge_set("tpuml_workers_alive", len(self.workers))
        self._mesh_changed("join", worker_id)
        return worker_id

    def unsubscribe(self, worker_id: str) -> List[Dict[str, Any]]:
        """Remove a worker; requeue its queued tasks. Returns the requeued tasks."""
        with self._lock:
            state = self.workers.pop(worker_id, None)
            gauge_set("tpuml_workers_alive", len(self.workers))
        self._drop_worker_gauges(worker_id)
        if state is None:
            return []
        logger.info("Worker %s unsubscribed; requeueing %d tasks", worker_id, len(state.tasks_queue))
        self._mesh_changed("unsubscribe", worker_id)
        return self._requeue(state.tasks_queue, from_worker=worker_id)

    # ---------------- elastic mesh fabric ----------------

    def total_devices(self) -> int:
        """Devices across every live worker's mesh slice — the fleet's
        current data-plane width."""
        with self._lock:
            return sum(
                max(int(w.n_devices or 1), 1) for w in self.workers.values()
            )

    def _mesh_changed(self, reason: str, worker_id: str) -> None:
        """The fleet's device topology changed: bump the mesh generation,
        record the reshard, and notify the journal hook. In-flight work
        placed under the old generation is re-placed by the existing
        lease/requeue machinery with fresh attempt ids — a killed host's
        trials resume on the reshaped fleet without manual restart
        (docs/ARCHITECTURE.md "Elastic trial fabric")."""
        # bump AND emit under one lock hold: two concurrent topology
        # changes must publish their gauges/events/journal entries in
        # generation order, or the gauge could regress to the earlier
        # generation and the event stream would read out of order. The
        # emission targets (registry, recorder, store journal) never
        # call back into this engine, so no lock-ordering hazard.
        with self._lock:
            self.mesh_generation += 1
            gen = self.mesh_generation
            snapshot = {
                "n_workers": len(self.workers),
                "total_devices": self.total_devices(),
            }
            gauge_set("tpuml_mesh_generation", float(gen))
            gauge_set(
                "tpuml_mesh_devices_total", float(snapshot["total_devices"])
            )
            counter_inc("tpuml_mesh_reshards_total", reason=reason)
            record_event(
                "mesh.reshard", generation=gen, reason=reason,
                worker_id=worker_id, **snapshot,
            )
            hook = self.on_mesh_change
            if hook is not None:
                try:
                    hook(gen, reason, snapshot)
                except Exception:  # noqa: BLE001 — journaling must not block
                    logger.exception("Mesh-change journal hook failed")

    def heartbeat(self, worker_id: str) -> bool:
        with self._lock:
            state = self.workers.get(worker_id)
            if state is None:
                return False
            state.last_heartbeat = time.time()
            return True

    def worker_snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                wid: {
                    "load_seconds": w.load_seconds,
                    "mem_load_mb": w.mem_load_mb,
                    "mem_capacity_mb": w.mem_capacity_mb,
                    "speed_factor": w.speed_factor,
                    "last_heartbeat": w.last_heartbeat,
                    "queue_depth": len(w.tasks_queue),
                    "n_devices": w.n_devices,
                    "mesh_shape": w.mesh_shape,
                }
                for wid, w in self.workers.items()
            }

    def queue_snapshot(self) -> Dict[str, List[str]]:
        with self._lock:
            return {
                wid: [t.get("subtask_id", "?") for t in w.tasks_queue]
                for wid, w in self.workers.items()
            }

    def hot_families(self, top_n: int = 5) -> List[str]:
        """The runtime predictor's recently-hot model families — what the
        coordinator ships as the AOT-prewarm hint ranking when a worker
        registers (runtime/prewarm.py). [] for stub predictors without
        the surface (engine-level tests)."""
        hf = getattr(self.predictor, "hot_families", None)
        return hf(top_n=top_n) if hf is not None else []

    # ---------------- per-worker health ----------------

    def record_outcome(self, worker_id: str, ok: bool) -> None:
        """Count one subtask outcome against a worker — the failure-rate
        input. Fed by the cluster's result paths (in-process worker
        callbacks and remote /task_result ingest). Also drives the circuit
        breaker: closed -> half-open on a tripped windowed failure ratio,
        half-open -> closed on a successful probe, eviction after
        ``breaker_max_trips`` trips (docs/ROBUSTNESS.md)."""
        cfg = self.cfg
        evict = False
        transition = None  # (from_state, to_state, trips) for the recorder
        with self._lock:
            w = self.workers.get(worker_id)
            if w is None:
                return
            if ok:
                w.n_completed += 1
                w.window_ok += 1
            else:
                w.n_failed += 1
                w.window_failed += 1
            if cfg.breaker_failure_ratio <= 0:
                return
            if w.breaker_state == "half_open":
                if ok:
                    w.breaker_state = "closed"
                    w.window_ok = w.window_failed = 0
                    transition = ("half_open", "closed", w.breaker_trips)
                    gauge_set(
                        "tpuml_worker_breaker_state", 0.0, wid=worker_id
                    )
                    logger.info(
                        "Worker %s breaker closed (probe succeeded)", worker_id
                    )
                else:
                    w.breaker_trips += 1
                    w.window_ok = w.window_failed = 0
                    evict = w.breaker_trips >= cfg.breaker_max_trips
                    transition = ("half_open", "half_open", w.breaker_trips)
                    logger.warning(
                        "Worker %s breaker probe failed (trip %d/%d)",
                        worker_id, w.breaker_trips, cfg.breaker_max_trips,
                    )
            else:
                total = w.window_ok + w.window_failed
                # bounded window: decay (halve) the counters once the
                # window outgrows the trip threshold by 8x, so a long-
                # healthy history cannot drown out a recent failure streak
                # (1000 past successes must not require 1000 failures to
                # trip). Halving preserves the ratio.
                if total >= 8 * max(cfg.breaker_min_outcomes, 4):
                    w.window_ok //= 2
                    w.window_failed //= 2
                    total = w.window_ok + w.window_failed
                if (
                    total >= cfg.breaker_min_outcomes
                    and w.window_failed / total >= cfg.breaker_failure_ratio
                ):
                    w.breaker_state = "half_open"
                    w.breaker_trips += 1
                    w.window_ok = w.window_failed = 0
                    transition = ("closed", "half_open", w.breaker_trips)
                    gauge_set(
                        "tpuml_worker_breaker_state", 1.0, wid=worker_id
                    )
                    logger.warning(
                        "Worker %s breaker tripped -> half-open (probe tasks "
                        "only; trip %d/%d)",
                        worker_id, w.breaker_trips, cfg.breaker_max_trips,
                    )
                    evict = w.breaker_trips >= cfg.breaker_max_trips
        if transition is not None:
            from_state, to_state, trips = transition
            record_event(
                "breaker.transition", worker_id=worker_id,
                **{"from": from_state, "to": to_state, "trips": trips,
                   "max_trips": cfg.breaker_max_trips,
                   "evicting": bool(evict)},
            )
        if evict:
            self.evict_worker(worker_id)

    def release_task(self, worker_id: str, subtask_id: Optional[str]) -> bool:
        """Clear a worker's bookkeeping for a subtask whose attempt ended
        WITHOUT a metrics message (failed batches emit results only): queue
        entry, load/memory reservation, lease, and placement stamp. No
        speed-factor update — a failure carries no timing signal."""
        if subtask_id is None:
            return False
        with self._lock:
            w = self.workers.get(worker_id)
            if w is None or subtask_id not in w.task_est:
                return False
            est = w.task_est.pop(subtask_id, 0.0)
            mem = w.task_mem.pop(subtask_id, 0.0)
            w.task_lease.pop(subtask_id, None)
            w.task_placed_at.pop(subtask_id, None)
            w.load_seconds = max(0.0, w.load_seconds - est)
            w.mem_load_mb = max(0.0, w.mem_load_mb - mem)
            w.tasks_queue = [
                t for t in w.tasks_queue if t.get("subtask_id") != subtask_id
            ]
        return True

    def evict_worker(self, worker_id: str, reason: str = "circuit breaker") -> List[Dict[str, Any]]:
        """Remove a worker the breaker gave up on; requeue its queued tasks
        onto survivors and notify the runtime via ``on_evict`` so transport
        state (in-process worker threads / remote long-poll subscriptions)
        is torn down too."""
        with self._lock:
            state = self.workers.pop(worker_id, None)
            gauge_set("tpuml_workers_alive", len(self.workers))
        if state is None:
            return []
        logger.warning(
            "Worker %s evicted (%s); requeueing %d tasks",
            worker_id, reason, len(state.tasks_queue),
        )
        record_event(
            "worker.evict", worker_id=worker_id, reason=reason,
            n_requeued=len(state.tasks_queue),
            breaker_trips=state.breaker_trips,
        )
        self._drop_worker_gauges(worker_id)
        self._mesh_changed("evict", worker_id)
        hook = self.on_evict
        if hook is not None:
            try:
                hook(worker_id)
            except Exception:  # noqa: BLE001 — teardown must not block requeue
                logger.exception("on_evict hook failed for %s", worker_id)
        requeued = self._requeue(state.tasks_queue, from_worker=worker_id)
        self.refresh_health_metrics()
        return requeued

    def _straggler_ids_locked(self) -> set:
        """Workers whose batch EWMA exceeds ``straggler_factor`` x the
        median EWMA of their PEERS (own value excluded, so a two-worker
        pool can still flag its slow half). Requires
        ``straggler_min_batches`` reported outcomes — one slow cold batch
        must not brand a fresh worker. Caller holds the lock."""
        cfg = self.cfg
        measured = [
            (wid, w.ewma_batch_s)
            for wid, w in self.workers.items()
            if w.ewma_batch_s is not None
            and w.n_batches >= cfg.straggler_min_batches
        ]
        if len(measured) < 2:
            return set()
        flagged = set()
        for wid, ewma in measured:
            others = sorted(v for o, v in measured if o != wid)
            mid = len(others) // 2
            median = (
                others[mid]
                if len(others) % 2
                else 0.5 * (others[mid - 1] + others[mid])
            )
            if median > 0 and ewma > cfg.straggler_factor * median:
                flagged.add(wid)
        return flagged

    def _health_snapshot_locked(self) -> Dict[str, Dict[str, Any]]:
        now = time.time()
        stragglers = self._straggler_ids_locked()
        return {
            wid: {
                "ewma_batch_s": w.ewma_batch_s,
                "heartbeat_age_s": round(now - w.last_heartbeat, 3),
                "completed": w.n_completed,
                "failed": w.n_failed,
                "failure_ratio": w.failure_ratio(),
                "queue_depth": len(w.tasks_queue),
                "load_seconds": w.load_seconds,
                "speed_factor": w.speed_factor,
                "straggler": wid in stragglers,
                "breaker_state": w.breaker_state,
                "breaker_trips": w.breaker_trips,
                "n_devices": w.n_devices,
            }
            for wid, w in self.workers.items()
        }

    def health_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-worker health view: EWMA batch latency, heartbeat age,
        outcome counts/failure ratio, queue depth, straggler flag — the
        ``GET /healthz`` body and the tpuml_worker_* gauge source."""
        with self._lock:
            return self._health_snapshot_locked()

    def refresh_health_metrics(self) -> Dict[str, Dict[str, Any]]:
        """Write the health snapshot into the ``tpuml_worker_*{wid=...}``
        gauges and log straggler transitions. Called on metrics feedback,
        at /metrics/prom scrape, and by the sweep; returns the snapshot so
        callers (healthz) reuse one read. Snapshot AND gauge writes happen
        under one lock hold: writing from a stale snapshot could resurrect
        a concurrently-removed worker's cells after _drop_worker_gauges
        already cleaned them — permanently, since refresh only writes
        registered workers."""
        with self._lock:
            snap = self._health_snapshot_locked()
            for wid, h in snap.items():
                if h["ewma_batch_s"] is not None:
                    gauge_set(
                        "tpuml_worker_ewma_batch_seconds", h["ewma_batch_s"],
                        wid=wid,
                    )
                gauge_set(
                    "tpuml_worker_heartbeat_age_seconds", h["heartbeat_age_s"],
                    wid=wid,
                )
                gauge_set(
                    "tpuml_worker_failure_ratio", h["failure_ratio"], wid=wid
                )
                gauge_set("tpuml_worker_queue_depth", h["queue_depth"], wid=wid)
                gauge_set(
                    "tpuml_worker_straggler",
                    1.0 if h["straggler"] else 0.0,
                    wid=wid,
                )
                gauge_set(
                    "tpuml_worker_breaker_state",
                    1.0 if h["breaker_state"] == "half_open" else 0.0,
                    wid=wid,
                )
            current = {wid for wid, h in snap.items() if h["straggler"]}
            newly_flagged = sorted(current - self._flagged)
            recovered = sorted(self._flagged - current)
            self._flagged = current
        for wid in newly_flagged:
            logger.warning(
                "Worker %s flagged as straggler (batch EWMA %.3fs vs peers); "
                "placement now carries a +%.0fs advisory penalty",
                wid, snap[wid]["ewma_batch_s"], self.cfg.straggler_penalty_s,
            )
        for wid in recovered:
            logger.info("Worker %s no longer a straggler", wid)
        return snap

    def _drop_worker_gauges(self, worker_id: str) -> None:
        """A dead/unsubscribed worker must stop being exposed: remove its
        labeled cells from every per-worker gauge family."""
        from ..obs import REGISTRY

        for name in (
            "tpuml_worker_ewma_batch_seconds",
            "tpuml_worker_heartbeat_age_seconds",
            "tpuml_worker_failure_ratio",
            "tpuml_worker_queue_depth",
            "tpuml_worker_straggler",
            "tpuml_worker_breaker_state",
        ):
            g = REGISTRY.get(name)
            if g is not None and hasattr(g, "remove"):
                g.remove(wid=worker_id)
        self._flagged.discard(worker_id)

    # ---------------- placement ----------------

    def place(self, task: Dict[str, Any]) -> Optional[str]:
        """Choose a worker for a task, update its load, and (when a bus is
        wired) publish to the train topic keyed by worker id. Returns the
        worker id, or None if no workers exist. The decision latency feeds
        the ``tpuml_scheduler_placement_seconds`` histogram and, when the
        task carries a trace id, a ``schedule.place`` span."""
        t_place = time.perf_counter()
        est = self.predictor.predict(task)
        mem_mb = float(task.get("mem_estimate_mb", 1.0))
        # flight-recorder explainability: the full decision — per-candidate
        # scores, exclusions, penalties, the lease — is captured only when
        # obs is on (the breakdown dicts are not free, the decision is)
        explain = obs_enabled()
        breakdown: Optional[Dict[str, Any]] = None
        with self._lock:
            if not self.workers:
                return None
            mem_fallback = False
            eligible = [
                w
                for w in self.workers.values()
                if w.mem_load_mb + mem_mb <= w.mem_capacity_mb
            ]
            if not eligible:
                logger.warning(
                    "No worker fits task %s (%.0f MB); falling back to all",
                    task.get("subtask_id"),
                    mem_mb,
                )
                eligible = list(self.workers.values())
                mem_fallback = True
            # excluded-worker memory (retries must not land on the worker
            # that just failed/hung the task) — a preference, not a gate:
            # when only excluded workers remain, liveness wins
            excluded = set(task.get("excluded_workers") or ())
            excluded_overridden = False
            if excluded:
                non_excluded = [
                    w for w in eligible if w.worker_id not in excluded
                ]
                if non_excluded:
                    eligible = non_excluded
                else:
                    excluded_overridden = True
                    logger.warning(
                        "Every eligible worker is excluded for %s; "
                        "falling back to the excluded pool",
                        task.get("subtask_id"),
                    )
            # circuit breaker: a half-open worker takes PROBE tasks only —
            # at most one in flight (empty queue). If no closed or
            # probe-ready worker exists, fall back rather than stall.
            breaker_ok = [
                w for w in eligible
                if w.breaker_state != "half_open" or not w.tasks_queue
            ]
            if breaker_ok:
                eligible = breaker_ok
            # straggler consumption is ADVISORY: a flat score penalty on
            # flagged workers only — eligibility, fallback, and the score
            # formula for healthy workers are untouched. Reads the flag
            # set maintained by refresh_health_metrics (feedback/scrape/
            # sweep) — recomputing peer medians on every placement would
            # put O(W^2 log W) work on the hot path this module times.
            stragglers = self._flagged
            penalty = self.cfg.straggler_penalty_s

            def _score(w: WorkerState) -> float:
                # predictor-aware mesh packing: the estimate is priced per
                # mesh slice (est / n_devices) so a wide slice absorbs the
                # expensive wide-W trials while cheap trials keep landing
                # on narrow workers instead of serializing behind them
                return (
                    w.effective_finish_time()
                    + w.slice_est(est) / max(w.speed_factor, 1e-3)
                    + (penalty if w.worker_id in stragglers else 0.0)
                )

            best = min(eligible, key=_score)
            stid = task.get("subtask_id")
            if explain:
                # snapshot the score terms BEFORE the books absorb this
                # task — the breakdown must show the inputs of the
                # decision, not its side effects
                ranked = sorted(eligible, key=_score)[:8]
                breakdown = {
                    "est_runtime_s": est,
                    "mem_estimate_mb": mem_mb,
                    "n_workers": len(self.workers),
                    "n_eligible": len(eligible),
                    "mem_fallback": mem_fallback,
                    "excluded": sorted(excluded),
                    "excluded_overridden": excluded_overridden,
                    "penalized": sorted(
                        w.worker_id for w in eligible
                        if w.worker_id in stragglers
                    ),
                    "chosen_score": _score(best),
                    # the packing decision's mesh context (docs/
                    # ARCHITECTURE.md "Elastic trial fabric"): the chosen
                    # worker's slice shape and the fleet generation the
                    # placement happened under
                    "mesh_slice": {
                        "n_devices": best.n_devices,
                        "mesh_shape": best.mesh_shape,
                        "generation": self.mesh_generation,
                    },
                    "candidates": [
                        {
                            "worker_id": w.worker_id,
                            "score": _score(w),
                            "effective_finish_time_s":
                                w.effective_finish_time(),
                            "est_over_speed_s":
                                w.slice_est(est) / max(w.speed_factor, 1e-3),
                            "speed_factor": w.speed_factor,
                            "n_devices": w.n_devices,
                            "load_seconds": w.load_seconds,
                            "mem_load_mb": w.mem_load_mb,
                            "queue_depth": len(w.tasks_queue),
                            "penalty_s": penalty
                            if w.worker_id in stragglers else 0.0,
                            "breaker_state": w.breaker_state,
                        }
                        for w in ranked
                    ],
                }
            # books absorb the SLICE-priced estimate: the same figure
            # on_metrics pops back out and the lease/calibration paths
            # consume — the predictor is measured against the estimate
            # that actually drove the decision
            est = best.slice_est(est)
            best.load_seconds += est
            best.mem_load_mb += mem_mb
            best.tasks_queue.append(task)
            best.task_est[stid] = est
            best.task_mem[stid] = mem_mb
            # stamp the fleet generation the placement happened under —
            # a reshard (join/death/evict) bumps it, and re-placements of
            # reclaimed work carry the new generation with their fresh
            # attempt id
            task["mesh_generation"] = self.mesh_generation
            now = time.time()
            best.task_placed_at[stid] = now
            lease_deadline = None
            if self.cfg.lease_factor > 0:
                # lease covers the PREDICTED completion time on this worker
                # — queue wait included (effective_finish_time already
                # absorbed this task's estimate above), speed-adjusted —
                # so deep queues don't expire healthy leases; the floor
                # absorbs cold-start noise
                lease_deadline = now + max(
                    self.cfg.lease_floor_s,
                    self.cfg.lease_factor * best.effective_finish_time(),
                )
                best.task_lease[stid] = lease_deadline
            wid = best.worker_id
        elapsed = time.perf_counter() - t_place
        observe("tpuml_scheduler_placement_seconds", elapsed)
        counter_inc("tpuml_subtasks_dispatched_total")
        attempt = int(task.get("attempt") or 0)
        if breakdown is not None:
            record_event(
                "placement",
                job_id=task.get("job_id"),
                subtask_id=stid,
                worker_id=wid,
                attempt=attempt,
                **breakdown,
            )
            if lease_deadline is not None:
                record_event(
                    "lease.grant",
                    job_id=task.get("job_id"),
                    subtask_id=stid,
                    worker_id=wid,
                    attempt=attempt,
                    deadline_ts=lease_deadline,
                    lease_s=lease_deadline - now,
                    lease_factor=self.cfg.lease_factor,
                    lease_floor_s=self.cfg.lease_floor_s,
                )
        tid = task.get("trace_id")
        if tid:
            # the decision already ran: back-date the span over it
            with span("schedule.place", trace_id=tid, parent_id=None,
                      subtask_id=stid, worker=wid, est_runtime_s=est,
                      attempt=attempt) as sp:
                sp.start = time.time() - elapsed
        hook = self.on_place
        if hook is not None:
            try:
                hook(task, wid, lease_deadline)
            except Exception:  # noqa: BLE001 — journaling must not kill dispatch
                logger.exception(
                    "Placement journal hook failed for %s", stid
                )
        if self.bus is not None:
            self.bus.publish(TOPIC_TRAIN, task, key=wid)
        return wid

    # ---------------- feedback ----------------

    def on_metrics(self, msg: Dict[str, Any]) -> None:
        """Consume a worker metrics message (schema: worker.py:233-243)."""
        wid = msg.get("worker_id")
        stid = msg.get("subtask_id")
        started = msg.get("started_at")
        finished = msg.get("finished_at")
        actual = None
        if started is not None and finished is not None:
            actual = max(float(finished) - float(started), 1e-3)
        # cooperative-cancel guard (docs/SEARCH.md): a cancelled/pruned
        # attempt's message releases the worker's books below but must
        # NEVER feed the predictor, the calibration windows, or the
        # speed/health EWMAs — a trial stopped at rung 1 would log a
        # wildly small "actual" against a full-budget estimate and poison
        # the ratio every lease is derived from
        if msg.get("cancelled"):
            actual = None
        with self._lock:
            w = self.workers.get(wid)
            if w is None:
                return
            n_dev = max(int(w.n_devices or 1), 1)
            est = w.task_est.pop(stid, 0.0)
            mem = w.task_mem.pop(stid, 0.0)
            w.task_lease.pop(stid, None)
            w.task_placed_at.pop(stid, None)
            w.load_seconds = max(0.0, w.load_seconds - est)
            w.mem_load_mb = max(0.0, w.mem_load_mb - mem)
            w.tasks_queue = [t for t in w.tasks_queue if t.get("subtask_id") != stid]
            if actual is not None and est > 0:
                ratio = est / actual
                w.speed_factor = min(
                    self.cfg.speed_factor_max,
                    max(
                        self.cfg.speed_factor_min,
                        (1 - self.cfg.speed_ema_alpha) * w.speed_factor
                        + self.cfg.speed_ema_alpha * ratio,
                    ),
                )
            # every subtask of a batch reports the SAME batch wall time, so
            # the health EWMA absorbs it once per batch — only the primary
            # message updates (messages without the marker, e.g. synthetic
            # feedback in tests, count as primary)
            batch_once = msg.get("batch_primary") is not False
            if actual is not None and batch_once:
                a = self.cfg.health_ema_alpha
                w.ewma_batch_s = (
                    actual
                    if w.ewma_batch_s is None
                    else (1 - a) * w.ewma_batch_s + a * actual
                )
                w.n_batches += 1
        if actual is not None:
            # the predictor learns DEVICE-NORMALIZED walls: a wall measured
            # on an N-device slice is already slice-shortened, and place()
            # divides the estimate by the candidate's slice width — feeding
            # the raw wall would divide by n_devices twice (estimates and
            # leases shrinking toward T/N^2 on wide fleets). Calibration
            # and the speed/health EWMAs below stay per-worker raw: they
            # measure the AS-USED sliced estimate against this worker.
            self.predictor.observe(msg, actual * n_dev)
            if est > 0:
                # calibration telemetry: est is the exact estimate the
                # placement consumed (algo multiplier included) and the
                # lease was derived from — measure the predictor AS USED.
                # getattr: engine-level tests run stub predictors without
                # the calibration surface.
                rec = getattr(self.predictor, "record_calibration", None)
                if rec is not None:
                    # executor metrics messages carry the family as "algo"
                    # (reference schema); synthetic test feedback uses
                    # "model_type"
                    rec(msg.get("algo") or msg.get("model_type"), est, actual)
            if batch_once:
                self.refresh_health_metrics()

    # ---------------- failure detection ----------------

    def start_monitor(self) -> None:
        if self._monitor_thread is not None:
            return
        self._stop.clear()
        self._monitor_thread = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor_thread.start()

    def stop_monitor(self) -> None:
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=2)
            self._monitor_thread = None

    def sweep(self) -> List[str]:
        """One failure-detection pass: dead-worker detection (heartbeat
        silence), lease reclaim from LIVE but hung workers, and the
        speculative-execution check. Returns ids of workers declared
        dead."""
        now = time.time()
        dead: List[WorkerState] = []
        reclaimed: List[tuple] = []  # (worker_id, task)
        with self._lock:
            for wid, w in list(self.workers.items()):
                if now - w.last_heartbeat > self.cfg.dead_after_s:
                    dead.append(self.workers.pop(wid))
                    continue
                # lease reclaim: an expired lease on a live worker means the
                # worker is hung (or silently dropped the result) — pull the
                # task back and release the books; re-dispatch happens below
                for task in list(w.tasks_queue):
                    stid = task.get("subtask_id")
                    deadline = w.task_lease.get(stid)
                    if deadline is None or now <= deadline:
                        continue
                    w.tasks_queue = [
                        t for t in w.tasks_queue
                        if t.get("subtask_id") != stid
                    ]
                    est = w.task_est.pop(stid, 0.0)
                    mem = w.task_mem.pop(stid, 0.0)
                    w.task_lease.pop(stid, None)
                    w.task_placed_at.pop(stid, None)
                    w.load_seconds = max(0.0, w.load_seconds - est)
                    w.mem_load_mb = max(0.0, w.mem_load_mb - mem)
                    reclaimed.append((wid, task, now - deadline))
            if dead:
                gauge_set("tpuml_workers_alive", len(self.workers))
        for wid, task, overdue_s in reclaimed:
            stid = task.get("subtask_id")
            if stid and self.ledger.is_done(stid):
                continue  # a duplicate attempt already delivered a result
            # a reclaim is a failed execution budget-wise: a subtask that
            # hangs EVERY worker must exhaust its budget and quarantine,
            # not cycle through reclaims until the job's hard deadline.
            # When this reclaim would be the final allowed execution, a
            # synthetic failed result goes to the coordinator (whose
            # ingest counts it and quarantines) instead of a re-dispatch.
            entry = self.ledger.get(stid)
            failures_so_far = entry.failures if entry is not None else 0
            record_event(
                "lease.reclaim",
                job_id=task.get("job_id"), subtask_id=stid, worker_id=wid,
                attempt=int(task.get("attempt") or 0),
                overdue_s=round(overdue_s, 3),
                failures_so_far=failures_so_far,
                budget_exhausted=(
                    failures_so_far + 1 >= self.cfg.retry_max_attempts
                ),
            )
            if failures_so_far + 1 >= self.cfg.retry_max_attempts:
                logger.error(
                    "Lease expired for %s on %s and its retry budget is "
                    "exhausted (%d prior failures); failing it for "
                    "quarantine", stid, wid, failures_so_far,
                )
                if self.bus is not None:
                    self.bus.publish(TOPIC_RESULT, {
                        "subtask_id": stid,
                        "job_id": task.get("job_id"),
                        "model_type": task.get("model_type"),
                        "parameters": task.get("parameters"),
                        "status": "failed",
                        "error": f"lease expired on worker {wid} "
                                 f"(hung or silent) with no budget left",
                        "error_kind": "lease_expired",
                        "attempt": int(task.get("attempt") or 0),
                        "worker_id": wid,
                    }, key=stid)
                continue
            self.ledger.record_failure(stid, wid)
            # COPY before stamping: the hung executor still holds this
            # dict (the bus delivers by reference) — mutating it in place
            # would let the zombie's eventual result carry the NEW attempt
            # id and defeat the attempt-stamp dedup
            task = dict(task)
            logger.warning(
                "Lease expired for %s on live worker %s; reclaiming and "
                "requeueing (attempt %d)",
                stid, wid, int(task.get("attempt") or 0) + 1,
            )
            self.ledger.next_attempt(task, exclude_worker=wid, reason="lease")
            counter_inc("tpuml_subtasks_retried_total", reason="lease")
            self._replace(task)
        for w in dead:
            logger.warning(
                "Worker %s dead (no heartbeat for >%ss); requeueing %d tasks",
                w.worker_id,
                self.cfg.dead_after_s,
                len(w.tasks_queue),
            )
            record_event(
                "worker.dead", worker_id=w.worker_id,
                heartbeat_silence_s=round(now - w.last_heartbeat, 3),
                n_requeued=len(w.tasks_queue),
            )
            self._drop_worker_gauges(w.worker_id)
            self._mesh_changed("death", w.worker_id)
            self._requeue(w.tasks_queue, from_worker=w.worker_id)
        self._speculate()
        if dead or reclaimed:
            self.refresh_health_metrics()
        # one time-series sample per sweep: the embedded metrics history
        # rides the cadence every other periodic decision already runs on
        # (obs/timeseries.py; throttled, no-op when disabled). The derived
        # route-p99 gauge refreshes first so the sample catches it even on
        # coordinators nothing ever scrapes (dashboard-only deployments).
        refresh_route_p99()
        timeseries_sample()
        # fleet-health tick rides the same cadence, AFTER the sample so
        # the alert rules see this sweep's datapoints
        hook = self.on_sweep_end
        if hook is not None:
            try:
                hook()
            except Exception:  # noqa: BLE001 — health derivation must not break the sweep
                logger.exception("on_sweep_end hook failed")
        return [w.worker_id for w in dead]

    def _speculate(self) -> List[Dict[str, Any]]:
        """Backup-task launch (Dean & Ghemawat OSDI'04; "The Tail at
        Scale"): an in-flight subtask whose age exceeds
        ``straggler_factor`` x the peer-median batch EWMA (floored at
        ``speculative_min_inflight_s``) gets ONE duplicate on an idle,
        breaker-closed worker, excluded from its owner. At most one launch
        per straggling worker per sweep; the coordinator's result ingest
        dedups by attempt id — first terminal result wins."""
        cfg = self.cfg
        if not cfg.speculative_enabled:
            return []
        shed = self.shed_check
        if shed is not None:
            try:
                overloaded = bool(shed())
            except Exception:  # noqa: BLE001 — the probe must not kill the sweep
                overloaded = False
            if overloaded:
                # graceful degradation (docs/ROBUSTNESS.md "Admission
                # control"): under overload the OPTIONAL duplicate work
                # goes first — capacity serves admitted jobs, not hedges
                counter_inc("tpuml_overload_shed_total", kind="speculative")
                return []
        now = time.time()
        launches: List[tuple] = []  # (owner_wid, task copy)
        with self._lock:
            measured = [
                (wid, w.ewma_batch_s)
                for wid, w in self.workers.items()
                if w.ewma_batch_s is not None
                and w.n_batches >= cfg.straggler_min_batches
            ]
            if len(measured) < 2:
                return []
            idle = sum(
                1 for w in self.workers.values()
                if not w.tasks_queue and w.breaker_state == "closed"
            )
            if idle == 0:
                return []
            for wid, w in self.workers.items():
                if len(launches) >= idle:
                    break
                if not w.tasks_queue:
                    continue
                others = sorted(v for o, v in measured if o != wid)
                if not others:
                    continue
                mid = len(others) // 2
                median = (
                    others[mid]
                    if len(others) % 2
                    else 0.5 * (others[mid - 1] + others[mid])
                )
                threshold = max(
                    cfg.speculative_min_inflight_s,
                    cfg.straggler_factor * median,
                )
                for task in w.tasks_queue:
                    stid = task.get("subtask_id")
                    if not stid:
                        continue
                    placed = w.task_placed_at.get(stid)
                    if placed is None or now - placed <= threshold:
                        continue
                    if self.ledger.was_speculated(stid) or self.ledger.is_done(stid):
                        continue
                    launches.append((wid, dict(task), now - placed))
                    break  # one duplicate per straggling worker per sweep
        launched = []
        for owner, task, age in launches:
            self.ledger.next_attempt(
                task, exclude_worker=owner, reason="speculative",
                speculative=True,
            )
            counter_inc("tpuml_speculative_launched_total")
            logger.warning(
                "Speculating duplicate of %s (in-flight %.1fs on %s, "
                "attempt %d)",
                task.get("subtask_id"), age, owner, task["attempt"],
            )
            record_event(
                "speculate.launch",
                job_id=task.get("job_id"),
                subtask_id=task.get("subtask_id"),
                worker_id=owner, attempt=task["attempt"],
                in_flight_s=round(age, 3),
            )
            tid = task.get("trace_id")
            if tid:
                with span("schedule.speculate", trace_id=tid, parent_id=None,
                          subtask_id=task.get("subtask_id"), owner=owner,
                          attempt=task["attempt"]):
                    pass
            self._replace(task)
            launched.append(task)
        return launched

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.cfg.sweep_interval_s):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001
                logger.exception("Heartbeat sweep failed")

    def _requeue(
        self, tasks: List[Dict[str, Any]], from_worker: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """Re-place tasks off a dead/unsubscribed/evicted worker. Each gets
        a fresh attempt id (attempt-stamp dedup stays sound even if a
        'dead' worker turns out to be a zombie and reports late) with the
        departed worker remembered as excluded; tasks whose ledger entry is
        already terminal are dropped, not re-run."""
        requeued = []
        for task in tasks:
            stid = task.get("subtask_id")
            if stid and self.ledger.is_done(stid):
                continue  # a duplicate attempt already delivered a result
            if stid:
                # copy before stamping: a zombie worker (swept as dead but
                # actually wedged) still holds this dict — in-place attempt
                # mutation would defeat the attempt-stamp dedup
                task = dict(task)
                self.ledger.next_attempt(
                    task, exclude_worker=from_worker, reason="requeue"
                )
            counter_inc("tpuml_subtasks_requeued_total")
            if self._replace(task) is not None:
                requeued.append(task)
        return requeued

    def _replace(self, task: Dict[str, Any]) -> Optional[str]:
        """Place a reclaimed/requeued/speculative task, or drop it back to
        the tasks topic when no worker survives."""
        wid = self.place(task)
        if wid is None:
            logger.error(
                "No surviving worker for %s; task dropped back to tasks topic",
                task.get("subtask_id"),
            )
            if self.bus is not None:
                self.bus.publish(TOPIC_TASKS, task)
        return wid
