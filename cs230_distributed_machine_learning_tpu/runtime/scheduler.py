"""Placement engine: learned-runtime, load/memory/speed-aware scheduling.

Capability parity with the reference scheduler service
(``aws-prod/scheduler/scheduler_service.py``), re-homed from Kafka-keyed
containers to mesh executors:

- ``WorkerState`` (scheduler_service.py:91-104): queued-runtime load,
  memory load vs capacity, EMA speed factor, heartbeat stamp, task queue.
- placement (scheduler_service.py:167-191): eligible = fits in memory
  (fallback: all, with a warning); score = effective_finish_time +
  est_runtime / max(speed, 1e-3); pick min.
- feedback (scheduler_service.py:295-351): on a metrics message, decrement
  load/memory, update ``speed_factor = clamp(0.2..5, 0.8*old +
  0.2*(est/actual))``, feed the runtime predictor.
- failure detection (scheduler_service.py:205-247): periodic sweep marks
  workers dead after ``dead_after_s`` of heartbeat silence and requeues
  their queued tasks onto survivors; ``unsubscribe`` does the same
  gracefully (scheduler.py:120-139). Elastic join assigns monotonically
  increasing ids (scheduler_service.py:157-165).

The engine is transport-agnostic: it consumes/produces on the in-process
TopicBus (runtime/queue.py) locally, and the same message schema rides DCN
RPC for multi-host agents (runtime/agent.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..obs import counter_inc, gauge_set, observe, span
from ..utils.config import get_config
from ..utils.logging import get_logger
from .predictor import RuntimePredictor

logger = get_logger("tpuml.scheduler")

TOPIC_TASKS = "tasks"
TOPIC_TRAIN = "train"


@dataclasses.dataclass
class WorkerState:
    worker_id: str
    mem_capacity_mb: float
    load_seconds: float = 0.0
    mem_load_mb: float = 0.0
    speed_factor: float = 1.0
    last_heartbeat: float = dataclasses.field(default_factory=time.time)
    tasks_queue: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # per-task bookkeeping for feedback decrements
    task_est: Dict[str, float] = dataclasses.field(default_factory=dict)
    task_mem: Dict[str, float] = dataclasses.field(default_factory=dict)
    alive: bool = True
    # ---- health telemetry (docs/OBSERVABILITY.md "Worker health") ----
    #: EWMA of this worker's batch wall time (None until the first batch)
    ewma_batch_s: Optional[float] = None
    #: batches absorbed into the EWMA (the straggler-guard denominator:
    #: outcomes arrive per SUBTASK, so counting them would let one cold
    #: multi-subtask batch satisfy the min-batches guard)
    n_batches: int = 0
    #: subtask outcomes reported for this worker
    n_completed: int = 0
    n_failed: int = 0

    def effective_finish_time(self) -> float:
        return self.load_seconds / max(self.speed_factor, 1e-3)

    def n_outcomes(self) -> int:
        return self.n_completed + self.n_failed

    def failure_ratio(self) -> float:
        total = self.n_outcomes()
        return self.n_failed / total if total else 0.0


class PlacementEngine:
    def __init__(self, bus=None, predictor: Optional[RuntimePredictor] = None):
        cfg = get_config().scheduler
        self.cfg = cfg
        self.bus = bus
        self.predictor = predictor or RuntimePredictor()
        self._lock = threading.RLock()
        self.workers: Dict[str, WorkerState] = {}
        self._next_id = 0
        self._stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        #: workers currently flagged as stragglers (transition logging)
        self._flagged: set = set()

    # ---------------- registry (subscribe/heartbeat/unsubscribe) ----------------

    def subscribe(self, mem_capacity_mb: Optional[float] = None, worker_id: Optional[str] = None) -> str:
        with self._lock:
            if worker_id is None:
                worker_id = f"worker-{self._next_id}"
                self._next_id += 1
            self.workers[worker_id] = WorkerState(
                worker_id=worker_id,
                mem_capacity_mb=mem_capacity_mb or self.cfg.default_mem_capacity_mb,
            )
            logger.info("Worker %s subscribed", worker_id)
            gauge_set("tpuml_workers_alive", len(self.workers))
            return worker_id

    def unsubscribe(self, worker_id: str) -> List[Dict[str, Any]]:
        """Remove a worker; requeue its queued tasks. Returns the requeued tasks."""
        with self._lock:
            state = self.workers.pop(worker_id, None)
            gauge_set("tpuml_workers_alive", len(self.workers))
        self._drop_worker_gauges(worker_id)
        if state is None:
            return []
        logger.info("Worker %s unsubscribed; requeueing %d tasks", worker_id, len(state.tasks_queue))
        return self._requeue(state.tasks_queue)

    def heartbeat(self, worker_id: str) -> bool:
        with self._lock:
            state = self.workers.get(worker_id)
            if state is None:
                return False
            state.last_heartbeat = time.time()
            return True

    def worker_snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                wid: {
                    "load_seconds": w.load_seconds,
                    "mem_load_mb": w.mem_load_mb,
                    "mem_capacity_mb": w.mem_capacity_mb,
                    "speed_factor": w.speed_factor,
                    "last_heartbeat": w.last_heartbeat,
                    "queue_depth": len(w.tasks_queue),
                }
                for wid, w in self.workers.items()
            }

    def queue_snapshot(self) -> Dict[str, List[str]]:
        with self._lock:
            return {
                wid: [t.get("subtask_id", "?") for t in w.tasks_queue]
                for wid, w in self.workers.items()
            }

    # ---------------- per-worker health ----------------

    def record_outcome(self, worker_id: str, ok: bool) -> None:
        """Count one subtask outcome against a worker — the failure-rate
        input. Fed by the cluster's result paths (in-process worker
        callbacks and remote /task_result ingest)."""
        with self._lock:
            w = self.workers.get(worker_id)
            if w is None:
                return
            if ok:
                w.n_completed += 1
            else:
                w.n_failed += 1

    def _straggler_ids_locked(self) -> set:
        """Workers whose batch EWMA exceeds ``straggler_factor`` x the
        median EWMA of their PEERS (own value excluded, so a two-worker
        pool can still flag its slow half). Requires
        ``straggler_min_batches`` reported outcomes — one slow cold batch
        must not brand a fresh worker. Caller holds the lock."""
        cfg = self.cfg
        measured = [
            (wid, w.ewma_batch_s)
            for wid, w in self.workers.items()
            if w.ewma_batch_s is not None
            and w.n_batches >= cfg.straggler_min_batches
        ]
        if len(measured) < 2:
            return set()
        flagged = set()
        for wid, ewma in measured:
            others = sorted(v for o, v in measured if o != wid)
            mid = len(others) // 2
            median = (
                others[mid]
                if len(others) % 2
                else 0.5 * (others[mid - 1] + others[mid])
            )
            if median > 0 and ewma > cfg.straggler_factor * median:
                flagged.add(wid)
        return flagged

    def _health_snapshot_locked(self) -> Dict[str, Dict[str, Any]]:
        now = time.time()
        stragglers = self._straggler_ids_locked()
        return {
            wid: {
                "ewma_batch_s": w.ewma_batch_s,
                "heartbeat_age_s": round(now - w.last_heartbeat, 3),
                "completed": w.n_completed,
                "failed": w.n_failed,
                "failure_ratio": w.failure_ratio(),
                "queue_depth": len(w.tasks_queue),
                "load_seconds": w.load_seconds,
                "speed_factor": w.speed_factor,
                "straggler": wid in stragglers,
            }
            for wid, w in self.workers.items()
        }

    def health_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-worker health view: EWMA batch latency, heartbeat age,
        outcome counts/failure ratio, queue depth, straggler flag — the
        ``GET /healthz`` body and the tpuml_worker_* gauge source."""
        with self._lock:
            return self._health_snapshot_locked()

    def refresh_health_metrics(self) -> Dict[str, Dict[str, Any]]:
        """Write the health snapshot into the ``tpuml_worker_*{wid=...}``
        gauges and log straggler transitions. Called on metrics feedback,
        at /metrics/prom scrape, and by the sweep; returns the snapshot so
        callers (healthz) reuse one read. Snapshot AND gauge writes happen
        under one lock hold: writing from a stale snapshot could resurrect
        a concurrently-removed worker's cells after _drop_worker_gauges
        already cleaned them — permanently, since refresh only writes
        registered workers."""
        with self._lock:
            snap = self._health_snapshot_locked()
            for wid, h in snap.items():
                if h["ewma_batch_s"] is not None:
                    gauge_set(
                        "tpuml_worker_ewma_batch_seconds", h["ewma_batch_s"],
                        wid=wid,
                    )
                gauge_set(
                    "tpuml_worker_heartbeat_age_seconds", h["heartbeat_age_s"],
                    wid=wid,
                )
                gauge_set(
                    "tpuml_worker_failure_ratio", h["failure_ratio"], wid=wid
                )
                gauge_set("tpuml_worker_queue_depth", h["queue_depth"], wid=wid)
                gauge_set(
                    "tpuml_worker_straggler",
                    1.0 if h["straggler"] else 0.0,
                    wid=wid,
                )
            current = {wid for wid, h in snap.items() if h["straggler"]}
            newly_flagged = sorted(current - self._flagged)
            recovered = sorted(self._flagged - current)
            self._flagged = current
        for wid in newly_flagged:
            logger.warning(
                "Worker %s flagged as straggler (batch EWMA %.3fs vs peers); "
                "placement now carries a +%.0fs advisory penalty",
                wid, snap[wid]["ewma_batch_s"], self.cfg.straggler_penalty_s,
            )
        for wid in recovered:
            logger.info("Worker %s no longer a straggler", wid)
        return snap

    def _drop_worker_gauges(self, worker_id: str) -> None:
        """A dead/unsubscribed worker must stop being exposed: remove its
        labeled cells from every per-worker gauge family."""
        from ..obs import REGISTRY

        for name in (
            "tpuml_worker_ewma_batch_seconds",
            "tpuml_worker_heartbeat_age_seconds",
            "tpuml_worker_failure_ratio",
            "tpuml_worker_queue_depth",
            "tpuml_worker_straggler",
        ):
            g = REGISTRY.get(name)
            if g is not None and hasattr(g, "remove"):
                g.remove(wid=worker_id)
        self._flagged.discard(worker_id)

    # ---------------- placement ----------------

    def place(self, task: Dict[str, Any]) -> Optional[str]:
        """Choose a worker for a task, update its load, and (when a bus is
        wired) publish to the train topic keyed by worker id. Returns the
        worker id, or None if no workers exist. The decision latency feeds
        the ``tpuml_scheduler_placement_seconds`` histogram and, when the
        task carries a trace id, a ``schedule.place`` span."""
        t_place = time.perf_counter()
        est = self.predictor.predict(task)
        mem_mb = float(task.get("mem_estimate_mb", 1.0))
        with self._lock:
            if not self.workers:
                return None
            eligible = [
                w
                for w in self.workers.values()
                if w.mem_load_mb + mem_mb <= w.mem_capacity_mb
            ]
            if not eligible:
                logger.warning(
                    "No worker fits task %s (%.0f MB); falling back to all",
                    task.get("subtask_id"),
                    mem_mb,
                )
                eligible = list(self.workers.values())
            # straggler consumption is ADVISORY: a flat score penalty on
            # flagged workers only — eligibility, fallback, and the score
            # formula for healthy workers are untouched. Reads the flag
            # set maintained by refresh_health_metrics (feedback/scrape/
            # sweep) — recomputing peer medians on every placement would
            # put O(W^2 log W) work on the hot path this module times.
            stragglers = self._flagged
            penalty = self.cfg.straggler_penalty_s
            best = min(
                eligible,
                key=lambda w: w.effective_finish_time()
                + est / max(w.speed_factor, 1e-3)
                + (penalty if w.worker_id in stragglers else 0.0),
            )
            best.load_seconds += est
            best.mem_load_mb += mem_mb
            best.tasks_queue.append(task)
            stid = task.get("subtask_id")
            best.task_est[stid] = est
            best.task_mem[stid] = mem_mb
            wid = best.worker_id
        elapsed = time.perf_counter() - t_place
        observe("tpuml_scheduler_placement_seconds", elapsed)
        counter_inc("tpuml_subtasks_dispatched_total")
        tid = task.get("trace_id")
        if tid:
            # the decision already ran: back-date the span over it
            with span("schedule.place", trace_id=tid, parent_id=None,
                      subtask_id=stid, worker=wid, est_runtime_s=est) as sp:
                sp.start = time.time() - elapsed
        if self.bus is not None:
            self.bus.publish(TOPIC_TRAIN, task, key=wid)
        return wid

    # ---------------- feedback ----------------

    def on_metrics(self, msg: Dict[str, Any]) -> None:
        """Consume a worker metrics message (schema: worker.py:233-243)."""
        wid = msg.get("worker_id")
        stid = msg.get("subtask_id")
        started = msg.get("started_at")
        finished = msg.get("finished_at")
        actual = None
        if started is not None and finished is not None:
            actual = max(float(finished) - float(started), 1e-3)
        with self._lock:
            w = self.workers.get(wid)
            if w is None:
                return
            est = w.task_est.pop(stid, 0.0)
            mem = w.task_mem.pop(stid, 0.0)
            w.load_seconds = max(0.0, w.load_seconds - est)
            w.mem_load_mb = max(0.0, w.mem_load_mb - mem)
            w.tasks_queue = [t for t in w.tasks_queue if t.get("subtask_id") != stid]
            if actual is not None and est > 0:
                ratio = est / actual
                w.speed_factor = min(
                    self.cfg.speed_factor_max,
                    max(
                        self.cfg.speed_factor_min,
                        (1 - self.cfg.speed_ema_alpha) * w.speed_factor
                        + self.cfg.speed_ema_alpha * ratio,
                    ),
                )
            # every subtask of a batch reports the SAME batch wall time, so
            # the health EWMA absorbs it once per batch — only the primary
            # message updates (messages without the marker, e.g. synthetic
            # feedback in tests, count as primary)
            batch_once = msg.get("batch_primary") is not False
            if actual is not None and batch_once:
                a = self.cfg.health_ema_alpha
                w.ewma_batch_s = (
                    actual
                    if w.ewma_batch_s is None
                    else (1 - a) * w.ewma_batch_s + a * actual
                )
                w.n_batches += 1
        if actual is not None:
            self.predictor.observe(msg, actual)
            if batch_once:
                self.refresh_health_metrics()

    # ---------------- failure detection ----------------

    def start_monitor(self) -> None:
        if self._monitor_thread is not None:
            return
        self._stop.clear()
        self._monitor_thread = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor_thread.start()

    def stop_monitor(self) -> None:
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=2)
            self._monitor_thread = None

    def sweep(self) -> List[str]:
        """One failure-detection pass; returns ids of workers declared dead."""
        now = time.time()
        dead: List[WorkerState] = []
        with self._lock:
            for wid, w in list(self.workers.items()):
                if now - w.last_heartbeat > self.cfg.dead_after_s:
                    dead.append(self.workers.pop(wid))
            if dead:
                gauge_set("tpuml_workers_alive", len(self.workers))
        for w in dead:
            logger.warning(
                "Worker %s dead (no heartbeat for >%ss); requeueing %d tasks",
                w.worker_id,
                self.cfg.dead_after_s,
                len(w.tasks_queue),
            )
            self._drop_worker_gauges(w.worker_id)
            self._requeue(w.tasks_queue)
        if dead:
            self.refresh_health_metrics()
        return [w.worker_id for w in dead]

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.cfg.sweep_interval_s):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001
                logger.exception("Heartbeat sweep failed")

    def _requeue(self, tasks: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        requeued = []
        for task in tasks:
            counter_inc("tpuml_subtasks_requeued_total")
            wid = self.place(task)
            if wid is None:
                logger.error(
                    "No surviving worker for %s; task dropped back to tasks topic",
                    task.get("subtask_id"),
                )
                if self.bus is not None:
                    self.bus.publish(TOPIC_TASKS, task)
            else:
                requeued.append(task)
        return requeued
