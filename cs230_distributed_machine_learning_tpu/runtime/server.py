"""Coordinator REST server: wire-compatible with the reference master.

Route parity with ``aws-prod/master/master.py:27-390`` (same paths, methods,
and response shapes — the home route enumerates them like master.py:30-44),
plus the reference scheduler's introspection endpoints (/workers, /queues —
scheduler.py:95-97,154-159) served from the placement engine when the
coordinator runs a cluster. SSE progress streaming (/train_status) keeps the
reference's event schema {job_status, tasks_pending, total_subtasks} with a
final event carrying job_result (master.py:237-266).

Built as a plain WSGI app on werkzeug (no Flask dependency): same
deployment surface, serve with ``serve()`` or any WSGI server.
"""

from __future__ import annotations

import json
from typing import Optional

from ..obs import (
    PARENT_HEADER,
    PROFILER,
    RECORDER,
    TIMESERIES,
    TRACE_HEADER,
    TRACER,
    activate,
    compare_critical_paths,
    counter_inc,
    export_trace,
    gauge_set,
    obs_enabled,
    observe,
    refresh_route_p99,
    render_prometheus,
    span,
    timeseries_sample,
)
from ..utils.serialization import json_safe
from .coordinator import Coordinator


#: Self-contained observability page (no external assets — fleets run
#: without egress). Tables over the JSON endpoints, 2 s auto-refresh.
_DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>tpuml coordinator</title>
<style>
 body{font:14px/1.45 system-ui,sans-serif;margin:24px;color:#1a1a1a;background:#fafafa}
 h1{font-size:18px;margin:0 0 4px} h2{font-size:15px;margin:24px 0 6px}
 table{border-collapse:collapse;width:100%;background:#fff}
 th,td{border:1px solid #ddd;padding:4px 8px;text-align:left;font-size:13px}
 th{background:#f0f0f0} .ok{color:#1a7f37} .bad{color:#b42318}
 #meta{color:#666;font-size:12px} code{background:#eee;padding:0 3px}
</style></head><body>
<h1>tpuml coordinator</h1>
<div id="meta">health: <span id="health">…</span> · refreshed <span id="ts">never</span>
 · JSON: <code>/jobs</code> <code>/workers</code> <code>/queues</code> <code>/supervisor</code>
 <code>/metrics/prom</code> <code>/metrics/history?name=</code> <code>/trace/&lt;job_id&gt;</code>
 <code>/critical_path/&lt;job_id&gt;</code> <code>/trace/&lt;job_id&gt;/export</code>
 <code>/cost/&lt;job_id&gt;</code> <code>/explain/&lt;job_id&gt;/&lt;subtask_id&gt;</code>
 <code>/curves/&lt;job_id&gt;</code> <code>/events</code> <code>/predictor/calibration</code> <code>/healthz</code>
 <code>/alerts</code> <code>/autoscale</code></div>
<h2>Jobs</h2><table id="jobs"><thead><tr><th>job</th><th>model</th><th>dataset</th>
<th>status</th><th>done</th><th>failed</th><th>pruned</th><th>diverged</th><th>total</th><th>session</th></tr></thead><tbody></tbody></table>
<h2>Learning curves (latest job)</h2>
<div id="curves" style="background:#fff;border:1px solid #ddd;padding:8px;font-size:12px">no curves yet</div>
<h2>Latest job trace</h2>
<div id="trace" style="background:#fff;border:1px solid #ddd;padding:8px;font-size:12px">no trace yet</div>
<h2>Critical path</h2>
<div id="critpath" style="background:#fff;border:1px solid #ddd;padding:8px;font-size:12px">no critical path yet</div>
<h2>Latest job cost</h2>
<div id="cost" style="background:#fff;border:1px solid #ddd;padding:8px;font-size:12px">no cost data yet</div>
<h2>Metrics history</h2>
<div id="spark" style="background:#fff;border:1px solid #ddd;padding:8px;font-size:12px">no samples yet</div>
<h2>Perf observatory</h2>
<div id="perfspark" style="background:#fff;border:1px solid #ddd;padding:8px;font-size:12px">no samples yet</div>
<h2>Fleet health</h2>
<div id="autoscale" style="background:#fff;border:1px solid #ddd;padding:8px;font-size:12px">no signals yet</div>
<table id="alerts"><thead></thead><tbody></tbody></table>
<h2>Flight recorder (latest events)</h2>
<table id="events"><thead></thead><tbody></tbody></table>
<h2>Workers</h2><table id="workers"><thead></thead><tbody></tbody></table>
<h2>Queues</h2><table id="queues"><thead></thead><tbody></tbody></table>
<h2>Supervised agents</h2><table id="sup"><thead></thead><tbody></tbody></table>
<script>
const get = u => fetch(u).then(r => r.ok ? r.json() : null).catch(() => null);
// quotes escaped too: esc() output lands inside attribute values (the
// trace rows' title tooltips), and attrs carry client-controlled strings
const esc = s => String(s ?? "").replace(/[&<>"']/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
// cell renderer: arrays (e.g. a worker's queued-subtask list) collapse to
// a count + sample, never one column per index
const cell = v => Array.isArray(v)
  ? `${v.length} queued${v.length ? ": " + v.slice(0, 3).join(", ") + (v.length > 3 ? ", …" : "") : ""}`
  : (typeof v === "object" && v ? JSON.stringify(v) : v);
function kvTable(el, obj){
  const rows = Object.entries(obj || {});
  if (!rows.length){ el.tBodies[0].innerHTML = "<tr><td>none</td></tr>"; el.tHead.innerHTML=""; return; }
  const plain = rows.every(([,v]) => typeof v !== "object" || !v || Array.isArray(v));
  const cols = plain ? null
    : [...new Set(rows.flatMap(([,v]) => Object.keys(v)))];
  el.tHead.innerHTML = plain
    ? "<tr><th>id</th><th>value</th></tr>"
    : "<tr><th>id</th>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  el.tBodies[0].innerHTML = rows.map(([k, v]) =>
    `<tr><td>${esc(k)}</td>` + (plain
      ? `<td>${esc(cell(v))}</td>`
      : cols.map(c => `<td>${esc(cell(v[c]))}</td>`).join("")) + "</tr>").join("");
}
function listTable(el, arr){
  if (!arr || !arr.length){ el.tBodies[0].innerHTML = "<tr><td>none</td></tr>"; el.tHead.innerHTML=""; return; }
  const cols = Object.keys(arr[0]);
  el.tHead.innerHTML = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  el.tBodies[0].innerHTML = arr.map(r =>
    "<tr>" + cols.map(c => `<td>${esc(JSON.stringify(r[c]))}</td>`).join("") + "</tr>").join("");
}
// span-tree timeline: one row per span, bar offset/width proportional to
// [start, end] within the trace window, indented by tree depth
function renderTrace(el, data){
  if (!data || !data.spans || !data.spans.length){ el.textContent = "no trace yet"; return; }
  const t0 = Math.min(...data.spans.map(s => s.start));
  const t1 = Math.max(...data.spans.map(s => s.end));
  const total = Math.max(t1 - t0, 1e-6);
  const rows = [];
  const walk = (nodes, depth) => (nodes || []).forEach(n => {
    rows.push({n, depth}); walk(n.children, depth + 1); });
  walk(data.tree, 0);
  el.innerHTML =
    `<div style="color:#666">trace <code>${esc(data.trace_id)}</code> · ` +
    `${data.spans.length} spans · ${(total * 1000).toFixed(1)} ms</div>` +
    rows.map(({n, depth}) => {
      const off = 100 * (n.start - t0) / total;
      const w = Math.max(100 * (n.end - n.start) / total, 0.4);
      return `<div style="display:flex;align-items:center;margin:1px 0">` +
        `<span style="width:230px;padding-left:${depth * 12}px;overflow:hidden;` +
        `white-space:nowrap" title="${esc(JSON.stringify(n.attrs))}">${esc(n.name)}</span>` +
        `<span style="flex:1;position:relative;height:10px;background:#f4f4f4">` +
        `<span style="position:absolute;left:${off}%;width:${w}%;height:10px;` +
        `background:${n.attrs && n.attrs.synthesized ? "#9bb8d3" : "#4a7fb5"}"></span></span>` +
        `<span style="width:80px;text-align:right">${((n.end - n.start) * 1000).toFixed(1)} ms</span></div>`;
    }).join("");
}
// critical-path waterfall (GET /critical_path/<job_id>): one stacked bar
// tiling the job wall plus a ranked per-segment table; untraced slices
// render hatched-gray so coverage gaps are visible, not hidden
const SEG_COLORS = {
  "frontend.proxy": "#8e7cc3", "submit.http": "#6fa8dc", submit: "#4a7fb5",
  expand: "#3d6d9e", "queue.wait": "#e6b84c", place: "#c27ba0",
  "reclaim.wait": "#b42318", "executor.compile": "#93c47d",
  "executor.stage": "#76a5af", "executor.dispatch": "#45818e",
  "executor.fetch": "#6aa84f", execute: "#38761d",
  "result.ingest": "#a2c4c9", aggregate: "#674ea7", untraced: "#d9d9d9",
};
function renderCritPath(el, cp){
  if (!cp || !cp.segments || !cp.segments.length){
    el.textContent = "no critical path yet"; return; }
  const wall = Math.max(cp.wall_s, 1e-9);
  el.innerHTML =
    `<div style="color:#666">job <code>${esc(cp.job_id)}</code> · ` +
    `wall ${(cp.wall_s * 1000).toFixed(1)} ms · coverage ` +
    `${(100 * cp.coverage).toFixed(1)}% · dominant ` +
    `<b>${esc((cp.dominant || [])[0] || "")}</b>` +
    (cp.n_reclaims ? ` · <span class="bad">${esc(cp.n_reclaims)} reclaim(s)</span>` : "") +
    (cp.speculated ? ` · speculative win` : "") + `</div>` +
    `<div style="display:flex;height:18px;margin:6px 0;border:1px solid #ccc">` +
    cp.segments.map(s =>
      `<span title="${esc(s.name)} ${(s.duration_s * 1000).toFixed(1)} ms" ` +
      `style="width:${(100 * s.duration_s / wall).toFixed(3)}%;` +
      `background:${SEG_COLORS[s.name] || "#999"}"></span>`).join("") +
    `</div>` +
    `<table><thead><tr><th>segment</th><th>total</th><th>share</th></tr></thead><tbody>` +
    (cp.dominant || []).map(n =>
      `<tr><td><span style="display:inline-block;width:10px;height:10px;` +
      `background:${SEG_COLORS[n] || "#999"}"></span> ${esc(n)}</td>` +
      `<td>${((cp.totals[n] || 0) * 1000).toFixed(1)} ms</td>` +
      `<td>${(100 * (cp.totals[n] || 0) / wall).toFixed(1)}%</td></tr>`).join("") +
    `</tbody></table>`;
}
// SI-ish magnitude formatter for FLOP/byte counts
const fmt = n => n == null ? "\\u2013"
  : n >= 1e12 ? (n / 1e12).toFixed(2) + " T"
  : n >= 1e9 ? (n / 1e9).toFixed(2) + " G"
  : n >= 1e6 ? (n / 1e6).toFixed(2) + " M"
  : String(Math.round(n));
const pct = v => v == null ? "\\u2013" : (100 * v).toFixed(1) + "%";
// per-job device cost report (GET /cost/<job_id>): totals line + one row
// per executed (dataset, model) group
function renderCost(el, c){
  if (!c || !c.n_groups){ el.textContent = "no cost data yet"; return; }
  el.innerHTML =
    `<div style="color:#666">job <code>${esc(c.job_id)}</code> · ` +
    `${(c.device_seconds || 0).toFixed(3)} device-s · ` +
    `model FLOPs ${fmt(c.model_flops)} · bytes ${fmt(c.bytes_accessed)} · ` +
    `MFU ${c.mfu == null ? "n/a" : pct(c.mfu)}</div>` +
    `<table><thead><tr><th>model</th><th>dataset</th><th>trials</th>` +
    `<th>device-s</th><th>FLOPs</th><th>bytes</th><th>MFU</th>` +
    `<th>HBM peak</th></tr></thead><tbody>` +
    c.groups.map(g => `<tr><td>${esc(g.model_type)}</td>` +
      `<td>${esc(g.dataset_id)}</td><td>${esc(g.n_subtasks)}</td>` +
      `<td>${(g.device_seconds || 0).toFixed(3)}</td>` +
      `<td>${fmt(g.model_flops != null ? g.model_flops : g.xla_flops)}</td>` +
      `<td>${fmt(g.bytes_accessed)}</td><td>${pct(g.mfu)}</td>` +
      `<td>${fmt(g.hbm_peak_bytes)}</td></tr>`).join("") +
    `</tbody></table>`;
}
// sparkline panels over GET /metrics/history (the embedded time-series
// ring, obs/timeseries.py): per-worker queue depth and breaker state,
// the retry RATE derived from the counter's samples, and MFU per model
const SPARKS = [
  {name: "tpuml_worker_queue_depth", title: "queue depth", mode: "raw"},
  {name: "tpuml_subtasks_retried_total", title: "retries/s", mode: "rate"},
  {name: "tpuml_worker_breaker_state", title: "breaker state", mode: "raw"},
  {name: "tpuml_executor_mfu", title: "MFU", mode: "raw"},
];
// perf-observatory panel (docs/OBSERVABILITY.md "Perf observatory"):
// per-route p99 (the derived gauge the scrape refreshes) and the
// device-seconds-per-phase RATE (fraction of wall the device pipeline
// spends staging / compiling / dispatching / fetching)
const PERF_SPARKS = [
  {name: "tpuml_http_route_p99_seconds", title: "route p99 (s)", mode: "raw"},
  {name: "tpuml_executor_device_seconds_total",
   title: "device-s/s by phase", mode: "rate"},
  {name: "tpuml_sse_lag_seconds", title: "SSE lag (s)", mode: "raw"},
];
function sparkSvg(pts){
  if (pts.length < 2) return "";
  const t0 = pts[0][0], t1 = pts[pts.length - 1][0];
  const vs = pts.map(p => p[1]);
  const vmin = Math.min(...vs, 0), vmax = Math.max(...vs);
  const W = 160, H = 26;
  const poly = pts.map(([t, v]) =>
    `${(W * (t - t0) / Math.max(t1 - t0, 1e-9)).toFixed(1)},` +
    `${(H - 2 - (H - 4) * (v - vmin) / Math.max(vmax - vmin, 1e-9)).toFixed(1)}`
  ).join(" ");
  return `<svg width="${W}" height="${H}" style="background:#f4f4f4;vertical-align:middle">` +
    `<polyline points="${poly}" fill="none" stroke="#4a7fb5" stroke-width="1.5"/></svg>`;
}
// counter samples -> per-interval rate (clamped at 0: restarts reset)
const rate = s => s.slice(1).map((p, i) =>
  [p[0], Math.max(p[1] - s[i][1], 0) / Math.max(p[0] - s[i][0], 1e-9)]);
async function renderSparks(el, sparks){
  const blocks = await Promise.all(sparks.map(async p => {
    const h = await get(`/metrics/history?name=${p.name}`);
    const series = ((h && h.series) || []).filter(s => s.samples.length > 1);
    if (!series.length) return "";
    return `<div style="margin:2px 0"><b>${esc(p.title)}</b> ` +
      series.slice(0, 8).map(s => {
        const pts = p.mode === "rate" ? rate(s.samples) : s.samples;
        if (!pts.length) return "";
        const last = pts[pts.length - 1][1];
        const lbl = Object.values(s.labels).join(",") || "total";
        return `<span style="margin-right:12px;white-space:nowrap">` +
          `${esc(lbl)} ${sparkSvg(pts)} <code>${(+last).toPrecision(3)}</code></span>`;
      }).join("") + `</div>`;
  }));
  const html = blocks.filter(Boolean).join("");
  el.innerHTML = html || "no samples yet";
}
// learning-curve panel (GET /curves/<job_id> — docs/OBSERVABILITY.md
// "Trial telemetry plane"): one sparkline per trial curve, drawn from
// the record's primary channel (loss > score > gmax), split 0. Diverged
// trials are flagged; None points (non-finite on device) are skipped.
function renderCurves(el, c){
  if (!c || !c.curves || !c.curves.length){ el.textContent = "no curves yet"; return; }
  el.innerHTML =
    `<div style="color:#666">job <code>${esc(c.job_id)}</code> · ` +
    `${c.n_curves} curves · ${c.tasks_diverged || 0} diverged</div>` +
    c.curves.slice(-10).map(e => {
      const rec = e.curve || {};
      const ch = rec.loss ? "loss" : (rec.score ? "score" : "gmax");
      const row = ((rec[ch] || [])[0] || []);
      const pts = row.map((v, i) => [i, v]).filter(p => p[1] != null && isFinite(p[1]));
      const tail = (rec.tail || [])[0];
      return `<div style="margin:2px 0;white-space:nowrap">` +
        `<code>${esc(e.subtask_id)}</code> r${esc(e.rung)} ` +
        sparkSvg(pts) + ` <b>${esc(ch)}</b>` +
        (tail == null ? "" : ` tail <code>${(+tail).toPrecision(3)}</code>`) +
        (e.diverged ? ` <span class="bad">diverged</span>` : "") + `</div>`;
    }).join("");
}
// fleet health panel (docs/OBSERVABILITY.md "Fleet health plane"):
// the derived capacity signals + per-rule alert states
function renderHealth(scaleEl, alertsEl, sc, al){
  if (sc && sc.desired_workers != null){
    const held = sc.hysteresis && sc.hysteresis.scale_down_held;
    const sig = sc.signals || {};
    scaleEl.innerHTML =
      `desired workers <b>${esc(sc.desired_workers)}</b> (live ${esc(sc.live_workers)})` +
      ` \\u00b7 desired shards <b>${esc(sc.desired_shards)}</b> (now ${esc(sc.n_shards)})` +
      (held ? ` \\u00b7 <span class="bad">scale-down held (drain)</span>` : "") +
      `<div style="color:#666">backlog ${esc(sig.backlog_seconds)} s \\u00b7 ` +
      `inflight ${esc(sig.inflight_jobs)} jobs / ${esc(sig.pending_subtasks)} subtasks \\u00b7 ` +
      `admission ${esc(((sig.admission_utilization || 0) * 100).toFixed(0))}% \\u00b7 ` +
      `p99 ${esc(sig.route_p99_s)} s \\u00b7 pressure ${esc(sig.pressure)}</div>`;
  } else scaleEl.textContent = "no signals yet";
  const rows = ((al && al.alerts) || []).map(a => ({
    rule: a.rule,
    state: a.state === "firing" ? "\\u25cf firing" : a.state,
    value: a.value == null ? "\\u2013" : (+a.value).toPrecision(3),
    threshold: `${a.cmp} ${a.threshold}`, severity: a.severity,
    since: a.for_s == null ? "" : `${a.for_s.toFixed(0)}s`,
  }));
  listTable(alertsEl, rows);
}
// flight-recorder feed: the newest events, newest first
async function renderEvents(el, ev){
  const rows = ((ev && ev.events) || []).slice(-15).reverse().map(e => ({
    seq: e.seq, kind: e.kind,
    subtask: e.subtask_id ? `${(e.job_id || "").slice(0, 8)}/${e.subtask_id}` : "",
    worker: e.worker_id || "", attempt: e.attempt == null ? "" : e.attempt,
    detail: JSON.stringify(e.data).slice(0, 120),
  }));
  listTable(el, rows);
}
async function tick(){
  // fire-and-forget scrape: refreshes the derived gauges (route p99) and
  // drives the time-series sampler even on direct-mode coordinators that
  // have no sweep loop and no external Prometheus
  fetch("/metrics/prom").catch(() => {});
  const [h, jobs, workers, queues, sup, ev, al, sc] = await Promise.all(
    ["/health", "/jobs", "/workers", "/queues", "/supervisor",
     "/events?limit=500", "/alerts", "/autoscale"].map(get));
  const he = document.getElementById("health");
  he.textContent = h ? h.status : "unreachable";
  he.className = h && h.status === "ok" ? "ok" : "bad";
  document.getElementById("jobs").tBodies[0].innerHTML =
    (Array.isArray(jobs) ? jobs : []).map(j => `<tr>
    <td>${esc(j.job_id)}</td><td>${esc(j.model_type)}</td><td>${esc(j.dataset_id)}</td>
    <td class="${j.status === "completed" ? "ok" : (j.status === "failed" || j.status === "completed_with_failures") ? "bad" : ""}">${esc(j.status)}</td>
    <td>${esc(j.completed_subtasks)}</td><td>${esc(j.failed_subtasks)}</td>
    <td>${esc(j.pruned_subtasks || 0)}</td>
    <td class="${j.diverged_subtasks ? "bad" : ""}">${esc(j.diverged_subtasks || 0)}</td>
    <td>${esc(j.total_subtasks)}</td><td>${esc((j.session_id || "").slice(0, 8))}</td></tr>`).join("")
    || "<tr><td colspan=10>no jobs yet</td></tr>";
  kvTable(document.getElementById("workers"), workers);
  kvTable(document.getElementById("queues"), queues);
  listTable(document.getElementById("sup"), sup);
  renderEvents(document.getElementById("events"), ev);
  renderHealth(document.getElementById("autoscale"),
               document.getElementById("alerts"), sc, al);
  await renderSparks(document.getElementById("spark"), SPARKS);
  await renderSparks(document.getElementById("perfspark"), PERF_SPARKS);
  const latest = Array.isArray(jobs) && jobs.length ? jobs[0].job_id : null;
  renderTrace(document.getElementById("trace"),
              latest ? await get(`/trace/${latest}`) : null);
  renderCritPath(document.getElementById("critpath"),
                 latest ? await get(`/critical_path/${latest}`) : null);
  renderCurves(document.getElementById("curves"),
               latest ? await get(`/curves/${latest}`) : null);
  renderCost(document.getElementById("cost"),
             latest ? await get(`/cost/${latest}`) : null);
  document.getElementById("ts").textContent = new Date().toLocaleTimeString();
}
tick(); setInterval(tick, 2000);
</script></body></html>
"""


def create_app(coordinator: Optional[Coordinator] = None):
    from werkzeug.exceptions import HTTPException, NotFound
    from werkzeug.routing import Map, Rule
    from werkzeug.wrappers import Request, Response

    coord = coordinator or Coordinator()

    url_map = Map(
        [
            Rule("/", endpoint="home", methods=["GET"]),
            Rule("/health", endpoint="health", methods=["GET"]),
            Rule("/create_session", endpoint="create_session", methods=["POST"]),
            Rule("/download_data/<sid>", endpoint="download_data", methods=["POST"]),
            Rule("/check_data/<sid>", endpoint="check_data", methods=["GET"]),
            Rule("/preprocess/<sid>", endpoint="preprocess", methods=["POST"]),
            Rule("/train/<sid>", endpoint="train", methods=["POST"]),
            Rule("/train_status/<sid>", endpoint="train_status", methods=["POST"]),
            Rule("/check_status/<sid>/<jid>", endpoint="check_status", methods=["GET"]),
            Rule("/metrics/<sid>/<jid>", endpoint="metrics", methods=["GET"]),
            Rule("/download_model/<sid>/<jid>", endpoint="download_model", methods=["GET"]),
            Rule("/workers", endpoint="workers", methods=["GET"]),
            Rule("/queues", endpoint="queues", methods=["GET"]),
            Rule("/supervisor", endpoint="supervisor", methods=["GET"]),
            # visual observability (the reference ran kafka-ui for this,
            # docker-compose.yml:69-84): one self-contained HTML page over
            # the JSON introspection endpoints + a flat job feed
            Rule("/jobs", endpoint="jobs", methods=["GET"]),
            Rule("/dashboard", endpoint="dashboard", methods=["GET"]),
            # observability plane (docs/OBSERVABILITY.md): Prometheus
            # exposition of the unified metrics registry, per-job span
            # trees, the agents' span-shipping ingest, the per-job device
            # cost report, and the deep-health probe
            Rule("/metrics/prom", endpoint="metrics_prom", methods=["GET"]),
            # on-demand deep profiling (docs/OBSERVABILITY.md "Perf
            # observatory"): bracket a live workload with a programmatic
            # jax.profiler capture dumped under <journal_dir>/profile/
            Rule("/profile/start", endpoint="profile_start", methods=["POST"]),
            Rule("/profile/stop", endpoint="profile_stop", methods=["POST"]),
            Rule("/profile/status", endpoint="profile_status", methods=["GET"]),
            Rule("/trace/<jid>", endpoint="trace", methods=["GET"]),
            Rule("/trace/<jid>/export", endpoint="trace_export",
                 methods=["GET"]),
            Rule("/critical_path/<jid>", endpoint="critical_path_report",
                 methods=["GET"]),
            Rule("/trace_spans/<wid>", endpoint="trace_spans", methods=["POST"]),
            Rule("/cost/<jid>", endpoint="cost", methods=["GET"]),
            Rule("/healthz", endpoint="healthz", methods=["GET"]),
            # liveness/readiness split (docs/ROBUSTNESS.md "Coordinator
            # recovery"): /livez answers as long as the process serves;
            # /readyz is 503 until journal replay + in-flight re-queue
            # finished, so load balancers and the chaos harness can gate
            Rule("/livez", endpoint="livez", methods=["GET"]),
            Rule("/readyz", endpoint="readyz", methods=["GET"]),
            # flight recorder + explainability (docs/OBSERVABILITY.md
            # "Flight recorder"): per-subtask decision timelines, the
            # event firehose, predictor calibration, and the embedded
            # metrics time-series history
            Rule("/explain/<jid>/<stid>", endpoint="explain", methods=["GET"]),
            Rule("/explain/<jid>", endpoint="explain_job", methods=["GET"]),
            # trial telemetry plane (docs/OBSERVABILITY.md "Trial
            # telemetry plane"): per-trial learning curves captured
            # in-fit, plus the numerical-health watchdog's verdicts
            Rule("/curves/<jid>", endpoint="curves_job", methods=["GET"]),
            Rule("/curves/<jid>/<stid>", endpoint="curves_subtask",
                 methods=["GET"]),
            Rule("/events", endpoint="events", methods=["GET"]),
            # fleet health plane (docs/OBSERVABILITY.md "Fleet health
            # plane"): SLO alert states and the derived capacity signals
            # an external autoscaler acts on
            Rule("/alerts", endpoint="alerts", methods=["GET"]),
            Rule("/autoscale", endpoint="autoscale", methods=["GET"]),
            Rule("/metrics/history", endpoint="metrics_history",
                 methods=["GET"]),
            Rule("/predictor/calibration", endpoint="predictor_calibration",
                 methods=["GET"]),
            # worker-agent control plane (reference scheduler.py:95-159)
            Rule("/subscribe", endpoint="subscribe", methods=["POST"]),
            Rule("/unsubscribe/<wid>", endpoint="unsubscribe", methods=["POST"]),
            Rule("/heartbeat/<wid>", endpoint="heartbeat", methods=["POST"]),
            Rule("/next_tasks/<wid>", endpoint="next_tasks", methods=["GET"]),
            Rule("/task_result/<wid>", endpoint="task_result", methods=["POST"]),
            Rule("/task_metrics/<wid>", endpoint="task_metrics", methods=["POST"]),
            # dataset distribution for remote agents: the DCN replacement
            # for the reference's shared EFS volume (compose.yml:92-94)
            Rule("/dataset/<dataset_id>", endpoint="dataset", methods=["GET"]),
            # SPMD slice liveness: every rank of a multi-process mesh
            # heartbeats here, and each rank's watchdog reads the others'
            # ages — a SIGKILLed sibling is detected even while survivors
            # block inside a collective (runtime/agent._slice_watchdog)
            Rule("/slice_heartbeat/<slice_id>/<int:rank>",
                 endpoint="slice_heartbeat", methods=["POST"]),
            Rule("/slice_status/<slice_id>", endpoint="slice_status",
                 methods=["GET"]),
            # shard-to-shard rebalancing plane (docs/ROBUSTNESS.md "Shard
            # rebalancing"): peers dialing peers, never client traffic
            Rule("/migrate_in", endpoint="migrate_in", methods=["POST"]),
            Rule("/steal_candidates", endpoint="steal_candidates",
                 methods=["GET"]),
            Rule("/steal_tasks", endpoint="steal_tasks", methods=["POST"]),
            Rule("/peer_result", endpoint="peer_result", methods=["POST"]),
        ]
    )

    import threading as _threading
    import time as _time

    _slices: dict = {}
    _slices_lock = _threading.Lock()

    def _json(data, status=200):
        return Response(
            json.dumps(json_safe(data)), status=status, mimetype="application/json"
        )

    def home(request):
        return _json(
            {
                "service": "tpuml-coordinator",
                "endpoints": [
                    "POST /create_session",
                    "POST /download_data/<session_id>",
                    "GET  /check_data/<session_id>?dataset_name=",
                    "POST /preprocess/<session_id>",
                    "POST /train/<session_id>",
                    "POST /train_status/<session_id>  (SSE)",
                    "GET  /check_status/<session_id>/<job_id>",
                    "GET  /metrics/<session_id>/<job_id>",
                    "GET  /download_model/<session_id>/<job_id>",
                    "GET  /workers",
                    "GET  /queues",
                    "GET  /jobs",
                    "GET  /dashboard  (HTML)",
                    "GET  /metrics/prom  (Prometheus exposition)",
                    "POST /profile/start  (on-demand jax.profiler capture)",
                    "POST /profile/stop",
                    "GET  /profile/status",
                    "GET  /metrics/history?name=&since=  (embedded time series)",
                    "GET  /trace/<job_id>  (span tree)",
                    "GET  /trace/<job_id>/export?format=perfetto|otlp",
                    "GET  /critical_path/<job_id>[?compare=<job_id>]",
                    "GET  /cost/<job_id>  (device cost report)",
                    "GET  /explain/<job_id>/<subtask_id>  (decision timeline)",
                    "GET  /curves/<job_id>[/<subtask_id>]  (learning curves)",
                    "GET  /events?since=&limit=  (flight-recorder firehose)",
                    "GET  /predictor/calibration  (predicted-vs-actual stats)",
                    "GET  /health",
                    "GET  /healthz  (deep health: device, workers, stragglers)",
                    "GET  /livez  (liveness probe)",
                    "GET  /readyz  (readiness: 503 while recovering)",
                ],
            }
        )

    def health(request):
        out = {"status": "ok"}
        if coord.shard_id is not None:
            out["shard"] = coord.shard_id
            out["n_shards"] = coord.n_shards
        sup = getattr(coord, "agent_supervisor", None)
        if sup is not None:
            slots = sup.status()
            out["agent_slots"] = {
                "alive": sum(1 for s in slots if s["alive"]),
                "total": len(slots),
                "gave_up": sum(1 for s in slots if s["gave_up"]),
            }
            if out["agent_slots"]["gave_up"] == len(slots) and slots:
                out["status"] = "degraded"  # every executor slot is down
        return _json(out)

    def _priority_or_400(value, default=0):
        """Malformed client input must 400, not 500 out of int()."""
        if value is None:
            return default
        try:
            return int(value)
        except (TypeError, ValueError):
            from werkzeug.exceptions import BadRequest

            raise BadRequest(f"priority must be an integer, got {value!r}")

    def create_session(request):
        # optional body {"session_id": ..., "priority": ...}: a sharded
        # front end mints the session id itself (so shard_of(sid) and the
        # owning shard agree — runtime/sharding.py) and may carry the
        # session's QoS lane; a bare POST keeps the legacy mint-here path
        body = request.get_json(force=True, silent=True) or {}
        sid_req = body.get("session_id")
        if sid_req is not None:
            from werkzeug.exceptions import BadRequest

            if coord.shard_id is None:
                # unsharded coordinators always mint server-side (the
                # legacy contract): honoring client ids here would let
                # two clients silently share — and read — one session
                # via the idempotent re-create path
                sid_req = None
            else:
                from .sharding import shard_of

                if shard_of(sid_req, coord.n_shards) != coord.shard_id:
                    # a session stored here but hashing elsewhere would
                    # be permanently unreachable through the front ends
                    raise BadRequest(
                        f"session id {sid_req!r} hashes to shard "
                        f"{shard_of(sid_req, coord.n_shards)}, not this "
                        f"shard ({coord.shard_id})"
                    )
        sid = coord.create_session(
            sid_req, priority=_priority_or_400(body.get("priority")),
        )
        out = {"session_id": sid}
        if coord.shard_id is not None:
            out["shard"] = coord.shard_id
        return _json(out, status=201)

    def download_data(request, sid):
        body = request.get_json(force=True)
        return _json(
            coord.download_data(
                sid, body["dataset_url"], body["dataset_name"], body["dataset_type"]
            )
        )

    def check_data(request, sid):
        return _json(coord.check_data(sid, request.args["dataset_name"]))

    def preprocess(request, sid):
        body = request.get_json(force=True)
        return _json(coord.preprocess(sid, body["dataset_id"], body.get("config")))

    def _admission_reject(sid):
        """429/503 + Retry-After for a submit the coordinator must not
        accept (admission caps, or recovery still in progress) — the
        overload contract of docs/ROBUSTNESS.md. None when admitted."""
        rejection = coord.admission_check(sid)
        if rejection is None:
            return None
        return Response(
            json.dumps(json_safe({
                "status": "rejected",
                "reason": rejection["reason"],
                "retry_after_s": rejection["retry_after_s"],
            })),
            status=rejection["status"],
            mimetype="application/json",
            headers={"Retry-After": f"{rejection['retry_after_s']:g}"},
        )

    def train(request, sid):
        reject = _admission_reject(sid)
        if reject is not None:
            return reject
        body = request.get_json(force=True)
        if "priority" in body:
            body["priority"] = _priority_or_400(body["priority"], None)
        return _json(coord.submit_train(sid, body))

    def train_status(request, sid):
        body = request.get_json(force=True)
        # an SSE RESUME (known job_id) is a read, not new load — it must
        # never be rejected, or a reconnecting client could not follow the
        # job it already owns through the very overload that dropped it.
        # The lookup uses the CANONICAL (shard-stamped) id: a client
        # resuming under its own minted id must still match.
        known = bool(
            body.get("job_id")
            and coord.store.has_job(
                sid, coord.canonical_job_id(body["job_id"])
            )
        )
        if known:
            # a resume against a job this shard ALREADY handed off must
            # redirect, not resubmit: re-running it here would mint a
            # second live copy of a job the recipient shard now owns
            moved = _moved(coord.canonical_job_id(body["job_id"]))
            if moved is not None:
                return moved
        if not known:
            reject = _admission_reject(sid)
            if reject is not None:
                return reject
        if "priority" in body:
            body["priority"] = _priority_or_400(body["priority"], None)
        submit = coord.submit_train(sid, body)
        job_id = submit["job_id"]

        def stream():
            # Time-to-first-event: the first progress snapshot is yielded
            # immediately (stream_status reads before its first tick
            # sleep), but common SSE clients buffer reads — http.client's
            # chunked read(amt) blocks until ~amt BYTES accumulate, which
            # used to delay the first ~150-byte event by 3+ ticks
            # (loadtest_single_shard.json: sse_first_event p50 4.9 s).
            # A 2 KB comment prologue (ignored by every SSE parser)
            # overflows those buffers so the immediate snapshot is
            # actually DELIVERED immediately.
            yield ":" + " " * 2048 + "\n\n"
            # SSE-lag SLO signal: the stream's producer yields one event
            # then sleeps one tick, so anything beyond the tick between
            # consecutive yields is delivery lag — store-read time, GIL
            # contention, and client/socket backpressure (the previous
            # yield blocks until the subscriber drained it)
            tick = coord.config.service.sse_tick_s
            prev = _time.monotonic()
            for progress in coord.stream_status(sid, job_id):
                now = _time.monotonic()
                gauge_set(
                    "tpuml_sse_lag_seconds", max(now - prev - tick, 0.0)
                )
                prev = now
                yield f"data: {json.dumps(json_safe(progress))}\n\n"

        return Response(stream(), mimetype="text/event-stream")

    def _moved(jid):
        """Forwarding stamp for a migrated job: 409 with the destination
        shard, or None when this shard still owns the job. Front ends
        (runtime/frontend.py) turn the 409 into a cached redirect."""
        dest = coord.store.migrated_to(jid)
        if dest is None:
            return None
        return _json(
            {"status": "moved", "migrated_to": dest, "job_id": jid},
            status=409,
        )

    def check_status(request, sid, jid):
        # canonicalize like the SSE-resume path: a client polling under
        # its own minted id must reach the shard-stamped job
        jid = coord.canonical_job_id(jid)
        moved = _moved(jid)
        if moved is not None:
            return moved
        return _json(coord.check_status(sid, jid))

    def metrics(request, sid, jid):
        # ?wait=1: block until the job finalizes before replying — opt-in
        # parity with the reference master's /metrics, which blocked until
        # every subtask had reported (master.py:325-332). The default stays
        # non-blocking (returns whatever has reported so far); see
        # docs/API.md "Differences from the reference".
        jid = coord.canonical_job_id(jid)
        moved = _moved(jid)
        if moved is not None:
            return moved
        if request.args.get("wait"):
            timeout = float(
                request.args.get("timeout", coord.config.service.client_timeout_s)
            )
            coord._require_session(sid)
            coord.store.wait_job(sid, jid, timeout)
        return _json(coord.job_metrics(sid, jid))

    def metrics_prom(request):
        # refresh point-in-time gauges at scrape time: fleet size, the
        # per-worker health families, and local-device HBM
        if coord.cluster is not None:
            gauge_set("tpuml_workers_alive", len(coord.cluster.engine.workers))
            coord.cluster.engine.refresh_health_metrics()
        from .executor import record_hbm_gauges

        record_hbm_gauges()
        # derived SLO gauges: per-route p99 from the request histogram —
        # refreshed here so the time-series ring samples a p99 without
        # sampling histogram buckets
        refresh_route_p99()
        # each scrape also feeds the embedded time-series ring (throttled;
        # the sweep is the other driver) — direct-mode coordinators have
        # no sweep loop, so history still accumulates at scrape cadence
        timeseries_sample()
        # ... and drives the fleet-health tick (capacity signals + alert
        # rules, throttled) for the same no-sweep reason, so the
        # autoscale/alert gauges in THIS exposition are current
        coord.health_tick()
        return Response(
            render_prometheus(),
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )

    #: profiler error reasons -> HTTP status: disabled valve is 503 (come
    #: back when obs is on), an open/absent capture is 409 (conflict with
    #: the profiler's state), a backend/filesystem failure is a real 500
    _PROFILE_STATUS = {"disabled": 503, "busy": 409, "idle": 409,
                       "backend": 500}

    def profile_start(request):
        """Begin an on-demand jax.profiler capture (obs/devprof.py). Body
        (optional JSON): ``{"tag": "..."}`` names the dump directory under
        ``<journal_dir>/profile/``. 409 while a capture is already open,
        503 when observability is disabled, 500 when the backend profiler
        or the dump filesystem refuses."""
        body = request.get_json(force=True, silent=True) or {}
        out = PROFILER.start(body.get("tag"))
        if out["status"] == "started":
            return _json(out, status=201)
        return _json(out, status=_PROFILE_STATUS.get(out.get("reason"), 500))

    def profile_stop(request):
        """Finish the active capture; returns the dump directory and file
        count. 409 when no capture is open; 500 on a failed stop (the
        capture stays active for a retry unless the backend reports the
        session already gone)."""
        out = PROFILER.stop()
        if out["status"] == "stopped":
            return _json(out, status=200)
        return _json(out, status=_PROFILE_STATUS.get(out.get("reason"), 500))

    def profile_status(request):
        return _json(PROFILER.status())

    def cost(request, jid):
        """Per-job device cost report (docs/OBSERVABILITY.md): device-
        seconds, total FLOPs/bytes, HBM high-water, per-group MFU."""
        report = coord.job_cost(coord.canonical_job_id(jid))
        if report is None:
            return _json(
                {"status": "error", "message": f"no job {jid!r}"}, status=404
            )
        return _json(report)

    def healthz(request):
        """Deep health, beyond /health's liveness ping: local device
        reachability + memory, per-worker health (EWMA batch latency,
        heartbeat age, failure ratio, queue depth), and the flagged
        straggler list. Always HTTP 200; ``status`` says ok/degraded.
        ``ready``/``recovery`` mirror /readyz (journal replay state)."""
        out = {
            "status": "ok",
            "obs_enabled": obs_enabled(),
            "ready": coord.ready,
        }
        if coord.shard_id is not None:
            out["shard"] = coord.shard_id
            out["n_shards"] = coord.n_shards
        if coord.recovery:
            out["recovery"] = coord.recovery
        if not coord.ready:
            out["status"] = "degraded"
        try:
            import jax

            from ..utils.flops import device_memory_stats

            devices = jax.local_devices()
            dev = {
                "reachable": True,
                "platform": devices[0].platform,
                "n_devices": len(devices),
                "device_kind": str(getattr(devices[0], "device_kind", "")),
            }
            stats = device_memory_stats()
            mem = {
                k: stats[k]
                for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                if k in stats
            }
            if mem:
                dev["memory"] = mem
        except Exception as e:  # noqa: BLE001 — unreachable backend IS the finding
            dev = {"reachable": False, "error": str(e)}
            out["status"] = "degraded"
        out["device"] = dev
        if coord.cluster is not None:
            snap = coord.cluster.engine.refresh_health_metrics()
            out["n_workers"] = len(snap)
            out["workers"] = snap
            # undelivered bus backlog per topic: a deep `train` backlog
            # means placements are outrunning the executor pool
            out["bus_depths"] = coord.cluster.bus.depths()
            out["queue_depths"] = {
                wid: h["queue_depth"] for wid, h in snap.items()
            }
            out["stragglers"] = sorted(
                wid for wid, h in snap.items() if h["straggler"]
            )
            if out["stragglers"] or not snap:
                out["status"] = "degraded"
        sup = getattr(coord, "agent_supervisor", None)
        if sup is not None:
            slots = sup.status()
            out["agent_slots"] = {
                "alive": sum(1 for s in slots if s["alive"]),
                "total": len(slots),
                "gave_up": sum(1 for s in slots if s["gave_up"]),
            }
            if slots and out["agent_slots"]["gave_up"] == len(slots):
                out["status"] = "degraded"
        return _json(out)

    def livez(request):
        """Pure liveness: the process answers requests. Never inspects
        recovery, workers, or devices — a recovering or degraded
        coordinator is still ALIVE (restarting it would only lose the
        recovery progress)."""
        return _json({"status": "ok"})

    def readyz(request):
        """Readiness: 200 only once journal replay + in-flight re-queue
        finished (``Coordinator.ready``). 503 + Retry-After while
        recovering, so load balancers hold traffic and the chaos harness
        can gate on recovery completion."""
        if coord.ready:
            return _json({"status": "ready", "recovery": coord.recovery})
        retry_after = coord.config.service.admission_retry_after_s
        return Response(
            json.dumps(json_safe({
                "status": "recovering", "recovery": coord.recovery,
            })),
            status=503,
            mimetype="application/json",
            headers={"Retry-After": f"{retry_after:g}"},
        )

    def explain(request, jid, stid):
        """Per-subtask decision timeline from the flight recorder: who
        placed it where and why (score breakdown), lease grant/reclaim,
        attempts/retries, speculation, terminal result — 404 when the
        recorder never saw the pair."""
        try:
            return _json(coord.explain(coord.canonical_job_id(jid), stid))
        except KeyError as e:
            return _json(
                {"status": "error", "message": str(e).strip("'")}, status=404
            )

    def explain_job(request, jid):
        """Subtask ids with a recorded timeline for the job — the
        discovery aid for /explain/<jid>/<stid>."""
        jid = coord.canonical_job_id(jid)
        stids = RECORDER.job_subtasks(jid)
        if not stids:
            return _json(
                {"status": "error",
                 "message": f"no recorded events for job {jid!r}"},
                status=404,
            )
        return _json({"job_id": jid, "subtask_ids": stids})

    def curves_job(request, jid):
        """All recorded learning curves for a job (docs/OBSERVABILITY.md
        "Trial telemetry plane"): one entry per (trial, rung, attempt)
        with the downsampled per-split trace and the watchdog's diverged
        flag. 404 for an unknown job; a known job with no curves yet
        returns an empty list."""
        jid = coord.canonical_job_id(jid)
        moved = _moved(jid)
        if moved is not None:
            return moved
        out = coord.job_curves(jid)
        if out is None:
            return _json(
                {"status": "error", "message": f"no job {jid!r}"}, status=404
            )
        return _json(out)

    def curves_subtask(request, jid, stid):
        """One trial's curve history across rungs/attempts — 404 when the
        pair never reported a curve (CS230_CURVES=0, or evicted)."""
        jid = coord.canonical_job_id(jid)
        moved = _moved(jid)
        if moved is not None:
            return moved
        try:
            return _json(coord.subtask_curves(jid, stid))
        except KeyError as e:
            return _json(
                {"status": "error", "message": str(e).strip("'")}, status=404
            )

    def events(request):
        """Flight-recorder firehose: events with seq > ?since= (oldest
        first, at most ?limit=). ``last_seq`` is the cursor for the next
        poll."""
        def _int_arg(name, default):
            try:
                return int(request.args.get(name, default))
            except ValueError:
                return default  # a malformed value falls back alone

        since = _int_arg("since", 0)
        limit = _int_arg("limit", 1000)
        evts, last = RECORDER.events(since=since, limit=limit)
        return _json({"events": evts, "n_events": len(evts), "last_seq": last})

    def alerts(request):
        """Fleet-health alert states (obs/slo.py): one entry per rule
        with its live ok/pending/firing state. Reading evaluates the
        rules first (throttled; ``?force=1`` bypasses the floor), so a
        poller never sees a state staler than the evaluation interval —
        direct-mode coordinators have no sweep to keep it fresh."""
        coord.health_tick(force=bool(request.args.get("force")))
        out = coord.alerts.snapshot()
        if coord.shard_id is not None:
            out["shard"] = coord.shard_id
        return _json(out)

    def autoscale(request):
        """Derived capacity signals (obs/signals.py): the
        desired_workers/desired_shards an external autoscaler acts on,
        with the raw signals and the hysteresis verdict that produced
        them. Evaluates first like /alerts."""
        coord.health_tick(force=bool(request.args.get("force")))
        out = dict(coord.signals.report())
        if coord.shard_id is not None:
            out["shard"] = coord.shard_id
        return _json(out)

    def metrics_history(request):
        """Embedded time-series read (obs/timeseries.py): ?name= selects a
        metric family, ?since= (epoch seconds) trims old samples. Without
        ?name=, lists the sampled family names."""
        name = request.args.get("name")
        if not name:
            return _json({"names": TIMESERIES.names()})
        try:
            since = float(request.args.get("since", 0.0))
        except ValueError:
            since = 0.0
        return _json({
            "name": name,
            "since": since,
            "series": TIMESERIES.history(name, since=since),
        })

    def predictor_calibration(request):
        """Per-model-family predicted-vs-actual calibration of the
        runtime predictor (docs/OBSERVABILITY.md "Predictor
        calibration")."""
        return _json(coord.predictor_calibration())

    def trace(request, jid):
        jid = coord.canonical_job_id(jid)
        tid = TRACER.trace_for_job(jid)
        if tid is None:
            return _json(
                {"status": "error", "message": f"no trace for job {jid!r}"},
                status=404,
            )
        spans = sorted(
            TRACER.spans_for(tid), key=lambda s: (s.get("start") or 0)
        )
        return _json(
            {
                "job_id": jid,
                "trace_id": tid,
                "n_spans": len(spans),
                "spans": spans,
                "tree": TRACER.tree(tid),
            }
        )

    def trace_export(request, jid):
        """Export a job's trace as an interchange document
        (obs/export.py): ``?format=perfetto`` (default — Chrome trace
        JSON for ui.perfetto.dev / chrome://tracing) or ``?format=otlp``
        (OTLP-shaped JSON). The document is written under the journal
        dir (``trace_<trace_id>.<format>.json``) and returned inline;
        400 on an unknown format, 404 when no trace is bound."""
        jid = coord.canonical_job_id(jid)
        tid = TRACER.trace_for_job(jid)
        if tid is None:
            return _json(
                {"status": "error", "message": f"no trace for job {jid!r}"},
                status=404,
            )
        fmt = request.args.get("format", "perfetto")
        try:
            out = export_trace(
                tid,
                sorted(TRACER.spans_for(tid),
                       key=lambda s: (s.get("start") or 0)),
                fmt,
                job_id=jid,
            )
        except ValueError as e:
            return _json({"status": "error", "message": str(e)}, status=400)
        return _json(out)

    def critical_path_report(request, jid):
        """Per-job latency attribution (docs/OBSERVABILITY.md "Critical
        path & trace export"): the span tree joined with flight-recorder
        events, tiled into segments that sum to the measured wall.
        ``?compare=<job_id>`` additionally diffs against that job as the
        baseline (``diff.delta_wall_s`` > 0 means this job is slower)."""
        report = coord.critical_path(coord.canonical_job_id(jid))
        if report is None:
            return _json(
                {"status": "error",
                 "message": f"no critical path for job {jid!r} "
                            "(no trace bound)"},
                status=404,
            )
        baseline_id = request.args.get("compare")
        if baseline_id:
            baseline = coord.critical_path(
                coord.canonical_job_id(baseline_id)
            )
            if baseline is None:
                return _json(
                    {"status": "error",
                     "message": f"no critical path for baseline job "
                                f"{baseline_id!r}"},
                    status=404,
                )
            report = dict(report)
            report["diff"] = compare_critical_paths(baseline, report)
        return _json(report)

    def trace_spans(request, wid):
        """Span-shipping ingest for remote agents (runtime/agent.py
        _ship_spans): the return leg of the X-Trace-Id propagation."""
        body = request.get_json(force=True, silent=True) or {}
        n = TRACER.ingest(body.get("spans") or [])
        counter_inc("tpuml_trace_spans_ingested_total", n)
        return _json({"status": "ok", "ingested": n})

    def download_model(request, sid, jid):
        moved = _moved(coord.canonical_job_id(jid))
        if moved is not None:
            return moved
        path = coord.best_model_path(sid, coord.canonical_job_id(jid))
        if path is None:
            return _json({"status": "error", "message": "no model artifact"}, status=404)
        with open(path, "rb") as f:
            payload = f.read()
        return Response(
            payload,
            mimetype="application/octet-stream",
            headers={"Content-Disposition": f"attachment; filename={jid}_best_model.pkl"},
        )

    def workers(request):
        if coord.cluster is None:
            return _json({})
        return _json(coord.cluster.engine.worker_snapshot())

    def queues(request):
        if coord.cluster is None:
            return _json({})
        return _json(coord.cluster.engine.queue_snapshot())

    def supervisor(request):
        sup = getattr(coord, "agent_supervisor", None)
        return _json(sup.status() if sup is not None else [])

    def jobs(request):
        return _json(coord.store.jobs_overview())

    def dashboard(request):
        return Response(_DASHBOARD_HTML, mimetype="text/html")

    def _cluster_or_400():
        if coord.cluster is None:
            from werkzeug.exceptions import BadRequest

            raise BadRequest("coordinator is not running a cluster")
        return coord.cluster

    def subscribe(request):
        from werkzeug.exceptions import BadRequest

        body = request.get_json(silent=True) or {}
        # n_devices / mesh_shape: the worker's mesh-slice report — the
        # placement engine's predictor-aware packing divisor
        # (docs/ARCHITECTURE.md "Elastic trial fabric"). Validated here:
        # a malformed report must be an immediate 400 the agent can act
        # on, not a 500 it burns its whole register-retry budget against.
        n_devices = body.get("n_devices")
        if n_devices is not None:
            try:
                n_devices = int(n_devices)
            except (TypeError, ValueError):
                raise BadRequest(
                    f"n_devices must be an integer, got {n_devices!r}"
                )
        mesh_shape = body.get("mesh_shape")
        if mesh_shape is not None:
            try:
                mesh_shape = {
                    str(k): int(v) for k, v in mesh_shape.items()
                }
            except (TypeError, ValueError, AttributeError):
                raise BadRequest(
                    "mesh_shape must be an object of integer axis sizes, "
                    f"got {mesh_shape!r}"
                )
        wid = _cluster_or_400().register_remote(
            body.get("mem_capacity_mb"),
            n_devices=n_devices,
            mesh_shape=mesh_shape,
        )
        resp = {"worker_id": wid}
        try:
            # predictor-driven AOT prewarm hints (docs/ARCHITECTURE.md
            # "Data-plane caching and prewarm"): hot job shapes the new
            # worker should warm in the background before first placement
            hints = coord.prewarm_hints()
        except Exception:  # noqa: BLE001 — hints are advisory, never
            # allowed to fail a registration
            hints = []
        if hints:
            resp["prewarm"] = hints
        return _json(resp, status=201)

    def unsubscribe(request, wid):
        _cluster_or_400().unregister_remote(wid)
        return _json({"status": "ok"})

    def heartbeat(request, wid):
        ok = _cluster_or_400().engine.heartbeat(wid)
        return _json({"status": "ok" if ok else "unknown_worker"}, status=200 if ok else 404)

    def next_tasks(request, wid):
        cluster = _cluster_or_400()
        max_n = int(request.args.get("max", 64))
        timeout_s = float(request.args.get("timeout", 10.0))
        out = {"tasks": cluster.pull_tasks(wid, max_n, timeout_s)}
        # cooperative-cancel list (docs/SEARCH.md): attempts the rung
        # controller pruned mid-flight — the agent feeds them to its
        # executor, which stops each at the next batch boundary and posts
        # a terminal ``pruned`` result
        cancels = cluster.cancel_list()
        if cancels:
            out["cancel"] = cancels
        return _json(out)

    def task_result(request, wid):
        _cluster_or_400().push_result(wid, request.get_json(force=True))
        return _json({"status": "ok"})

    def task_metrics(request, wid):
        _cluster_or_400().push_metrics(wid, request.get_json(force=True))
        return _json({"status": "ok"})

    def dataset(request, dataset_id):
        """Serve the coordinator's staged CSV (preprocessed preferred) so
        remote agents can fetch-on-miss (FetchingDatasetCache). ``?probe=1``
        returns only the staged kind (cheap freshness check — agents probe
        before downloading)."""
        from werkzeug.wsgi import wrap_file

        from ..data.datasets import find_csv

        root = coord.config.storage.datasets_dir
        path = find_csv(dataset_id, preprocessed=True, root=root)
        kind = "preprocessed"
        if path is None:
            path = find_csv(dataset_id, root=root)
            kind = "raw"
        if path is None:
            return _json(
                {"status": "error", "message": f"dataset {dataset_id!r} not staged"},
                status=404,
            )
        if request.args.get("probe"):
            return _json({"kind": kind, "size": __import__("os").path.getsize(path)})
        # streamed, not read into memory: N agents cold-starting on a
        # 100 MB dataset must not allocate N full copies coordinator-side
        return Response(
            wrap_file(request.environ, open(path, "rb")),
            mimetype="text/csv",
            direct_passthrough=True,
            headers={
                "X-Dataset-Kind": kind,
                "Content-Disposition": f"attachment; filename={dataset_id}.csv",
            },
        )

    def slice_heartbeat(request, slice_id, rank):
        now = _time.time()
        with _slices_lock:
            _slices.setdefault(slice_id, {})[int(rank)] = now
            # prune slices whose every rank went silent (crash-looping
            # slices mint a fresh uuid per restart — without a sweep the
            # table grows one dead dict per restart forever)
            for sid in [
                s for s, ranks in _slices.items()
                if s != slice_id and ranks
                and now - max(ranks.values()) > 900
            ]:
                del _slices[sid]
        return _json({"status": "ok"})

    def slice_status(request, slice_id):
        now = _time.time()
        with _slices_lock:
            ranks = dict(_slices.get(slice_id, {}))
        return _json({
            "ranks": {str(r): round(now - ts, 3) for r, ts in ranks.items()}
        })

    def migrate_in(request):
        """Peer-to-peer job handoff ingest (docs/ROBUSTNESS.md "Shard
        rebalancing"): a hot donor shard POSTs a quiesced job's full
        record here. The recipient journals ``migrate_in`` BEFORE the
        donor journals its forwarding stamp, so a crash between the two
        duplicates ownership (deduped by attempt fencing) rather than
        losing the job. Idempotent: a duplicate export is re-accepted."""
        body = request.get_json(force=True, silent=True) or {}
        try:
            return _json(coord.migrate_in(body))
        except ValueError as e:
            return _json({"status": "error", "message": str(e)}, status=400)

    def steal_candidates(request):
        """Queued subtasks this shard would surrender to an idle peer
        (work stealing). Empty unless rebalancing is enabled AND the
        local shard_pressure is over the hot threshold — a busy-but-
        coping shard keeps its queue."""
        return _json(coord.steal_candidates())

    def steal_tasks(request):
        """Grant endpoint for work stealing: the thief POSTs
        ``{"thief_shard": k, "max_n": n}`` and receives fenced task
        attempts (fresh attempt number, donor-side tombstone journaled)
        it may run locally. Results flow back via /peer_result."""
        body = request.get_json(force=True, silent=True) or {}
        try:
            thief = int(body.get("thief_shard", -1))
            max_n = int(body.get("max_n", coord.config.service.steal_max_tasks))
            # mesh-aware stealing (optional, backward-compatible): the
            # thief's widest idle slice caps the priced candidate width
            max_nd = body.get("max_n_devices")
            max_nd = int(max_nd) if max_nd is not None else None
        except (TypeError, ValueError):
            from werkzeug.exceptions import BadRequest

            raise BadRequest(
                "thief_shard, max_n and max_n_devices must be integers"
            )
        return _json({"tasks": coord.release_for_steal(
            thief, max_n,
            max_n_devices=max_nd,
            prefer_wide=bool(body.get("prefer_wide")),
        )})

    def peer_result(request):
        """Result relay from a peer shard: forwarded late results from a
        migration donor, or stolen-task results from a thief. Each result
        is published onto the local bus exactly as a worker result would
        be — the ingest loop's dedup/staleness rules apply unchanged."""
        body = request.get_json(force=True, silent=True) or {}
        results = body.get("results")
        if results is None:
            results = [body]
        n = 0
        for r in results:
            if isinstance(r, dict) and r.get("subtask_id"):
                coord.ingest_peer_result(r)
                n += 1
        return _json({"status": "ok", "ingested": n})

    handlers = locals()

    # CORS parity with the reference master's flask-cors default config
    # (allow-all; master.py:20-24): browser dashboards may call the API
    # cross-origin, including OPTIONS preflights
    _cors = {
        "Access-Control-Allow-Origin": "*",
        "Access-Control-Allow-Headers": "Content-Type, Authorization",
        "Access-Control-Allow-Methods": "GET, POST, OPTIONS",
    }

    @Request.application
    def app(request):
        if request.method == "OPTIONS":
            return Response(status=204, headers=_cors)
        # trace middleware: an inbound X-Trace-Id activates that trace for
        # the handler (contextvar), so spans opened inside — including the
        # coordinator's job.submit — join the CLIENT's trace; the id is
        # echoed on the response. Untraced requests open no span at all
        # (a /health poll must not mint garbage traces).
        trace_id = request.headers.get(TRACE_HEADER)
        # RED middleware (docs/OBSERVABILITY.md "Perf observatory"): every
        # request lands in tpuml_http_request_seconds{route,method,code}.
        # Routes label by ENDPOINT name (bounded cardinality — path params
        # never become label values); unmatched paths pool under one
        # "unmatched" cell. Streaming (SSE) responses record time to the
        # response object — the submit latency; delivery lag has its own
        # gauge (tpuml_sse_lag_seconds).
        t0 = _time.perf_counter()
        endpoint = None
        try:
            endpoint, values = url_map.bind_to_environ(request.environ).match()
            counter_inc("tpuml_http_requests_total", endpoint=endpoint)
            # trace_spans is the span TRANSPORT — tracing it would append
            # one meta-span to every shipped batch's timeline
            if trace_id and endpoint != "trace_spans" and obs_enabled():
                # X-Parent-Span: a front end sends its open frontend.proxy
                # span id so this hop's span nests under it — the stitch
                # that makes the proxy span the trace's single root
                parent_id = request.headers.get(PARENT_HEADER)
                with activate(trace_id, parent_id):
                    with span(f"http.{endpoint}", trace_id=trace_id):
                        resp = handlers[endpoint](request, **values)
            else:
                resp = handlers[endpoint](request, **values)
        except NotFound:
            resp = _json({"status": "error", "message": "not found"}, status=404)
        except HTTPException as e:
            resp = _json({"status": "error", "message": str(e)}, status=e.code or 500)
        except (KeyError, FileNotFoundError) as e:
            resp = _json({"status": "error", "message": str(e)}, status=404)
        except Exception as e:  # noqa: BLE001
            resp = _json({"status": "error", "message": str(e)}, status=500)
        observe(
            "tpuml_http_request_seconds",
            _time.perf_counter() - t0,
            route=endpoint or "unmatched",
            method=request.method,
            code=str(resp.status_code),
        )
        resp.headers.extend(_cors)
        if trace_id:
            resp.headers[TRACE_HEADER] = trace_id
        return resp

    app.coordinator = coord
    return app


def serve(coordinator: Optional[Coordinator] = None, host: Optional[str] = None, port: Optional[int] = None):
    from werkzeug.serving import run_simple

    from ..utils.config import get_config

    cfg = get_config().service
    app = create_app(coordinator)
    run_simple(host or cfg.host, port or cfg.port, app, threaded=True)


def main() -> None:
    """``tpuml-coordinator`` console entry point: serve the REST surface.

    - ``--cluster`` (default): scheduler-mediated dispatch — remote agents
      register over /subscribe; optionally ``--local-executors N`` adds
      in-process workers so the box serves jobs with no agents attached, or
      ``--agent-executors N`` runs them as supervised child processes
      (device-fault containment: a poisoned backend kills only the child,
      tasks requeue, the supervisor respawns — runtime/supervisor.py).
    - ``--direct``: single in-process executor, no placement engine (the
      laptop / single-TPU-VM mode).
    The compose analog: reference docker-compose.yml:86-131 (master +
    scheduler services collapsed into this one process).
    """
    import argparse

    parser = argparse.ArgumentParser(description="tpuml coordinator server")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--direct", action="store_true",
                        help="in-process executor, no placement engine")
    parser.add_argument("--local-executors", type=int, default=0, metavar="N",
                        help="cluster mode: also attach N in-process executors")
    parser.add_argument("--agent-executors", type=int, default=0, metavar="N",
                        help="cluster mode: run N supervised child agent "
                             "processes (fault-isolated executors)")
    parser.add_argument("--journal", action="store_true",
                        help="journal job state; resume in-flight jobs on restart")
    # sharded control plane (docs/ARCHITECTURE.md "Sharded control
    # plane"): this process serves ONE shard of an N-shard fleet behind
    # stateless front ends (runtime/frontend.py). Job/worker ids get the
    # s<k>- stamp, the journal moves to <journal_dir>/shard-<k> (the
    # hot-standby takeover unit), and the GLOBAL admission caps are
    # carved into per-shard shares so the fleet-wide accepted load stays
    # bounded by the configured totals.
    parser.add_argument("--shard-index", type=int, default=None, metavar="K",
                        help="serve shard K of a sharded control plane")
    parser.add_argument("--num-shards", type=int, default=1, metavar="N",
                        help="total shards in the fleet (with --shard-index)")
    # rebalancing peer directory: base URLs of EVERY shard (index == list
    # position, including this one — it is skipped when dialing). Static
    # because ShardFleet allocates ports before any shard starts; action
    # is still gated on service.rebalance_enabled.
    parser.add_argument("--peers", default=None, metavar="URL,URL,...",
                        help="comma-separated shard base URLs for "
                             "cross-shard migration / work stealing")
    args = parser.parse_args()
    if args.direct and args.agent_executors > 0:
        parser.error("--agent-executors requires cluster mode (drop --direct)")
    if args.shard_index is not None and not (
        0 <= args.shard_index < max(args.num_shards, 1)
    ):
        parser.error("--shard-index must be in [0, --num-shards)")
    if args.num_shards > 100:
        # the 2-digit s<k>- stamp grammar bounds the fleet (sharding.py
        # MAX_SHARDS); fail at launch, not at first unroutable id
        parser.error("--num-shards is capped at 100 by the id stamp grammar")
    if args.shard_index is not None and args.direct:
        parser.error("--shard-index requires cluster mode (drop --direct)")

    supervisor = None
    slot_envs = None
    if args.agent_executors > 0:
        import os as _os

        # single-accelerator host policy: exactly one process may own the
        # chip. The parent pins itself to CPU and agent slot 0 inherits the
        # original platform — unless --local-executors run in the parent,
        # which then keeps the chip and every child slot pins to CPU. This
        # MUST happen before Coordinator() below: its eager artifact-refit
        # executor latches the platform via setup_jax on construction.
        chip_taken = args.local_executors > 0
        inherit = {"TPUML_PLATFORM": _os.environ.get("TPUML_PLATFORM")}
        if not chip_taken:
            _os.environ["TPUML_PLATFORM"] = "cpu"
        slot_envs = [
            inherit if (i == 0 and not chip_taken)
            else {"TPUML_PLATFORM": "cpu"}
            for i in range(args.agent_executors)
        ]

    if args.direct:
        coord = Coordinator(journal=args.journal)
    else:
        from .cluster import ClusterRuntime

        shard_kwargs = {}
        if args.shard_index is not None:
            import os as _os

            from ..utils.config import get_config as _cfg
            from .sharding import shard_service_config

            cfg = shard_service_config(_cfg(), args.num_shards)
            shard_kwargs = {
                "config": cfg,
                "shard_id": args.shard_index,
                "n_shards": args.num_shards,
                "journal_dir": _os.path.join(
                    cfg.storage.journal_dir, f"shard-{args.shard_index}"
                ),
            }
        cluster = ClusterRuntime(shard_id=args.shard_index)
        for _ in range(max(args.local_executors, 0)):
            cluster.add_executor()
        coord = Coordinator(
            cluster=cluster, journal=args.journal, **shard_kwargs
        )
        if args.peers:
            coord.peer_urls = [
                u.strip().rstrip("/")
                for u in args.peers.split(",") if u.strip()
            ]
        if args.agent_executors > 0:
            from ..utils.config import get_config as _cfg
            from .supervisor import AgentSupervisor, agent_command

            cfg = _cfg().service
            # children must dial an address the bound server answers on:
            # wildcard binds answer loopback, a specific --host only itself
            host = args.host or cfg.host
            dial = "127.0.0.1" if host in (None, "", "0.0.0.0", "::") else host
            url = f"http://{dial}:{args.port or cfg.port}"
            supervisor = AgentSupervisor(
                agent_command(url), n=args.agent_executors,
                slot_envs=slot_envs,
            )
            supervisor.start()
            coord.agent_supervisor = supervisor
    try:
        serve(coord, host=args.host, port=args.port)
    finally:
        if supervisor is not None:
            supervisor.stop()


if __name__ == "__main__":
    main()
