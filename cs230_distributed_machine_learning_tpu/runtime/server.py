"""Coordinator REST server: wire-compatible with the reference master.

Route parity with ``aws-prod/master/master.py:27-390`` (same paths, methods,
and response shapes — the home route enumerates them like master.py:30-44),
plus the reference scheduler's introspection endpoints (/workers, /queues —
scheduler.py:95-97,154-159) served from the placement engine when the
coordinator runs a cluster. SSE progress streaming (/train_status) keeps the
reference's event schema {job_status, tasks_pending, total_subtasks} with a
final event carrying job_result (master.py:237-266).

Built as a plain WSGI app on werkzeug (no Flask dependency): same
deployment surface, serve with ``serve()`` or any WSGI server.
"""

from __future__ import annotations

import json
from typing import Optional

from ..utils.serialization import json_safe
from .coordinator import Coordinator


def create_app(coordinator: Optional[Coordinator] = None):
    from werkzeug.exceptions import HTTPException, NotFound
    from werkzeug.routing import Map, Rule
    from werkzeug.wrappers import Request, Response

    coord = coordinator or Coordinator()

    url_map = Map(
        [
            Rule("/", endpoint="home", methods=["GET"]),
            Rule("/health", endpoint="health", methods=["GET"]),
            Rule("/create_session", endpoint="create_session", methods=["POST"]),
            Rule("/download_data/<sid>", endpoint="download_data", methods=["POST"]),
            Rule("/check_data/<sid>", endpoint="check_data", methods=["GET"]),
            Rule("/preprocess/<sid>", endpoint="preprocess", methods=["POST"]),
            Rule("/train/<sid>", endpoint="train", methods=["POST"]),
            Rule("/train_status/<sid>", endpoint="train_status", methods=["POST"]),
            Rule("/check_status/<sid>/<jid>", endpoint="check_status", methods=["GET"]),
            Rule("/metrics/<sid>/<jid>", endpoint="metrics", methods=["GET"]),
            Rule("/download_model/<sid>/<jid>", endpoint="download_model", methods=["GET"]),
            Rule("/workers", endpoint="workers", methods=["GET"]),
            Rule("/queues", endpoint="queues", methods=["GET"]),
            Rule("/supervisor", endpoint="supervisor", methods=["GET"]),
            # worker-agent control plane (reference scheduler.py:95-159)
            Rule("/subscribe", endpoint="subscribe", methods=["POST"]),
            Rule("/unsubscribe/<wid>", endpoint="unsubscribe", methods=["POST"]),
            Rule("/heartbeat/<wid>", endpoint="heartbeat", methods=["POST"]),
            Rule("/next_tasks/<wid>", endpoint="next_tasks", methods=["GET"]),
            Rule("/task_result/<wid>", endpoint="task_result", methods=["POST"]),
            Rule("/task_metrics/<wid>", endpoint="task_metrics", methods=["POST"]),
            # dataset distribution for remote agents: the DCN replacement
            # for the reference's shared EFS volume (compose.yml:92-94)
            Rule("/dataset/<dataset_id>", endpoint="dataset", methods=["GET"]),
        ]
    )

    def _json(data, status=200):
        return Response(
            json.dumps(json_safe(data)), status=status, mimetype="application/json"
        )

    def home(request):
        return _json(
            {
                "service": "tpuml-coordinator",
                "endpoints": [
                    "POST /create_session",
                    "POST /download_data/<session_id>",
                    "GET  /check_data/<session_id>?dataset_name=",
                    "POST /preprocess/<session_id>",
                    "POST /train/<session_id>",
                    "POST /train_status/<session_id>  (SSE)",
                    "GET  /check_status/<session_id>/<job_id>",
                    "GET  /metrics/<session_id>/<job_id>",
                    "GET  /download_model/<session_id>/<job_id>",
                    "GET  /workers",
                    "GET  /queues",
                    "GET  /health",
                ],
            }
        )

    def health(request):
        out = {"status": "ok"}
        sup = getattr(coord, "agent_supervisor", None)
        if sup is not None:
            slots = sup.status()
            out["agent_slots"] = {
                "alive": sum(1 for s in slots if s["alive"]),
                "total": len(slots),
                "gave_up": sum(1 for s in slots if s["gave_up"]),
            }
            if out["agent_slots"]["gave_up"] == len(slots) and slots:
                out["status"] = "degraded"  # every executor slot is down
        return _json(out)

    def create_session(request):
        return _json({"session_id": coord.create_session()}, status=201)

    def download_data(request, sid):
        body = request.get_json(force=True)
        return _json(
            coord.download_data(
                sid, body["dataset_url"], body["dataset_name"], body["dataset_type"]
            )
        )

    def check_data(request, sid):
        return _json(coord.check_data(sid, request.args["dataset_name"]))

    def preprocess(request, sid):
        body = request.get_json(force=True)
        return _json(coord.preprocess(sid, body["dataset_id"], body.get("config")))

    def train(request, sid):
        return _json(coord.submit_train(sid, request.get_json(force=True)))

    def train_status(request, sid):
        submit = coord.submit_train(sid, request.get_json(force=True))
        job_id = submit["job_id"]

        def stream():
            for progress in coord.stream_status(sid, job_id):
                yield f"data: {json.dumps(json_safe(progress))}\n\n"

        return Response(stream(), mimetype="text/event-stream")

    def check_status(request, sid, jid):
        return _json(coord.check_status(sid, jid))

    def metrics(request, sid, jid):
        return _json(coord.job_metrics(sid, jid))

    def download_model(request, sid, jid):
        path = coord.best_model_path(sid, jid)
        if path is None:
            return _json({"status": "error", "message": "no model artifact"}, status=404)
        with open(path, "rb") as f:
            payload = f.read()
        return Response(
            payload,
            mimetype="application/octet-stream",
            headers={"Content-Disposition": f"attachment; filename={jid}_best_model.pkl"},
        )

    def workers(request):
        if coord.cluster is None:
            return _json({})
        return _json(coord.cluster.engine.worker_snapshot())

    def queues(request):
        if coord.cluster is None:
            return _json({})
        return _json(coord.cluster.engine.queue_snapshot())

    def supervisor(request):
        sup = getattr(coord, "agent_supervisor", None)
        return _json(sup.status() if sup is not None else [])

    def _cluster_or_400():
        if coord.cluster is None:
            from werkzeug.exceptions import BadRequest

            raise BadRequest("coordinator is not running a cluster")
        return coord.cluster

    def subscribe(request):
        body = request.get_json(silent=True) or {}
        wid = _cluster_or_400().register_remote(body.get("mem_capacity_mb"))
        return _json({"worker_id": wid}, status=201)

    def unsubscribe(request, wid):
        _cluster_or_400().unregister_remote(wid)
        return _json({"status": "ok"})

    def heartbeat(request, wid):
        ok = _cluster_or_400().engine.heartbeat(wid)
        return _json({"status": "ok" if ok else "unknown_worker"}, status=200 if ok else 404)

    def next_tasks(request, wid):
        cluster = _cluster_or_400()
        max_n = int(request.args.get("max", 64))
        timeout_s = float(request.args.get("timeout", 10.0))
        return _json({"tasks": cluster.pull_tasks(wid, max_n, timeout_s)})

    def task_result(request, wid):
        _cluster_or_400().push_result(wid, request.get_json(force=True))
        return _json({"status": "ok"})

    def task_metrics(request, wid):
        _cluster_or_400().push_metrics(wid, request.get_json(force=True))
        return _json({"status": "ok"})

    def dataset(request, dataset_id):
        """Serve the coordinator's staged CSV (preprocessed preferred) so
        remote agents can fetch-on-miss (FetchingDatasetCache). ``?probe=1``
        returns only the staged kind (cheap freshness check — agents probe
        before downloading)."""
        from werkzeug.wsgi import wrap_file

        from ..data.datasets import find_csv

        root = coord.config.storage.datasets_dir
        path = find_csv(dataset_id, preprocessed=True, root=root)
        kind = "preprocessed"
        if path is None:
            path = find_csv(dataset_id, root=root)
            kind = "raw"
        if path is None:
            return _json(
                {"status": "error", "message": f"dataset {dataset_id!r} not staged"},
                status=404,
            )
        if request.args.get("probe"):
            return _json({"kind": kind, "size": __import__("os").path.getsize(path)})
        # streamed, not read into memory: N agents cold-starting on a
        # 100 MB dataset must not allocate N full copies coordinator-side
        return Response(
            wrap_file(request.environ, open(path, "rb")),
            mimetype="text/csv",
            direct_passthrough=True,
            headers={
                "X-Dataset-Kind": kind,
                "Content-Disposition": f"attachment; filename={dataset_id}.csv",
            },
        )

    handlers = locals()

    # CORS parity with the reference master's flask-cors default config
    # (allow-all; master.py:20-24): browser dashboards may call the API
    # cross-origin, including OPTIONS preflights
    _cors = {
        "Access-Control-Allow-Origin": "*",
        "Access-Control-Allow-Headers": "Content-Type, Authorization",
        "Access-Control-Allow-Methods": "GET, POST, OPTIONS",
    }

    @Request.application
    def app(request):
        if request.method == "OPTIONS":
            return Response(status=204, headers=_cors)
        try:
            endpoint, values = url_map.bind_to_environ(request.environ).match()
            resp = handlers[endpoint](request, **values)
        except NotFound:
            resp = _json({"status": "error", "message": "not found"}, status=404)
        except HTTPException as e:
            resp = _json({"status": "error", "message": str(e)}, status=e.code or 500)
        except (KeyError, FileNotFoundError) as e:
            resp = _json({"status": "error", "message": str(e)}, status=404)
        except Exception as e:  # noqa: BLE001
            resp = _json({"status": "error", "message": str(e)}, status=500)
        resp.headers.extend(_cors)
        return resp

    app.coordinator = coord
    return app


def serve(coordinator: Optional[Coordinator] = None, host: Optional[str] = None, port: Optional[int] = None):
    from werkzeug.serving import run_simple

    from ..utils.config import get_config

    cfg = get_config().service
    app = create_app(coordinator)
    run_simple(host or cfg.host, port or cfg.port, app, threaded=True)


def main() -> None:
    """``tpuml-coordinator`` console entry point: serve the REST surface.

    - ``--cluster`` (default): scheduler-mediated dispatch — remote agents
      register over /subscribe; optionally ``--local-executors N`` adds
      in-process workers so the box serves jobs with no agents attached, or
      ``--agent-executors N`` runs them as supervised child processes
      (device-fault containment: a poisoned backend kills only the child,
      tasks requeue, the supervisor respawns — runtime/supervisor.py).
    - ``--direct``: single in-process executor, no placement engine (the
      laptop / single-TPU-VM mode).
    The compose analog: reference docker-compose.yml:86-131 (master +
    scheduler services collapsed into this one process).
    """
    import argparse

    parser = argparse.ArgumentParser(description="tpuml coordinator server")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--direct", action="store_true",
                        help="in-process executor, no placement engine")
    parser.add_argument("--local-executors", type=int, default=0, metavar="N",
                        help="cluster mode: also attach N in-process executors")
    parser.add_argument("--agent-executors", type=int, default=0, metavar="N",
                        help="cluster mode: run N supervised child agent "
                             "processes (fault-isolated executors)")
    parser.add_argument("--journal", action="store_true",
                        help="journal job state; resume in-flight jobs on restart")
    args = parser.parse_args()
    if args.direct and args.agent_executors > 0:
        parser.error("--agent-executors requires cluster mode (drop --direct)")

    supervisor = None
    slot_envs = None
    if args.agent_executors > 0:
        import os as _os

        # single-accelerator host policy: exactly one process may own the
        # chip. The parent pins itself to CPU and agent slot 0 inherits the
        # original platform — unless --local-executors run in the parent,
        # which then keeps the chip and every child slot pins to CPU. This
        # MUST happen before Coordinator() below: its eager artifact-refit
        # executor latches the platform via setup_jax on construction.
        chip_taken = args.local_executors > 0
        inherit = {"TPUML_PLATFORM": _os.environ.get("TPUML_PLATFORM")}
        if not chip_taken:
            _os.environ["TPUML_PLATFORM"] = "cpu"
        slot_envs = [
            inherit if (i == 0 and not chip_taken)
            else {"TPUML_PLATFORM": "cpu"}
            for i in range(args.agent_executors)
        ]

    if args.direct:
        coord = Coordinator(journal=args.journal)
    else:
        from .cluster import ClusterRuntime

        cluster = ClusterRuntime()
        for _ in range(max(args.local_executors, 0)):
            cluster.add_executor()
        coord = Coordinator(cluster=cluster, journal=args.journal)
        if args.agent_executors > 0:
            from ..utils.config import get_config as _cfg
            from .supervisor import AgentSupervisor, agent_command

            cfg = _cfg().service
            # children must dial an address the bound server answers on:
            # wildcard binds answer loopback, a specific --host only itself
            host = args.host or cfg.host
            dial = "127.0.0.1" if host in (None, "", "0.0.0.0", "::") else host
            url = f"http://{dial}:{args.port or cfg.port}"
            supervisor = AgentSupervisor(
                agent_command(url), n=args.agent_executors,
                slot_envs=slot_envs,
            )
            supervisor.start()
            coord.agent_supervisor = supervisor
    try:
        serve(coord, host=args.host, port=args.port)
    finally:
        if supervisor is not None:
            supervisor.stop()


if __name__ == "__main__":
    main()
