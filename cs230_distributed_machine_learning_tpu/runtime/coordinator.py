"""Coordinator: sessions, job fan-out, result collection, aggregation.

The TPU-native replacement for the reference master + its Redis/Kafka glue
(``aws-prod/master/master.py``, ``task_handler.py``): one process owning the
job store, the topic bus, and the executor pool. The job lifecycle mirrors
the reference exactly — create session, stage dataset, preprocess, expand a
train job into per-trial subtasks, dispatch, collect results, aggregate by
``mean_cv_score`` (``task_handler.py:254-263``) — minus the brokers: fan-out
is an in-process dispatch to the mesh executor, results flow back through
callbacks + the bus, progress is a store read instead of a Redis poll.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ..data.datasets import DatasetCache
from ..data.download import download_dataset
from ..data.preprocess import preprocess_dataframe
from ..obs import (
    RECORDER,
    TRACER,
    activate,
    counter_inc,
    current_trace_id,
    gauge_set,
    new_trace_id,
    record_event,
    span,
)
from ..obs.curves import CurveStore, divergence
from ..utils.config import FrameworkConfig, get_config
from ..utils.logging import get_logger
from ..utils.serialization import json_safe
from .artifacts import save_artifact
from .executor import LocalExecutor
from .queue import TopicBus
from .search import SearchJobDriver, Step
from .store import SUBTASK_TERMINAL_STATUSES, TERMINAL_STATUSES, JobStore
from .subtasks import create_subtasks

logger = get_logger("tpuml.coordinator")

TOPIC_RESULTS = "result"
TOPIC_METRICS = "metrics"


class JobMigratedError(Exception):
    """Raised inside a job's ingest loop when the rebalancer has marked
    the job for migration: the loop unwinds WITHOUT finalizing (the
    destination shard owns completion now) and without the generic
    failure path (nothing failed — the job moved)."""


class Coordinator:
    def __init__(
        self,
        config: Optional[FrameworkConfig] = None,
        *,
        mesh=None,
        executor: Optional[LocalExecutor] = None,
        cluster=None,
        journal: bool = False,
        journal_dir: Optional[str] = None,
        shard_id: Optional[int] = None,
        n_shards: int = 1,
    ):
        """Two dispatch modes: direct (default — one in-process executor, the
        single-host TPU deployment) and scheduled (``cluster=`` a
        ClusterRuntime — placement-engine dispatch over an executor pool
        with heartbeats/requeue, the reference's full topology).

        ``shard_id``/``n_shards`` make this coordinator ONE shard of a
        sharded control plane (docs/ARCHITECTURE.md "Sharded control
        plane"): job ids are stamped ``s<shard>-`` so any stateless front
        end routes them, and ``journal_dir`` points at this shard's OWN
        journal (``<journal>/shard-<k>``) — the unit of hot-standby
        takeover (a replacement process replaying it finishes the dead
        shard's jobs, docs/ROBUSTNESS.md "Shard takeover")."""
        self.config = config or get_config()
        self.cluster = cluster
        self.shard_id = shard_id
        self.n_shards = max(int(n_shards), 1)
        self.bus = cluster.bus if cluster is not None else TopicBus()
        self.store = JobStore(
            journal_dir=(
                (journal_dir or self.config.storage.journal_dir)
                if journal else None
            )
        )
        self.cache = (
            cluster.cache
            if cluster is not None and cluster.cache is not None
            else DatasetCache(root=self.config.storage.datasets_dir)
        )
        if cluster is not None and cluster.cache is None:
            cluster.cache = self.cache
        # retained in cluster mode too: artifact refits run coordinator-side
        self.executor = executor or LocalExecutor(mesh=mesh, cache=self.cache)
        self._job_threads: Dict[str, threading.Thread] = {}
        self._artifact_lock = threading.Lock()
        self._artifact_paths: Dict[Any, str] = {}
        self._artifact_specs: Dict[Any, Dict[str, Any]] = {}
        #: submit-dedupe guard: job_ids currently being expanded, so a
        #: retried duplicate POST arriving DURING expansion (the store
        #: doesn't know the job yet) can't double-expand
        self._submit_lock = threading.Lock()
        self._submitting: set = set()
        #: readiness (GET /readyz): False while the journal is being
        #: replayed / in-flight jobs re-queued, so load balancers and the
        #: chaos harness can gate on recovery completion
        self.ready = not journal
        #: recovery forensics for /healthz (replayed-op counts, wall time)
        self.recovery: Dict[str, Any] = {}
        # fleet health plane (docs/OBSERVABILITY.md "Fleet health
        # plane"): capacity-signal deriver (GET /autoscale) + SLO alert
        # rules engine (GET /alerts), evaluated on the engine sweep in
        # cluster mode and at scrape//read time in direct mode
        from ..obs.signals import CapacitySignals
        from ..obs.slo import AlertEngine, default_rules

        # trial telemetry plane (docs/OBSERVABILITY.md "Trial telemetry
        # plane"): bounded in-memory store of per-trial learning curves —
        # fed by result/metrics ingest, read by GET /curves and the SSE
        # stream, consulted by the numerical-health watchdog
        self.curves = CurveStore()
        self.signals = CapacitySignals(self)
        self.alerts = AlertEngine(
            default_rules(self.config),
            interval_s=self.config.service.alert_eval_interval_s,
        )
        #: peer shard base URLs, index == shard id (server --peers /
        #: ShardFleet). Empty on unsharded deployments — every
        #: rebalancing path below is inert without peers.
        self.peer_urls: List[str] = []
        #: jobs being quiesced for migration: (sid, jid) -> dest shard.
        #: The scheduled ingest loop checks this each iteration and
        #: unwinds via JobMigratedError — the quiesce half of the
        #: migration state machine (docs/ROBUSTNESS.md).
        self._migrating: Dict[tuple, int] = {}
        self._rebalance_lock = threading.Lock()
        self._rebalance_busy = False
        self._last_rebalance = 0.0
        if cluster is not None:
            # journal every attempt issue (lease reclaim / retry / requeue /
            # speculation) into the job store so replay preserves budgets,
            # and every placement/lease grant so a restarted coordinator
            # can tell dispatched in-flight subtasks from never-dispatched
            # ones (docs/ROBUSTNESS.md "Coordinator recovery")
            cluster.ledger.on_attempt = self._journal_attempt
            cluster.engine.on_place = self._journal_placement
            # journal mesh-generation bumps (worker join/death/evict) so
            # a recovered coordinator replays the fleet's reshard history
            # (docs/ARCHITECTURE.md "Elastic trial fabric")
            cluster.engine.on_mesh_change = self._journal_mesh_change
            # overload probe: speculation sheds first under load
            cluster.engine.shed_check = self.overload_shedding
            cluster.engine.on_sweep_end = self.health_tick
        if journal:
            self._recover()

    def health_tick(self, force: bool = False) -> None:
        """One fleet-health evaluation: derive the capacity signals and
        run the alert rules. Driven by the engine sweep (cluster mode),
        every ``/metrics/prom`` scrape, and ``/alerts`` / ``/autoscale``
        reads (direct-mode coordinators have no sweep) — both halves are
        internally throttled so the drivers don't multi-evaluate."""
        try:
            self.signals.evaluate(force=force)
        except Exception:  # noqa: BLE001 — health derivation must never break a caller
            logger.exception("Capacity-signal derivation failed")
        try:
            self.alerts.evaluate(force=force)
        except Exception:  # noqa: BLE001
            logger.exception("Alert-rule evaluation failed")
        try:
            self.rebalance_tick()
        except Exception:  # noqa: BLE001 — rebalancing must never break a caller
            logger.exception("Rebalance tick failed")

    def _recover(self) -> None:
        """Boot-time crash recovery: surface the journal replay the store
        already ran, re-queue in-flight work, and flip readiness. The
        whole sequence is synchronous — a coordinator is never serving
        while half-recovered."""
        t0 = time.time()
        for op, n in self.store.replay_ops.items():
            counter_inc("tpuml_recovery_replayed_ops_total", n, op=op)
        record_event(
            "recovery.start",
            replayed_ops=sum(self.store.replay_ops.values()),
            replay_skipped=self.store.replay_skipped,
            replay_seconds=round(self.store.replay_seconds, 6),
        )
        if self.cluster is not None and self.store.mesh_generation:
            # resume the reshard counter monotonically: workers that
            # registered before recovery finished already bumped the live
            # engine, so take the max of both histories — and refresh the
            # gauges, which otherwise keep the pre-recovery value until
            # the next live reshard
            eng = self.cluster.engine
            with eng._lock:  # merge under the bump lock: a concurrent
                # join's increment must not be overwritten
                eng.mesh_generation = max(
                    eng.mesh_generation, self.store.mesh_generation
                )
                gauge_set(
                    "tpuml_mesh_generation", float(eng.mesh_generation)
                )
                gauge_set(
                    "tpuml_mesh_devices_total", float(eng.total_devices())
                )
        # re-seed the curve store from journaled ``curve`` ops: rung-
        # boundary curves survive a restart, so /curves and the watchdog's
        # divergence history pick up where the dead coordinator left off
        replayed_curves = self.store.drain_replayed_curves()
        for e in replayed_curves:
            self.curves.ingest(
                e["jid"], e["stid"], e["curve"],
                rung=e.get("rung", 0), attempt=e.get("attempt", 0),
                diverged=bool(e.get("diverged")),
            )
        resumed = self.resume_inflight()
        recovery_s = self.store.replay_seconds + (time.time() - t0)
        self.recovery = {
            "replayed_ops": dict(self.store.replay_ops),
            "replay_skipped": self.store.replay_skipped,
            "jobs_resumed": len(resumed),
            "subtasks_requeued": self._resume_requeued,
            "curves_replayed": len(replayed_curves),
            "recovery_seconds": recovery_s,
        }
        gauge_set("tpuml_coordinator_recovery_seconds", recovery_s)
        record_event("recovery.done", **self.recovery)
        if resumed:
            logger.info(
                "Recovery done in %.3fs: %d ops replayed, %d jobs resumed, "
                "%d subtasks re-queued",
                recovery_s, sum(self.store.replay_ops.values()),
                len(resumed), self._resume_requeued,
            )
        self.ready = True

    def _journal_attempt(self, task: Dict[str, Any], entry, reason: str) -> None:
        sid = task.get("session_id")
        jid = task.get("job_id")
        stid = task.get("subtask_id")
        if not (sid and jid and stid):
            return
        try:
            self.store.record_attempt(
                sid, jid, stid,
                attempt=entry.attempt,
                failures=entry.failures,
                excluded=entry.excluded,
            )
        except KeyError:
            # a job this store never saw (foreign traffic on a shared
            # cluster): nothing to journal
            pass

    def _journal_mesh_change(
        self, generation: int, reason: str, snapshot: Dict[str, Any]
    ) -> None:
        try:
            self.store.record_mesh_generation(generation, reason)
        except Exception:  # noqa: BLE001 — journaling must not block resharding
            logger.exception("Mesh-generation journal failed")

    def _journal_placement(self, task: Dict[str, Any], worker_id: str,
                           lease_deadline=None) -> None:
        sid = task.get("session_id")
        jid = task.get("job_id")
        stid = task.get("subtask_id")
        if not (sid and jid and stid):
            return
        try:
            self.store.record_placement(
                sid, jid, stid, worker_id,
                attempt=int(task.get("attempt") or 0),
                lease_deadline=lease_deadline,
            )
        except KeyError:
            pass  # foreign traffic on a shared cluster: nothing to journal

    #: subtasks re-dispatched by the most recent resume_inflight()
    _resume_requeued = 0

    def resume_inflight(self) -> List[str]:
        """Re-dispatch jobs the journal shows as unfinished: replay restores
        state, this restores WORK — a coordinator killed mid-job completes it
        after restart without client resubmission (beyond the reference,
        whose master restart loses in-flight jobs; Redis AOF only kept
        state, SURVEY.md §5.4). Subtasks with a journaled terminal result
        are not re-run. In cluster mode, subtasks the journal shows as
        PLACED pre-crash get a fresh attempt id before re-queueing: a
        zombie worker's late FAILED report then carries a superseded stamp
        and cannot burn retry budget, while its late COMPLETED report is
        still accepted (first terminal result wins — the at-least-once
        re-ingest contract, docs/ROBUSTNESS.md)."""
        resumed = []
        self._resume_requeued = 0
        for sid, job_id in self.store.unfinished_jobs():
            job = self.store.get_job(sid, job_id)
            specs = [sub["spec"] for sub in job["subtasks"].values()]
            existing = {
                stid: sub["result"]
                for stid, sub in job["subtasks"].items()
                if sub["status"] in SUBTASK_TERMINAL_STATUSES
                and sub["result"]
            }
            remaining = [
                st for st in specs if st["subtask_id"] not in existing
            ]
            if self.cluster is not None:
                for st in remaining:
                    if st.get("placed_worker") is None:
                        continue  # never dispatched (or pre-place journal)
                    self.cluster.ledger.seed(st)
                    self.cluster.ledger.next_attempt(st, reason="recovery")
            logger.info(
                "Resuming job %s: %d/%d subtasks already journaled",
                job_id, len(existing), len(specs),
            )
            record_event(
                "job.resume", job_id=job_id,
                n_done=len(existing), n_requeued=len(remaining),
            )
            counter_inc("tpuml_recovery_jobs_resumed_total")
            counter_inc(
                "tpuml_recovery_subtasks_requeued_total", len(remaining)
            )
            self._resume_requeued += len(remaining)
            t = threading.Thread(
                target=self._run_job,
                args=(sid, job_id, specs),
                kwargs={"existing": existing},
                daemon=True,
            )
            self._job_threads[job_id] = t
            t.start()
            resumed.append(job_id)
        return resumed

    # ------------- cross-shard rebalancing (docs/ROBUSTNESS.md "Shard rebalancing") -------------
    # The fleet acting on its own telemetry: a HOT shard (high
    # tpuml_shard_pressure) migrates whole jobs to a drainable-COLD peer
    # and offers queued subtasks to thieves; an idle shard steals. Both
    # paths ride the existing crash-safety machinery — journal ops with
    # total replay, attempt-stamp fencing, first-terminal-result-wins
    # dedup — so a SIGKILL of either party at any phase loses nothing.

    def rebalance_tick(self) -> None:
        """Throttled entry point, driven by health_tick (engine sweep /
        scrapes). The actual pass runs on a background thread — it makes
        peer HTTP probes and must never stall a sweep."""
        svc = self.config.service
        if (
            not svc.rebalance_enabled
            or self.cluster is None
            or self.shard_id is None
            or not self.peer_urls
            or not self.ready
        ):
            return
        now = time.time()
        with self._rebalance_lock:
            if (
                self._rebalance_busy
                or now - self._last_rebalance < svc.rebalance_interval_s
            ):
                return
            self._rebalance_busy = True
            self._last_rebalance = now
        threading.Thread(target=self._rebalance_once, daemon=True).start()

    def _rebalance_once(self) -> None:
        try:
            self._reclaim_stale_steals()
            rep = self.signals.evaluate()
            sig = rep.get("signals") or {}
            my_p = float(sig.get("shard_pressure") or 0.0)
            svc = self.config.service
            if my_p >= svc.rebalance_hot_pressure:
                self._migrate_if_peer_cold(my_p)
            elif (
                my_p <= svc.rebalance_cold_pressure
                and int(sig.get("idle_workers") or 0) > 0
            ):
                self._steal_from_hot_peer()
        except Exception:  # noqa: BLE001 — a failed pass must not wedge the next
            logger.exception("Rebalance pass failed")
        finally:
            with self._rebalance_lock:
                self._rebalance_busy = False

    def _peer_pressures(self) -> Dict[int, float]:
        """shard_pressure of every answering peer (short timeouts — a
        dead peer is simply not a candidate)."""
        import requests

        out: Dict[int, float] = {}
        for k, url in enumerate(self.peer_urls):
            if k == self.shard_id or not url:
                continue
            try:
                r = requests.get(f"{url}/autoscale", timeout=3)
                if r.ok:
                    sig = (r.json() or {}).get("signals") or {}
                    out[k] = float(sig.get("shard_pressure") or 0.0)
            except (requests.RequestException, ValueError):
                continue
        return out

    def _migrate_if_peer_cold(self, my_pressure: float) -> None:
        svc = self.config.service
        peers = self._peer_pressures()
        if not peers:
            return
        dest, cold = min(peers.items(), key=lambda kv: kv[1])
        if cold > svc.rebalance_cold_pressure:
            return
        if cold > 0 and my_pressure / cold < svc.rebalance_imbalance_ratio:
            return  # hot, but not hot ENOUGH relative to the peer
        picked = self._pick_migratable()
        if picked is None:
            return
        sid, jid = picked
        self.migrate_job(sid, jid, dest)

    def _pick_migratable(self) -> Optional[tuple]:
        """Cheapest unfinished job that can move: not mid-expansion, not
        already migrating, and not an adaptive-search job (the rung
        controller's in-memory ladder state has no export contract — a
        migrated ASHA job would restart its schedule from the journaled
        rung history on the WRONG shard's recorder; excluded by design,
        documented in docs/ROBUSTNESS.md). Among the eligible, a job with
        nothing currently EXECUTING (no subtask at the head of a worker
        queue — the same queued-vs-running line the steal offer draws)
        wins: quiescing it fences only queued attempts and throws away no
        in-flight work. A job mid-execution is the fallback, not the
        first pick."""
        heads = set()
        if self.cluster is not None:
            for q in self.cluster.engine.queue_snapshot().values():
                if q:
                    heads.add(q[0])
        fallback: Optional[tuple] = None
        for sid, jid in self.store.unfinished_jobs():
            if (sid, jid) in self._migrating:
                continue
            with self._submit_lock:
                if jid in self._submitting:
                    continue
            try:
                job = self.store.get_job(sid, jid)
            except KeyError:
                continue
            subs = job.get("subtasks") or {}
            if any((s.get("spec") or {}).get("asha") for s in subs.values()):
                continue
            # anti-ping-pong: a job migrates at most once. Re-exporting
            # an adopted job would let two shards trade the same job
            # every tick while both hover near the hot threshold.
            if job.get("migrated_from") is not None:
                continue
            live = [
                stid for stid, s in subs.items()
                if s["status"] not in SUBTASK_TERMINAL_STATUSES
            ]
            if not live:
                continue
            if not any(stid in heads for stid in live):
                return sid, jid
            if fallback is None:
                fallback = (sid, jid)
        return fallback

    def migrate_job(self, sid: str, job_id: str, dest_shard: int) -> bool:
        """Donor half of the migration state machine:

        1. **quiesce** — mark the job migrating; its ingest loop unwinds
           (JobMigratedError) without finalizing.
        2. **fence** — bump every non-terminal subtask's attempt
           (journaled via the on_attempt hook) and release its engine
           book entry: no donor-side copy can re-dispatch, and any
           still-executing worker's late FAILED report is stale by
           construction (its COMPLETED is still accepted — at-least-once).
        3. **export** — POST the full job record to the peer's
           ``/migrate_in``; the RECIPIENT journals ``migrate_in`` first.
        4. **stamp** — only after the peer accepted, journal
           ``migrate_out`` (the forwarding stamp). Crash between 3 and 4
           leaves BOTH shards owning the job: clients still route to the
           donor (no stamp), so results stay consistent and the
           recipient's copy is wasted work deduped by first-wins — never
           a lost job. Crash before 3 (or a failed POST) aborts and the
           job respawns locally.
        5. **forward** — replay-forward late donor-side results to the
           new owner for ``rebalance_forward_s``.
        """
        import os as _os

        import requests

        if self.cluster is None or not self.peer_urls:
            return False
        try:
            url = self.peer_urls[int(dest_shard)]
        except (IndexError, ValueError):
            return False
        record_event(
            "migrate.start", job_id=job_id, dest_shard=int(dest_shard),
        )
        self._migrating[(sid, job_id)] = int(dest_shard)
        try:
            t = self._job_threads.get(job_id)
            if t is not None and t.is_alive():
                t.join(timeout=30.0)
                if t.is_alive():
                    record_event(
                        "migrate.abort", job_id=job_id,
                        dest_shard=int(dest_shard),
                        reason="quiesce_timeout",
                    )
                    return False  # loop never unwound: leave the job alone
            # ---- fence ----
            job = self.store.get_job(sid, job_id)
            owner = {
                stid: wid
                for wid, q in self.cluster.engine.queue_snapshot().items()
                for stid in q
            }
            fenced = 0
            for stid, sub in job["subtasks"].items():
                if sub["status"] in SUBTASK_TERMINAL_STATUSES:
                    continue
                task = dict(sub["spec"])
                self.cluster.ledger.seed(task)
                self.cluster.ledger.next_attempt(task, reason="migrate")
                wid = owner.get(stid) or task.get("placed_worker")
                if wid:
                    self.cluster.engine.release_task(wid, stid)
                self.store.clear_steal(stid)
                fenced += 1
            # ---- export (re-read: the fence journaled fresh attempts
            # into the specs, and the recipient must adopt THOSE) ----
            job = self.store.get_job(sid, job_id)
            export = {
                "session_id": sid,
                "priority": self.store.session_priority(sid),
                "source_shard": self.shard_id,
                "job": job,
            }
            try:
                r = requests.post(
                    f"{url}/migrate_in", json=json_safe(export), timeout=30
                )
            except requests.RequestException as e:
                self._abort_migration(sid, job_id, f"peer_unreachable: {e}")
                return False
            if r.status_code != 200:
                self._abort_migration(
                    sid, job_id, f"peer_rejected: HTTP {r.status_code}"
                )
                return False
            # chaos-drill hook: hold the riskiest window (recipient has
            # the job, donor not yet stamped) open so the harness can
            # land a deterministic SIGKILL inside it
            delay = float(_os.environ.get("CS230_MIGRATE_DELAY_S", 0) or 0)
            if delay > 0:
                time.sleep(delay)
            # ---- stamp ----
            self.store.record_migrate_out(sid, job_id, int(dest_shard))
            counter_inc("tpuml_jobs_migrated_total", direction="out")
            record_event(
                "migrate.out", job_id=job_id, dest_shard=int(dest_shard),
                n_fenced=fenced,
            )
            logger.info(
                "Migrated job %s to shard %d (%d subtasks fenced)",
                job_id, int(dest_shard), fenced,
            )
            # ---- forward late results ----
            pending = [
                stid for stid, sub in job["subtasks"].items()
                if sub["status"] not in SUBTASK_TERMINAL_STATUSES
            ]
            self._forward_late_results(job_id, int(dest_shard), pending)
            self.cluster.ledger.forget(list(job["subtasks"]))
            return True
        finally:
            self._migrating.pop((sid, job_id), None)

    def _abort_migration(self, sid: str, job_id: str, reason: str) -> None:
        """Failed export: the job never left. Clear the quiesce mark and
        respawn it locally — the fenced attempts simply re-dispatch here
        (same recovery semantics as a restart)."""
        record_event("migrate.abort", job_id=job_id, reason=reason)
        logger.warning("Migration of job %s aborted: %s", job_id, reason)
        self._migrating.pop((sid, job_id), None)
        self._respawn_job(sid, job_id)

    def _respawn_job(self, sid: str, job_id: str) -> None:
        """Resume ONE job from its store record (the per-job slice of
        resume_inflight): dispatch what isn't terminal, keep what is."""
        job = self.store.get_job(sid, job_id)
        specs = [sub["spec"] for sub in job["subtasks"].values()]
        existing = {
            stid: sub["result"]
            for stid, sub in job["subtasks"].items()
            if sub["status"] in SUBTASK_TERMINAL_STATUSES and sub["result"]
        }
        t = threading.Thread(
            target=self._run_job,
            args=(sid, job_id, specs),
            kwargs={"existing": existing},
            daemon=True,
        )
        self._job_threads[job_id] = t
        t.start()

    def _forward_late_results(
        self, job_id: str, dest_shard: int, pending_ids: List[str]
    ) -> None:
        """Donor-side replay-forward: results for a migrated job's
        still-open subtasks (zombie workers finishing fenced attempts)
        are POSTed to the new owner's ``/peer_result`` for a bounded
        window, so the at-least-once ingest contract survives the
        handoff — the recipient's first-wins dedup absorbs any overlap
        with its own re-dispatched attempts."""
        if not pending_ids:
            return
        import queue as _q

        import requests

        url = self.peer_urls[dest_shard]
        wanted = set(pending_ids)
        sub = self.bus.subscribe(
            TOPIC_RESULTS, key_filter=lambda k: k in wanted
        )
        deadline = time.time() + self.config.service.rebalance_forward_s

        def _pump():
            # one successful relay per subtask: duplicate reports (a
            # worker re-sending, or the recipient echoing a stolen
            # result we already forwarded) must not re-post, or a
            # migrated-after-steal subtask ping-pongs between the two
            # shards until both relay deadlines expire
            done: set = set()
            try:
                while time.time() < deadline and len(done) < len(wanted):
                    try:
                        stid, result = sub.get(timeout=1.0)
                    except _q.Empty:
                        continue
                    if stid in done:
                        continue
                    try:
                        requests.post(
                            f"{url}/peer_result",
                            json=json_safe(result or {}),
                            timeout=10,
                        )
                        done.add(stid)
                        counter_inc("tpuml_results_forwarded_total")
                        record_event(
                            "migrate.forward", job_id=job_id,
                            subtask_id=stid, dest_shard=dest_shard,
                        )
                    except requests.RequestException:
                        logger.warning(
                            "Forwarding late result %s to shard %d failed",
                            stid, dest_shard,
                        )
            finally:
                sub.close()

        threading.Thread(target=_pump, daemon=True).start()

    def migrate_in(self, export: Dict[str, Any]) -> Dict[str, Any]:
        """Recipient half: journal the adopted record (``migrate_in`` —
        BEFORE the donor stamps ``migrate_out``, so no crash ordering
        loses the job), then resume it like a recovered local job. A
        duplicate POST (donor retry) is answered idempotently."""
        if self.cluster is None:
            raise ValueError(
                "job migration requires a clustered coordinator"
            )
        job = (export or {}).get("job") or {}
        sid = (export or {}).get("session_id")
        job_id = job.get("job_id")
        if not (sid and job_id and job.get("subtasks") is not None):
            raise ValueError("malformed migration export")
        if self.store.has_job(sid, job_id):
            return {
                "status": "accepted", "job_id": job_id,
                "shard": self.shard_id, "duplicate": True,
            }
        src = export.get("source_shard")
        self.store.create_session(
            sid, priority=int(export.get("priority") or 0)
        )
        self.store.import_job(sid, job, source_shard=src)
        counter_inc("tpuml_jobs_migrated_total", direction="in")
        record_event(
            "migrate.in", job_id=job_id, source_shard=src,
            n_subtasks=len(job.get("subtasks") or {}),
        )
        logger.info(
            "Adopted job %s from shard %s (%d subtasks)",
            job_id, src, len(job.get("subtasks") or {}),
        )
        self._respawn_job(sid, job_id)
        return {
            "status": "accepted", "job_id": job_id, "shard": self.shard_id,
        }

    # ---- work stealing ----

    def steal_candidates(self) -> Dict[str, Any]:
        """Donor surface (``GET /steal_candidates``): queued, steal-
        eligible subtasks an idle peer may pull — offered only while this
        shard is HOT (a balanced fleet advertises nothing). Per-worker
        queue heads are withheld (likely already executing), as are
        tombstoned (already-granted) and adaptive-search subtasks."""
        out: Dict[str, Any] = {
            "shard": self.shard_id,
            "candidates": [],
            "shard_pressure": None,
            "backlog_device_seconds": None,
        }
        if self.cluster is None or not self.config.service.rebalance_enabled:
            return out
        sig = (self.signals.report() or {}).get("signals") or {}
        out["shard_pressure"] = sig.get("shard_pressure")
        out["backlog_device_seconds"] = sig.get("backlog_device_seconds")
        if (
            float(sig.get("shard_pressure") or 0.0)
            < self.config.service.rebalance_hot_pressure
        ):
            return out
        tomb = dict(self.store.steal_tombstones)
        snap = self.cluster.engine.worker_snapshot()
        owner = {
            stid: wid
            for wid, q in self.cluster.engine.queue_snapshot().items()
            for stid in q[1:]
            if stid not in tomb
        }
        info = self.store.lookup_specs(list(owner))
        for stid, rec in info.items():
            spec = rec["spec"]
            if spec.get("asha"):
                continue
            out["candidates"].append(
                {
                    "subtask_id": stid,
                    "job_id": rec["job_id"],
                    "session_id": rec["session_id"],
                    "est_s": spec.get("est_s"),
                    # priced width: the mesh slice the donor's engine
                    # packed this trial onto — a thief filters candidates
                    # to what its own widest IDLE slice can serve
                    # (heterogeneous fleets must not pull 8-device work
                    # onto a 1-device shard)
                    "n_devices": int(
                        (snap.get(owner[stid]) or {}).get("n_devices") or 1
                    ),
                }
            )
        return out

    def release_for_steal(
        self, thief_shard: int, max_n: int,
        max_n_devices: Optional[int] = None, prefer_wide: bool = False,
    ) -> List[Dict[str, Any]]:
        """Donor grant (``POST /steal_tasks``): hand up to ``max_n``
        queued subtasks to a thief shard as FRESH ledger attempts. Each
        grant bumps the attempt (fencing the queued donor copy — its
        late FAILED is stale, its late COMPLETED still wins first),
        releases the engine book entry, and journals a ``steal``
        tombstone so neither a live nor a restarted donor re-dispatches
        the subtask inside the steal lease.

        Mesh-aware grants: ``max_n_devices`` (the thief's widest idle
        slice) filters out candidates priced wider than the thief can
        serve; ``prefer_wide`` grants the widest-priced candidates first
        so wide trials land on wide slices. Both default to the legacy
        width-blind behavior for old thieves."""
        if (
            self.cluster is None
            or not self.config.service.rebalance_enabled
            or max_n <= 0
        ):
            return []
        tomb = dict(self.store.steal_tombstones)
        snap = self.cluster.engine.worker_snapshot()
        owner = {
            stid: wid
            for wid, q in self.cluster.engine.queue_snapshot().items()
            for stid in q[1:]
            if stid not in tomb
        }
        width = {
            stid: int((snap.get(wid) or {}).get("n_devices") or 1)
            for stid, wid in owner.items()
        }
        if max_n_devices is not None:
            owner = {
                stid: wid for stid, wid in owner.items()
                if width[stid] <= int(max_n_devices)
            }
        info = self.store.lookup_specs(list(owner))
        items = sorted(
            info.items(),
            key=(
                (lambda kv: (-width.get(kv[0], 1), kv[0]))
                if prefer_wide else (lambda kv: kv[0])
            ),
        )
        granted: List[Dict[str, Any]] = []
        for stid, rec in items:
            if len(granted) >= int(max_n):
                break
            if rec["spec"].get("asha"):
                continue
            task = dict(rec["spec"])
            self.cluster.ledger.seed(task)
            self.cluster.ledger.next_attempt(task, reason="steal")
            self.cluster.engine.release_task(owner[stid], stid)
            self.store.record_steal(
                rec["session_id"], rec["job_id"], stid,
                thief_shard=int(thief_shard),
                attempt=int(task.get("attempt") or 0),
            )
            task["metadata"] = rec["metadata"]
            task["stolen_from"] = self.shard_id
            granted.append(task)
            counter_inc("tpuml_subtasks_stolen_total", direction="out")
            record_event(
                "steal.out", job_id=rec["job_id"], subtask_id=stid,
                attempt=int(task.get("attempt") or 0),
                thief_shard=int(thief_shard),
                n_devices=width.get(stid, 1),
            )
        if granted:
            logger.info(
                "Granted %d queued subtasks to thief shard %d",
                len(granted), int(thief_shard),
            )
        return granted

    def _steal_from_hot_peer(self) -> None:
        """Thief half: poll peers' ``/steal_candidates``, pull from the
        hottest offering shard, run the grants on the local fabric, and
        relay every result back to the donor's ``/peer_result`` (the
        donor's still-running ingest loop counts them — its ledger
        expects exactly the granted attempt).

        Mesh-aware: candidates are priced with the device width of the
        slice the donor packed them onto, and this thief only pulls work
        its widest IDLE slice can serve — preferring the widest-priced
        candidates so wide trials land on wide slices instead of
        serializing on whatever narrow worker is free."""
        import requests

        svc = self.config.service
        # widest idle local slice: the upper bound on the candidate width
        # this shard can usefully absorb right now
        widest_idle = 0
        try:
            snap = self.cluster.engine.worker_snapshot()
            for wid, q in self.cluster.engine.queue_snapshot().items():
                if not q:
                    widest_idle = max(
                        widest_idle,
                        int((snap.get(wid) or {}).get("n_devices") or 1),
                    )
        except Exception:  # noqa: BLE001 — a torn snapshot must not crash the sweep
            widest_idle = 0
        if widest_idle <= 0:
            return  # no idle slice: stolen work would only queue here
        offers: Dict[int, Dict[str, Any]] = {}
        for k, url in enumerate(self.peer_urls):
            if k == self.shard_id or not url:
                continue
            try:
                r = requests.get(f"{url}/steal_candidates", timeout=3)
                if r.ok:
                    body = r.json() or {}
                    servable = [
                        c for c in (body.get("candidates") or [])
                        if int(c.get("n_devices") or 1) <= widest_idle
                    ]
                    if servable:
                        body["candidates"] = servable
                        offers[k] = body
            except (requests.RequestException, ValueError):
                continue
        if not offers:
            return
        donor = max(
            offers,
            key=lambda k: float(offers[k].get("shard_pressure") or 0.0),
        )
        try:
            r = requests.post(
                f"{self.peer_urls[donor]}/steal_tasks",
                json={
                    "thief_shard": self.shard_id,
                    "max_n": int(svc.steal_max_tasks),
                    "max_n_devices": widest_idle,
                    "prefer_wide": widest_idle > 1,
                },
                timeout=10,
            )
        except requests.RequestException:
            return
        if not r.ok:
            return
        try:
            tasks = (r.json() or {}).get("tasks") or []
        except ValueError:
            return
        if tasks:
            self._run_stolen(donor, tasks)

    def _run_stolen(
        self, donor_shard: int, tasks: List[Dict[str, Any]]
    ) -> None:
        """Execute stolen grants on this shard's fabric and relay the
        results home. The thief journals nothing — if it dies, the
        donor's steal lease expires and reclaims the subtasks with a
        fresh (fencing) attempt, so a resurrected thief's late result is
        deduped, never double-counted."""
        import queue as _q

        import requests

        url = self.peer_urls[donor_shard]
        wanted = {t["subtask_id"] for t in tasks if t.get("subtask_id")}
        sub = self.bus.subscribe(
            TOPIC_RESULTS, key_filter=lambda k: k in wanted
        )
        for t in tasks:
            counter_inc("tpuml_subtasks_stolen_total", direction="in")
            record_event(
                "steal.in", job_id=t.get("job_id"),
                subtask_id=t.get("subtask_id"),
                attempt=int(t.get("attempt") or 0),
                donor_shard=donor_shard,
            )
        logger.info(
            "Stole %d queued subtasks from shard %d", len(tasks), donor_shard
        )
        self.cluster.submit([dict(t) for t in tasks])

        def _pump():
            deadline = time.time() + 20.0 * self.config.service.client_timeout_s
            pending = set(wanted)
            try:
                while pending and time.time() < deadline:
                    try:
                        stid, result = sub.get(timeout=1.0)
                    except _q.Empty:
                        continue
                    if stid not in pending:
                        # echo of an already-relayed result (the donor
                        # forward-relays it back here if it migrated the
                        # job after granting the steal) — re-posting
                        # would ping-pong it between the shards
                        continue
                    try:
                        requests.post(
                            f"{url}/peer_result",
                            json=json_safe(result or {}),
                            timeout=10,
                        )
                        pending.discard(stid)
                    except requests.RequestException:
                        logger.warning(
                            "Relaying stolen result %s to shard %d failed",
                            stid, donor_shard,
                        )
            finally:
                sub.close()
                self.cluster.ledger.forget(wanted)

        threading.Thread(target=_pump, daemon=True).start()

    def _reclaim_stale_steals(self) -> None:
        """Donor lease sweep: a tombstone older than ``steal_lease_s``
        whose subtask is still open means the thief went dark — reclaim
        with a fresh attempt (fencing any resurrected thief) and
        re-dispatch locally; the job's still-running ingest loop picks
        the result up by subtask id."""
        svc = self.config.service
        now = time.time()
        for stid, t in list(self.store.steal_tombstones.items()):
            if now - float(t.get("ts") or 0) < svc.steal_lease_s:
                continue
            self.store.clear_steal(stid)
            info = self.store.lookup_specs([stid])
            if stid not in info:
                continue  # already terminal: nothing to reclaim
            rec = info[stid]
            task = dict(rec["spec"])
            self.cluster.ledger.seed(task)
            self.cluster.ledger.next_attempt(task, reason="steal_reclaim")
            task["metadata"] = rec["metadata"]
            counter_inc(
                "tpuml_subtasks_retried_total", reason="steal_reclaim"
            )
            record_event(
                "steal.reclaim", job_id=rec["job_id"], subtask_id=stid,
                attempt=int(task.get("attempt") or 0),
                thief_shard=t.get("thief"),
            )
            logger.warning(
                "Steal lease expired for %s (thief shard %s): reclaimed",
                stid, t.get("thief"),
            )
            self.cluster.submit([task])

    def ingest_peer_result(self, result: Dict[str, Any]) -> None:
        """``POST /peer_result``: a peer shard handing back a result —
        a thief returning a stolen grant, or a donor replay-forwarding a
        late result for a migrated job. Published onto the local result
        topic keyed by subtask id; the owning job loop applies the exact
        same first-wins / stale-attempt rules as any worker result."""
        result = dict(result or {})
        stid = result.get("subtask_id")
        if not stid:
            return
        counter_inc("tpuml_peer_results_ingested_total")
        self.bus.publish(TOPIC_RESULTS, result, key=stid)

    # ------------- trial telemetry plane (docs/OBSERVABILITY.md) -------------

    def ingest_curve(
        self, sid: str, job_id: str, subtask_id: str, curve: Dict[str, Any],
        *, rung: int = 0, attempt: int = 0,
    ) -> bool:
        """Ingest one trial's learning-curve record into the curve store
        and return the watchdog's divergence verdict. The store dedups on
        (subtask, rung, attempt) — the same curve arriving over both the
        metrics and the result transport counts, journals, and events
        exactly once. The divergence verdict is recomputed either way:
        the CALLER decides whether it terminates the trial (search loops
        do; plain jobs only mark the curve)."""
        if not isinstance(curve, dict) or not subtask_id:
            return False
        diverged = divergence(
            curve, self.config.service.curve_divergence_factor
        )
        added = self.curves.ingest(
            job_id, subtask_id, curve,
            rung=rung, attempt=attempt, diverged=diverged,
        )
        if added:
            counter_inc("tpuml_curve_points_total", float(added))
            record_event(
                "curve.ingest", job_id=job_id, subtask_id=subtask_id,
                rung=int(rung or 0), attempt=int(attempt or 0),
                n_points=added, diverged=diverged,
            )
            try:
                # journal so a restarted coordinator replays /curves and
                # the divergence history (torn tails are skipped by the
                # store's line-checksum replay)
                self.store.record_curve(
                    sid, job_id, subtask_id, curve,
                    rung=rung, attempt=attempt, diverged=diverged,
                )
            except KeyError:
                pass  # foreign/evicted job: serve from memory only
        return diverged

    def job_curves(self, job_id: str) -> Optional[Dict[str, Any]]:
        """All recorded learning curves for a job (``GET /curves/<jid>``),
        joined with the job's live status. None when the job id is
        unknown; a known job with no curves yet (CS230_CURVES=0, or no
        rung has reported) returns an empty ``curves`` list."""
        sid = next(
            (
                j["session_id"]
                for j in self.store.jobs_overview()
                if j["job_id"] == job_id
            ),
            None,
        )
        if sid is None:
            return None
        progress = self.store.job_progress(sid, job_id)
        out = self.curves.job(job_id) or {
            "job_id": job_id, "n_curves": 0, "curves": []
        }
        out["job_status"] = progress.get("job_status")
        out["tasks_diverged"] = progress.get("tasks_diverged", 0)
        return out

    def subtask_curves(self, job_id: str, subtask_id: str) -> Dict[str, Any]:
        """One trial's curve history across rungs/attempts
        (``GET /curves/<jid>/<stid>``). Raises KeyError when the pair
        never reported a curve — the route's 404."""
        out = self.curves.subtask(job_id, subtask_id)
        if out is None:
            raise KeyError(
                f"no curves recorded for subtask {subtask_id!r} of job "
                f"{job_id!r}"
            )
        return out

    # ------------- admission control (docs/ROBUSTNESS.md "Overload") -------------

    def admission_check(self, sid: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Admission decision for one would-be submit. None = admitted.
        Otherwise a rejection dict {reason, retry_after_s, status} the
        server maps to 429 (+ Retry-After) — or 503 while recovering.
        Caps (``service`` config): global / per-session in-flight job
        counts and the pending-subtask queue-depth watermark."""
        svc = self.config.service
        if not self.ready:
            return {
                "reason": "recovering",
                "retry_after_s": svc.admission_retry_after_s,
                "status": 503,
            }
        counts = self.store.unfinished_counts()
        reason = None
        if 0 < svc.max_inflight_jobs <= counts["jobs"]:
            reason = "global_inflight"
        elif (
            sid is not None
            and 0 < svc.max_inflight_jobs_per_session
            <= counts["per_session"].get(sid, 0)
        ):
            reason = "session_inflight"
        elif 0 < svc.admission_queue_watermark <= counts["pending_subtasks"]:
            reason = "queue_depth"
        if reason is None:
            return None
        counter_inc("tpuml_jobs_rejected_total", reason=reason)
        record_event(
            "admission.reject", reason=reason, session_id=sid,
            inflight_jobs=counts["jobs"],
            pending_subtasks=counts["pending_subtasks"],
        )
        logger.warning(
            "Rejecting submit for session %s: %s (%d jobs in flight, "
            "%d subtasks pending)", sid, reason, counts["jobs"],
            counts["pending_subtasks"],
        )
        return {
            "reason": reason,
            "retry_after_s": svc.admission_retry_after_s,
            "status": 429,
        }

    def overload_shedding(self) -> bool:
        """True while accepted load sits above ``shed_fraction`` of any
        enabled admission cap — the graceful-degradation band where the
        engine sheds OPTIONAL work (speculative duplicates, prewarm hints)
        before admission starts rejecting submits."""
        svc = self.config.service
        frac = svc.shed_fraction
        if frac <= 0:
            return False
        counts = self.store.unfinished_counts()
        if svc.max_inflight_jobs > 0 and (
            counts["jobs"] >= frac * svc.max_inflight_jobs
        ):
            return True
        return svc.admission_queue_watermark > 0 and (
            counts["pending_subtasks"]
            >= frac * svc.admission_queue_watermark
        )

    # ------------- session / data management (master.py:56-112 parity) -------------

    def create_session(
        self,
        session_id: Optional[str] = None,
        priority: int = 0,
    ) -> str:
        """``session_id`` lets a sharded front end mint the id (so
        ``shard_of(session_id)`` and the owning shard agree by
        construction); ``priority`` is the session's QoS lane — its jobs'
        subtasks dispatch ahead of lower lanes (docs/ARCHITECTURE.md
        "QoS priority lanes")."""
        if session_id is None and self.shard_id is not None:
            # a shard minting its own session id must mint one that
            # HASHES here — otherwise every front end would route the
            # session elsewhere and it would be unreachable through the
            # fleet. Rejection-sample (expected n_shards draws).
            from .sharding import shard_of

            while True:
                session_id = str(uuid.uuid4())
                if shard_of(session_id, self.n_shards) == self.shard_id:
                    break
        return self.store.create_session(session_id, priority=priority)

    def canonical_job_id(self, job_id: str) -> str:
        """The id a job is stored and routed under: on a shard, client-
        minted ids gain this shard's ``s<k>-`` stamp (deterministic, so
        idempotent-resubmit dedupe survives sharding); already-stamped
        and unsharded ids pass through."""
        if self.shard_id is None or not job_id:
            return job_id
        # a job adopted from a donor shard keeps the DONOR's stamp —
        # re-wrapping (stamp_job_id wraps foreign-looking stamps by
        # design) would mint an id this shard never stored and every
        # status poll on the migrated job would 404
        if self.store.is_adopted_job(job_id):
            return job_id
        from .sharding import stamp_job_id

        return stamp_job_id(self.shard_id, job_id)

    def check_session(self, sid: str) -> bool:
        return self.store.has_session(sid)

    def download_data(self, sid: str, dataset_url: str, dataset_name: str, dataset_type: str) -> Dict[str, Any]:
        self._require_session(sid)
        path = download_dataset(
            dataset_url, dataset_name, dataset_type, root=self.config.storage.datasets_dir
        )
        self.cache.invalidate(dataset_name)
        return {"status": "success", "dataset_path": path}

    def check_data(self, sid: str, dataset_name: str) -> Dict[str, Any]:
        self._require_session(sid)
        from ..data.datasets import find_csv

        path = find_csv(dataset_name, root=self.config.storage.datasets_dir)
        return {"exists": path is not None, "path": path}

    def preprocess(self, sid: str, dataset_id: str, config: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Run the YAML preprocessing pipeline on a staged dataset. Accepts
        an inline config dict or reads <configs_dir>/<dataset_id>/*.yaml like
        the reference (master.py:352-379)."""
        self._require_session(sid)
        import glob
        import os

        import pandas as pd

        from ..data.datasets import dataset_dir, find_csv

        csv = find_csv(dataset_id, root=self.config.storage.datasets_dir)
        if csv is None:
            raise FileNotFoundError(f"Dataset {dataset_id!r} not staged")
        if config is None:
            import yaml

            hits = sorted(
                glob.glob(os.path.join(self.config.storage.configs_dir, dataset_id, "*.yaml"))
            )
            if not hits:
                raise FileNotFoundError(f"No preprocess config for {dataset_id!r}")
            config = yaml.safe_load(open(hits[0]).read())
        df = preprocess_dataframe(pd.read_csv(csv), config)
        out_dir = os.path.join(dataset_dir(dataset_id, self.config.storage.datasets_dir), "preprocessed")
        os.makedirs(out_dir, exist_ok=True)
        out_path = os.path.join(out_dir, f"{dataset_id}_preprocessed.csv")
        df.to_csv(out_path, index=False)
        self.cache.invalidate(dataset_id)
        return {"status": "success", "preprocessed_path": out_path, "n_rows": len(df)}

    # ------------- training (master.py:170-268 parity) -------------

    def submit_train(self, sid: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Expand a train job into subtasks, persist, and dispatch async.
        Payload schema matches the reference client (core.py:152-174):
        {job_id?, dataset_id, model_details, train_params}."""
        self._require_session(sid)
        job_id = self.canonical_job_id(
            payload.get("job_id") or str(uuid.uuid4())
        )
        if payload.get("job_id"):
            # idempotent resubmit: the client minted this job_id and is
            # retrying a submit whose response it never saw (coordinator
            # restart, dropped SSE stream, 429 backoff loop). Re-expanding
            # would duplicate every subtask — return the original
            # acceptance instead (docs/ROBUSTNESS.md "Reconnecting edges").
            # The check and the in-progress claim happen under one lock:
            # a duplicate arriving DURING the first copy's expansion (the
            # store doesn't know the job yet) must dedupe too, not race
            # has_job-then-create.
            with self._submit_lock:
                known = self.store.has_job(sid, job_id)
                if known or job_id in self._submitting:
                    logger.info("Duplicate submit of job %s deduped", job_id)
                    return {
                        "status": "submitted",
                        "job_id": job_id,
                        # unknown while the first copy is still expanding
                        "total_subtasks": (
                            self.store.job_progress(sid, job_id)[
                                "total_subtasks"
                            ] if known else None
                        ),
                        "duplicate": True,
                    }
                self._submitting.add(job_id)
            try:
                return self._submit_train_locked(sid, job_id, payload)
            finally:
                with self._submit_lock:
                    self._submitting.discard(job_id)
        return self._submit_train_locked(sid, job_id, payload)

    def _submit_train_locked(
        self, sid: str, job_id: str, payload: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Expansion + persistence + dispatch for an admitted, deduped
        submit (``_submitting`` guard held by the caller for client-minted
        job ids)."""
        dataset_id = payload["dataset_id"]
        model_details = payload["model_details"]
        train_params = dict(payload.get("train_params") or {})
        cv_params = model_details.get("cv_params") or {}
        if "cv" in cv_params and "cv" not in train_params:
            train_params["cv"] = cv_params["cv"]
        scoring = train_params.get("scoring", cv_params.get("scoring"))
        if (
            callable(scoring) and not isinstance(scoring, str)
            and self.cluster is not None
        ):
            # a cluster's remote agents pull tasks over REST, where
            # json_safe would stringify the function into a confusing
            # "unsupported scoring '<function ...>'" server error per
            # trial; fail the submission with the real reason instead
            # (the default in-process executor honors callables)
            raise ValueError(
                "callable scoring is not supported on a clustered "
                "coordinator (tasks are serialized to worker agents); "
                "use a scorer name, or a coordinator without a cluster"
            )

        # one trace id per job, minted here unless the client already sent
        # one (X-Trace-Id via the REST server, or an activate() in local
        # mode); stamped into every subtask spec so it rides the task bus /
        # /next_tasks long-poll to remote agents (docs/OBSERVABILITY.md)
        trace_id = current_trace_id() or new_trace_id()
        TRACER.bind_job(job_id, trace_id)
        with span("job.submit", trace_id=trace_id, job_id=job_id,
                  dataset_id=dataset_id,
                  model_type=model_details.get("model_type")) as sub_sp:
            with span("job.expand", job_id=job_id):
                subtasks = create_subtasks(
                    job_id, sid, dataset_id, model_details, train_params
                )
            # QoS lane: the payload may override, else the session's
            # priority rides every subtask spec — the dispatch queues
            # (task ingress + per-worker train queues) order on it, and
            # retries/requeues/speculation copy the spec so the lane
            # survives the whole fault-tolerance machinery
            priority = payload.get("priority")
            if priority is None:
                priority = self.store.session_priority(sid)
            for st in subtasks:
                st["trace_id"] = trace_id
                st["priority"] = int(priority or 0)
            sub_sp.attrs["total_subtasks"] = len(subtasks)
            try:
                metadata = self.cache.metadata(dataset_id)
            except FileNotFoundError:
                metadata = {}
            self.store.create_job(sid, job_id, payload, subtasks, metadata)
        counter_inc("tpuml_jobs_submitted_total")

        t = threading.Thread(
            target=self._run_job, args=(sid, job_id, subtasks), daemon=True
        )
        self._job_threads[job_id] = t
        t.start()
        return {
            "status": "submitted",
            "job_id": job_id,
            "total_subtasks": len(subtasks),
        }

    def _run_job(
        self,
        sid: str,
        job_id: str,
        subtasks: List[Dict[str, Any]],
        existing: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> None:
        """Execute a job's subtasks and aggregate. ``existing`` (resume path)
        maps already-finished subtask ids to their journaled results; only
        the remainder is dispatched."""

        def on_result(subtask_id: str, status: str, result: Optional[Dict[str, Any]]):
            self.store.update_subtask(sid, job_id, subtask_id, status, result)
            r = result or {}
            if isinstance(r.get("curve"), dict):
                # terminal curve ingest (deduped against the metrics-path
                # delivery): verdict only — termination decisions belong
                # to the search loops, and this result is terminal anyway
                self.ingest_curve(
                    sid, job_id, subtask_id, r["curve"],
                    rung=int((r.get("asha") or {}).get("rung") or 0),
                    attempt=int(r.get("attempt") or 0),
                )
            record_event(
                "result", job_id=job_id, subtask_id=subtask_id,
                worker_id=r.get("worker_id"),
                attempt=int(r.get("attempt") or 0), status=status,
                mean_cv_score=r.get("mean_cv_score"),
                error=r.get("error"),
            )
            self.bus.publish(TOPIC_RESULTS, result, key=subtask_id)

        def on_metrics(msg: Dict[str, Any]):
            if isinstance(msg.get("curve"), dict):
                # live curve ingest: the trace reaches /curves and the SSE
                # stream at the batch boundary, before the result settles
                self.ingest_curve(
                    sid, job_id, msg.get("subtask_id"), msg["curve"],
                    rung=int(msg.get("rung") or 0),
                    attempt=int(msg.get("attempt") or 0),
                )
            self.bus.publish(TOPIC_METRICS, msg, key=msg.get("subtask_id"))

        def on_intermediate(subtask_id: str, result: Optional[Dict[str, Any]]):
            # non-terminal rung boundary (promoted/paused): journal the
            # report + record the event, but do NOT publish to the result
            # topic — in cluster mode that topic is this coordinator's own
            # ingest channel, and republishing would echo the report back
            # into the rung loop forever
            self.store.update_subtask(
                sid, job_id, subtask_id, "promoted", result
            )
            r = result or {}
            if isinstance(r.get("curve"), dict):
                # rung-boundary curve of a promoted trial — journaled here
                # so a replayed coordinator has each rung's trace
                self.ingest_curve(
                    sid, job_id, subtask_id, r["curve"],
                    rung=int((r.get("asha") or {}).get("rung") or 0),
                    attempt=int(r.get("attempt") or 0),
                )
            record_event(
                "result", job_id=job_id, subtask_id=subtask_id,
                worker_id=r.get("worker_id"),
                attempt=int(r.get("attempt") or 0), status="promoted",
                mean_cv_score=r.get("mean_cv_score"),
                rung=(r.get("asha") or {}).get("rung"),
            )

        existing = existing or {}
        remaining = [st for st in subtasks if st["subtask_id"] not in existing]
        # adaptive-search job (docs/SEARCH.md): specs carry an ``asha``
        # rung block — route through the rung controller instead of the
        # run-everything-to-completion paths below
        driver: Optional[SearchJobDriver] = None
        if any(st.get("asha") for st in subtasks):
            driver = SearchJobDriver(subtasks)
            # rebuild rung state from the journaled rung history — always,
            # not just when a terminal result exists: a coordinator killed
            # after rung-0 reports but before the first prune/complete has
            # promotions to re-derive too (a fresh job's empty history is
            # a no-op). Determinism means nothing is promoted twice.
            driver.resume(self.store.get_job(sid, job_id))
        # job threads start with an empty contextvar context: re-activate the
        # trace the subtask specs carry (journaled specs keep it across a
        # coordinator restart, so resumed jobs stitch into the same trace)
        trace_id = next(
            (st.get("trace_id") for st in subtasks if st.get("trace_id")), None
        ) or TRACER.trace_for_job(job_id) or new_trace_id()
        TRACER.bind_job(job_id, trace_id)
        try:
            with activate(trace_id):
                with span("job.execute", trace_id=trace_id, job_id=job_id,
                          n_subtasks=len(remaining),
                          n_resumed=len(existing),
                          search="asha" if driver is not None else None,
                          mode="scheduled" if self.cluster is not None
                          else "direct"):
                    if driver is not None:
                        by_id = dict(existing)
                        if self.cluster is not None:
                            by_id.update(self._run_job_search_scheduled(
                                sid, job_id, driver, on_result,
                                on_intermediate,
                            ))
                        else:
                            by_id.update(self._run_job_search_direct(
                                sid, job_id, driver, on_result,
                                on_intermediate, on_metrics,
                            ))
                        new_results = []
                    elif not remaining:
                        new_results = []
                    elif self.cluster is not None:
                        new_results = self._run_job_scheduled(
                            sid, job_id, remaining, on_result
                        )
                    else:
                        new_results = self.executor.run_subtasks(
                            remaining, on_result=on_result, on_metrics=on_metrics
                        )
                if driver is None:
                    by_id = dict(existing)
                    for st, r in zip(remaining, new_results):
                        by_id[st["subtask_id"]] = r
                results = [by_id.get(st["subtask_id"]) for st in subtasks]
                with span("job.aggregate", trace_id=trace_id, job_id=job_id):
                    self._aggregate(
                        sid, job_id, subtasks, results,
                        search_summary=(
                            driver.summary() if driver is not None else None
                        ),
                    )
            counter_inc("tpuml_jobs_completed_total")
        except JobMigratedError:
            # not a failure: the job left this shard mid-flight. The
            # migration driver (migrate_job) owns the rest of the
            # handoff; finalization happens on the destination shard.
            logger.info("Job %s quiesced for migration", job_id)
        except Exception as e:  # noqa: BLE001
            logger.exception("Job %s failed", job_id)
            counter_inc("tpuml_jobs_failed_total")
            self.store.finalize_job(
                sid, job_id, {"status": "failed", "error": str(e)}
            )

    def _run_job_scheduled(self, sid, job_id, subtasks, on_result) -> List[Dict[str, Any]]:
        """Dispatch through the placement engine and collect results from
        the bus — the reference's consume_results thread
        (task_handler.py:18-68) — upgraded with the fault-tolerance layer
        (docs/ROBUSTNESS.md):

        - **at-least-once + dedup by attempt id**: the first terminal
          COMPLETED result for a subtask wins; later duplicates (requeue
          races, speculative losers) are dropped. A FAILED result only
          counts against the retry budget when it belongs to the CURRENT
          attempt — failures of superseded attempts are stale.
        - **bounded retries with backoff**: a failed attempt is re-
          dispatched up to ``retry_max_attempts`` total executions, with
          exponential per-attempt backoff and the failing worker excluded.
        - **poison quarantine**: a subtask that exhausts its budget — or
          killed ``poison_kill_threshold`` worker backends — is accepted
          as a quarantined failure; the job completes with partial results
          instead of stalling.
        """
        import queue as _q

        cfg = self.config.scheduler
        ledger = self.cluster.ledger
        wanted = {st["subtask_id"]: i for i, st in enumerate(subtasks)}
        spec_by_id = {st["subtask_id"]: st for st in subtasks}
        results: List[Optional[Dict[str, Any]]] = [None] * len(subtasks)
        #: failure retries awaiting their backoff: (due_ts, stamped task)
        retry_due: List[tuple] = []
        sub = self.bus.subscribe("result", key_filter=lambda k: k in wanted)
        try:
            job = self.store.get_job(sid, job_id)
            metadata = job.get("metadata") or None
            for st in subtasks:
                ledger.seed(st)
            self.cluster.submit(subtasks, metadata=metadata)
            pending = set(wanted)
            # Progress-aware liveness, not a wall-clock deadline: a long job
            # whose executors are still productively computing must not be
            # failed server-side. The job times out only when BOTH hold for
            # client_timeout_s: no result arrived, AND no live worker owns
            # any of its pending tasks (a placed task stays in its worker's
            # queue until the metrics feedback clears it).
            stall_grace = self.config.service.client_timeout_s
            # ownership proves placement, not computation: a wedged worker
            # whose heartbeat thread survives would hold its queue entry
            # forever. The lease layer normally reclaims those; a generous
            # hard bound restores eventual liveness even with leases off.
            hard_deadline = time.time() + 20.0 * stall_grace
            last_progress = time.time()
            while pending:
                # quiesce gate: the rebalancer marked this job for
                # migration — unwind without finalizing; the migration
                # driver fences the remaining attempts and the
                # destination shard finishes the job
                if self._migrating.get((sid, job_id)) is not None:
                    raise JobMigratedError(job_id)
                now = time.time()
                if now > hard_deadline:
                    raise TimeoutError(
                        f"{len(pending)} subtasks unfinished at the hard "
                        f"deadline ({20.0 * stall_grace:.0f}s)"
                    )
                if retry_due:
                    due = [t for ts, t in retry_due if ts <= now]
                    if due:
                        retry_due = [
                            (ts, t) for ts, t in retry_due if ts > now
                        ]
                        self.cluster.submit(due, metadata=metadata)
                try:
                    stid, result = sub.get(timeout=0.5)
                except _q.Empty:
                    if time.time() - last_progress > stall_grace:
                        owned: set = {
                            t["subtask_id"] for _, t in retry_due
                        }  # backoff-parked retries count as owned
                        for q in self.cluster.engine.queue_snapshot().values():
                            owned.update(q)
                        # subtasks granted to a thief shard are owned
                        # remotely: the steal lease (not this stall
                        # check) reclaims them if the thief goes dark
                        owned.update(self.store.steal_tombstones)
                        if not (pending & owned):
                            raise TimeoutError(
                                f"{len(pending)} subtasks stalled with no live "
                                f"owner for {stall_grace:.0f}s "
                                f"(e.g. {sorted(pending)[:3]})"
                            )
                        last_progress = time.time()  # workers still own tasks
                    continue
                result = result or {}
                # any result settles an outstanding steal grant for this
                # subtask (terminal → done; failed → back in the local
                # retry path below)
                self.store.clear_steal(stid)
                if stid not in pending:
                    # duplicate delivery: a requeue race, the losing copy
                    # of a speculative pair, or a zombie attempt from
                    # before a coordinator restart — dropped here, which
                    # IS the cancellation ("first terminal result wins")
                    counter_inc("tpuml_results_duplicate_dropped_total")
                    record_event(
                        "result.duplicate", job_id=job_id, subtask_id=stid,
                        worker_id=result.get("worker_id"),
                        attempt=int(result.get("attempt") or 0),
                    )
                    if ledger.was_speculated(stid):
                        counter_inc("tpuml_speculative_wasted_total")
                        record_event(
                            "speculate.loss", job_id=job_id,
                            subtask_id=stid,
                            worker_id=result.get("worker_id"),
                            attempt=int(result.get("attempt") or 0),
                        )
                    continue
                if result.get("status", "completed") != "failed":
                    pending.discard(stid)
                    ledger.mark_done(stid)
                    results[wanted[stid]] = result
                    if result.get("speculative"):
                        counter_inc("tpuml_speculative_won_total")
                        record_event(
                            "speculate.win", job_id=job_id, subtask_id=stid,
                            worker_id=result.get("worker_id"),
                            attempt=int(result.get("attempt") or 0),
                        )
                    on_result(stid, "completed", result)
                    last_progress = time.time()
                    continue
                # ---- failed result: retry budget / quarantine ----
                attempt = int(result.get("attempt") or 0)
                if ledger.is_stale(stid, attempt):
                    # a newer attempt (lease reclaim / speculation) owns
                    # this subtask now; the old attempt's failure must not
                    # consume budget
                    record_event(
                        "result.stale", job_id=job_id, subtask_id=stid,
                        worker_id=result.get("worker_id"), attempt=attempt,
                        error=result.get("error"),
                    )
                    continue
                wid = result.get("worker_id")
                entry = ledger.record_failure(stid, wid)
                poisoned = entry.device_losses >= cfg.poison_kill_threshold
                if poisoned or entry.failures >= cfg.retry_max_attempts:
                    quarantined = {
                        **result,
                        "quarantined": True,
                        "attempts": entry.failures,
                        "quarantine_reason": (
                            "poisoned" if poisoned else "retries_exhausted"
                        ),
                    }
                    counter_inc("tpuml_subtasks_quarantined_total")
                    logger.error(
                        "Quarantining %s after %d failed attempts (%s): %s",
                        stid, entry.failures,
                        quarantined["quarantine_reason"],
                        result.get("error"),
                    )
                    with span("job.quarantine", job_id=job_id,
                              subtask_id=stid, attempts=entry.failures,
                              reason=quarantined["quarantine_reason"]):
                        pass
                    record_event(
                        "quarantine", job_id=job_id, subtask_id=stid,
                        worker_id=wid, attempt=attempt,
                        reason=quarantined["quarantine_reason"],
                        attempts=entry.failures,
                        device_losses=entry.device_losses,
                        error=result.get("error"),
                    )
                    pending.discard(stid)
                    ledger.mark_done(stid)
                    results[wanted[stid]] = quarantined
                    on_result(stid, "failed", quarantined)
                else:
                    task = dict(spec_by_id[stid])
                    task.pop("speculative", None)
                    ledger.next_attempt(
                        task, exclude_worker=wid, reason="failure"
                    )
                    backoff = min(
                        cfg.retry_backoff_s * 2 ** max(entry.failures - 1, 0),
                        cfg.retry_backoff_max_s,
                    )
                    counter_inc(
                        "tpuml_subtasks_retried_total", reason="failure"
                    )
                    logger.warning(
                        "Retrying %s (attempt %d/%d) in %.2fs, excluding "
                        "worker %s",
                        stid, task["attempt"], cfg.retry_max_attempts,
                        backoff, wid,
                    )
                    with span("job.retry", job_id=job_id, subtask_id=stid,
                              attempt=task["attempt"], backoff_s=backoff,
                              excluded_worker=wid):
                        pass
                    record_event(
                        "retry", job_id=job_id, subtask_id=stid,
                        worker_id=wid, attempt=task["attempt"],
                        reason="failure", backoff_s=backoff,
                        failures=entry.failures,
                        max_attempts=cfg.retry_max_attempts,
                        error=result.get("error"),
                    )
                    retry_due.append((time.time() + backoff, task))
                last_progress = time.time()
            return results  # type: ignore[return-value]
        finally:
            sub.close()
            self.cluster.ledger.forget(wanted)

    # ------------- adaptive search (docs/SEARCH.md) -------------

    def _apply_search_step(
        self, step: Step, sid, job_id, pending, results_by_id, on_result,
        on_intermediate, metadata,
    ) -> None:
        """Apply one rung-controller step to the scheduled job loop:
        journal intermediate (promoted) results FIRST, then issue cancels,
        finalize terminals, and submit the fresh rung dispatches LAST — so
        a crash between any two phases replays into a state the resume
        path handles (an unjournaled dispatch is re-issued; a journaled
        report re-derives its promotion)."""
        ledger = self.cluster.ledger
        for tid, res in step.promoted:
            if res is not None:
                on_intermediate(tid, res)
        new_tasks = []
        for task in step.new_tasks:
            task.pop("speculative", None)
            ledger.next_attempt(task, reason="promotion")
            new_tasks.append(task)
        for c in step.cancels:
            self.cluster.cancel_subtask(
                c["subtask_id"], c.get("attempt", 0), job_id=job_id
            )
        for tid, status, res in step.finished:
            pending.discard(tid)
            ledger.mark_done(tid)
            results_by_id[tid] = res
            on_result(tid, status, res)
            # deliberately NOT clearing the cancel registry here: a prune's
            # synthesized terminal lands in the SAME step as its cancel, and
            # clearing now would empty the registry before any remote
            # agent's next poll ever saw the entry. The registry clears when
            # the WORKER's own terminal result arrives (push_result) or at
            # job end (the loop's finally).
        if new_tasks:
            self.cluster.submit(new_tasks, metadata=metadata)

    def _run_job_search_scheduled(
        self, sid, job_id, driver: SearchJobDriver, on_result,
        on_intermediate,
    ) -> Dict[str, Dict[str, Any]]:
        """Scheduled-mode rung loop: like ``_run_job_scheduled`` (same
        at-least-once ingest, attempt dedup, bounded retries, poison
        quarantine) but result ingest feeds the rung controller — a
        completed rung dispatch may promote its trial (fresh attempt at
        the eta-times budget), pause it, or prune peers; quarantined
        trials leave the ladder so their rungs close for the survivors."""
        import queue as _q

        cfg = self.config.scheduler
        ledger = self.cluster.ledger
        all_ids = set(driver.specs)
        results_by_id: Dict[str, Dict[str, Any]] = {}
        pending = {tid for tid in all_ids if tid not in driver._finalized}
        retry_due: List[tuple] = []
        sub = self.bus.subscribe("result", key_filter=lambda k: k in all_ids)
        try:
            job = self.store.get_job(sid, job_id)
            metadata = job.get("metadata") or None
            # resume: terminal states the replayed controller derived
            # whose store writes the crash swallowed
            self._apply_search_step(
                driver.resume_step(), sid, job_id, pending, results_by_id,
                on_result, on_intermediate, metadata,
            )
            tasks = driver.pending_tasks()
            for st in tasks:
                ledger.seed(st)
            if tasks:
                self.cluster.submit(tasks, metadata=metadata)
            self.store.set_search_state(sid, job_id, driver.summary())
            stall_grace = self.config.service.client_timeout_s
            hard_deadline = time.time() + 20.0 * stall_grace
            last_progress = time.time()
            while pending:
                now = time.time()
                if now > hard_deadline:
                    raise TimeoutError(
                        f"{len(pending)} trials unfinished at the hard "
                        f"deadline ({20.0 * stall_grace:.0f}s)"
                    )
                if retry_due:
                    due = [t for ts, t in retry_due if ts <= now]
                    if due:
                        retry_due = [
                            (ts, t) for ts, t in retry_due if ts > now
                        ]
                        self.cluster.submit(due, metadata=metadata)
                try:
                    stid, result = sub.get(timeout=0.5)
                except _q.Empty:
                    if time.time() - last_progress > stall_grace:
                        owned: set = {
                            t["subtask_id"] for _, t in retry_due
                        }
                        for q in self.cluster.engine.queue_snapshot().values():
                            owned.update(q)
                        if not (pending & owned):
                            raise TimeoutError(
                                f"{len(pending)} trials stalled with no "
                                f"live owner for {stall_grace:.0f}s "
                                f"(e.g. {sorted(pending)[:3]})"
                            )
                        last_progress = time.time()
                    continue
                result = result or {}
                if stid not in pending:
                    counter_inc("tpuml_results_duplicate_dropped_total")
                    record_event(
                        "result.duplicate", job_id=job_id, subtask_id=stid,
                        worker_id=result.get("worker_id"),
                        attempt=int(result.get("attempt") or 0),
                    )
                    continue
                status = result.get("status", "completed")
                if status != "failed":
                    # a rung report (completed) or a cooperative-cancel
                    # terminal (pruned) — both feed the controller; the
                    # driver dedups duplicate/stale deliveries itself
                    curve = result.get("curve")
                    if status == "pruned":
                        step = driver.handle_pruned_result(stid, result)
                    elif isinstance(curve, dict) and self.ingest_curve(
                        sid, job_id, stid, curve,
                        rung=int((result.get("asha") or {}).get("rung") or 0),
                        attempt=int(result.get("attempt") or 0),
                    ):
                        # numerical-health watchdog: the rung's trace is
                        # non-finite or blowing up — terminate the trial
                        # as ``diverged`` (never a failure: no retry
                        # budget burns, no quarantine) instead of letting
                        # the ladder promote it
                        step = driver.handle_diverged(
                            stid, curve, result=result
                        )
                    else:
                        step = driver.handle_result(stid, result)
                    self._apply_search_step(
                        step, sid, job_id, pending, results_by_id,
                        on_result, on_intermediate, metadata,
                    )
                    self.store.set_search_state(
                        sid, job_id, driver.summary()
                    )
                    last_progress = time.time()
                    continue
                # ---- failed rung execution: retry budget / quarantine ----
                attempt = int(result.get("attempt") or 0)
                if ledger.is_stale(stid, attempt):
                    record_event(
                        "result.stale", job_id=job_id, subtask_id=stid,
                        worker_id=result.get("worker_id"), attempt=attempt,
                        error=result.get("error"),
                    )
                    continue
                wid = result.get("worker_id")
                entry = ledger.record_failure(stid, wid)
                poisoned = entry.device_losses >= cfg.poison_kill_threshold
                if poisoned or entry.failures >= cfg.retry_max_attempts:
                    quarantined = {
                        **result,
                        "quarantined": True,
                        "attempts": entry.failures,
                        "quarantine_reason": (
                            "poisoned" if poisoned else "retries_exhausted"
                        ),
                    }
                    counter_inc("tpuml_subtasks_quarantined_total")
                    logger.error(
                        "Quarantining trial %s after %d failed attempts "
                        "(%s): %s", stid, entry.failures,
                        quarantined["quarantine_reason"],
                        result.get("error"),
                    )
                    record_event(
                        "quarantine", job_id=job_id, subtask_id=stid,
                        worker_id=wid, attempt=attempt,
                        reason=quarantined["quarantine_reason"],
                        attempts=entry.failures,
                        device_losses=entry.device_losses,
                        error=result.get("error"),
                    )
                    step = driver.handle_quarantine(stid, quarantined)
                    self._apply_search_step(
                        step, sid, job_id, pending, results_by_id,
                        on_result, on_intermediate, metadata,
                    )
                    self.store.set_search_state(
                        sid, job_id, driver.summary()
                    )
                else:
                    task = dict(driver.specs[stid])
                    task.pop("speculative", None)
                    ledger.next_attempt(
                        task, exclude_worker=wid, reason="failure"
                    )
                    # keep the driver's spec in sync with the live attempt
                    # (the promotion path already does — _stamp stores the
                    # dict next_attempt mutates): a later prune's
                    # cooperative cancel must carry THIS attempt, or the
                    # executor's attempt guard lets the retry run its
                    # full doomed budget
                    driver.specs[stid] = task
                    backoff = min(
                        cfg.retry_backoff_s * 2 ** max(entry.failures - 1, 0),
                        cfg.retry_backoff_max_s,
                    )
                    counter_inc(
                        "tpuml_subtasks_retried_total", reason="failure"
                    )
                    logger.warning(
                        "Retrying rung dispatch %s (attempt %d/%d) in "
                        "%.2fs, excluding worker %s",
                        stid, task["attempt"], cfg.retry_max_attempts,
                        backoff, wid,
                    )
                    record_event(
                        "retry", job_id=job_id, subtask_id=stid,
                        worker_id=wid, attempt=task["attempt"],
                        reason="failure", backoff_s=backoff,
                        failures=entry.failures,
                        max_attempts=cfg.retry_max_attempts,
                        error=result.get("error"),
                    )
                    retry_due.append((time.time() + backoff, task))
                last_progress = time.time()
            return results_by_id
        finally:
            sub.close()
            self.cluster.ledger.forget(all_ids)
            self.cluster.clear_cancels(all_ids)

    def _apply_search_step_direct(
        self, step: Step, results_by_id, on_result, on_intermediate, job_id
    ) -> List[Dict[str, Any]]:
        """Direct-mode step application; returns the fresh rung dispatches
        for the next wave."""
        for tid, res in step.promoted:
            if res is not None:
                on_intermediate(tid, res)
        new_tasks = []
        for task in step.new_tasks:
            # no ledger in direct mode: bump the attempt stamp in place so
            # rung dispatches stay distinguishable in results/journals
            task["attempt"] = int(task.get("attempt") or 0) + 1
            task.pop("speculative", None)
            new_tasks.append(task)
        if step.cancels:
            self.executor.cancel(step.cancels)
        for tid, status, res in step.finished:
            results_by_id[tid] = res
            on_result(tid, status, res)
        return new_tasks

    def _run_job_search_direct(
        self, sid, job_id, driver: SearchJobDriver, on_result,
        on_intermediate, on_metrics,
    ) -> Dict[str, Dict[str, Any]]:
        """Direct-mode rung loop: synchronous waves on the in-process
        executor. The executor's per-batch metrics messages carry the
        rung-boundary score; ``on_metrics`` feeds the controller DURING
        the wave (the stop_score fast path), so cancels reach the
        executor before its next batch boundary. Failures keep the legacy
        direct-mode semantics (terminal, no retries) and simply drop the
        trial off its ladder."""
        results_by_id: Dict[str, Dict[str, Any]] = {}
        # resume synthesis first (a resume_step never carries new tasks —
        # dispatches come from pending_tasks below)
        self._apply_search_step_direct(
            driver.resume_step(), results_by_id, on_result, on_intermediate,
            job_id,
        )
        tasks = driver.pending_tasks()
        while tasks:
            steps: List[Step] = []

            def _metrics(msg):
                curve = msg.get("curve")
                stid_m = msg.get("subtask_id")
                if isinstance(curve, dict) and stid_m:
                    # numerical-health watchdog, metrics path: the trace
                    # arrives at the batch boundary while sibling groups
                    # of the wave may still be running — a diverged trial
                    # is terminated NOW (cooperative cancel reaches the
                    # executor before its next batch boundary) instead of
                    # burning the rest of its rung budget
                    if self.ingest_curve(
                        sid, job_id, stid_m, curve,
                        rung=int(msg.get("rung") or 0),
                        attempt=int(msg.get("attempt") or 0),
                    ):
                        dstep = driver.handle_diverged(
                            stid_m, curve, result=None
                        )
                        if dstep.cancels:
                            self.executor.cancel(dstep.cancels)
                        if dstep.finished or dstep.new_tasks or dstep.promoted:
                            steps.append(dstep)
                step = driver.handle_metrics(msg)
                if step.cancels:
                    # reach the executor before its next batch boundary
                    self.executor.cancel(step.cancels)
                if step.finished or step.new_tasks or step.promoted:
                    steps.append(step)
                on_metrics(msg)

            wave = self.executor.run_subtasks(tasks, on_metrics=_metrics)
            for st, r in zip(tasks, wave):
                stid = st["subtask_id"]
                r = r or {}
                status = r.get("status", "completed")
                if status == "failed":
                    steps.append(driver.handle_quarantine(stid, r))
                elif status == "pruned":
                    steps.append(driver.handle_pruned_result(stid, r))
                else:
                    steps.append(driver.handle_result(stid, r))
            tasks = []
            for step in steps:
                tasks.extend(
                    self._apply_search_step_direct(
                        step, results_by_id, on_result, on_intermediate,
                        job_id,
                    )
                )
            self.store.set_search_state(sid, job_id, driver.summary())
        if not driver.done():
            logger.warning(
                "Search job %s: wave loop drained with %d trials "
                "undecided", job_id,
                sum(1 for t in driver.specs
                    if t not in driver.controller.decided),
            )
        return results_by_id

    def _aggregate(self, sid, job_id, subtasks, results,
                   search_summary: Optional[Dict[str, Any]] = None) -> None:
        """Sort completed trials by mean_cv_score desc; best_result first
        (task_handler.py:254-263). The winner is refit once and stored as a
        downloadable artifact."""
        completed = [r for r in results if r and r.get("status") == "completed"]
        failed = [r for r in results if r and r.get("status") == "failed"]
        pruned = [r for r in results if r and r.get("status") == "pruned"]
        diverged = [r for r in results if r and r.get("status") == "diverged"]

        def score_key(r):
            # None survives JSON round-trips from remote agents (inf/NaN are
            # nulled by json_safe); rank those trials last
            v = r.get("mean_cv_score")
            return v if isinstance(v, (int, float)) else float("-inf")

        ranked = sorted(completed, key=score_key, reverse=True)
        best = dict(ranked[0]) if ranked else None
        # Winner selection by the ON-DEVICE collective argmax: on a
        # multi-device mesh the trial engine reduces each sharded score
        # chunk over ICI (trial_map._chunk_best) and marks the per-group
        # winner (device_argmax). The host only max-combines those few
        # marked results. On a single chip the scores are host scalars
        # already and the host sort IS the production path (a device round
        # trip to reduce a handful of floats buys nothing).
        marked = [r for r in completed if r.get("device_argmax")]
        if best is not None and marked:
            # max() keeps the first of equals and `completed` is in
            # submission order, so ties resolve like sklearn's first-max
            dev_best = max(marked, key=score_key)
            if dev_best["subtask_id"] == best["subtask_id"]:
                best["winner_via"] = "ici_argmax"
            else:  # near-tie under f32-vs-f64 rounding, or the true winner
                # ran in an unsharded group: keep the host-ranked winner
                logger.info(
                    "device argmax winner %s (%.6f) differs from host-ranked "
                    "%s (%.6f); keeping host winner",
                    dev_best["subtask_id"], score_key(dev_best),
                    best["subtask_id"], score_key(best),
                )
        if best is not None:
            # artifact refit is lazy: materialized on the first
            # download_best_model call (the reference eagerly pickled every
            # trial's model, worker.py:352-356 — pure overhead for searches)
            st = next(s for s in subtasks if s["subtask_id"] == best["subtask_id"])
            if best.get("asha") and best.get("parameters"):
                # adaptive search: refit at the winner's FINAL rung budget
                # (the subtask list still holds the rung-0 spec)
                st = {**st, "parameters": best["parameters"]}
            with self._artifact_lock:
                self._artifact_specs[(sid, job_id)] = st
        final = {
            "results": ranked,
            "failed": failed,
            "best_result": best,
            "completion_time": time.time(),
        }
        if pruned or search_summary is not None:
            # adaptive search (docs/SEARCH.md): early-stopped trials are a
            # separate, NON-failure report — ranked by their last rung
            # score — plus the final rung-state summary
            final["pruned_results"] = sorted(
                pruned, key=score_key, reverse=True
            )
            final["n_pruned"] = len(pruned)
            if search_summary is not None:
                final["search"] = search_summary
        if diverged:
            # watchdog terminations (docs/OBSERVABILITY.md "Trial
            # telemetry plane"): numerically-unhealthy trials are their
            # own NON-failure report — like pruned, they never count
            # against retry budgets or quarantine
            final["diverged_results"] = diverged
            final["n_diverged"] = len(diverged)
        # quarantine contract (docs/ROBUSTNESS.md): subtasks the retry
        # layer gave up on surface as a structured report, and the job
        # finalizes as ``completed_with_failures`` (partial results)
        # instead of plain ``completed``. Direct-mode failures (no retry
        # machinery ran, no ``quarantined`` stamp) keep the legacy
        # ``completed`` + failed-list semantics.
        quarantined = [r for r in failed if r.get("quarantined")]
        if quarantined:
            final["failed_subtasks"] = [
                {
                    "subtask_id": r.get("subtask_id"),
                    "attempts": r.get("attempts"),
                    "reason": r.get("quarantine_reason"),
                    "error": r.get("error"),
                }
                for r in quarantined
            ]
            logger.warning(
                "Job %s completed with %d quarantined subtasks "
                "(partial results)", job_id, len(quarantined),
            )
        self.store.finalize_job(sid, job_id, json_safe(final))

    # ------------- status / metrics / model (master.py:115-340 parity) -------------

    def check_status(self, sid: str, job_id: str) -> Dict[str, Any]:
        self._require_session(sid)
        progress = self.store.job_progress(sid, job_id)
        status = progress["job_status"]
        if (
            status in ("completed", "completed_with_failures")
            and progress["job_result"]
        ):
            result = progress["job_result"]
            out = {"job_status": status, "job_result": result}
            if result.get("results") and len(result["results"]) > 1:
                out["best_result"] = result.get("best_result")
            if result.get("failed_subtasks"):
                out["failed_subtasks"] = result["failed_subtasks"]
            return out
        return progress

    def stream_status(self, sid: str, job_id: str, tick_s: Optional[float] = None):
        """Generator yielding progress dicts until completion — the SSE body
        (master.py:237-266 semantics, 1.5 s default tick). Between progress
        snapshots, freshly-ingested learning curves are interleaved as
        ``{"kind": "curve", ...}`` events (incremental: the store's version
        counter is the cursor, so each curve streams exactly once). The
        progress snapshot is read BEFORE the curve drain: a terminal
        status implies aggregation finished, so every curve ingested
        before it is already behind the cursor and flushes on this final
        iteration — nothing is lost to the return."""
        tick = tick_s if tick_s is not None else self.config.service.sse_tick_s
        since = 0
        while True:
            progress = self.store.job_progress(sid, job_id)
            fresh, since = self.curves.updates(job_id, since)
            for entry in fresh:
                yield {"kind": "curve", "job_id": job_id, **entry}
            yield progress
            if progress["job_status"] in TERMINAL_STATUSES:
                return
            time.sleep(tick)

    def job_metrics(self, sid: str, job_id: str) -> List[Dict[str, Any]]:
        """Per-subtask results array (the reference's /metrics endpoint
        replays the Kafka metrics topic, master.py:294-340; here it's a
        store read — same payload, no broker rewind). Snapshots to
        metrics.json like the reference (master.py:336-337)."""
        self._require_session(sid)
        results = self.store.subtask_results(sid, job_id)
        try:
            import json
            import os

            os.makedirs(self.config.storage.root, exist_ok=True)
            with open(os.path.join(self.config.storage.root, "metrics.json"), "w") as f:
                json.dump(json_safe(results), f, indent=2)
        except OSError:
            logger.exception("metrics.json snapshot failed")
        return results

    def job_cost(self, job_id: str) -> Optional[Dict[str, Any]]:
        """Hardware-grounded cost report for a job: device-seconds, total
        model/XLA FLOPs and bytes, HBM high-water, and per-group MFU —
        aggregated from the ``batch_cost`` records the executors stamp onto
        each batch's primary result (runtime/executor._record_batch_cost).
        None when the job id is unknown; a known job with no cost records
        (CS230_OBS=0, or a run predating the accounting layer) reports
        zeros with an empty ``groups`` list. Schema:
        docs/OBSERVABILITY.md "Job cost report"."""
        sid = next(
            (
                j["session_id"]
                for j in self.store.jobs_overview()
                if j["job_id"] == job_id
            ),
            None,
        )
        if sid is None:
            return None
        from ..utils.flops import device_peak_flops

        progress = self.store.job_progress(sid, job_id)
        groups: List[Dict[str, Any]] = []
        device_seconds = 0.0
        capacity_device_seconds = 0.0  # device_seconds x participating devices
        model_flops = 0.0
        xla_flops = 0.0
        bytes_accessed = 0.0
        hbm_peak = None
        priced = True  # every group carries a model-FLOP figure
        for r in self.store.subtask_results(sid, job_id):
            cost = (r or {}).get("batch_cost")
            if not cost:
                continue
            groups.append(dict(cost))
            device_seconds += float(cost.get("device_seconds") or 0.0)
            capacity_device_seconds += float(
                cost.get("device_seconds") or 0.0
            ) * max(int(cost.get("n_devices") or 1), 1)
            # a group counts as priced only with a COMPLETE model-FLOP sum
            # (flops_coverage 1.0) — job MFU from partial sums would
            # understate utilization and read as a real figure
            if (
                cost.get("model_flops") is not None
                and cost.get("flops_coverage") == 1.0
            ):
                model_flops += float(cost["model_flops"])
            else:
                priced = False
            if cost.get("xla_flops") is not None:
                xla_flops += float(cost["xla_flops"])
            if cost.get("bytes_accessed") is not None:
                bytes_accessed += float(cost["bytes_accessed"])
            if cost.get("hbm_peak_bytes") is not None:
                hbm_peak = max(hbm_peak or 0, int(cost["hbm_peak_bytes"]))
        peak = device_peak_flops()
        mfu = None
        if peak and capacity_device_seconds > 0 and model_flops > 0 and priced:
            # capacity-weighted: each group's window counts once per
            # participating device, so mesh batches don't inflate MFU
            mfu = model_flops / (capacity_device_seconds * peak)
        return {
            "job_id": job_id,
            "session_id": sid,
            "job_status": progress.get("job_status"),
            "n_groups": len(groups),
            "device_seconds": device_seconds,
            "model_flops": model_flops if groups and priced else None,
            "xla_flops": xla_flops if xla_flops > 0 else None,
            "bytes_accessed": bytes_accessed if bytes_accessed > 0 else None,
            "hbm_peak_bytes": hbm_peak,
            # MFU is null off-accelerator (device_peak_flops() is None on
            # CPU — utilization of a host backend is not a meaningful
            # number) and whenever any group lacks a model-FLOP estimate
            "mfu": mfu,
            "device_peak_flops": peak,
            "groups": groups,
        }

    def critical_path(self, job_id: str) -> Optional[Dict[str, Any]]:
        """Exact wall-clock decomposition of one job (obs/critpath.py):
        the span tree joined with the flight-recorder timelines, tiled
        into labeled critical-path segments that sum to the measured
        wall (gaps labeled ``untraced``). None when no trace is bound to
        the job — the ``GET /critical_path`` 404. Schema:
        docs/OBSERVABILITY.md "Critical path & trace export"."""
        from ..obs.critpath import critical_path as _critical_path

        tid = TRACER.trace_for_job(job_id)
        if tid is None:
            return None
        timelines = {
            stid: RECORDER.timeline(job_id, stid) or []
            for stid in RECORDER.job_subtasks(job_id)
        }
        # the store-measured wall (created_at -> completion_time), when
        # the job record still exists, cross-checks the span window
        job_wall = None
        sid = next(
            (
                j["session_id"]
                for j in self.store.jobs_overview()
                if j["job_id"] == job_id
            ),
            None,
        )
        if sid is not None:
            try:
                job = self.store.get_job(sid, job_id)
                if job.get("completion_time") and job.get("created_at"):
                    job_wall = float(job["completion_time"]) - float(
                        job["created_at"]
                    )
            except KeyError:
                pass
        return _critical_path(
            job_id,
            trace_id=tid,
            spans=TRACER.spans_for(tid),
            timelines=timelines,
            job_wall_s=job_wall,
        )

    def explain(self, job_id: str, subtask_id: str) -> Dict[str, Any]:
        """Flight-recorder timeline for one subtask — every lifecycle
        decision in order (placement with score breakdown, lease grant/
        reclaim, attempts, retries, speculation, terminal result /
        quarantine). Raises KeyError when the recorder never saw the pair
        (unknown ids, a run under ``CS230_OBS=0``, or a timeline already
        evicted from the bounded ring) — the ``GET /explain`` 404. Schema:
        docs/OBSERVABILITY.md "Flight recorder"."""
        timeline = RECORDER.timeline(job_id, subtask_id)
        if timeline is None:
            raise KeyError(
                f"no recorded events for subtask {subtask_id!r} of job "
                f"{job_id!r}"
            )
        return {
            "job_id": job_id,
            "subtask_id": subtask_id,
            "n_events": len(timeline),
            "events": timeline,
        }

    def prewarm_hints(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Prewarm hints for a freshly-registered worker: the most recent
        job shape per (model family, dataset), ranked by the runtime
        predictor's hot families (``PlacementEngine.hot_families`` — the
        families the fleet has actually been running), newest-first within
        a rank. Shipped in the ``POST /subscribe`` response so the agent's
        background prewarm (runtime/prewarm.py) can load those
        executables and stage those datasets BEFORE the first placement
        arrives. Empty when ``CS230_PREWARM=0`` or nothing has run yet."""
        from .prewarm import enabled as prewarm_enabled
        from .prewarm import max_hints

        if not prewarm_enabled():
            return []
        if self.overload_shedding():
            # graceful degradation: an overloaded fleet must not spend
            # idle-window device time warming SPECULATIVE shapes — shed
            # prewarm before admission starts rejecting real submits
            counter_inc("tpuml_overload_shed_total", kind="prewarm")
            return []
        limit = limit if limit is not None else max_hints()
        if limit <= 0:
            return []
        hints: Dict[Any, Dict[str, Any]] = {}
        # jobs_overview is newest-first: the first job seen per
        # (family, dataset) is the most recent shape of that family.
        # hint_shape extracts one param dict + scalar train_params per
        # selected job — NOT the get_job deep copy, which would serialize
        # every subtask spec/result of thousand-trial jobs under the
        # store lock on every /subscribe (agent restarts re-register
        # routinely under the fault-tolerance layer)
        for job in self.store.jobs_overview():
            family, dataset_id = job.get("model_type"), job.get("dataset_id")
            if not family or not dataset_id or (family, dataset_id) in hints:
                continue
            try:
                shape = self.store.hint_shape(
                    job["session_id"], job["job_id"]
                )
            except Exception:  # noqa: BLE001 — evicted/foreign job
                continue
            hints[(family, dataset_id)] = {
                "model_type": family,
                "dataset_id": dataset_id,
                **shape,
            }
        ranked = list(hints.values())
        hot = (
            self.cluster.engine.hot_families(top_n=max(limit, 5))
            if self.cluster is not None
            else []
        )
        rank = {family: i for i, family in enumerate(hot)}
        ranked.sort(key=lambda h: rank.get(h["model_type"], len(rank)))
        return ranked[:limit]

    def predictor_calibration(self) -> Dict[str, Any]:
        """Per-model-family predicted-vs-actual calibration of the runtime
        predictor driving placement/lease decisions — the
        ``GET /predictor/calibration`` body. Empty ``families`` in direct
        mode (no placement engine ran, nothing was predicted)."""
        families: Dict[str, Any] = {}
        if self.cluster is not None:
            report = getattr(
                self.cluster.engine.predictor, "calibration_report", None
            )
            if report is not None:
                families = report()
        return {"families": families, "n_families": len(families)}

    def wait_for_completion(self, sid: str, job_id: str, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        timeout = timeout_s or self.config.service.client_timeout_s
        if not self.store.wait_job(sid, job_id, timeout):
            raise TimeoutError(f"Job {job_id} did not complete in time")
        return self.store.job_progress(sid, job_id)

    def best_model_path(self, sid: str, job_id: str) -> Optional[str]:
        self._require_session(sid)
        job = self.store.get_job(sid, job_id)
        result = job.get("result") or {}
        best = result.get("best_result") or {}
        if best.get("model_path"):
            return best["model_path"]
        with self._artifact_lock:
            path = self._artifact_paths.get((sid, job_id))
            if path is not None:
                return path
            st = self._artifact_specs.get((sid, job_id))
        if st is None:
            return None
        artifact = self.executor.fit_artifact(st)
        path = save_artifact(st["subtask_id"], artifact, self.config.storage.models_dir)
        with self._artifact_lock:
            self._artifact_paths[(sid, job_id)] = path
        return path

    def _require_session(self, sid: str) -> None:
        if not self.store.has_session(sid):
            raise KeyError(f"Invalid session id: {sid}")
