"""Export fitted kernel artifacts to real sklearn estimators.

Parity target: the reference serves a pickle any sklearn user can
``.predict()`` with (``aws-prod/worker/worker.py:352-356``,
``aws-prod/master/master.py:270-291``). Our artifacts are plain dicts of
numpy arrays (runtime/artifacts.py); this module CONSTRUCTS the matching
sklearn estimator and injects the fitted state — so a user migrating off
the reference can drop the winner into an existing sklearn pipeline, for
every model family, not just linear ones (VERDICT r3 item 5).

Injection contracts (verified per family in tests/test_sklearn_export.py):
the exported estimator's ``predict`` matches the kernel's predictions on
held-out data. Trees translate binned splits (feature, bin) into float
thresholds via the stored quantile edges; boosting folds the prior into
stage 0 so ``init='zero'`` reproduces the raw scores exactly; SVC repacks
the OvO duals into libsvm's class-grouped layout.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List

import numpy as np


def to_sklearn(artifact: Dict[str, Any]):
    """Build a fitted sklearn estimator equivalent to the artifact.

    Raises NotImplementedError for the one unrepresentable case
    (multiclass Nyström SVC — sklearn has no OvO-voting linear-feature
    form; use ``predict_with_artifact`` for those).
    """
    mt = artifact["model_type"]
    fn = _EXPORTERS.get(mt)
    if fn is None:
        raise NotImplementedError(
            f"no sklearn export for model_type {mt!r} "
            f"(supported: {sorted(_EXPORTERS)}); predict_with_artifact "
            "always works"
        )
    return fn(artifact)


def _ctor(cls, params: Dict[str, Any]):
    """Construct ``cls`` with the subset of ``params`` its __init__ takes,
    so get_params round-trips and repr shows the real hyperparameters."""
    sig = inspect.signature(cls.__init__)
    kept = {}
    for k, v in (params or {}).items():
        if k in sig.parameters and k != "self":
            kept[k] = tuple(v) if isinstance(v, list) else v
    return cls(**kept)


def _np64(a):
    return np.ascontiguousarray(np.asarray(a), dtype=np.float64)


# ---------------------------------------------------------------------------
# linear family
# ---------------------------------------------------------------------------


def _export_logistic(a):
    from sklearn.linear_model import LogisticRegression

    W = np.asarray(a["fitted_params"])  # [d(+1), c]
    st = a["static"]
    fit_intercept = bool(st.get("fit_intercept", True))
    c = W.shape[1]
    if fit_intercept:
        coef, inter = W[:-1].T, W[-1]
    else:
        coef, inter = W.T, np.zeros(c, np.float32)
    if c == 2:
        # sklearn stores the single class-1 logit for binary problems; the
        # 2-column softmax's logit difference is that logit (models/logistic.py)
        coef = (coef[1] - coef[0])[None, :]
        inter = np.asarray([inter[1] - inter[0]])
    est = _ctor(LogisticRegression, a["parameters"])
    est.coef_ = _np64(coef)
    est.intercept_ = _np64(inter)
    est.classes_ = np.arange(c)
    est.n_features_in_ = int(est.coef_.shape[1])
    est.n_iter_ = np.asarray([int(a["parameters"].get("max_iter", 100))])
    return est


def _export_linear(cls_name):
    def export(a):
        import sklearn.linear_model as lm

        cls = getattr(lm, cls_name)
        W = np.asarray(a["fitted_params"])  # [d(+1)]
        fit_intercept = bool(a["static"].get("fit_intercept", True))
        est = _ctor(cls, a["parameters"])
        if fit_intercept:
            est.coef_ = _np64(W[:-1])
            est.intercept_ = float(W[-1])
        else:
            est.coef_ = _np64(W)
            est.intercept_ = 0.0
        est.n_features_in_ = int(est.coef_.shape[0])
        return est

    return export


# ---------------------------------------------------------------------------
# MLP
# ---------------------------------------------------------------------------


def _export_mlp(classifier: bool):
    def export(a):
        from sklearn.neural_network import MLPClassifier, MLPRegressor
        from sklearn.preprocessing import LabelBinarizer

        layers: List[Dict[str, np.ndarray]] = a["fitted_params"]
        coefs = [_np64(layer["W"]) for layer in layers]
        inters = [_np64(layer["b"]) for layer in layers]
        cls = MLPClassifier if classifier else MLPRegressor
        est = _ctor(cls, a["parameters"])
        c = int(a["static"].get("_n_classes", 2))
        if classifier and c == 2:
            # our binary head is a 2-unit softmax; sklearn's is a single
            # logistic unit — convert via the logit difference
            coefs[-1] = (coefs[-1][:, 1] - coefs[-1][:, 0])[:, None]
            inters[-1] = np.asarray([inters[-1][1] - inters[-1][0]])
        est.coefs_ = coefs
        est.intercepts_ = inters
        est.n_layers_ = len(coefs) + 1
        est.n_features_in_ = int(coefs[0].shape[0])
        est.activation = a["static"].get("activation", "relu")
        if classifier:
            est.n_outputs_ = int(coefs[-1].shape[1])
            est.out_activation_ = "logistic" if c == 2 else "softmax"
            est.classes_ = np.arange(c)
            est._label_binarizer = LabelBinarizer().fit(est.classes_)
        else:
            est.n_outputs_ = 1
            est.out_activation_ = "identity"
        return est

    return export


# ---------------------------------------------------------------------------
# KNN: the fitted state IS the training data — refit sklearn on it
# ---------------------------------------------------------------------------


def _export_knn(classifier: bool):
    def export(a):
        from sklearn.neighbors import KNeighborsClassifier, KNeighborsRegressor

        fp = a["fitted_params"]
        X, y, w = np.asarray(fp["X"]), np.asarray(fp["y"]), np.asarray(fp["w"])
        keep = w > 0
        cls = KNeighborsClassifier if classifier else KNeighborsRegressor
        est = _ctor(cls, a["parameters"])
        return est.fit(X[keep], y[keep].astype(int) if classifier else y[keep])

    return export


# ---------------------------------------------------------------------------
# trees: binned splits -> float thresholds via the stored quantile edges
# ---------------------------------------------------------------------------


def _threshold(edges_f: np.ndarray, b: int) -> float:
    """Our routing: go left iff bin_code <= b iff x < edges_f[b]
    (bin_data uses searchsorted side='right'). sklearn routes left iff
    x <= threshold, so the threshold is the largest double below the edge.
    b >= len(edges) encodes a pass-through node (everything left)."""
    if b >= len(edges_f):
        return np.inf
    return float(np.nextafter(np.float64(np.float32(edges_f[b])), -np.inf))


def _sk_tree(n_features: int, n_classes: int, nodes: List[dict], max_depth: int):
    """Assemble an sklearn.tree._tree.Tree from a node list with
    left/right/feature/threshold/value entries (leaves: left == -1)."""
    from sklearn.tree._tree import NODE_DTYPE, Tree

    k = max(n_classes, 1)
    tree = Tree(n_features, np.asarray([k], dtype=np.intp), 1)
    arr = np.zeros(len(nodes), dtype=NODE_DTYPE)
    values = np.zeros((len(nodes), 1, k), dtype=np.float64)
    for i, nd in enumerate(nodes):
        leaf = nd["left"] == -1
        arr[i] = (
            nd["left"],
            nd["right"],
            -2 if leaf else nd["feature"],
            -2.0 if leaf else nd["threshold"],
            0.0,
            max(int(nd.get("n_samples", 1)), 1),
            max(float(nd.get("weight", 1.0)), 1e-12),
            0,
        )
        values[i, 0, :] = nd.get("value", np.zeros(k))
    tree.__setstate__(
        {"max_depth": max_depth, "node_count": len(nodes), "nodes": arr, "values": values}
    )
    return tree


def _complete_tree_nodes(tree: Dict[str, np.ndarray], edges: np.ndarray, depth: int):
    """Heap-layout complete tree {split_feat, split_bin, leaf_val} ->
    sklearn node list (preorder)."""
    split_feat = np.asarray(tree["split_feat"])
    split_bin = np.asarray(tree["split_bin"])
    leaf_val = np.asarray(tree["leaf_val"])  # [2^depth, k]
    leaf_weight = np.asarray(tree.get("leaf_weight", np.ones(leaf_val.shape[0])))
    nodes: List[dict] = []

    def emit(heap: int, level: int) -> int:
        idx = len(nodes)
        if level == depth:  # leaf
            j = heap - (2**depth - 1)
            nodes.append(
                {"left": -1, "right": -1, "feature": -2, "threshold": -2.0,
                 "value": leaf_val[j], "weight": float(leaf_weight[j]),
                 "n_samples": max(int(round(float(leaf_weight[j]))), 1)}
            )
            return idx
        f, b = int(split_feat[heap]), int(split_bin[heap])
        nodes.append({})  # placeholder, fill after children exist
        left = emit(2 * heap + 1, level + 1)
        right = emit(2 * heap + 2, level + 1)
        nodes[idx] = {
            "left": left, "right": right, "feature": f,
            "threshold": _threshold(edges[f], b),
            "value": np.zeros(leaf_val.shape[1]),
        }
        return idx

    emit(0, 0)
    return nodes, depth


def _arena_tree_nodes(tree: Dict[str, np.ndarray], edges: np.ndarray, levels: int):
    """Deep arena tree {feat, bin, child, leaf_val} -> sklearn node list.
    ``child[i]`` is the left-child arena slot (0 = leaf; right = left+1)."""
    feat = np.asarray(tree["feat"])
    bin_ = np.asarray(tree["bin"])
    child = np.asarray(tree["child"])
    leaf_val = np.asarray(tree["leaf_val"])
    leaf_weight = np.asarray(tree.get("leaf_weight", np.ones(leaf_val.shape[0])))
    nodes: List[dict] = []
    max_d = [0]

    def emit(slot: int, d: int) -> int:
        idx = len(nodes)
        max_d[0] = max(max_d[0], d)
        c = int(child[slot])
        if c == 0 or d >= levels:  # leaf
            nodes.append(
                {"left": -1, "right": -1, "feature": -2, "threshold": -2.0,
                 "value": leaf_val[slot], "weight": float(leaf_weight[slot]),
                 "n_samples": max(int(round(float(leaf_weight[slot]))), 1)}
            )
            return idx
        f, b = int(feat[slot]), int(bin_[slot])
        nodes.append({})
        left = emit(c, d + 1)
        right = emit(c + 1, d + 1)
        nodes[idx] = {
            "left": left, "right": right, "feature": f,
            "threshold": _threshold(edges[f], b),
            "value": np.zeros(leaf_val.shape[1]),
        }
        return idx

    # the arena root is always slot 0 (build_tree_deep routes from node 0;
    # child[0] == 0 just means the root never split — a single-leaf tree)
    emit(0, 0)
    return nodes, max_d[0]


def _tree_from_artifact(tree_dict, edges, static, n_classes):
    if "split_feat" in tree_dict:
        nodes, d = _complete_tree_nodes(tree_dict, edges, int(static["_depth"]))
    else:
        nodes, d = _arena_tree_nodes(
            tree_dict, edges, int(static.get("_levels", static["_depth"]))
        )
    n_features = edges.shape[0]
    return _sk_tree(n_features, n_classes, nodes, d)


def _stacked(trees: Dict[str, np.ndarray], i: int) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v)[i] for k, v in trees.items()}


def _export_decision_tree(classifier: bool):
    def export(a):
        from sklearn.tree import DecisionTreeClassifier, DecisionTreeRegressor

        fp, st = a["fitted_params"], a["static"]
        c = int(st.get("_n_classes", 0)) if classifier else 0
        k = max(c, 2) if classifier else 1
        skt = _tree_from_artifact(fp["tree"], np.asarray(fp["edges"]), st, k)
        cls = DecisionTreeClassifier if classifier else DecisionTreeRegressor
        est = _ctor(cls, a["parameters"])
        est.tree_ = skt
        est.n_features_in_ = int(np.asarray(fp["edges"]).shape[0])
        est.n_outputs_ = 1
        if classifier:
            est.classes_ = np.arange(k)
            est.n_classes_ = k
        est.max_features_ = est.n_features_in_
        return est

    return export


def _export_forest(classifier: bool):
    def export(a):
        from sklearn.ensemble import RandomForestClassifier, RandomForestRegressor
        from sklearn.tree import DecisionTreeClassifier, DecisionTreeRegressor

        fp, st = a["fitted_params"], a["static"]
        edges = np.asarray(fp["edges"])
        c = int(st.get("_n_classes", 0)) if classifier else 0
        k = max(c, 2) if classifier else 1
        n_trees = int(np.asarray(fp["trees"]["leaf_val"]).shape[0])
        sub_cls = DecisionTreeClassifier if classifier else DecisionTreeRegressor
        subs = []
        for i in range(n_trees):
            skt = _tree_from_artifact(_stacked(fp["trees"], i), edges, st, k)
            sub = sub_cls()
            sub.tree_ = skt
            sub.n_features_in_ = int(edges.shape[0])
            sub.n_outputs_ = 1
            if classifier:
                sub.classes_ = np.arange(k)
                sub.n_classes_ = k
            subs.append(sub)
        cls = RandomForestClassifier if classifier else RandomForestRegressor
        est = _ctor(cls, a["parameters"])
        est.estimators_ = subs
        est.n_features_in_ = int(edges.shape[0])
        est.n_outputs_ = 1
        if classifier:
            est.classes_ = np.arange(k)
            est.n_classes_ = k
        return est

    return export


def _export_gradient_boosting(classifier: bool):
    def export(a):
        from sklearn.ensemble import (
            GradientBoostingClassifier,
            GradientBoostingRegressor,
        )
        from sklearn.tree import DecisionTreeRegressor

        fp, st = a["fitted_params"], a["static"]
        edges = np.asarray(fp["edges"])
        lr = float(np.asarray(fp["lr"]))
        prior = np.asarray(fp["prior"])
        trees = fp["trees"]
        leaf_val = np.asarray(trees["leaf_val"])
        c = int(st.get("_n_classes", 0)) if classifier else 0
        if classifier:
            n_stages, kdim = leaf_val.shape[0], leaf_val.shape[1]
            # our raw scores: F = F0 + lr * leaf_scale * sum(stage deltas)
            # (binary: F[:, 1] only). sklearn with init='zero': raw =
            # lr * sum(tree values) — fold leaf_scale into the values and
            # F0 into stage 0.
            leaf_scale = (c - 1) / c if c > 2 else 1.0
            if c > 2:
                raw0 = prior  # [c]
            else:
                raw0 = np.asarray([prior[1] - prior[0]])  # single logit
        else:
            n_stages, kdim = leaf_val.shape[0], 1
            leaf_scale = 1.0
            raw0 = np.asarray([float(prior)])

        ests = np.empty((n_stages, kdim), dtype=object)
        for s in range(n_stages):
            for j in range(kdim):
                if classifier:  # stage trees carry a kdim axis (1 for binary)
                    td = {kk: np.asarray(v)[s, j] for kk, v in trees.items()}
                else:
                    td = {kk: np.asarray(v)[s] for kk, v in trees.items()}
                lv = np.asarray(td["leaf_val"], np.float64) * leaf_scale
                if s == 0:
                    lv = lv + raw0[j] / lr
                td["leaf_val"] = lv
                skt = _tree_from_artifact(td, edges, st, 1)
                sub = DecisionTreeRegressor()
                sub.tree_ = skt
                sub.n_features_in_ = int(edges.shape[0])
                sub.n_outputs_ = 1
                ests[s, j] = sub

        cls = GradientBoostingClassifier if classifier else GradientBoostingRegressor
        est = _ctor(cls, a["parameters"])
        est.estimators_ = ests
        est.init_ = "zero"
        est.init = "zero"
        est.learning_rate = lr
        est.n_features_in_ = int(edges.shape[0])
        est.n_estimators_ = n_stages
        est.n_trees_per_iteration_ = kdim
        if classifier:
            est.classes_ = np.arange(max(c, 2))
            est.n_classes_ = max(c, 2)
        return est

    return export


# ---------------------------------------------------------------------------
# SVM: repack OvO duals into libsvm's class-grouped layout
# ---------------------------------------------------------------------------


def _svc_kernel_params(a):
    st = a["static"]
    return {
        "kernel": st.get("kernel", "rbf"),
        "degree": int(st.get("degree", 3)),
        "coef0": float(st.get("coef0", 0.0)),
    }


def _export_svc(a):
    from sklearn.svm import SVC

    fp = a["fitted_params"]
    if "W" in fp:
        return _export_svc_nystrom(a)
    X = np.asarray(fp["X"])
    dual = np.asarray(fp["dual"])  # [n_pairs, n] signed alpha (t * alpha)
    intercept = np.asarray(fp["intercept"])  # [n_pairs]
    pa = np.asarray(fp["pairs_a"])
    pb = np.asarray(fp["pairs_b"])
    c = int(np.max(pb)) + 1 if len(pb) else 2
    n = X.shape[0]

    # infer each row's class from the signs is unreliable for non-SVs; the
    # artifact doesn't store y, but every row's class is recoverable from
    # which pair-columns are nonzero only for SVs. Instead keep EVERY row as
    # a "support vector" with zero coefficients where inactive — libsvm
    # predict is a plain weighted kernel sum, so zero rows are harmless.
    # Rows must be grouped by class; recover class labels from the stored
    # training targets when present, else from sign structure.
    y = np.asarray(fp["y"]) if "y" in fp else _infer_classes(dual, pa, pb, c, n)

    order = np.argsort(y, kind="stable")
    Xs = X[order]
    ys = y[order]
    n_support = np.asarray([int(np.sum(ys == i)) for i in range(c)], np.int32)

    # _dual_coef_ rows: for an SV of class i, row r holds its coefficient in
    # the machine (i vs other) where other = r if r < i else r + 1
    pair_index = {(int(pa[p]), int(pb[p])): p for p in range(len(pa))}
    dc = np.zeros((c - 1, n), np.float64)
    ds = dual[:, order]
    for v in range(n):
        i = int(ys[v])
        for r in range(c - 1):
            other = r if r < i else r + 1
            p = pair_index[(min(i, other), max(i, other))]
            dc[r, v] = ds[p, v]
    est = _ctor(SVC, a["parameters"])
    est._sparse = False
    est.support_ = order.astype(np.int32)
    est.support_vectors_ = _np64(Xs)
    est._n_support = n_support
    est._dual_coef_ = dc
    est._intercept_ = _np64(intercept)
    # sklearn's public attrs negate the libsvm internals for BINARY models
    # only (BaseLibSVM.fit flips both iff len(classes_) == 2)
    if c == 2:
        est.dual_coef_ = -dc
        est.intercept_ = -est._intercept_
    else:
        est.dual_coef_ = dc
        est.intercept_ = est._intercept_
    est._probA = np.empty(0)
    est._probB = np.empty(0)
    est.classes_ = np.arange(c)
    est._gamma = float(np.asarray(fp["gamma"]))
    est.gamma = est._gamma
    est.fit_status_ = 0
    est.shape_fit_ = X.shape
    est.n_features_in_ = X.shape[1]
    est.class_weight_ = np.ones(c)
    return est


def _infer_classes(dual, pa, pb, c, n):
    """Recover row classes from the OvO sign structure: in pair (a, b) a
    positive coefficient marks class a, negative class b. Rows inactive in
    every pair default to class 0 (zero coefficients — harmless)."""
    y = np.zeros(n, np.int32)
    for p in range(dual.shape[0]):
        pos = dual[p] > 0
        neg = dual[p] < 0
        y[pos] = pa[p]
        y[neg] = pb[p]
    return y


def _export_svc_nystrom(a):
    from sklearn.kernel_approximation import Nystroem
    from sklearn.pipeline import Pipeline
    from sklearn.svm import LinearSVC

    fp = a["fitted_params"]
    pa = np.asarray(fp["pairs_a"])
    if len(pa) > 1:
        raise NotImplementedError(
            "multiclass Nystrom SVC has no sklearn form (OvO voting over "
            "approximate-feature machines); use predict_with_artifact"
        )
    st = a["static"]
    landmarks = np.asarray(fp["landmarks"])
    W = np.asarray(fp["W"])[0]  # [m+1] (last = bias)
    nys = Nystroem(
        kernel=st.get("kernel", "rbf"),
        gamma=float(np.asarray(fp["gamma"])),
        degree=int(st.get("degree", 3)),
        coef0=float(st.get("coef0", 0.0)),
        n_components=landmarks.shape[0],
    )
    nys.components_ = _np64(landmarks)
    nys.component_indices_ = np.arange(landmarks.shape[0])
    # our Z = K(X, L) @ inv_sqrt (inv_sqrt = V diag(1/sqrt(lam)), NOT the
    # symmetric sqrt); sklearn transforms with normalization_.T, so inject
    # the transpose to reproduce the exact feature map
    nys.normalization_ = _np64(np.asarray(fp["inv_sqrt"])).T
    nys.n_features_in_ = landmarks.shape[1]
    lin = LinearSVC()
    # our pair decision is positive for class pairs_a (= class 0); LinearSVC
    # decision is positive for class 1, hence the sign flip
    lin.coef_ = -_np64(W[:-1])[None, :]
    lin.intercept_ = np.asarray([-float(W[-1])])
    lin.classes_ = np.arange(2)
    lin.n_features_in_ = landmarks.shape[0]
    return Pipeline([("nystroem", nys), ("svc", lin)])


def _export_svr(a):
    from sklearn.svm import SVR

    fp = a["fitted_params"]
    if "W" in fp:
        return _export_svr_nystrom(a)
    X = np.asarray(fp["X"])
    dual = np.asarray(fp["dual"])  # [n] signed coefficients
    est = _ctor(SVR, a["parameters"])
    est._sparse = False
    est.support_ = np.arange(X.shape[0], dtype=np.int32)
    est.support_vectors_ = _np64(X)
    # libsvm regression models carry two (identical) per-"class" SV counts
    est._n_support = np.asarray([X.shape[0], X.shape[0]], np.int32)
    est._dual_coef_ = _np64(dual)[None, :]
    est.dual_coef_ = est._dual_coef_
    est._intercept_ = np.asarray([float(np.asarray(fp["intercept"]))])
    est.intercept_ = est._intercept_
    est._probA = np.empty(0)
    est._probB = np.empty(0)
    est._gamma = float(np.asarray(fp["gamma"]))
    est.gamma = est._gamma
    est.fit_status_ = 0
    est.shape_fit_ = X.shape
    est.n_features_in_ = X.shape[1]
    return est


def _export_svr_nystrom(a):
    from sklearn.kernel_approximation import Nystroem
    from sklearn.pipeline import Pipeline
    from sklearn.svm import LinearSVR

    fp = a["fitted_params"]
    st = a["static"]
    landmarks = np.asarray(fp["landmarks"])
    W = np.asarray(fp["W"]).reshape(-1)  # [m+1]
    nys = Nystroem(
        kernel=st.get("kernel", "rbf"),
        gamma=float(np.asarray(fp["gamma"])),
        degree=int(st.get("degree", 3)),
        coef0=float(st.get("coef0", 0.0)),
        n_components=landmarks.shape[0],
    )
    nys.components_ = _np64(landmarks)
    nys.component_indices_ = np.arange(landmarks.shape[0])
    # our Z = K(X, L) @ inv_sqrt (inv_sqrt = V diag(1/sqrt(lam)), NOT the
    # symmetric sqrt); sklearn transforms with normalization_.T, so inject
    # the transpose to reproduce the exact feature map
    nys.normalization_ = _np64(np.asarray(fp["inv_sqrt"])).T
    nys.n_features_in_ = landmarks.shape[1]
    lin = LinearSVR()
    lin.coef_ = _np64(W[:-1])
    lin.intercept_ = np.asarray([float(W[-1])])
    lin.n_features_in_ = landmarks.shape[0]
    return Pipeline([("nystroem", nys), ("svr", lin)])


# ---------------------------------------------------------------------------
# GaussianNB
# ---------------------------------------------------------------------------


def _export_gaussian_nb(a):
    from sklearn.naive_bayes import GaussianNB

    fp = a["fitted_params"]
    est = _ctor(GaussianNB, a["parameters"])
    est.theta_ = _np64(fp["mean"])
    est.var_ = _np64(fp["var"])
    est.class_prior_ = np.exp(_np64(fp["log_prior"]))
    est.class_count_ = est.class_prior_ * 100.0  # relative weights suffice
    c = est.theta_.shape[0]
    est.classes_ = np.arange(c)
    est.n_features_in_ = est.theta_.shape[1]
    est.epsilon_ = 0.0
    return est


_EXPORTERS = {
    "LogisticRegression": _export_logistic,
    "LinearRegression": _export_linear("LinearRegression"),
    "Ridge": _export_linear("Ridge"),
    "MLPClassifier": _export_mlp(True),
    "MLPRegressor": _export_mlp(False),
    "KNeighborsClassifier": _export_knn(True),
    "KNeighborsRegressor": _export_knn(False),
    "DecisionTreeClassifier": _export_decision_tree(True),
    "DecisionTreeRegressor": _export_decision_tree(False),
    "RandomForestClassifier": _export_forest(True),
    "RandomForestRegressor": _export_forest(False),
    "GradientBoostingClassifier": _export_gradient_boosting(True),
    "GradientBoostingRegressor": _export_gradient_boosting(False),
    "SVC": _export_svc,
    "SVR": _export_svr,
    "GaussianNB": _export_gaussian_nb,
}
