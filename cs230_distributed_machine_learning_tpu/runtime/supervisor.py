"""Child-process supervision for executor agents.

The reference leans on Docker/EC2 restart policies to bring dead workers
back (``aws-prod/docker-compose.yml`` service restarts; ``scripts/setup.sh``
EC2 boot). This is the framework-native equivalent for a single host: the
coordinator can run its executors as *supervised child agent processes*
(``tpuml-coordinator --agent-executors N``) instead of in-process threads,
so a fatal accelerator fault (executor.DeviceLostError) kills only the
child — the scheduler's dead-worker sweep requeues its tasks, and the
supervisor respawns a fresh process with a fresh backend. This closes the
local-mode containment gap: an in-process executor shares the coordinator's
backend, so a poisoned device would otherwise take the whole service down.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from typing import List, Optional, Sequence

from ..utils.logging import get_logger

logger = get_logger("tpuml.supervisor")


class AgentSupervisor:
    """Spawn and keep-alive N child processes.

    Restart policy: exponential backoff per slot starting at
    ``backoff_s`` (doubling to ``max_backoff_s``), reset after a child
    stays up ``healthy_after_s``. ``max_restarts`` (per slot)
    guards against crash *loops*: the counter is windowed — it resets (with
    the backoff) once a child stays up ``healthy_after_s`` — so routine
    device-fault exits over a long deployment never exhaust it; only
    back-to-back failures do. A slot that exhausts it stays down and is
    reported via ``status()`` (``restarts_total`` keeps the lifetime count).

    ``slot_envs`` (optional, one dict per slot) overlays environment
    variables onto a slot's children (a ``None`` value unsets the variable)
    — used to pin all but one slot to the CPU backend
    (``TPUML_PLATFORM=cpu``) on a single-accelerator host, where only one
    process can own the chip.
    """

    def __init__(
        self,
        command: Sequence[str],
        n: int = 1,
        *,
        backoff_s: float = 1.0,
        max_backoff_s: float = 30.0,
        healthy_after_s: float = 60.0,
        max_restarts: int = 50,
        poll_interval_s: float = 0.5,
        slot_envs: Optional[Sequence[Optional[dict]]] = None,
    ):
        self.command = list(command)
        self.n = n
        self.slot_envs = list(slot_envs) if slot_envs else None
        if self.slot_envs is not None and len(self.slot_envs) != n:
            raise ValueError("slot_envs must have one entry per slot")
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.healthy_after_s = healthy_after_s
        self.max_restarts = max_restarts
        self.poll_interval_s = poll_interval_s
        self._procs: List[Optional[subprocess.Popen]] = [None] * n
        self._started_at: List[float] = [0.0] * n
        self._backoff: List[float] = [backoff_s] * n
        self._next_spawn: List[float] = [0.0] * n
        self._restarts: List[int] = [0] * n  # consecutive, reset on healthy
        self._restarts_total: List[int] = [0] * n
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        for i in range(self.n):
            self._spawn(i)
        self._thread = threading.Thread(target=self._monitor, daemon=True)
        self._thread.start()

    def _spawn(self, i: int) -> None:
        try:
            env = None
            if self.slot_envs and self.slot_envs[i] is not None:
                import os

                env = {**os.environ}
                for k, v in self.slot_envs[i].items():
                    if v is None:  # overlay None = unset in the child
                        env.pop(k, None)
                    else:
                        env[k] = v
            self._procs[i] = subprocess.Popen(self.command, env=env)
            self._started_at[i] = time.time()
            logger.info(
                "Spawned agent slot %d (pid %s)", i, self._procs[i].pid
            )
        except OSError:
            # count a failed spawn like a crash: backoff + restart budget,
            # otherwise a persistently failing Popen retries every poll tick
            # forever and the crash-loop guard never triggers
            logger.exception("Spawn failed for slot %d", i)
            self._procs[i] = None
            self._restarts[i] += 1
            self._restarts_total[i] += 1
            self._next_spawn[i] = time.time() + self._backoff[i]
            self._backoff[i] = min(self._backoff[i] * 2, self.max_backoff_s)

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            now = time.time()
            for i, proc in enumerate(self._procs):
                if proc is not None:
                    rc = proc.poll()
                    if rc is None:
                        if now - self._started_at[i] > self.healthy_after_s:
                            self._backoff[i] = self.backoff_s
                            self._restarts[i] = 0
                        continue
                    uptime = now - self._started_at[i]
                    logger.warning(
                        "Agent slot %d (pid %s) exited rc=%s after %.1fs",
                        i, proc.pid, rc, uptime,
                    )
                    self._procs[i] = None
                    self._restarts[i] += 1
                    self._restarts_total[i] += 1
                    self._next_spawn[i] = now + self._backoff[i]
                    self._backoff[i] = min(self._backoff[i] * 2, self.max_backoff_s)
                if self._procs[i] is None and self._restarts[i] <= self.max_restarts:
                    if now >= self._next_spawn[i]:
                        self._spawn(i)

    def status(self) -> List[dict]:
        out = []
        for i, proc in enumerate(self._procs):
            out.append({
                "slot": i,
                "pid": proc.pid if proc is not None else None,
                "alive": proc is not None and proc.poll() is None,
                "restarts": self._restarts[i],
                "restarts_total": self._restarts_total[i],
                "gave_up": self._restarts[i] > self.max_restarts,
            })
        return out

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        deadline = time.time() + timeout_s
        # terminate in a loop until the monitor thread is confirmed dead:
        # a join timeout can leave it mid-iteration, able to _spawn a fresh
        # child AFTER a single terminate pass — which would leak an
        # unsupervised agent process (ADVICE r2)
        while True:
            for proc in self._procs:
                if proc is not None and proc.poll() is None:
                    proc.terminate()
            if self._thread is None or not self._thread.is_alive():
                break
            self._thread.join(timeout=max(0.1, min(2.0, deadline - time.time())))
            if time.time() >= deadline:
                # monitor wedged past the budget: sweep once more and move on
                for proc in self._procs:
                    if proc is not None and proc.poll() is None:
                        proc.terminate()
                break
        for proc in self._procs:
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()


def agent_command(url: str, *, mem_mb: Optional[float] = None,
                  max_batch: Optional[int] = None) -> List[str]:
    """argv for one child agent process pointing at ``url``."""
    cmd = [
        sys.executable,
        "-m",
        "cs230_distributed_machine_learning_tpu.runtime.agent",
        "--url",
        url,
    ]
    if mem_mb is not None:
        cmd += ["--mem-mb", str(mem_mb)]
    if max_batch is not None:
        cmd += ["--max-batch", str(max_batch)]
    return cmd
