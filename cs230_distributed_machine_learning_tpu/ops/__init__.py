from .folds import SplitPlan, build_split_plan
from .metrics import weighted_accuracy, weighted_r2, weighted_mse

__all__ = [
    "SplitPlan",
    "build_split_plan",
    "weighted_accuracy",
    "weighted_r2",
    "weighted_mse",
]
