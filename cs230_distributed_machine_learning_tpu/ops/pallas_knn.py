"""Fused KNN top-k search as a Pallas TPU kernel.

The hot op of the KNN kernels (models/knn.py) is "for each query, the k
nearest masked training rows". The pure-XLA path computes a [block, n]
distance matrix and runs ``lax.top_k`` on it — for large n that round-trips
hundreds of MB of distances through HBM per block. This kernel fuses the
whole search: it streams training-set tiles through VMEM, computes the
distance tile on the MXU, and folds it into a running per-query top-k held
in VMEM scratch — the [nq, n] distance matrix never exists.

Grid: (query_blocks, train_blocks), train innermost so the running-best
scratch persists across a query block's sweep. Top-k merge is k rounds of
(min, first-argmin-via-iota, mask) — VPU reductions only, no sort.

Used on TPU for large n (models/knn.py gates on backend + size);
``interpret=True`` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BQ = 256   # query tile rows
_BT = 2048  # train tile cols (VMEM: BT*d floats + BQ*BT distance tile)
_INF = 3.4e38  # plain float: jnp constants would be captured consts in the kernel


def _kernel(q_ref, qsq_ref, xt_ref, tsq_ref, w_ref, d2_out, idx_out, best_d2, best_idx, *, k: int):
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        best_d2[:] = jnp.full_like(best_d2, jnp.float32(_INF))
        best_idx[:] = jnp.full_like(best_idx, -1)

    # distance tile on the MXU: [BQ, BT]
    d2 = (
        qsq_ref[:]
        + tsq_ref[:]
        - 2.0 * jnp.dot(q_ref[:], xt_ref[:].T, preferred_element_type=jnp.float32)
    )
    d2 = jnp.maximum(d2, 0.0)
    d2 = jnp.where(w_ref[:] > 0.0, d2, _INF)

    col = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1)
    global_col = col + j * _BT

    def merge_one(s, carry):
        d2_c, bd, bi = carry
        # row minimum of the remaining tile
        m = jnp.min(d2_c, axis=1, keepdims=True)                     # [BQ, 1]
        is_min = d2_c == m
        # first position achieving the minimum
        pos = jnp.min(jnp.where(is_min, global_col, jnp.int32(2**30)), axis=1, keepdims=True)
        first = is_min & (global_col == pos)
        # fold into the worst best-slot if better
        worst = jnp.max(bd, axis=1, keepdims=True)                   # [BQ, 1]
        wcol = jax.lax.broadcasted_iota(jnp.int32, bd.shape, 1)
        wpos = jnp.min(
            jnp.where(bd == worst, wcol, jnp.int32(2**30)), axis=1, keepdims=True
        )
        take = (m < worst)                                           # [BQ, 1]
        slot = (wcol == wpos) & take
        bd = jnp.where(slot, m, bd)
        bi = jnp.where(slot, pos, bi)
        # retire the extracted column
        d2_c = jnp.where(first & take, _INF, d2_c)
        return d2_c, bd, bi

    carry = (d2, best_d2[:], best_idx[:])
    carry = jax.lax.fori_loop(0, k, lambda s, c: merge_one(s, c), carry)
    _, bd, bi = carry
    best_d2[:] = bd
    best_idx[:] = bi

    @pl.when(j == n_j - 1)
    def _emit():
        # sort the k slots ascending by distance (k is tiny: selection sort
        # with the same min/mask trick)
        bd = best_d2[:]
        bi = best_idx[:]
        out_d = jnp.full_like(bd, _INF)
        out_i = jnp.full_like(bi, -1)
        wcol = jax.lax.broadcasted_iota(jnp.int32, bd.shape, 1)

        def sort_step(s, c):
            bd_c, bi_c, od, oi = c
            m = jnp.min(bd_c, axis=1, keepdims=True)
            mpos = jnp.min(
                jnp.where(bd_c == m, wcol, jnp.int32(2**30)), axis=1, keepdims=True
            )
            sel = wcol == mpos
            val_i = jnp.sum(jnp.where(sel, bi_c, 0), axis=1, keepdims=True)
            od = jnp.where(wcol == s, m, od)
            oi = jnp.where(wcol == s, val_i, oi)
            bd_c = jnp.where(sel, _INF, bd_c)
            return bd_c, bi_c, od, oi

        _, _, out_d, out_i = jax.lax.fori_loop(0, k, sort_step, (bd, bi, out_d, out_i))
        d2_out[:] = out_d
        idx_out[:] = out_i


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def knn_topk(Q, Xt, w, k: int, interpret: bool = False):
    """For each row of Q: the k smallest masked squared distances into Xt.

    Returns (d2 [nq, k] ascending, idx [nq, k] global train-row indices).
    Rows with w<=0 are excluded. Shapes are padded to tile multiples
    internally.
    """
    nq, d = Q.shape
    n = Xt.shape[0]
    k = int(k)

    nq_p = pl.cdiv(nq, _BQ) * _BQ
    n_p = pl.cdiv(n, _BT) * _BT
    Qp = jnp.zeros((nq_p, d), jnp.float32).at[:nq].set(Q.astype(jnp.float32))
    Xp = jnp.zeros((n_p, d), jnp.float32).at[:n].set(Xt.astype(jnp.float32))
    wp = jnp.zeros((n_p,), jnp.float32).at[:n].set(w.astype(jnp.float32))
    qsq = jnp.sum(Qp * Qp, axis=1, keepdims=True)          # [nq_p, 1]
    tsq = jnp.sum(Xp * Xp, axis=1)[None, :]                # [1, n_p]

    grid = (nq_p // _BQ, n_p // _BT)
    d2_out, idx_out = pl.pallas_call(
        functools.partial(_kernel, k=k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BQ, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BQ, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BT, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BT), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BT), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_BQ, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BQ, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nq_p, k), jnp.float32),
            jax.ShapeDtypeStruct((nq_p, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_BQ, k), jnp.float32),
            pltpu.VMEM((_BQ, k), jnp.int32),
        ],
        interpret=interpret,
    )(Qp, qsq, Xp, tsq, wp[None, :])
    return d2_out[:nq], idx_out[:nq]
