"""Histogram decision-tree builder: the TPU-native tree-learning core.

Replaces the sklearn tree fits the reference workers run per trial
(RandomForest*/GradientBoosting* rows of the whitelist,
``aws-prod/worker/worker.py:38-52``). sklearn's exact, depth-first,
sorted-split CART is sequential and pointer-chasing — the histogram
formulation (LightGBM-style) is the TPU shape of the same computation:

- features are pre-binned once per dataset into ``n_bins`` quantile bins
  (int codes), so a split candidate is (feature, bin);
- trees grow **level-wise** over a complete binary tree of static depth:
  at level l every sample sits at one of 2^l nodes, and all node×feature×bin
  histograms are built as one-hot matmul contractions on the MXU
  (``_level_histogram``; TPU scatters serialize, matmuls don't), with the
  right-child histograms derived by subtraction from the parent level,
  followed by a cumulative sum over bins;
- the split score is the unified proxy ``sum_k S_k^2 / C`` (left+right),
  which instantiates to variance gain (regression, S=sum y, C=count), gini
  gain (classification, S=class counts), and the Newton gain
  (boosting, S=grad sums, C=hess sums) — one builder serves RF and GBT;
- nodes that can't split become pass-through (route everything left), so
  shapes never depend on data.

Everything is jittable and vmappable over trials; per-node random feature
subsets (RF's max_features) use threshold-masked uniforms.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-12


def quantile_bins(X: np.ndarray, n_bins: int) -> np.ndarray:
    """Host-side: per-feature bin edges (n_bins-1 interior cutpoints) from
    quantiles of the full dataset. Computed once per dataset+n_bins and
    shared by every trial/fold (the reference re-reads and re-sorts data
    per subtask; here binning is a one-time cost).

    Duplicate quantiles (low-cardinality features — e.g. one-hot columns,
    where most quantiles coincide) are DEDUPED per feature and the tail
    padded with +inf: the distinct cut set is unchanged (identical split
    candidates), but bin codes become compact ([0, n_distinct]), which is
    what lets the deep builder histogram low-cardinality features in a
    narrow-bin group (see build_tree_deep ``groups``)."""
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T  # [d, n_bins-1]
    out = np.full(edges.shape, np.inf, np.float32)
    for f in range(edges.shape[0]):
        u = np.unique(edges[f])  # sorted, deduped
        out[f, : len(u)] = u
    return np.ascontiguousarray(out)


@jax.jit
def _bin_data_impl(X, edges):
    return jax.vmap(
        lambda col, e: jnp.searchsorted(e, col, side="right"), in_axes=(1, 0), out_axes=1
    )(X, edges).astype(jnp.int32)


def bin_data(X, edges) -> jnp.ndarray:
    """Map raw features to bin codes with per-column searchsorted (jitted:
    one cached executable per dataset shape, not per-primitive dispatches)."""
    return _bin_data_impl(jnp.asarray(X, jnp.float32), jnp.asarray(edges, jnp.float32))


_HIST_ROW_CHUNK = 16384


def _hist_kernel_mode() -> str:
    """CS230_HIST_KERNEL valve over the level-histogram implementations:

    - ``matmul``  — the XLA one-hot matmul contraction below (the
      pre-PR-6 form; both 0/1 operands materialize in HBM);
    - ``pallas``  — the fused Pallas kernel (ops/pallas_hist.py): one-hot
      tiles built in VMEM, accumulator page resident across row tiles;
    - ``scatter`` — the literal bin-and-scatter segment-sum form
      (O(n*d*kk) adds; the fast form without an MXU);
    - ``auto`` (default) — pallas on TPU for integer stats at eligible
      shapes, scatter on CPU, matmul otherwise.

    The valve is read at trace time and keyed into every executable cache
    via the tree kernels' ``trace_salt``.
    """
    mode = os.environ.get("CS230_HIST_KERNEL", "auto").lower()
    return mode if mode in ("auto", "matmul", "scatter", "pallas") else "auto"


def _resolve_hist_kernel(integer_stats: bool, ds, n_binss, kk: int) -> str:
    mode = _hist_kernel_mode()
    if mode != "auto":
        return mode
    backend = jax.default_backend()
    if backend == "tpu":
        from .pallas_hist import pallas_hist_applicable

        if integer_stats and all(
            pallas_hist_applicable(d, nb, kk) for d, nb in zip(ds, n_binss)
        ):
            return "pallas"
        return "matmul"  # float stats keep the HIGHEST-precision contraction
    if backend == "cpu":
        return "scatter"
    return "matmul"


def _level_histogram_multi(local, xbs, SC, n_nodes: int, n_binss,
                           precision=None, integer_stats: bool = False):
    """Feature-grouped level histograms in ONE row scan: a tuple of
    [n_nodes, d_g, nb_g, kk] histograms, one per (xb_g, nb_g) feature group.

    Computed as (one_hot(node) ⊗ SC)ᵀ @ one_hot(bins_g) over row chunks: two
    0/1 one-hot operands make the contraction a pure MXU matmul, replacing
    segment-sum scatters (which serialize on TPU and dominated tree-fit time
    ~10-30x). Rows stream through a lax.scan so peak memory is
    O(row_chunk · (n_nodes·kk + sum d_g·nb_g)) regardless of n.

    The left operand T1 = one_hot(node) ⊗ SC ([row_chunk, n_nodes*kk], the
    histogram's dominant memory-traffic term at wide frontiers) is built
    ONCE per chunk and contracted against every group's bin one-hot — this
    is why grouped histograms fuse into one scan instead of calling a
    single-group kernel per group (an A/B of the two-scan form measured NO
    win: the duplicated T1 traffic ate the narrower matmuls' savings).

    ``integer_stats``: the stat columns are small non-negative integers
    (< 128 — classification one-hots times bootstrap counts, which
    _bootstrap_counts caps): run the contraction as s8 x s8 -> s32 on the
    MXU (2x the bf16 rate on v5e), bit-exact by construction.

    The CS230_HIST_KERNEL valve (see ``_hist_kernel_mode``) can replace
    this whole contraction with the fused Pallas kernel or the
    bin-and-scatter segment-sum form — all three share the contract and
    the parity guarantees pinned in tests/test_pallas_hist.py.
    """
    n = xbs[0].shape[0]
    ds = tuple(xb.shape[1] for xb in xbs)
    kk = SC.shape[1]
    kern = _resolve_hist_kernel(integer_stats, ds, n_binss, kk)
    if kern == "scatter":
        from .pallas_hist import level_histogram_scatter

        return tuple(
            level_histogram_scatter(local, xb, SC, n_nodes, nb)
            for xb, nb in zip(xbs, n_binss)
        )
    if kern == "pallas":
        from .pallas_hist import level_histogram_pallas

        interp = jax.default_backend() != "tpu"
        return tuple(
            level_histogram_pallas(
                local, xb, SC, n_nodes, nb,
                integer_stats=integer_stats, interpret=interp,
            )
            for xb, nb in zip(xbs, n_binss)
        )
    rc = min(_HIST_ROW_CHUNK, n)
    n_pad = ((n + rc - 1) // rc) * rc
    if n_pad != n:
        # padded rows carry zero stats — they land in node 0/bin 0 cells
        # with zero contribution
        local = jnp.pad(local, (0, n_pad - n))
        xbs = tuple(jnp.pad(xb, ((0, n_pad - n), (0, 0))) for xb in xbs)
        SC = jnp.pad(SC, ((0, n_pad - n), (0, 0)))

    # Integer stats under DEFAULT precision ride the s8 MXU path (2x bf16
    # rate on v5e), exact by construction: 0/1 one-hots pick single <128
    # terms, accumulation in s32. Float stats keep their dtype — TPU's
    # in-dot DEFAULT truncation applies there, but an explicit bf16 cast
    # would ALSO degrade CPU/GPU backends (where DEFAULT is full f32).
    int8_path = bool(integer_stats) and precision in (
        None, jax.lax.Precision.DEFAULT
    )
    op_dt = jnp.int8 if int8_path else SC.dtype
    acc_dt = jnp.int32 if int8_path else jnp.float32

    def body(Hs, start):
        lb = jax.lax.dynamic_slice(local, (start,), (rc,))
        SCb = jax.lax.dynamic_slice(SC, (start, 0), (rc, kk)).astype(op_dt)
        N = jax.nn.one_hot(lb, n_nodes, dtype=op_dt)  # [rc, nodes]
        T1 = (N[:, :, None] * SCb[:, None, :]).reshape(rc, n_nodes * kk)
        out = []
        for H, xb, d, n_bins in zip(Hs, xbs, ds, n_binss):
            xbb = jax.lax.dynamic_slice(xb, (start, 0), (rc, d))
            B = (
                xbb[:, :, None]
                == jnp.arange(n_bins, dtype=xbb.dtype)[None, None, :]
            ).astype(op_dt).reshape(rc, d * n_bins)
            out.append(H + jnp.dot(
                T1.T,
                B,
                precision=None if int8_path else precision,
                preferred_element_type=acc_dt,
            ))
        return tuple(out), None

    H0 = tuple(
        jnp.zeros((n_nodes * kk, d * n_bins), acc_dt)
        for d, n_bins in zip(ds, n_binss)
    )
    starts = jnp.arange(0, n_pad, rc, dtype=jnp.int32)
    Hs, _ = jax.lax.scan(body, H0, starts)
    # rows are node-major over kk; cols feature-major over bins
    return tuple(
        H.astype(jnp.float32).reshape(n_nodes, kk, d, n_bins).transpose(
            0, 2, 3, 1
        )
        for H, d, n_bins in zip(Hs, ds, n_binss)
    )


def _level_histogram(local, xb, SC, n_nodes: int, n_bins: int, precision=None,
                     integer_stats: bool = False):
    """Single-group form of ``_level_histogram_multi`` (same contract as
    always: [n_nodes, d, n_bins, kk])."""
    return _level_histogram_multi(
        local, (xb,), SC, n_nodes, (n_bins,), precision, integer_stats
    )[0]


#: compact-histogram geometry (sparsity-exploiting level histograms below).
#: R rows per block, M one-hot node columns per block; arithmetic shrinks
#: by ~W/M relative to the dense one-hot form. Env-tunable for sweeps.
#:
#: MEASURED NEGATIVE RESULT (kept off by default, r3 A/B on v5e, 25%
#: Covertype RF: dense 87 ms vs compact 107 ms per tree-split): the W-fold
#: arithmetic redundancy of the dense one-hot matmul is CHEAPER on the MXU
#: than the row movement compaction needs — one [n]-row sort + two row
#: gathers cost ~2 ms/level/lane, more than the entire dense histogram
#: matmul they replace (~2 ms at peak). FLOPs are free; data movement
#: isn't. The kernel stays for narrow-MXU parts / future sweeps
#: (CS230_HIST_COMPACT=1), exactness covered by tests.
_COMPACT_R = int(os.environ.get("CS230_HIST_BLOCK_ROWS", "2048"))
_COMPACT_M = int(os.environ.get("CS230_HIST_BLOCK_NODES", "64"))
_COMPACT_ENABLE = os.environ.get("CS230_HIST_COMPACT", "0") == "1"


def _level_histogram_compact(local, xb, SC, n_nodes: int, n_bins: int,
                             precision=None, integer_stats: bool = False):
    """Sparsity-exploiting level histogram: same contract as
    ``_level_histogram`` ([n_nodes, d, n_bins, kk] from per-row stats), but
    ~W/M less arithmetic for wide frontiers.

    The dense form pays ``n x n_nodes`` one-hot work although each row
    belongs to exactly ONE node — a W-fold redundancy at the deep arena's
    W=256 (VERDICT r2 weak #2). This kernel compacts rows per node first
    (the LightGBM-style layout, rebuilt for static XLA shapes):

    1. sort rows by node id (dead rows, ``local == n_nodes``, sort last);
    2. rank each row by its node's *distinct index* in sorted order, and
       split ranks into supergroups of M distinct nodes; pad the sorted
       layout so every R-row block holds rows of ONE supergroup — then
       every block sees at most M distinct nodes BY CONSTRUCTION (no
       data-dependent fallback; at most ceil((n_nodes+1)/M) supergroups
       exist, so padding is bounded by K*R rows, all static);
    3. per block, contract a *narrow* one-hot ``[R, M*kk]`` against the
       bin one-hot ``[R, d*n_bins]`` on the MXU (this is where the W/M
       saving lives);
    4. route each block's M mini-rows to their global node rows with a
       small ``one_hot(slot_of) @ mini`` matmul (scatter-free).

    All steps are gathers, cumsums, and matmuls — no scatter, no cond —
    so the kernel vmaps over (trials, splits, trees) like the dense form.
    """
    n, d = xb.shape
    kk = SC.shape[1]
    R, M = _COMPACT_R, _COMPACT_M
    K = (n_nodes + 1 + M - 1) // M  # supergroups (incl. the dead id)
    n_blocks = (n + R - 1) // R + K  # upper bound incl. supergroup padding
    n_pad = n_blocks * R
    dt = jnp.bfloat16 if (n_bins <= 256 and precision in
                          (None, jax.lax.Precision.DEFAULT)) else jnp.float32

    # ---- 1. sort rows by node ----
    perm = jnp.argsort(local)
    sl = local[perm]

    # ---- 2. distinct-rank, supergroups, padded layout ----
    change = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), (sl[1:] != sl[:-1]).astype(jnp.int32)]
    )
    drank = jnp.cumsum(change)  # [n] global distinct index of each row
    sg = drank // M  # supergroup of each sorted row, < K
    # s[k] = first sorted index of supergroup k (n if absent)
    s = jnp.searchsorted(sg, jnp.arange(K + 1, dtype=jnp.int32), side="left")
    c = s[1:] - s[:-1]  # rows per supergroup
    padded_len = ((c + R - 1) // R) * R
    t = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(padded_len)]
    )  # padded start of each supergroup

    # source index for every padded position (gather form — no scatter)
    p = jnp.arange(n_pad, dtype=jnp.int32)
    k_p = jnp.clip(
        jnp.searchsorted(t, p, side="right") - 1, 0, K - 1
    )
    src = p - t[k_p] + s[k_p]
    valid = (src < s[k_p + 1]) & (p < t[K])
    src = jnp.where(valid, src, 0)

    # ---- gather the padded layout ----
    take = jnp.where(valid, perm[src], 0)
    xbs = jnp.take(xb, take, axis=0).astype(dt)  # [n_pad, d] codes
    SCs = jnp.where(
        valid[:, None], jnp.take(SC, take, axis=0), 0.0
    ).astype(dt)
    # block-local node rank, < M by construction
    loc = jnp.where(valid, drank[src] - M * k_p, M - 1)

    # ---- 3+4a. per-block narrow one-hot contraction, accumulated into
    # supergroup pages as we go. A block's m-th one-hot column is its
    # supergroup's distinct rank k*M + m — a GLOBAL coordinate — so each
    # block's mini histogram can be added straight onto its supergroup's
    # [M*kk, d*n_bins] page (dynamic_update_slice accumulate under scan).
    # Doing the block matmuls one-at-a-time this way keeps the working set
    # at one page instead of materializing the full [nb, M*kk, d*n_bins]
    # tensor (~750 MB/level at production shapes, profiled as the top
    # fusion cost of the naive form).
    locb = loc.reshape(n_blocks, R)
    xbsb = xbs.reshape(n_blocks, R, d)
    SCsb = SCs.reshape(n_blocks, R, kk)
    sg_of_block = k_p.reshape(n_blocks, R)[:, 0]  # [nb]

    def block_body(acc, args):
        lb, xbb, SCb, sg = args
        N = jax.nn.one_hot(lb, M, dtype=dt)  # [R, M]
        T1 = (N[:, :, None] * SCb[:, None, :]).reshape(R, M * kk)
        B = (
            xbb[:, :, None] == jnp.arange(n_bins, dtype=dt)[None, None, :]
        ).astype(dt).reshape(R, d * n_bins)
        page = jax.lax.dot_general(
            T1, B, (((0,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32,
        )  # [M*kk, d*n_bins]
        upd = jax.lax.dynamic_slice(
            acc, (sg, 0, 0), (1, M * kk, d * n_bins)
        ) + page[None]
        return jax.lax.dynamic_update_slice(acc, upd, (sg, 0, 0)), None

    acc0 = jnp.zeros((K, M * kk, d * n_bins), jnp.float32)
    acc, _ = jax.lax.scan(
        block_body, acc0, (locb, xbsb, SCsb, sg_of_block)
    )
    mini_sg = acc.reshape(K * M, kk, d * n_bins)
    # node id of global distinct rank q = sl at the first row with drank==q
    q = jnp.arange(K * M, dtype=jnp.int32)
    first = jnp.searchsorted(drank, q, side="left")
    nid = jnp.where(
        (first < n) & (jnp.take(drank, jnp.minimum(first, n - 1)) == q),
        jnp.take(sl, jnp.minimum(first, n - 1)),
        n_nodes,
    )
    route = jax.nn.one_hot(nid, n_nodes, dtype=jnp.float32)  # [K*M, W]
    H = jnp.einsum(
        "qw,qkx->wkx",
        route,
        mini_sg,
        precision=jax.lax.Precision.HIGHEST,
    )
    return H.reshape(n_nodes, kk, d, n_bins).transpose(0, 2, 3, 1)


def _use_compact(n: int, n_nodes: int) -> bool:
    """Static gate: compaction wins when the frontier is wider than the
    block one-hot (arithmetic shrinks ~n_nodes/M) and the data is large
    enough that the K*R padding overhead is amortized."""
    return (
        _COMPACT_ENABLE
        and n_nodes > 2 * _COMPACT_M
        and n >= 8 * _COMPACT_R
    )


def _split_gain(H, k: int, n_bins: int, min_samples_leaf: float):
    """Per-(node, feature, bin) split gain from a histogram.

    H: [m, d, n_bins, k+1] (stats + count). Returns gain [m, d, n_bins] with
    invalid candidates at -inf. The score is the unified S^2/C proxy (gini /
    variance / Newton gain depending on what S, C carry); identical math to
    the level-wise builder's inline version.
    """
    Sh = H[..., :k]
    Ch = jnp.maximum(H[..., k], 0.0)
    # prefix sums over bins as a triangular-ones contraction: jnp.cumsum on
    # the [.., n_bins, ..] axis lowers to a slow sequential/log-pass TPU
    # fusion (profiled ~30 ms per stage at production batch); the matmul is
    # one MXU pass over a [n_bins, n_bins] mask
    tri = jnp.tril(jnp.ones((n_bins, n_bins), jnp.float32))  # tri[b', b<=b']
    hp = jax.lax.Precision.HIGHEST
    Scum = jnp.einsum("mdbk,cb->mdck", Sh, tri, precision=hp)
    Ccum = jnp.einsum("mdb,cb->mdc", Ch, tri, precision=hp)
    S_tot = Scum[:, :, -1:, :]
    C_tot = Ccum[:, :, -1:]
    Sr = S_tot - Scum
    Cr = C_tot - Ccum
    gain = jnp.sum(Scum**2, -1) / jnp.maximum(Ccum, _EPS) + jnp.sum(
        Sr**2, -1
    ) / jnp.maximum(Cr, _EPS)
    parent = jnp.sum(S_tot**2, -1) / jnp.maximum(C_tot, _EPS)  # [m, d, 1]
    valid = (Ccum >= min_samples_leaf) & (Cr >= min_samples_leaf)
    # last bin = degenerate split (empty right)
    valid = valid & (jnp.arange(n_bins)[None, None, :] < n_bins - 1)
    return jnp.where(valid, gain - parent, -jnp.inf)


def _pick_best(gain, n_bins: int):
    """argmax over (feature, bin) per node: (best_gain, feat, bin)."""
    m = gain.shape[0]
    flat = gain.reshape(m, -1)
    best = jnp.argmax(flat, axis=1)
    bg = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    bf = (best // n_bins).astype(jnp.int32)
    bb = (best % n_bins).astype(jnp.int32)
    return bg, bf, bb


#: largest per-level node count handled by the gather-free routing /
#: leaf-aggregation forms below. Per-sample gathers from tiny tables
#: (``tab[node]``) and tiny-segment scatters (``segment_sum``) both lower to
#: serialized TPU kernels — profiled at ~45 ms per gather per level and
#: ~46 ms per segment_sum at a production trial batch (168 lanes x 29k
#: rows), which made them >95% of a GradientBoosting stage's device time.
#: The one-hot matmul / compare-reduce forms are MXU/VPU passes (~3-5 ms).
#: Past this node count the O(n*m) masked forms lose to the O(n) gather.
_LOOKUP_M = 256


def _col_select(xb, feats, n_bins: int):
    """[n, m] matrix whose column j is ``xb[:, feats[j]]`` — a dynamic
    column gather expressed as a one-hot contraction. Exact: bin codes are
    integers < 256, representable in bf16, and the one-hot picks a single
    term per output, so f32 accumulation reproduces the codes bit-exactly.
    """
    d = xb.shape[1]
    if n_bins > 256:  # codes could exceed bf16's exact-integer range
        oh = jax.nn.one_hot(feats, d, dtype=jnp.float32)
        return jnp.dot(
            xb.astype(jnp.float32), oh.T, precision=jax.lax.Precision.HIGHEST
        )
    oh = jax.nn.one_hot(feats, d, dtype=jnp.bfloat16)
    return jnp.dot(
        xb.astype(jnp.bfloat16), oh.T, preferred_element_type=jnp.float32
    )


def _route_left(xb, local, bf, bb, n_bins: int):
    """Per-sample go-left decision for one level, gather-free: compare every
    node's split column against its bin and mask-reduce by the sample's node
    id, instead of ``xb[arange(n), bf[local]] <= bb[local]``."""
    m = bf.shape[0]
    cols = _col_select(xb, bf, n_bins)                      # [n, m] f32
    le = cols <= bb[None, :].astype(cols.dtype)             # [n, m]
    oh = local[:, None] == jnp.arange(m, dtype=local.dtype)
    return jnp.any(oh & le, axis=1)


def _leaf_sums(leaf_local, SC, n_leaves: int):
    """``one_hot(leaf).T @ SC`` — scatter-free segment_sum over tree leaves.
    Exact one-hot selection with f32 accumulation; summation order differs
    from segment_sum only in float addition order (~1 ulp)."""
    oh = jax.nn.one_hot(leaf_local, n_leaves, dtype=SC.dtype)
    return jax.lax.dot_general(
        oh,
        SC,
        (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )


def _leaf_select(leaf_local, V, n_leaves: int):
    """``one_hot(leaf) @ V`` — gather-free ``V[leaf]`` for leaf-value
    lookup. Exact: the one-hot picks a single f32 row per sample."""
    oh = jax.nn.one_hot(leaf_local, n_leaves, dtype=V.dtype)
    return jnp.dot(oh, V, precision=jax.lax.Precision.HIGHEST)


def _feature_subset_allowed(node_ids, key, max_features: Optional[int], d: int):
    """[m, d] bool mask of each node's random feature subset (or None when
    all features are allowed), keyed by arena node id (fold_in) so chunked/
    monolithic fits draw identical subsets. The mask is computed over the
    GLOBAL feature space so grouped-histogram builds (which slice it per
    group) sample the same subsets as ungrouped builds."""
    if max_features is None or max_features >= d:
        return None

    def one(cid):
        return jax.random.uniform(jax.random.fold_in(key, cid), (d,))

    u = jax.vmap(one)(jnp.maximum(node_ids, 0))
    thresh = jnp.sort(u, axis=1)[:, max_features - 1 : max_features]
    return u <= thresh




def _hist_with_count_multi(local, xbs, SC, n_nodes, n_binss, precision, k,
                           count_from_stats: bool):
    """Feature-grouped level histograms, each [m, d_g, nb_g, k+1], in one
    row scan. When the stat columns sum to the count column exactly
    (classification: S = one_hot(y) * w, C = w), the count histogram is
    derived as the sum over class histograms instead of contracting an
    extra column — one fewer MXU row per node, exact."""
    if not count_from_stats:
        return _level_histogram_multi(local, xbs, SC, n_nodes, n_binss, precision)
    # count_from_stats == classification: stats are one_hot(y) x integer
    # bootstrap/fold counts (< 128 by _bootstrap_counts' cap) — the s8 MXU
    # path applies
    Hs = _level_histogram_multi(local, xbs, SC[:, :k], n_nodes, n_binss,
                                precision, integer_stats=True)
    return tuple(
        jnp.concatenate([H, jnp.sum(H, axis=-1, keepdims=True)], axis=-1)
        for H in Hs
    )


def _hist_with_count(local, xb, SC, n_nodes, n_bins, precision, k,
                     count_from_stats: bool):
    """Single-group level histogram [m, d, nb, k+1]. Wide frontiers on
    large data may route to the compacted (sparsity-exploiting) histogram;
    the static gate keeps the dense form where its one-hot is already
    narrow."""
    if _use_compact(xb.shape[0], n_nodes):
        if not count_from_stats:
            return _level_histogram_compact(local, xb, SC, n_nodes, n_bins, precision)
        H = _level_histogram_compact(local, xb, SC[:, :k], n_nodes, n_bins,
                                     precision, integer_stats=True)
        return jnp.concatenate([H, jnp.sum(H, axis=-1, keepdims=True)], axis=-1)
    return _hist_with_count_multi(
        local, (xb,), SC, n_nodes, (n_bins,), precision, k, count_from_stats
    )[0]


def build_tree(
    xb,
    S,
    C,
    *,
    depth: int,
    n_bins: int,
    min_samples_leaf: float = 1.0,
    max_features: Optional[int] = None,
    key=None,
    precision=jax.lax.Precision.HIGHEST,
    count_from_stats: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Fit one tree.

    xb: [n, d] int32 bin codes. S: [n, k] per-sample weighted target stats
    (already multiplied by sample weight). C: [n] per-sample weights
    (counts for RF, hessians for boosting; 0 = sample not in this fit).
    Returns {"split_feat" [2^depth-1], "split_bin" [2^depth-1],
    "leaf_val" [2^depth, k]}.

    precision: matmul precision for the histogram contraction. HIGHEST
    (default) for float-valued stats (boosting gradients); integer-valued
    stats (RF one-hot counts, exact in bf16) may pass DEFAULT for ~3x
    faster histograms with bit-identical sums.
    """
    n, d = xb.shape
    k = S.shape[1]
    S = S.astype(jnp.float32)
    C = C.astype(jnp.float32)
    n_internal = 2**depth - 1

    split_feat = jnp.zeros((n_internal,), jnp.int32)
    split_bin = jnp.full((n_internal,), n_bins - 1, jnp.int32)  # pass-through
    node = jnp.zeros((n,), jnp.int32)
    feat_ids = jnp.arange(d, dtype=jnp.int32)

    SC = jnp.concatenate([S, C[:, None]], axis=1)  # [n, k+1] stats+count

    H_prev = None
    for level in range(depth):
        n_nodes = 2**level
        base = n_nodes - 1
        local = node - base
        # histograms [n_nodes, d, n_bins, k+1] via one-hot matmuls on the
        # MXU (node/bin membership as 0/1 operands contracted over rows) —
        # TPU scatters serialize, matmuls don't. Levels past the root use
        # the subtraction trick: build only LEFT children (half the node
        # dim), right = parent − left (exact for integer stats; gains clamp
        # the f32 cancellation tails) — halves total histogram work.
        if level == 0:
            H = _hist_with_count(local, xb, SC, n_nodes, n_bins, precision,
                                 k, count_from_stats)
        else:
            went_left = (local % 2 == 0).astype(SC.dtype)
            H_left = _hist_with_count(
                local // 2, xb, SC * went_left[:, None], n_nodes // 2, n_bins,
                precision, k, count_from_stats,
            )
            H = jnp.stack([H_left, H_prev - H_left], axis=1).reshape(
                n_nodes, d, n_bins, k + 1
            )
        H_prev = H
        gain = _split_gain(H, k, n_bins, min_samples_leaf)

        if max_features is not None and max_features < d:
            key, sub = jax.random.split(key)
            u = jax.random.uniform(sub, (n_nodes, d))
            thresh = jnp.sort(u, axis=1)[:, max_features - 1 : max_features]
            allowed = u <= thresh
            gain = jnp.where(allowed[:, :, None], gain, -jnp.inf)

        best_gain, bf, bb = _pick_best(gain, n_bins)
        do_split = best_gain > 1e-7
        bf = jnp.where(do_split, bf, 0)
        bb = jnp.where(do_split, bb, n_bins - 1)

        split_feat = jax.lax.dynamic_update_slice(split_feat, bf, (base,))
        split_bin = jax.lax.dynamic_update_slice(split_bin, bb, (base,))

        if n_nodes <= _LOOKUP_M:
            go_left = _route_left(xb, local, bf, bb, n_bins)
        else:
            f_i = split_feat[node]
            b_i = split_bin[node]
            go_left = xb[jnp.arange(n), f_i] <= b_i
        node = 2 * node + 1 + jnp.where(go_left, 0, 1)

    leaf_local = node - n_internal
    n_leaves = 2**depth
    if n_leaves <= _LOOKUP_M:
        SCl = _leaf_sums(leaf_local, SC, n_leaves)
        Sl, Cl = SCl[:, :k], SCl[:, k]
    else:
        Sl = jax.ops.segment_sum(S, leaf_local, num_segments=n_leaves)
        Cl = jax.ops.segment_sum(C, leaf_local, num_segments=n_leaves)
    leaf_val = Sl / jnp.maximum(Cl, _EPS)[:, None]
    return {
        "split_feat": split_feat,
        "split_bin": split_bin,
        "leaf_val": leaf_val,
        "leaf_weight": Cl,
    }


# ---------------- out-of-core streamed builder ----------------
#
# build_tree's per-level work is two row reductions (the level histogram
# and, at the end, the leaf stat sums) plus O(2^depth) node-level math.
# Both reductions are plain sums over rows, so they block-accumulate: one
# streamed pass per level (route the pending previous-level split, then
# add the block's histogram contribution), one final pass for the last
# routing + leaf sums — depth + 1 passes total, with resident state only
# the per-sample node ids [n_pad] and stats [n_pad, k+1] (a few bytes per
# row vs the [n, d] bin matrix). For integer stats (RF classification:
# one-hot counts, the s8 histogram path) every partial sum is exact, so
# the streamed tree is BITWISE-identical to build_tree's — the parity
# tests/test_streaming.py pins split_feat/split_bin/leaf_val equality.
# Float stats (boosting gradients) match within f32 summation order.

#: jitted per-level block steps, keyed on static geometry so every tree
#: of every trial re-dispatches the same executables
_STREAM_TREE_FNS: Dict[Any, Any] = {}


def _stream_tree_level_fn(d, k, n_bins, level, precision, count_from_stats):
    """One block's step of streamed level ``level``: apply the pending
    previous-level routing to the block's rows, then accumulate the
    block's contribution to the level histogram (left-children only past
    the root — the subtraction trick runs AFTER the pass, on the summed
    histogram, exactly as in build_tree)."""
    ckey = ("level", d, k, n_bins, level, precision, count_from_stats)
    fn = _STREAM_TREE_FNS.get(ckey)
    if fn is not None:
        return fn
    n_nodes = 2**level
    base = n_nodes - 1

    @jax.jit
    def fn(carry, SC, bf, bb, xb_b, start):
        node, H = carry
        rows = xb_b.shape[0]
        nb = jax.lax.dynamic_slice(node, (start,), (rows,))
        scb = jax.lax.dynamic_slice(SC, (start, 0), (rows, SC.shape[1]))
        if level > 0:
            prev_nodes = n_nodes // 2
            prev_base = prev_nodes - 1
            lp = nb - prev_base
            if prev_nodes <= _LOOKUP_M:
                go_left = _route_left(xb_b, lp, bf, bb, n_bins)
            else:
                go_left = xb_b[jnp.arange(rows), bf[lp]] <= bb[lp]
            nb = 2 * nb + 1 + jnp.where(go_left, 0, 1)
            node = jax.lax.dynamic_update_slice(node, nb, (start,))
        local = nb - base
        if level == 0:
            Hb = _hist_with_count(local, xb_b, scb, n_nodes, n_bins,
                                  precision, k, count_from_stats)
        else:
            went_left = (local % 2 == 0).astype(scb.dtype)
            Hb = _hist_with_count(
                local // 2, xb_b, scb * went_left[:, None], n_nodes // 2,
                n_bins, precision, k, count_from_stats,
            )
        return node, H + Hb

    _STREAM_TREE_FNS[ckey] = fn
    return fn


def _stream_tree_leaf_fn(d, k, n_bins, depth):
    """The final streamed pass: apply the last level's pending routing,
    then accumulate per-leaf stat sums."""
    ckey = ("leaf", d, k, n_bins, depth)
    fn = _STREAM_TREE_FNS.get(ckey)
    if fn is not None:
        return fn
    n_internal = 2**depth - 1
    n_leaves = 2**depth
    prev_nodes = 2 ** (depth - 1)
    prev_base = prev_nodes - 1

    @jax.jit
    def fn(carry, SC, bf, bb, xb_b, start):
        node, SCl = carry
        rows = xb_b.shape[0]
        nb = jax.lax.dynamic_slice(node, (start,), (rows,))
        scb = jax.lax.dynamic_slice(SC, (start, 0), (rows, SC.shape[1]))
        lp = nb - prev_base
        if prev_nodes <= _LOOKUP_M:
            go_left = _route_left(xb_b, lp, bf, bb, n_bins)
        else:
            go_left = xb_b[jnp.arange(rows), bf[lp]] <= bb[lp]
        nb = 2 * nb + 1 + jnp.where(go_left, 0, 1)
        node = jax.lax.dynamic_update_slice(node, nb, (start,))
        leaf_local = nb - n_internal
        if n_leaves <= _LOOKUP_M:
            add = _leaf_sums(leaf_local, scb, n_leaves)
        else:
            add = jnp.concatenate(
                [
                    jax.ops.segment_sum(
                        scb[:, :k], leaf_local, num_segments=n_leaves
                    ),
                    jax.ops.segment_sum(
                        scb[:, k], leaf_local, num_segments=n_leaves
                    )[:, None],
                ],
                axis=1,
            )
        return node, SCl + add

    _STREAM_TREE_FNS[ckey] = fn
    return fn


def build_tree_streamed(
    stream_pass,
    S,
    C,
    d: int,
    *,
    depth: int,
    n_bins: int,
    min_samples_leaf: float = 1.0,
    max_features: Optional[int] = None,
    key=None,
    precision=jax.lax.Precision.HIGHEST,
    count_from_stats: bool = False,
):
    """build_tree over streamed row blocks: depth + 1 passes, identical
    split/leaf math.

    ``stream_pass(fn, carry, *consts)`` must run one ascending pass over
    the bin-code blocks, folding ``carry = fn(carry, *consts, xb_b,
    start)`` per block (the kernel drivers wrap a RowBlockStreamer plus
    the staged-form decode). ``S``/``C`` are the full padded per-sample
    stats/counts — zero on pad rows, so pads land in node 0's histograms
    with zero weight and contribute nothing anywhere, exactly like a
    zero-count sample in build_tree.

    Returns ``(tree, node)`` where ``tree`` matches build_tree's dict and
    ``node`` is the final per-sample node id array — prediction for the
    fitting dataset is a resident ``leaf_val[node - n_internal]`` lookup,
    no extra pass over the data. The per-level random feature subsets
    consume ``key`` in build_tree's exact split order, so subset draws
    are bitwise-identical."""
    if depth < 1:
        raise ValueError("build_tree_streamed requires depth >= 1")
    n_pad = S.shape[0]
    k = S.shape[1]
    S = S.astype(jnp.float32)
    C = C.astype(jnp.float32)
    SC = jnp.concatenate([S, C[:, None]], axis=1)
    n_internal = 2**depth - 1

    split_feat = jnp.zeros((n_internal,), jnp.int32)
    split_bin = jnp.full((n_internal,), n_bins - 1, jnp.int32)
    node = jnp.zeros((n_pad,), jnp.int32)

    H_prev = None
    bf = jnp.zeros((1,), jnp.int32)
    bb = jnp.zeros((1,), jnp.int32)
    for level in range(depth):
        n_nodes = 2**level
        base = n_nodes - 1
        fn = _stream_tree_level_fn(d, k, n_bins, level, precision,
                                   count_from_stats)
        H0 = jnp.zeros(
            (n_nodes if level == 0 else n_nodes // 2, d, n_bins, k + 1),
            jnp.float32,
        )
        node, Hl = stream_pass(fn, (node, H0), SC, bf, bb)
        if level == 0:
            H = Hl
        else:
            H = jnp.stack([Hl, H_prev - Hl], axis=1).reshape(
                n_nodes, d, n_bins, k + 1
            )
        H_prev = H
        gain = _split_gain(H, k, n_bins, min_samples_leaf)

        if max_features is not None and max_features < d:
            key, sub = jax.random.split(key)
            u = jax.random.uniform(sub, (n_nodes, d))
            thresh = jnp.sort(u, axis=1)[:, max_features - 1 : max_features]
            allowed = u <= thresh
            gain = jnp.where(allowed[:, :, None], gain, -jnp.inf)

        best_gain, bf, bb = _pick_best(gain, n_bins)
        do_split = best_gain > 1e-7
        bf = jnp.where(do_split, bf, 0)
        bb = jnp.where(do_split, bb, n_bins - 1)

        split_feat = jax.lax.dynamic_update_slice(split_feat, bf, (base,))
        split_bin = jax.lax.dynamic_update_slice(split_bin, bb, (base,))

    leaf_fn = _stream_tree_leaf_fn(d, k, n_bins, depth)
    SCl0 = jnp.zeros((2**depth, k + 1), jnp.float32)
    node, SCl = stream_pass(leaf_fn, (node, SCl0), SC, bf, bb)
    Sl, Cl = SCl[:, :k], SCl[:, k]
    leaf_val = Sl / jnp.maximum(Cl, _EPS)[:, None]
    tree = {
        "split_feat": split_feat,
        "split_bin": split_bin,
        "leaf_val": leaf_val,
        "leaf_weight": Cl,
    }
    return tree, node


#: features with at most this many bin codes qualify for the deep builder's
#: narrow coarse-histogram group (one-hot/binary columns: 2 codes)
COARSE_BINS = int(os.environ.get("CS230_COARSE_BINS", "4"))


def build_tree_deep(
    xb,
    S,
    C,
    *,
    levels: int,
    width: int,
    n_bins: int,
    min_samples_leaf: float = 1.0,
    max_features: Optional[int] = None,
    key=None,
    precision=jax.lax.Precision.HIGHEST,
    count_from_stats: bool = False,
    groups: Optional[Dict[str, jnp.ndarray]] = None,
    w_schedule: Optional[Tuple[int, int, int]] = None,
    nb_schedule: Optional[Tuple[int, int]] = None,
) -> Dict[str, jnp.ndarray]:
    """Deep tree via frontier-compacted level-wise growth (batched best-first).

    The complete-tree builder above pays 2^level histogram rows per level —
    infeasible past depth ~10. sklearn's ``max_depth=None`` grows to purity
    (depth 25-45 on Covertype-scale data, the reference's exact-CART fit at
    ``aws-prod/worker/worker.py:315``), so this builder keeps an *arena* of
    nodes and, per level, histograms only an active frontier of at most
    ``width`` nodes:

    - each level: split every frontier node whose best gain is positive;
      histogram the LEFT children mapped to parent slots (one matmul with
      one-hot dim ``width``), derive right children by subtraction — so both
      children's exact best-split gains are known for the cost of one
      histogram;
    - the next frontier = top-``width`` children by their OWN best gain
      (``lax.top_k``) — true-gain best-first selection, not a proxy; children
      not selected (budget) or unsplittable (gain <= eps, min_samples_leaf)
      become leaves;
    - per-level cost is O(n * width * kk * d * n_bins) MACs regardless of
      depth, all on the MXU; total leaf budget ~ width * levels (~12k at the
      defaults), the regime sklearn's grow-to-purity needs.

    ``groups`` (optional): feature-grouped histograms. Low-cardinality
    features (one-hot/binary columns — 44 of Covertype's 54) waste nearly
    the whole n_bins axis of the histogram, and per-level cost is linear in
    the bin total; splitting features into a continuous group (full n_bins)
    and a coarse group (COARSE_BINS bins) cuts histogram MACs by
    sum(nb_f)/d*n_bins — ~3x on Covertype — with the identical split
    candidate set (quantile_bins dedup makes coarse codes compact). The
    dict carries {"xb_cont" [n, dc], "xb_coarse" [n, db], "fid_cont" [dc],
    "fid_coarse" [db]}; split records stay in GLOBAL feature ids, so
    routing, prediction, and artifacts are unchanged.

    ``nb_schedule`` (occ_w, nb_deep): ADAPTIVE bin resolution by frontier
    occupancy. Split resolution matters most while nodes are big (early,
    narrow frontier) and the histogram conv's cost is linear in bins x
    frontier width (the per-level MXU term profiled as >50% of a deep
    level) — so candidate evaluation runs at the full ``n_bins`` while the
    candidate frontier is <= occ_w nodes, and at the 2^s-fold coarser
    ``nb_deep`` beyond. Coarse candidates are formed by summing ADJACENT
    fine histogram bins, so the coarse candidate set is an exact subset of
    the fine threshold set: split records stay in FINE bin units (the last
    fine code of the chosen coarse bin) and routing/prediction/artifacts
    are untouched. Resolution is monotone non-increasing over levels (a
    width-schedule drop never re-raises it).

    Shapes are static: the frontier width at level l is min(2^l, width)
    (early levels don't pay the full budget), the arena is a fixed
    ``2*width*levels + 2`` slots, and routing state is one int32 per sample.
    Returns {"feat","bin","child" [A+1], "leaf_val" [A+1, k]}; ``child`` is
    the left-child arena id (0 = leaf; right child = left + 1).
    """
    n, d = xb.shape
    k = S.shape[1]
    S = S.astype(jnp.float32)
    C = C.astype(jnp.float32)
    # decaying width schedule (hi, split_level, lo): full breadth while
    # nodes are big, prune past split_level — per-level cost is linear in
    # the frontier width, and deep levels split mostly-pure low-gain
    # nodes, so narrowing them buys wall time at small CV cost (measured
    # on full Covertype: (1024, 16, 512) = 232 -> 176 s at -0.0017 CV).
    # ``w_schedule`` comes from the kernel's resolved static (production
    # path, in every cache key); env CS230_DEEP_WSCHED is the sweep hook
    # and takes precedence (keyed via trace_salt).
    sched = os.environ.get("CS230_DEEP_WSCHED", "")
    if sched:
        w_schedule = tuple(int(x) for x in sched.split(":"))
    if w_schedule is not None:
        w_hi, w_split, w_lo = (int(x) for x in w_schedule)
        width_at = lambda lvl: w_hi if lvl < w_split else w_lo  # noqa: E731
        width = max(w_hi, w_lo)
    else:
        width_at = lambda lvl: width  # noqa: E731
    A = 2 * width * levels + 2  # arena capacity; index A = scratch slot
    SC = jnp.concatenate([S, C[:, None]], axis=1)
    if key is None:
        key = jax.random.PRNGKey(0)

    feat_a = jnp.zeros((A + 1,), jnp.int32)
    bin_a = jnp.full((A + 1,), n_bins - 1, jnp.int32)
    child_a = jnp.zeros((A + 1,), jnp.int32)
    node = jnp.zeros((n,), jnp.int32)
    n_alloc = jnp.int32(1)
    # per-level routing tables [levels, width] for the gather-free predict
    # walk: arena id / split column / bin / left child of every node SPLIT
    # at that level (-1 id = no node). predict_tree_deep routes with the
    # same compare/matmul forms the fit uses, instead of per-row gathers
    # from the [A+1] arena tables (profiled ~3x slower).
    lvl_ids, lvl_feat, lvl_bin, lvl_left = [], [], [], []

    # feature groups: (xb columns, global feature ids or None, bin count)
    if groups is not None:
        gspec = (
            (groups["xb_cont"], groups["fid_cont"], n_bins),
            (groups["xb_coarse"], groups["fid_coarse"], COARSE_BINS),
        )
    else:
        gspec = ((xb, None, n_bins),)

    # adaptive bin resolution (docstring): r(level) = n_bins while the
    # candidate frontier is narrow, nb_deep once wide; monotone. Applies
    # to groups histogrammed at the full n_bins (the continuous/single
    # group) — the COARSE_BINS group is already minimal.
    nbsched = os.environ.get("CS230_DEEP_NBSCHED", "")
    if nbsched:
        occ_w, nb_deep = (int(x) for x in nbsched.split(":"))
        nb_schedule = (occ_w, nb_deep)
    if nb_schedule is not None:
        occ_w, nb_deep = (int(x) for x in nb_schedule)
        if nb_deep <= 0 or n_bins % max(nb_deep, 1) or nb_deep > n_bins:
            raise ValueError(
                f"nb_schedule deep bins {nb_deep} must divide n_bins {n_bins}"
            )
    else:
        occ_w, nb_deep = 0, n_bins

    def res_at(cand_w: int) -> int:
        # strict <: a band whose saturated candidate frontier equals occ_w
        # (2 x its width cap) must go coarse AT saturation, not stay fine
        return n_bins if (occ_w <= 0 or cand_w < occ_w) else nb_deep

    def g_res(r: int, nbg: int) -> int:
        # per-group resolution: only full-resolution groups follow r
        return r if nbg == n_bins else nbg

    def coarsen(H, r_from: int, r_to: int):
        if r_from == r_to:
            return H
        m, dg, _, kkp = H.shape
        return H.reshape(m, dg, r_to, r_from // r_to, kkp).sum(3)

    def hist_groups(local, m, r):
        xgs = tuple(
            xg if g_res(r, nbg) == nbg else xg // (nbg // r)
            for xg, _, nbg in gspec
        )
        nbs = tuple(g_res(r, nbg) for _, _, nbg in gspec)
        if len(gspec) == 1:
            # single group: keep the compact-histogram opt-in gate reachable
            # (_use_compact routes wide frontiers when CS230_HIST_COMPACT=1)
            return (_hist_with_count(
                local, xgs[0], SC, m, nbs[0], precision, k,
                count_from_stats,
            ),)
        # ONE row scan for all groups: the dominant [row_chunk, m*kk]
        # one-hot ⊗ stats operand is built once and contracted against each
        # group's bin one-hot (see _level_histogram_multi)
        return _hist_with_count_multi(
            local,
            xgs,
            SC, m,
            nbs,
            precision, k, count_from_stats,
        )

    def best_from_hists(Hs, node_ids, r):
        """Per-node best (gain, GLOBAL feature, FINE bin) across groups;
        ties keep the earlier group (continuous first)."""
        allowed = _feature_subset_allowed(node_ids, key, max_features, d)
        best = None
        for Hg, (_, fidg, nbg) in zip(Hs, gspec):
            rg = g_res(r, nbg)
            g = _split_gain(Hg, k, rg, min_samples_leaf)
            if allowed is not None:
                ag = allowed if fidg is None else jnp.take(allowed, fidg, axis=1)
                g = jnp.where(ag[:, :, None], g, -jnp.inf)
            bg, bfl, bbl = _pick_best(g, rg)
            if rg != nbg:
                # coarse candidate b covers fine codes [b*ratio, (b+1)*ratio)
                # -> the equivalent FINE threshold is its last code
                bbl = (bbl + 1) * (nbg // rg) - 1
            bfg = bfl if fidg is None else jnp.take(fidg, bfl).astype(jnp.int32)
            if best is None:
                best = (bg, bfg, bbl)
            else:
                new = bg > best[0]
                best = (
                    jnp.maximum(bg, best[0]),
                    jnp.where(new, bfg, best[1]),
                    jnp.where(new, bbl, best[2]),
                )
        return best

    # root: full histogram + its best split
    frontier = jnp.zeros((1,), jnp.int32)
    r_H = res_at(2)
    H = hist_groups(node, 1, r_H)
    gain, bf, bb = best_from_hists(H, frontier, r_H)

    for level in range(levels):
        W_l = frontier.shape[0]
        do_split = (gain > 1e-7) & (frontier >= 0)
        rank_inc = jnp.cumsum(do_split.astype(jnp.int32))
        do_split = do_split & (n_alloc + 2 * rank_inc <= A)
        rank_inc = jnp.cumsum(do_split.astype(jnp.int32))
        rank_exc = rank_inc - do_split.astype(jnp.int32)
        left_id = n_alloc + 2 * rank_exc

        # write split records; masked rows land in the scratch slot A
        idx = jnp.where(do_split, frontier, A)
        feat_a = feat_a.at[idx].set(jnp.where(do_split, bf, 0))
        bin_a = bin_a.at[idx].set(jnp.where(do_split, bb, n_bins - 1))
        child_a = child_a.at[idx].set(jnp.where(do_split, left_id, 0))

        # route samples sitting in split nodes to their children —
        # gather-free: per-row arena-table gathers (slot_tab[node],
        # tab[slot], xb[arange, f]) serialize on TPU (~1.9 ms/level/lane
        # profiled at 25% Covertype vs 0.57 ms for this compare/matmul
        # form). Frontier width <= W keeps the [n, W_l] masks small.
        eq = node[:, None] == jnp.where(frontier >= 0, frontier, -1)[None, :]
        slot = jnp.where(
            eq.any(1), jnp.argmax(eq, axis=1), W_l
        ).astype(jnp.int32)
        in_split = (eq & do_split[None, :]).any(1)
        # per-node split column for each row, as a one-hot matmul column
        # select (bf16 exact: codes < 256); threshold compare per node
        cols = _col_select(xb, bf, n_bins)                     # [n, W_l]
        le_node = cols <= bb[None, :].astype(cols.dtype)
        go_left = jnp.any(eq & le_node, axis=1)
        # left-child ids can exceed bf16's exact range: f32 one-hot matmul
        l_i = jnp.dot(
            eq.astype(jnp.float32),
            left_id.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)
        node = jnp.where(
            in_split, l_i + 1 - go_left.astype(jnp.int32), node
        )
        n_alloc = n_alloc + 2 * rank_inc[-1]

        pad = width - W_l
        lvl_ids.append(jnp.pad(
            jnp.where(do_split, frontier, -1), (0, pad), constant_values=-1))
        lvl_feat.append(jnp.pad(bf, (0, pad)))
        lvl_bin.append(jnp.pad(bb, (0, pad)))
        lvl_left.append(jnp.pad(left_id, (0, pad)))

        if level == levels - 1:
            break  # children of the last level are leaves

        # children's histograms: left by matmul over parent slots, right by
        # subtraction (exact for integer stats; float tails are gain-clamped)
        local_left = jnp.where(in_split & go_left, slot, W_l)
        # candidate resolution for this level's 2*W_l children (monotone
        # non-increasing); parents coarsen by adjacent-bin sums — exact
        r_c = min(r_H, res_at(2 * W_l))
        if r_c != r_H:
            H = tuple(
                coarsen(h, g_res(r_H, nbg), g_res(r_c, nbg))
                for h, (_, _, nbg) in zip(H, gspec)
            )
            r_H = r_c
        H_L = hist_groups(local_left, W_l, r_c)
        cand_H = tuple(
            jnp.concatenate([hl, h - hl], axis=0)  # [2*W_l, d_g, nb_g, k+1]
            for h, hl in zip(H, H_L)
        )
        cand_id = jnp.concatenate(
            [jnp.where(do_split, left_id, -1), jnp.where(do_split, left_id + 1, -1)]
        )
        cgain, cbf, cbb = best_from_hists(cand_H, cand_id, r_c)
        cgain = jnp.where(cand_id >= 0, cgain, -jnp.inf)

        W_next = min(2 * W_l, width_at(level + 1))
        vals, sel = jax.lax.top_k(cgain, W_next)
        live = vals > -jnp.inf
        frontier = jnp.where(live, cand_id[sel], -1)
        gain = vals
        bf = cbf[sel]
        bb = cbb[sel]
        H = tuple(h[sel] for h in cand_H)

    leaf_S = jax.ops.segment_sum(S, node, num_segments=A + 1)
    leaf_C = jax.ops.segment_sum(C, node, num_segments=A + 1)
    leaf_val = leaf_S / jnp.maximum(leaf_C, _EPS)[:, None]
    return {
        "feat": feat_a,
        "bin": bin_a,
        "child": child_a,
        "leaf_val": leaf_val,
        "leaf_weight": leaf_C,
        "level_ids": jnp.stack(lvl_ids),
        "level_feat": jnp.stack(lvl_feat),
        "level_bin": jnp.stack(lvl_bin),
        "level_left": jnp.stack(lvl_left),
    }


@partial(jax.jit, static_argnames=("levels",))
def _route_deep(xb, feat, bins, child, levels: int):
    n = xb.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for _ in range(levels):
        c = child[node]
        go_left = xb[jnp.arange(n), feat[node]] <= bins[node]
        node = jnp.where(c > 0, c + 1 - go_left.astype(jnp.int32), node)
    return node


@partial(jax.jit, static_argnames=("levels", "n_bins"))
def _route_deep_levels(xb, level_ids, level_feat, level_bin, level_left,
                       levels: int, n_bins: int):
    """Gather-free arena routing: at step l a row advances iff its node is
    in that level's split table (a node is split at exactly one level, so
    the walk is equivalent to the child[node] gather walk — profiled ~3x
    faster: [n, W] compare/one-hot-matmul forms instead of three per-row
    [A+1]-table gathers per level)."""
    n = xb.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for lvl in range(levels):
        ids = level_ids[lvl]
        eq = node[:, None] == ids[None, :]  # -1 ids never match (node >= 0)
        in_split = eq.any(1)
        cols = _col_select(xb, level_feat[lvl], n_bins or 1 << 30)
        le = cols <= level_bin[lvl][None, :].astype(cols.dtype)
        go_left = jnp.any(eq & le, axis=1)
        l_i = jnp.dot(
            eq.astype(jnp.float32),
            level_left[lvl].astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)
        node = jnp.where(in_split, l_i + 1 - go_left.astype(jnp.int32), node)
    return node


def predict_tree_deep(xb, tree, levels: int, n_bins: int = 0):
    """Leaf values for binned query rows against an arena tree. Trees
    fitted with per-level routing tables take the gather-free walk;
    older artifacts fall back to the arena-table gather walk."""
    if "level_ids" in tree:
        leaf = _route_deep_levels(
            xb, tree["level_ids"], tree["level_feat"], tree["level_bin"],
            tree["level_left"], levels, n_bins,
        )
    else:
        leaf = _route_deep(xb, tree["feat"], tree["bin"], tree["child"], levels)
    return tree["leaf_val"][leaf]


@partial(jax.jit, static_argnames=("depth", "n_bins"))
def _route(xb, split_feat, split_bin, depth: int, n_bins: int = 0):
    n = xb.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for level in range(depth):
        base, m = 2**level - 1, 2**level
        if m <= _LOOKUP_M:
            # gather-free: this level's split records are a static slice
            bf = jax.lax.slice(split_feat, (base,), (base + m,))
            bb = jax.lax.slice(split_bin, (base,), (base + m,))
            go_left = _route_left(xb, node - base, bf, bb, n_bins or 1 << 30)
        else:
            f_i = split_feat[node]
            b_i = split_bin[node]
            go_left = xb[jnp.arange(n), f_i] <= b_i
        node = 2 * node + 1 + jnp.where(go_left, 0, 1)
    return node - (2**depth - 1)


def predict_tree(xb, tree, depth: int, n_bins: int = 0):
    """Leaf values for each row of binned query data. ``n_bins`` (when
    known) lets the gather-free router use the fast bf16 column select."""
    leaf = _route(xb, tree["split_feat"], tree["split_bin"], depth, n_bins)
    n_leaves = 2**depth
    if n_leaves <= _LOOKUP_M:
        return _leaf_select(leaf, tree["leaf_val"], n_leaves)
    return tree["leaf_val"][leaf]
