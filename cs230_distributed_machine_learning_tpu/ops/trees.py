"""Histogram decision-tree builder: the TPU-native tree-learning core.

Replaces the sklearn tree fits the reference workers run per trial
(RandomForest*/GradientBoosting* rows of the whitelist,
``aws-prod/worker/worker.py:38-52``). sklearn's exact, depth-first,
sorted-split CART is sequential and pointer-chasing — the histogram
formulation (LightGBM-style) is the TPU shape of the same computation:

- features are pre-binned once per dataset into ``n_bins`` quantile bins
  (int codes), so a split candidate is (feature, bin);
- trees grow **level-wise** over a complete binary tree of static depth:
  at level l every sample sits at one of 2^l nodes, and all node×feature×bin
  histograms are built as one-hot matmul contractions on the MXU
  (``_level_histogram``; TPU scatters serialize, matmuls don't), with the
  right-child histograms derived by subtraction from the parent level,
  followed by a cumulative sum over bins;
- the split score is the unified proxy ``sum_k S_k^2 / C`` (left+right),
  which instantiates to variance gain (regression, S=sum y, C=count), gini
  gain (classification, S=class counts), and the Newton gain
  (boosting, S=grad sums, C=hess sums) — one builder serves RF and GBT;
- nodes that can't split become pass-through (route everything left), so
  shapes never depend on data.

Everything is jittable and vmappable over trials; per-node random feature
subsets (RF's max_features) use threshold-masked uniforms.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-12


def quantile_bins(X: np.ndarray, n_bins: int) -> np.ndarray:
    """Host-side: per-feature bin edges (n_bins-1 interior cutpoints) from
    quantiles of the full dataset. Computed once per dataset+n_bins and
    shared by every trial/fold (the reference re-reads and re-sorts data
    per subtask; here binning is a one-time cost)."""
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0)  # [n_bins-1, d]
    return np.ascontiguousarray(edges.T.astype(np.float32))  # [d, n_bins-1]


@jax.jit
def _bin_data_impl(X, edges):
    return jax.vmap(
        lambda col, e: jnp.searchsorted(e, col, side="right"), in_axes=(1, 0), out_axes=1
    )(X, edges).astype(jnp.int32)


def bin_data(X, edges) -> jnp.ndarray:
    """Map raw features to bin codes with per-column searchsorted (jitted:
    one cached executable per dataset shape, not per-primitive dispatches)."""
    return _bin_data_impl(jnp.asarray(X, jnp.float32), jnp.asarray(edges, jnp.float32))


_HIST_ROW_CHUNK = 16384


def _level_histogram(local, xb, SC, n_nodes: int, n_bins: int, precision=None):
    """[n_nodes, d, n_bins, kk] histogram of per-sample stats ``SC`` grouped
    by (tree node, feature, bin code).

    Computed as (one_hot(node) ⊗ SC)ᵀ @ one_hot(bins) over row chunks: two
    0/1 one-hot operands make the contraction a pure MXU matmul, replacing
    segment-sum scatters (which serialize on TPU and dominated tree-fit time
    ~10-30x). Rows stream through a lax.scan so peak memory is
    O(row_chunk · (n_nodes·kk + d·n_bins)) regardless of n.
    """
    n, d = xb.shape
    kk = SC.shape[1]
    rc = min(_HIST_ROW_CHUNK, n)
    n_pad = ((n + rc - 1) // rc) * rc
    if n_pad != n:
        # padded rows carry zero stats — they land in node 0/bin 0 cells
        # with zero contribution
        local = jnp.pad(local, (0, n_pad - n))
        xb = jnp.pad(xb, ((0, n_pad - n), (0, 0)))
        SC = jnp.pad(SC, ((0, n_pad - n), (0, 0)))

    def body(H, start):
        lb = jax.lax.dynamic_slice(local, (start,), (rc,))
        xbb = jax.lax.dynamic_slice(xb, (start, 0), (rc, d))
        SCb = jax.lax.dynamic_slice(SC, (start, 0), (rc, kk))
        N = jax.nn.one_hot(lb, n_nodes, dtype=SCb.dtype)  # [rc, nodes]
        T1 = (N[:, :, None] * SCb[:, None, :]).reshape(rc, n_nodes * kk)
        B = (
            xbb[:, :, None] == jnp.arange(n_bins, dtype=xbb.dtype)[None, None, :]
        ).astype(SCb.dtype).reshape(rc, d * n_bins)
        H = H + jnp.dot(
            T1.T,
            B,
            precision=precision,
            preferred_element_type=jnp.float32,
        )
        return H, None

    H0 = jnp.zeros((n_nodes * kk, d * n_bins), jnp.float32)
    starts = jnp.arange(0, n_pad, rc, dtype=jnp.int32)
    H, _ = jax.lax.scan(body, H0, starts)
    # rows are node-major over kk; cols feature-major over bins
    return H.reshape(n_nodes, kk, d, n_bins).transpose(0, 2, 3, 1)


def _split_gain(H, k: int, n_bins: int, min_samples_leaf: float):
    """Per-(node, feature, bin) split gain from a histogram.

    H: [m, d, n_bins, k+1] (stats + count). Returns gain [m, d, n_bins] with
    invalid candidates at -inf. The score is the unified S^2/C proxy (gini /
    variance / Newton gain depending on what S, C carry); identical math to
    the level-wise builder's inline version.
    """
    Sh = H[..., :k]
    Ch = jnp.maximum(H[..., k], 0.0)
    # prefix sums over bins as a triangular-ones contraction: jnp.cumsum on
    # the [.., n_bins, ..] axis lowers to a slow sequential/log-pass TPU
    # fusion (profiled ~30 ms per stage at production batch); the matmul is
    # one MXU pass over a [n_bins, n_bins] mask
    tri = jnp.tril(jnp.ones((n_bins, n_bins), jnp.float32))  # tri[b', b<=b']
    hp = jax.lax.Precision.HIGHEST
    Scum = jnp.einsum("mdbk,cb->mdck", Sh, tri, precision=hp)
    Ccum = jnp.einsum("mdb,cb->mdc", Ch, tri, precision=hp)
    S_tot = Scum[:, :, -1:, :]
    C_tot = Ccum[:, :, -1:]
    Sr = S_tot - Scum
    Cr = C_tot - Ccum
    gain = jnp.sum(Scum**2, -1) / jnp.maximum(Ccum, _EPS) + jnp.sum(
        Sr**2, -1
    ) / jnp.maximum(Cr, _EPS)
    parent = jnp.sum(S_tot**2, -1) / jnp.maximum(C_tot, _EPS)  # [m, d, 1]
    valid = (Ccum >= min_samples_leaf) & (Cr >= min_samples_leaf)
    # last bin = degenerate split (empty right)
    valid = valid & (jnp.arange(n_bins)[None, None, :] < n_bins - 1)
    return jnp.where(valid, gain - parent, -jnp.inf)


def _pick_best(gain, n_bins: int):
    """argmax over (feature, bin) per node: (best_gain, feat, bin)."""
    m = gain.shape[0]
    flat = gain.reshape(m, -1)
    best = jnp.argmax(flat, axis=1)
    bg = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    bf = (best // n_bins).astype(jnp.int32)
    bb = (best % n_bins).astype(jnp.int32)
    return bg, bf, bb


#: largest per-level node count handled by the gather-free routing /
#: leaf-aggregation forms below. Per-sample gathers from tiny tables
#: (``tab[node]``) and tiny-segment scatters (``segment_sum``) both lower to
#: serialized TPU kernels — profiled at ~45 ms per gather per level and
#: ~46 ms per segment_sum at a production trial batch (168 lanes x 29k
#: rows), which made them >95% of a GradientBoosting stage's device time.
#: The one-hot matmul / compare-reduce forms are MXU/VPU passes (~3-5 ms).
#: Past this node count the O(n*m) masked forms lose to the O(n) gather.
_LOOKUP_M = 256


def _col_select(xb, feats, n_bins: int):
    """[n, m] matrix whose column j is ``xb[:, feats[j]]`` — a dynamic
    column gather expressed as a one-hot contraction. Exact: bin codes are
    integers < 256, representable in bf16, and the one-hot picks a single
    term per output, so f32 accumulation reproduces the codes bit-exactly.
    """
    d = xb.shape[1]
    if n_bins > 256:  # codes could exceed bf16's exact-integer range
        oh = jax.nn.one_hot(feats, d, dtype=jnp.float32)
        return jnp.dot(
            xb.astype(jnp.float32), oh.T, precision=jax.lax.Precision.HIGHEST
        )
    oh = jax.nn.one_hot(feats, d, dtype=jnp.bfloat16)
    return jnp.dot(
        xb.astype(jnp.bfloat16), oh.T, preferred_element_type=jnp.float32
    )


def _route_left(xb, local, bf, bb, n_bins: int):
    """Per-sample go-left decision for one level, gather-free: compare every
    node's split column against its bin and mask-reduce by the sample's node
    id, instead of ``xb[arange(n), bf[local]] <= bb[local]``."""
    m = bf.shape[0]
    cols = _col_select(xb, bf, n_bins)                      # [n, m] f32
    le = cols <= bb[None, :].astype(cols.dtype)             # [n, m]
    oh = local[:, None] == jnp.arange(m, dtype=local.dtype)
    return jnp.any(oh & le, axis=1)


def _leaf_sums(leaf_local, SC, n_leaves: int):
    """``one_hot(leaf).T @ SC`` — scatter-free segment_sum over tree leaves.
    Exact one-hot selection with f32 accumulation; summation order differs
    from segment_sum only in float addition order (~1 ulp)."""
    oh = jax.nn.one_hot(leaf_local, n_leaves, dtype=SC.dtype)
    return jax.lax.dot_general(
        oh,
        SC,
        (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )


def _leaf_select(leaf_local, V, n_leaves: int):
    """``one_hot(leaf) @ V`` — gather-free ``V[leaf]`` for leaf-value
    lookup. Exact: the one-hot picks a single f32 row per sample."""
    oh = jax.nn.one_hot(leaf_local, n_leaves, dtype=V.dtype)
    return jnp.dot(oh, V, precision=jax.lax.Precision.HIGHEST)


def _node_feature_mask(gain, node_ids, key, max_features: Optional[int], d: int):
    """RF per-node feature subsets for the deep builder, keyed by arena node
    id (fold_in) so chunked/monolithic fits draw identical subsets."""
    if max_features is None or max_features >= d:
        return gain

    def one(cid):
        return jax.random.uniform(jax.random.fold_in(key, cid), (d,))

    u = jax.vmap(one)(jnp.maximum(node_ids, 0))
    thresh = jnp.sort(u, axis=1)[:, max_features - 1 : max_features]
    allowed = u <= thresh
    return jnp.where(allowed[:, :, None], gain, -jnp.inf)


def _hist_with_count(local, xb, SC, n_nodes, n_bins, precision, k,
                     count_from_stats: bool):
    """Level histogram [m, d, nb, k+1]. When the stat columns sum to the
    count column exactly (classification: S = one_hot(y) * w, C = w), the
    count histogram is derived as the sum over class histograms instead of
    contracting an extra column — one fewer MXU row per node, exact."""
    if not count_from_stats:
        return _level_histogram(local, xb, SC, n_nodes, n_bins, precision)
    H = _level_histogram(local, xb, SC[:, :k], n_nodes, n_bins, precision)
    return jnp.concatenate([H, jnp.sum(H, axis=-1, keepdims=True)], axis=-1)


def build_tree(
    xb,
    S,
    C,
    *,
    depth: int,
    n_bins: int,
    min_samples_leaf: float = 1.0,
    max_features: Optional[int] = None,
    key=None,
    precision=jax.lax.Precision.HIGHEST,
    count_from_stats: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Fit one tree.

    xb: [n, d] int32 bin codes. S: [n, k] per-sample weighted target stats
    (already multiplied by sample weight). C: [n] per-sample weights
    (counts for RF, hessians for boosting; 0 = sample not in this fit).
    Returns {"split_feat" [2^depth-1], "split_bin" [2^depth-1],
    "leaf_val" [2^depth, k]}.

    precision: matmul precision for the histogram contraction. HIGHEST
    (default) for float-valued stats (boosting gradients); integer-valued
    stats (RF one-hot counts, exact in bf16) may pass DEFAULT for ~3x
    faster histograms with bit-identical sums.
    """
    n, d = xb.shape
    k = S.shape[1]
    S = S.astype(jnp.float32)
    C = C.astype(jnp.float32)
    n_internal = 2**depth - 1

    split_feat = jnp.zeros((n_internal,), jnp.int32)
    split_bin = jnp.full((n_internal,), n_bins - 1, jnp.int32)  # pass-through
    node = jnp.zeros((n,), jnp.int32)
    feat_ids = jnp.arange(d, dtype=jnp.int32)

    SC = jnp.concatenate([S, C[:, None]], axis=1)  # [n, k+1] stats+count

    H_prev = None
    for level in range(depth):
        n_nodes = 2**level
        base = n_nodes - 1
        local = node - base
        # histograms [n_nodes, d, n_bins, k+1] via one-hot matmuls on the
        # MXU (node/bin membership as 0/1 operands contracted over rows) —
        # TPU scatters serialize, matmuls don't. Levels past the root use
        # the subtraction trick: build only LEFT children (half the node
        # dim), right = parent − left (exact for integer stats; gains clamp
        # the f32 cancellation tails) — halves total histogram work.
        if level == 0:
            H = _hist_with_count(local, xb, SC, n_nodes, n_bins, precision,
                                 k, count_from_stats)
        else:
            went_left = (local % 2 == 0).astype(SC.dtype)
            H_left = _hist_with_count(
                local // 2, xb, SC * went_left[:, None], n_nodes // 2, n_bins,
                precision, k, count_from_stats,
            )
            H = jnp.stack([H_left, H_prev - H_left], axis=1).reshape(
                n_nodes, d, n_bins, k + 1
            )
        H_prev = H
        gain = _split_gain(H, k, n_bins, min_samples_leaf)

        if max_features is not None and max_features < d:
            key, sub = jax.random.split(key)
            u = jax.random.uniform(sub, (n_nodes, d))
            thresh = jnp.sort(u, axis=1)[:, max_features - 1 : max_features]
            allowed = u <= thresh
            gain = jnp.where(allowed[:, :, None], gain, -jnp.inf)

        best_gain, bf, bb = _pick_best(gain, n_bins)
        do_split = best_gain > 1e-7
        bf = jnp.where(do_split, bf, 0)
        bb = jnp.where(do_split, bb, n_bins - 1)

        split_feat = jax.lax.dynamic_update_slice(split_feat, bf, (base,))
        split_bin = jax.lax.dynamic_update_slice(split_bin, bb, (base,))

        if n_nodes <= _LOOKUP_M:
            go_left = _route_left(xb, local, bf, bb, n_bins)
        else:
            f_i = split_feat[node]
            b_i = split_bin[node]
            go_left = xb[jnp.arange(n), f_i] <= b_i
        node = 2 * node + 1 + jnp.where(go_left, 0, 1)

    leaf_local = node - n_internal
    n_leaves = 2**depth
    if n_leaves <= _LOOKUP_M:
        SCl = _leaf_sums(leaf_local, SC, n_leaves)
        Sl, Cl = SCl[:, :k], SCl[:, k]
    else:
        Sl = jax.ops.segment_sum(S, leaf_local, num_segments=n_leaves)
        Cl = jax.ops.segment_sum(C, leaf_local, num_segments=n_leaves)
    leaf_val = Sl / jnp.maximum(Cl, _EPS)[:, None]
    return {
        "split_feat": split_feat,
        "split_bin": split_bin,
        "leaf_val": leaf_val,
        "leaf_weight": Cl,
    }


def build_tree_deep(
    xb,
    S,
    C,
    *,
    levels: int,
    width: int,
    n_bins: int,
    min_samples_leaf: float = 1.0,
    max_features: Optional[int] = None,
    key=None,
    precision=jax.lax.Precision.HIGHEST,
    count_from_stats: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Deep tree via frontier-compacted level-wise growth (batched best-first).

    The complete-tree builder above pays 2^level histogram rows per level —
    infeasible past depth ~10. sklearn's ``max_depth=None`` grows to purity
    (depth 25-45 on Covertype-scale data, the reference's exact-CART fit at
    ``aws-prod/worker/worker.py:315``), so this builder keeps an *arena* of
    nodes and, per level, histograms only an active frontier of at most
    ``width`` nodes:

    - each level: split every frontier node whose best gain is positive;
      histogram the LEFT children mapped to parent slots (one matmul with
      one-hot dim ``width``), derive right children by subtraction — so both
      children's exact best-split gains are known for the cost of one
      histogram;
    - the next frontier = top-``width`` children by their OWN best gain
      (``lax.top_k``) — true-gain best-first selection, not a proxy; children
      not selected (budget) or unsplittable (gain <= eps, min_samples_leaf)
      become leaves;
    - per-level cost is O(n * width * kk * d * n_bins) MACs regardless of
      depth, all on the MXU; total leaf budget ~ width * levels (~12k at the
      defaults), the regime sklearn's grow-to-purity needs.

    Shapes are static: the frontier width at level l is min(2^l, width)
    (early levels don't pay the full budget), the arena is a fixed
    ``2*width*levels + 2`` slots, and routing state is one int32 per sample.
    Returns {"feat","bin","child" [A+1], "leaf_val" [A+1, k]}; ``child`` is
    the left-child arena id (0 = leaf; right child = left + 1).
    """
    n, d = xb.shape
    k = S.shape[1]
    S = S.astype(jnp.float32)
    C = C.astype(jnp.float32)
    A = 2 * width * levels + 2  # arena capacity; index A = scratch slot
    SC = jnp.concatenate([S, C[:, None]], axis=1)
    if key is None:
        key = jax.random.PRNGKey(0)

    feat_a = jnp.zeros((A + 1,), jnp.int32)
    bin_a = jnp.full((A + 1,), n_bins - 1, jnp.int32)
    child_a = jnp.zeros((A + 1,), jnp.int32)
    node = jnp.zeros((n,), jnp.int32)
    n_alloc = jnp.int32(1)

    # root: full histogram + its best split
    frontier = jnp.zeros((1,), jnp.int32)
    H = _hist_with_count(node, xb, SC, 1, n_bins, precision, k, count_from_stats)
    g = _split_gain(H, k, n_bins, min_samples_leaf)
    g = _node_feature_mask(g, frontier, key, max_features, d)
    gain, bf, bb = _pick_best(g, n_bins)

    for level in range(levels):
        W_l = frontier.shape[0]
        do_split = (gain > 1e-7) & (frontier >= 0)
        rank_inc = jnp.cumsum(do_split.astype(jnp.int32))
        do_split = do_split & (n_alloc + 2 * rank_inc <= A)
        rank_inc = jnp.cumsum(do_split.astype(jnp.int32))
        rank_exc = rank_inc - do_split.astype(jnp.int32)
        left_id = n_alloc + 2 * rank_exc

        # write split records; masked rows land in the scratch slot A
        idx = jnp.where(do_split, frontier, A)
        feat_a = feat_a.at[idx].set(jnp.where(do_split, bf, 0))
        bin_a = bin_a.at[idx].set(jnp.where(do_split, bb, n_bins - 1))
        child_a = child_a.at[idx].set(jnp.where(do_split, left_id, 0))

        # route samples sitting in split nodes to their children
        slot_tab = jnp.full((A + 1,), W_l, jnp.int32)
        slot_tab = slot_tab.at[jnp.where(frontier >= 0, frontier, A)].set(
            jnp.arange(W_l, dtype=jnp.int32)
        )
        slot_tab = slot_tab.at[A].set(W_l)  # scratch writes above must stay dead
        slot = slot_tab[node]  # [n], == W_l when not in frontier
        pad_b = jnp.zeros((1,), jnp.int32)
        sp = jnp.concatenate([do_split, jnp.zeros((1,), bool)])[slot]
        f_i = jnp.concatenate([bf, pad_b])[slot]
        b_i = jnp.concatenate([bb, pad_b])[slot]
        l_i = jnp.concatenate([left_id, pad_b])[slot]
        go_left = xb[jnp.arange(n), f_i] <= b_i
        node = jnp.where(sp, l_i + 1 - go_left.astype(jnp.int32), node)
        n_alloc = n_alloc + 2 * rank_inc[-1]

        if level == levels - 1:
            break  # children of the last level are leaves

        # children's histograms: left by matmul over parent slots, right by
        # subtraction (exact for integer stats; float tails are gain-clamped)
        local_left = jnp.where(sp & go_left, slot, W_l)
        H_L = _hist_with_count(local_left, xb, SC, W_l, n_bins, precision,
                               k, count_from_stats)
        H_R = H - H_L
        cand_H = jnp.concatenate([H_L, H_R], axis=0)  # [2*W_l, d, bins, k+1]
        cand_id = jnp.concatenate(
            [jnp.where(do_split, left_id, -1), jnp.where(do_split, left_id + 1, -1)]
        )
        cg = _split_gain(cand_H, k, n_bins, min_samples_leaf)
        cg = _node_feature_mask(cg, cand_id, key, max_features, d)
        cgain, cbf, cbb = _pick_best(cg, n_bins)
        cgain = jnp.where(cand_id >= 0, cgain, -jnp.inf)

        W_next = min(2 * W_l, width)
        vals, sel = jax.lax.top_k(cgain, W_next)
        live = vals > -jnp.inf
        frontier = jnp.where(live, cand_id[sel], -1)
        gain = vals
        bf = cbf[sel]
        bb = cbb[sel]
        H = cand_H[sel]

    leaf_S = jax.ops.segment_sum(S, node, num_segments=A + 1)
    leaf_C = jax.ops.segment_sum(C, node, num_segments=A + 1)
    leaf_val = leaf_S / jnp.maximum(leaf_C, _EPS)[:, None]
    return {
        "feat": feat_a,
        "bin": bin_a,
        "child": child_a,
        "leaf_val": leaf_val,
        "leaf_weight": leaf_C,
    }


@partial(jax.jit, static_argnames=("levels",))
def _route_deep(xb, feat, bins, child, levels: int):
    n = xb.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for _ in range(levels):
        c = child[node]
        go_left = xb[jnp.arange(n), feat[node]] <= bins[node]
        node = jnp.where(c > 0, c + 1 - go_left.astype(jnp.int32), node)
    return node


def predict_tree_deep(xb, tree, levels: int):
    """Leaf values for binned query rows against an arena tree."""
    leaf = _route_deep(xb, tree["feat"], tree["bin"], tree["child"], levels)
    return tree["leaf_val"][leaf]


@partial(jax.jit, static_argnames=("depth", "n_bins"))
def _route(xb, split_feat, split_bin, depth: int, n_bins: int = 0):
    n = xb.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for level in range(depth):
        base, m = 2**level - 1, 2**level
        if m <= _LOOKUP_M:
            # gather-free: this level's split records are a static slice
            bf = jax.lax.slice(split_feat, (base,), (base + m,))
            bb = jax.lax.slice(split_bin, (base,), (base + m,))
            go_left = _route_left(xb, node - base, bf, bb, n_bins or 1 << 30)
        else:
            f_i = split_feat[node]
            b_i = split_bin[node]
            go_left = xb[jnp.arange(n), f_i] <= b_i
        node = 2 * node + 1 + jnp.where(go_left, 0, 1)
    return node - (2**depth - 1)


def predict_tree(xb, tree, depth: int, n_bins: int = 0):
    """Leaf values for each row of binned query data. ``n_bins`` (when
    known) lets the gather-free router use the fast bf16 column select."""
    leaf = _route(xb, tree["split_feat"], tree["split_bin"], depth, n_bins)
    n_leaves = 2**depth
    if n_leaves <= _LOOKUP_M:
        return _leaf_select(leaf, tree["leaf_val"], n_leaves)
    return tree["leaf_val"][leaf]
