"""Fused softmax-regression gradient as a Pallas TPU kernel.

The LogisticRegression north-star config (1000-trial RandomizedSearchCV on
Covertype, BASELINE.md) is HBM-bound on the pure-XLA path: every solver
iteration materializes the softmax probabilities tensor
``[trials, splits, n, classes]`` between the two matmuls, and with
``classes`` (7) as the minor dimension the layout pads to 128 lanes —
measured ~10 ms/iteration at 6.6 TF/s on v5e for a 64-trial x 6-split
batch. This kernel fuses the whole gradient:

    G[b] = A^T @ (w[b] * (softmax(A @ W[b]) - Y))     for all b = (trial, split)

streaming row tiles of the shared design matrix A through VMEM. The
probabilities never touch HBM.

Packing: all trials' weight columns are packed into one matrix with a
**class-major** column layout, ``col = (a * S + s) * Tw + t`` per
128-trial block (a = class, s = split, t = trial-in-block). The grouped
softmax over classes then becomes elementwise ops over ``c`` statically
sliced ``[bm, S*Tw]`` tiles — no lane shuffles, no padding of the class
dimension, and the matmul minor dimension is fully lane-packed.

Replaces (in effect) the per-trial sklearn fit of the reference worker
(``aws-prod/worker/worker.py:289-349``) for the LogisticRegression family;
see models/logistic.py for the solver that drives it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: trials per weight block; the packed block width is ``c * S * TRIAL_BLOCK``
TRIAL_BLOCK = 128


def _tile_softmax_gram(a, W, yv, wsp_ref, acc_ref, *, c: int, S: int, Tw: int):
    """Shared (row-tile x weight-block) gradient body of ``_grad_kernel``
    and ``_fused_step_kernel``: logits -> grouped softmax -> masked
    residual -> per-class Gram accumulation into ``acc_ref[0]``. The two
    kernels MUST run op-for-op identical gradients (the fused-vs-legacy
    parity contract), which this single body enforces by construction.

    a   [bm, dpp]      bf16  design-matrix row tile (shared by all trials)
    W   [dpp, NB]      bf16  packed weights operand, NB = c*S*Tw, class-major
    yv  [bm, 1]        i32   labels for the tile rows
    wsp_ref [bm, S]    f32   per-split {0,1} sample-weight ref
    acc_ref [1, dpp, NB] f32 accumulator block, revisited across row tiles
    """
    B = S * Tw
    bm = a.shape[0]
    # logits for every (class, split, trial) column: one MXU pass, f32 out
    logits = jnp.dot(a, W, preferred_element_type=jnp.float32)  # [bm, NB]

    # per-(sample, split, trial) weight tile, broadcast from the S columns
    wexp_parts = [
        jnp.broadcast_to(wsp_ref[:, s : s + 1], (bm, Tw)) for s in range(S)
    ]
    wexp = jnp.concatenate(wexp_parts, axis=1)  # [bm, B]

    # grouped softmax over the c class slices (elementwise; classes are
    # separate [bm, B] tiles, so no cross-lane reductions are needed)
    m = logits[:, 0:B]
    for a_i in range(1, c):
        m = jnp.maximum(m, logits[:, a_i * B : (a_i + 1) * B])
    es = [jnp.exp(logits[:, a_i * B : (a_i + 1) * B] - m) for a_i in range(c)]
    den = es[0]
    for a_i in range(1, c):
        den = den + es[a_i]
    rden = 1.0 / den

    # per class: residual tile and its gradient contribution (7 small dots
    # instead of one concat keeps everything statically sliced)
    for a_i in range(c):
        onehot = (yv == a_i).astype(jnp.float32)  # [bm, 1] broadcasts
        r = ((es[a_i] * rden - onehot) * wexp).astype(jnp.bfloat16)  # [bm, B]
        g_a = jax.lax.dot_general(
            a,
            r,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [dpp, B]
        acc_ref[0, :, a_i * B : (a_i + 1) * B] += g_a


def _grad_kernel(a_ref, w_ref, y_ref, wsp_ref, g_ref, *, c: int, S: int, Tw: int):
    """One (weight-block, row-tile) grid step.

    a_ref   [bm, dpp]      bf16  design-matrix row tile (shared by all trials)
    w_ref   [1, dpp, NB]   bf16  packed weights, NB = c*S*Tw, class-major
    y_ref   [bm, 1]        i32   labels for the tile rows
    wsp_ref [bm, S]        f32   per-split {0,1} sample weights
    g_ref   [1, dpp, NB]   f32   output: A^T (w (P - Y)), accumulated over row tiles
    """
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        g_ref[0] = jnp.zeros_like(g_ref[0])

    _tile_softmax_gram(a_ref[:], w_ref[0], y_ref[:], wsp_ref, g_ref, c=c, S=S, Tw=Tw)


@functools.partial(jax.jit, static_argnames=("c", "S", "Tw", "bm", "interpret"))
def packed_softmax_grad(
    Ab, W3, y2, WSP, *, c: int, S: int, Tw: int = TRIAL_BLOCK, bm: int = 256, interpret: bool = False
):
    """G3[wb] = A^T @ (w * (softmax(A @ W3[wb]) - Y)) for every packed column.

    Ab  [n_pad, dpp]       bf16, n_pad % bm == 0 (pad rows must have w == 0)
    W3  [n_wb, dpp, NB]    bf16, NB == c*S*Tw, column = (a*S + s)*Tw + t
    y2  [n_pad, 1]         i32
    WSP [n_pad, S]         f32
    returns G3 [n_wb, dpp, NB] f32
    """
    n_pad, dpp = Ab.shape
    n_wb, _, NB = W3.shape
    assert NB == c * S * Tw, (NB, c, S, Tw)
    assert n_pad % bm == 0, (n_pad, bm)

    grid = (n_wb, n_pad // bm)
    kernel = functools.partial(_grad_kernel, c=c, S=S, Tw=Tw)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, dpp), lambda wb, i: (i, 0)),
            pl.BlockSpec((1, dpp, NB), lambda wb, i: (wb, 0, 0)),
            pl.BlockSpec((bm, 1), lambda wb, i: (i, 0)),
            pl.BlockSpec((bm, S), lambda wb, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, dpp, NB), lambda wb, i: (wb, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_wb, dpp, NB), jnp.float32),
        interpret=interpret,
    )(Ab, W3, y2, WSP)


#: conservative VMEM budget for the fused step's weight-resident blocks
#: (W/Wp in + W/Wp out, all f32 — 16 bytes per (row, packed column)). The
#: row-tile intermediates (logits, per-class exp tiles) match the plain
#: gradient kernel's and are not re-counted here; this bounds only what
#: the fused form ADDS over ``packed_softmax_grad``. Re-tune on real TPU
#: (BENCH_r06 follow-up).
_FUSED_STEP_VMEM_BYTES = 8 * 1024 * 1024


def fused_step_applicable(dpp: int, NB: int, bm: int = 256) -> bool:
    """VMEM gate for ``packed_nesterov_step``'s ``auto`` routing: the four
    f32 weight blocks (W/Wp, in + aliased out) must fit the budget. Forced
    modes (``CS230_FUSED_STEP=pallas``) bypass this — tests run tiny
    shapes, and an operator forcing the kernel owns the consequences."""
    return 16 * dpp * NB + 2 * bm * dpp <= _FUSED_STEP_VMEM_BYTES


def _fused_step_kernel(
    a_ref, w_ref, wp_ref, y_ref, wsp_ref, t_ref, done_ref, step_ref,
    cb_ref, maxit_ref, pen_ref, wout_ref, wpout_ref, gmax_ref,
    *, c: int, S: int, Tw: int, lam: float, n_tiles: int
):
    """One (weight-block, row-tile) grid step of the FULL Nesterov update.

    a_ref     [bm, dpp]      bf16  design-matrix row tile (shared by all trials)
    w_ref     [1, dpp, NB]   f32   W, packed class-major (NB = c*S*Tw)
    wp_ref    [1, dpp, NB]   f32   W_prev
    y_ref     [bm, 1]        i32   labels for the tile rows
    wsp_ref   [bm, S]        f32   per-split {0,1} sample weights
    t_ref     [1, 1]         f32   iteration index t (SMEM scalar)
    done_ref  [1, B]         f32   1.0 where the trial already converged
    step_ref  [1, B]         f32   per-(split, trial) step size
    cb_ref    [1, B]         f32   per-trial C
    maxit_ref [1, B]         f32   per-trial max_iter
    pen_ref   [dpp, 1]       f32   L2 penalty row mask (0 on intercept/pad)
    wout_ref  [1, dpp, NB]   f32   OUT W_new — aliased onto w_ref's buffer;
                                   doubles as the cross-tile Gram accumulator
    wpout_ref [1, dpp, NB]   f32   OUT Wp_new — aliased onto wp_ref's buffer
    gmax_ref  [1, B]         f32   OUT per-(split, trial) max|G|

    The look-ahead iterate ``V = W + mom*(W - Wp)`` is formed in VMEM from
    the resident W/Wp blocks each row tile (VPU-cheap next to the tile's
    MXU work) — V never exists in HBM. The raw gradient accumulates across
    row tiles in the wout block; the LAST tile's epilogue applies the
    per-trial C scaling + L2 penalty, reduces ``max|G|``, and performs the
    done/max_iter-masked W/Wp writeback in place.
    """
    i = pl.program_id(1)
    B = S * Tw
    t = t_ref[0, 0]
    mom = t / (t + 3.0)

    @pl.when(i == 0)
    def _init():
        wout_ref[0] = jnp.zeros_like(wout_ref[0])

    # look-ahead iterate, recomputed per tile from the VMEM-resident blocks
    Vb = (w_ref[0] + mom * (w_ref[0] - wp_ref[0])).astype(jnp.bfloat16)
    # the one shared gradient body with _grad_kernel (parity by
    # construction), accumulating into the W_new output block
    _tile_softmax_gram(a_ref[:], Vb, y_ref[:], wsp_ref, wout_ref, c=c, S=S, Tw=Tw)

    @pl.when(i == n_tiles - 1)
    def _epilogue():
        W = w_ref[0]
        Wp = wp_ref[0]
        V = W + mom * (W - Wp)  # f32 this time: the writeback operand
        cb = cb_ref[:]  # [1, B]
        step = step_ref[:]
        pen = pen_ref[:]  # [dpp, 1]
        active = jnp.logical_and(t < maxit_ref[:], done_ref[:] == 0.0)  # [1, B]
        gmax = None
        for a_i in range(c):
            sl = slice(a_i * B, (a_i + 1) * B)
            Vb_ = V[:, sl]
            G = cb * wout_ref[0, :, sl] + lam * (pen * Vb_)  # [dpp, B]
            gm = jnp.max(jnp.abs(G), axis=0, keepdims=True)  # [1, B]
            gmax = gm if gmax is None else jnp.maximum(gmax, gm)
            wout_ref[0, :, sl] = jnp.where(active, Vb_ - step * G, W[:, sl])
            wpout_ref[0, :, sl] = jnp.where(active, W[:, sl], Wp[:, sl])
        gmax_ref[:] = gmax


@functools.partial(
    jax.jit, static_argnames=("c", "S", "Tw", "bm", "lam", "interpret")
)
def packed_nesterov_step(
    Ab, W3, Wp3, y2, WSP, t, done, step_b, Cb, maxit_b, pen_col,
    *, c: int, S: int, Tw: int = TRIAL_BLOCK, bm: int = 256,
    lam: float = 0.0, interpret: bool = False,
):
    """ONE full Nesterov iteration of the packed LogReg fit, fused.

    Replaces the legacy scan body's four XLA elementwise round-trips over
    the ``[n_wb, dpp, NB]`` weight tensors (momentum extrapolation, C/L2
    gradient scaling, the ``max|G|`` reduce, the done-masked writeback)
    with in-VMEM epilogues around the streamed softmax-Gram gradient.
    Per-iteration HBM traffic on the weight tensors drops from ~10 full
    f32 passes to 4 (W/Wp read + W/Wp write, aliased in place).

    Ab      [n_pad, dpp]     bf16  (n_pad % bm == 0; pad rows carry w == 0)
    W3      [n_wb, dpp, NB]  f32   NB == c*S*Tw, column = (a*S + s)*Tw + t
    Wp3     [n_wb, dpp, NB]  f32
    y2      [n_pad, 1]       i32
    WSP     [n_pad, S]       f32
    t       scalar           f32   iteration index (momentum = t/(t+3))
    done    [n_wb, B]        f32   1.0 freezes the (split, trial) column
    step_b  [n_wb, B]        f32   per-column step size
    Cb      [n_wb, B]        f32   per-column C
    maxit_b [n_wb, B]        f32   per-column max_iter
    pen_col [dpp, 1]         f32   L2 row mask (0 on intercept + pad rows)
    lam     static float           L2 strength (0 disables the penalty)

    Returns ``(W_new, Wp_new, gmax)`` with shapes/dtypes of
    ``(W3, Wp3, [n_wb, B] f32)``. ALIASING CAVEAT: ``W3`` and ``Wp3`` are
    donated to the outputs (``input_output_aliases``) — inside the solver
    scan XLA updates them in place; a caller holding the input arrays
    must treat them as consumed after the call.
    """
    n_pad, dpp = Ab.shape
    n_wb, _, NB = W3.shape
    B = S * Tw
    assert NB == c * B, (NB, c, S, Tw)
    assert n_pad % bm == 0, (n_pad, bm)
    n_tiles = n_pad // bm

    t2 = jnp.asarray(t, jnp.float32).reshape(1, 1)
    kernel = functools.partial(
        _fused_step_kernel, c=c, S=S, Tw=Tw, lam=float(lam), n_tiles=n_tiles
    )
    return pl.pallas_call(
        kernel,
        grid=(n_wb, n_tiles),
        in_specs=[
            pl.BlockSpec((bm, dpp), lambda wb, i: (i, 0)),
            pl.BlockSpec((1, dpp, NB), lambda wb, i: (wb, 0, 0)),
            pl.BlockSpec((1, dpp, NB), lambda wb, i: (wb, 0, 0)),
            pl.BlockSpec((bm, 1), lambda wb, i: (i, 0)),
            pl.BlockSpec((bm, S), lambda wb, i: (i, 0)),
            pl.BlockSpec((1, 1), lambda wb, i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, B), lambda wb, i: (wb, 0)),
            pl.BlockSpec((1, B), lambda wb, i: (wb, 0)),
            pl.BlockSpec((1, B), lambda wb, i: (wb, 0)),
            pl.BlockSpec((1, B), lambda wb, i: (wb, 0)),
            pl.BlockSpec((dpp, 1), lambda wb, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, dpp, NB), lambda wb, i: (wb, 0, 0)),
            pl.BlockSpec((1, dpp, NB), lambda wb, i: (wb, 0, 0)),
            pl.BlockSpec((1, B), lambda wb, i: (wb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_wb, dpp, NB), jnp.float32),
            jax.ShapeDtypeStruct((n_wb, dpp, NB), jnp.float32),
            jax.ShapeDtypeStruct((n_wb, B), jnp.float32),
        ],
        input_output_aliases={1: 0, 2: 1},
        interpret=interpret,
    )(Ab, W3, Wp3, y2, WSP, t2, done, step_b, Cb, maxit_b, pen_col)


def packed_nesterov_step_reference(
    Ab, W3, Wp3, y2, WSP, t, done, step_b, Cb, maxit_b, pen_col,
    *, c: int, S: int, Tw: int = TRIAL_BLOCK, lam: float = 0.0,
):
    """Pure-XLA reference of ``packed_nesterov_step`` — literally the
    legacy scan body's algebra (models/logistic.py pre-fusion) on the
    same packed layout, for parity tests."""
    n_wb, dpp, NB = W3.shape
    B = S * Tw
    t = jnp.asarray(t, jnp.float32)
    mom = t / (t + 3.0)
    V = W3 + mom * (W3 - Wp3)
    Graw = packed_softmax_grad_reference(
        Ab, V.astype(jnp.bfloat16), y2, WSP, c=c, S=S, Tw=Tw
    )
    cb_full = jnp.tile(Cb, (1, c))[:, None, :]  # [n_wb, 1, NB]
    step_full = jnp.tile(step_b, (1, c))[:, None, :]
    pen_row = pen_col.reshape(1, dpp, 1)
    G = cb_full * Graw + lam * pen_row * V
    gmax = jnp.max(jnp.abs(G).reshape(n_wb, dpp, c, B), axis=(1, 2))
    active = jnp.logical_and(t < maxit_b, done == 0.0)  # [n_wb, B]
    act = jnp.tile(active, (1, c))[:, None, :]
    W_new = jnp.where(act, V - step_full * G, W3)
    Wp_new = jnp.where(act, W3, Wp3)
    return W_new, Wp_new, gmax


def _masked_grad_kernel(a_ref, w_ref, y_ref, wm_ref, g_ref, *, c: int):
    """One row-tile grid step of the per-lane masked gradient.

    a_ref  [bm, dpp]  bf16  design-matrix row tile (shared by every lane)
    w_ref  [dpp, cp]  bf16  one lane's weights, classes zero-padded to cp
    y_ref  [bm, 1]    i32   labels for the tile rows
    wm_ref [bm, 1]    f32   per-(sample, split) {0,1} fold weight (or any
                            non-negative sample weight)
    g_ref  [dpp, cp]  f32   output accumulator, revisited across row tiles

    The fold mask streams through VMEM with the row tile and is applied to
    the residual *inside* the kernel — the masked copies of the
    probabilities / residual never exist in HBM. The Gram product
    ``A^T @ r`` runs with bf16 operands and f32 accumulation (the MXU's
    native mode), reduced across row tiles in the f32 output block.
    """
    i = pl.program_id(0)
    a = a_ref[:]
    logits = jnp.dot(a, w_ref[:], preferred_element_type=jnp.float32)  # [bm, cp]
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    # zero-padded weight columns produce logits == 0 which would pollute
    # the softmax: mask them to -inf-ish before the row max
    logits = jnp.where(col < c, logits, -1e30)
    m = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    onehot = (y_ref[:] == col).astype(jnp.float32)
    r = ((p - onehot) * wm_ref[:]).astype(jnp.bfloat16)  # [bm, cp], VMEM-only

    @pl.when(i == 0)
    def _init():
        g_ref[:] = jnp.zeros_like(g_ref)

    g_ref[:] += jax.lax.dot_general(
        a,
        r,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("c", "bm", "interpret"))
def masked_softmax_grad(Ab, W, y2, wm, *, c: int, bm: int = 256, interpret: bool = False):
    """G = A^T @ (w * (softmax(A @ W) - Y)) for ONE (trial, split) lane.

    The generic (non-packed) drivers' masked gradient as a fused kernel:
    fold mask applied in-kernel, probabilities never materialized in HBM,
    Gram product in bf16 with f32 reduction. Composes with ``jax.vmap``
    (the engine's trials x splits batching adds grid dimensions).

    Ab [n_pad, dpp] bf16 (n_pad % bm == 0; pad rows must carry wm == 0)
    W  [dpp, cp]    bf16 (classes zero-padded to cp; cols >= c are ignored)
    y2 [n_pad, 1]   i32
    wm [n_pad, 1]   f32
    returns G [dpp, cp] f32 (cols >= c are zero)
    """
    n_pad, dpp = Ab.shape
    cp = W.shape[1]
    assert n_pad % bm == 0, (n_pad, bm)
    return pl.pallas_call(
        functools.partial(_masked_grad_kernel, c=c),
        grid=(n_pad // bm,),
        in_specs=[
            pl.BlockSpec((bm, dpp), lambda i: (i, 0)),
            pl.BlockSpec((dpp, cp), lambda i: (0, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((dpp, cp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((dpp, cp), jnp.float32),
        interpret=interpret,
    )(Ab, W, y2, wm)


def masked_softmax_grad_reference(Ab, W, y2, wm, *, c: int):
    """Pure-XLA reference of ``masked_softmax_grad`` (same padded layout).

    This is also the *fused-mask formulation* the solver uses on non-TPU
    backends: the fold weight folds into the softmax normalizer
    (``w * softmax(z) == exp(z - max) * (w / den)``), so a masked
    iteration replaces softmax's [n, c] divide with an [n, 1] divide and
    an [n, c] multiply — never costlier than an unmasked gradient, and no
    masked copy of the probabilities is ever materialized as a separate
    elementwise pass.
    """
    A = Ab.astype(jnp.float32)
    cp = W.shape[1]
    Z = A @ W.astype(jnp.float32)
    col = jnp.arange(cp)[None, :]
    Z = jnp.where(col < c, Z, -1e30)
    e = jnp.exp(Z - jnp.max(Z, axis=-1, keepdims=True))
    Pw = e * (wm / jnp.sum(e, axis=-1, keepdims=True))
    WY = jnp.where(y2 == col, wm, 0.0)
    return A.T @ (Pw - WY)


def packed_softmax_grad_reference(Ab, W3, y2, WSP, *, c: int, S: int, Tw: int = TRIAL_BLOCK):
    """Pure-XLA reference of the kernel (same packing), for parity tests."""
    n_pad, dpp = Ab.shape
    n_wb, _, NB = W3.shape
    B = S * Tw
    A = Ab.astype(jnp.float32)
    y = y2[:, 0]

    def one_block(W):  # [dpp, NB]
        logits = A @ W  # [n, NB]
        L = logits.reshape(n_pad, c, B)
        P = jax.nn.softmax(L, axis=1)
        onehot = jax.nn.one_hot(y, c, dtype=jnp.float32)  # [n, c]
        wexp = jnp.repeat(WSP, Tw, axis=1)  # [n, B] (split-major blocks)
        R = (P - onehot[:, :, None]) * wexp[:, None, :]
        return jnp.einsum("nd,ncb->dcb", A, R).reshape(dpp, NB)

    return jax.vmap(one_block)(W3)
