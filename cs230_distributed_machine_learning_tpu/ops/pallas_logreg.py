"""Fused softmax-regression gradient as a Pallas TPU kernel.

The LogisticRegression north-star config (1000-trial RandomizedSearchCV on
Covertype, BASELINE.md) is HBM-bound on the pure-XLA path: every solver
iteration materializes the softmax probabilities tensor
``[trials, splits, n, classes]`` between the two matmuls, and with
``classes`` (7) as the minor dimension the layout pads to 128 lanes —
measured ~10 ms/iteration at 6.6 TF/s on v5e for a 64-trial x 6-split
batch. This kernel fuses the whole gradient:

    G[b] = A^T @ (w[b] * (softmax(A @ W[b]) - Y))     for all b = (trial, split)

streaming row tiles of the shared design matrix A through VMEM. The
probabilities never touch HBM.

Packing: all trials' weight columns are packed into one matrix with a
**class-major** column layout, ``col = (a * S + s) * Tw + t`` per
128-trial block (a = class, s = split, t = trial-in-block). The grouped
softmax over classes then becomes elementwise ops over ``c`` statically
sliced ``[bm, S*Tw]`` tiles — no lane shuffles, no padding of the class
dimension, and the matmul minor dimension is fully lane-packed.

Replaces (in effect) the per-trial sklearn fit of the reference worker
(``aws-prod/worker/worker.py:289-349``) for the LogisticRegression family;
see models/logistic.py for the solver that drives it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: trials per weight block; the packed block width is ``c * S * TRIAL_BLOCK``
TRIAL_BLOCK = 128


def _grad_kernel(a_ref, w_ref, y_ref, wsp_ref, g_ref, *, c: int, S: int, Tw: int):
    """One (weight-block, row-tile) grid step.

    a_ref   [bm, dpp]      bf16  design-matrix row tile (shared by all trials)
    w_ref   [1, dpp, NB]   bf16  packed weights, NB = c*S*Tw, class-major
    y_ref   [bm, 1]        i32   labels for the tile rows
    wsp_ref [bm, S]        f32   per-split {0,1} sample weights
    g_ref   [1, dpp, NB]   f32   output: A^T (w (P - Y)), accumulated over row tiles
    """
    i = pl.program_id(1)
    B = S * Tw
    bm = a_ref.shape[0]

    a = a_ref[:]
    W = w_ref[0]
    # logits for every (class, split, trial) column: one MXU pass, f32 out
    logits = jnp.dot(a, W, preferred_element_type=jnp.float32)  # [bm, NB]

    # per-(sample, split, trial) weight tile, broadcast from the S columns
    wexp_parts = [
        jnp.broadcast_to(wsp_ref[:, s : s + 1], (bm, Tw)) for s in range(S)
    ]
    wexp = jnp.concatenate(wexp_parts, axis=1)  # [bm, B]

    # grouped softmax over the c class slices (elementwise; classes are
    # separate [bm, B] tiles, so no cross-lane reductions are needed)
    m = logits[:, 0:B]
    for a_i in range(1, c):
        m = jnp.maximum(m, logits[:, a_i * B : (a_i + 1) * B])
    es = [jnp.exp(logits[:, a_i * B : (a_i + 1) * B] - m) for a_i in range(c)]
    den = es[0]
    for a_i in range(1, c):
        den = den + es[a_i]
    rden = 1.0 / den

    yv = y_ref[:]  # [bm, 1]

    @pl.when(i == 0)
    def _init():
        g_ref[0] = jnp.zeros_like(g_ref[0])

    # per class: residual tile and its gradient contribution (7 small dots
    # instead of one concat keeps everything statically sliced)
    for a_i in range(c):
        onehot = (yv == a_i).astype(jnp.float32)  # [bm, 1] broadcasts
        r = ((es[a_i] * rden - onehot) * wexp).astype(jnp.bfloat16)  # [bm, B]
        g_a = jax.lax.dot_general(
            a,
            r,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [dpp, B]
        g_ref[0, :, a_i * B : (a_i + 1) * B] += g_a


@functools.partial(jax.jit, static_argnames=("c", "S", "Tw", "bm", "interpret"))
def packed_softmax_grad(
    Ab, W3, y2, WSP, *, c: int, S: int, Tw: int = TRIAL_BLOCK, bm: int = 256, interpret: bool = False
):
    """G3[wb] = A^T @ (w * (softmax(A @ W3[wb]) - Y)) for every packed column.

    Ab  [n_pad, dpp]       bf16, n_pad % bm == 0 (pad rows must have w == 0)
    W3  [n_wb, dpp, NB]    bf16, NB == c*S*Tw, column = (a*S + s)*Tw + t
    y2  [n_pad, 1]         i32
    WSP [n_pad, S]         f32
    returns G3 [n_wb, dpp, NB] f32
    """
    n_pad, dpp = Ab.shape
    n_wb, _, NB = W3.shape
    assert NB == c * S * Tw, (NB, c, S, Tw)
    assert n_pad % bm == 0, (n_pad, bm)

    grid = (n_wb, n_pad // bm)
    kernel = functools.partial(_grad_kernel, c=c, S=S, Tw=Tw)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, dpp), lambda wb, i: (i, 0)),
            pl.BlockSpec((1, dpp, NB), lambda wb, i: (wb, 0, 0)),
            pl.BlockSpec((bm, 1), lambda wb, i: (i, 0)),
            pl.BlockSpec((bm, S), lambda wb, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, dpp, NB), lambda wb, i: (wb, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_wb, dpp, NB), jnp.float32),
        interpret=interpret,
    )(Ab, W3, y2, WSP)


def _masked_grad_kernel(a_ref, w_ref, y_ref, wm_ref, g_ref, *, c: int):
    """One row-tile grid step of the per-lane masked gradient.

    a_ref  [bm, dpp]  bf16  design-matrix row tile (shared by every lane)
    w_ref  [dpp, cp]  bf16  one lane's weights, classes zero-padded to cp
    y_ref  [bm, 1]    i32   labels for the tile rows
    wm_ref [bm, 1]    f32   per-(sample, split) {0,1} fold weight (or any
                            non-negative sample weight)
    g_ref  [dpp, cp]  f32   output accumulator, revisited across row tiles

    The fold mask streams through VMEM with the row tile and is applied to
    the residual *inside* the kernel — the masked copies of the
    probabilities / residual never exist in HBM. The Gram product
    ``A^T @ r`` runs with bf16 operands and f32 accumulation (the MXU's
    native mode), reduced across row tiles in the f32 output block.
    """
    i = pl.program_id(0)
    a = a_ref[:]
    logits = jnp.dot(a, w_ref[:], preferred_element_type=jnp.float32)  # [bm, cp]
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    # zero-padded weight columns produce logits == 0 which would pollute
    # the softmax: mask them to -inf-ish before the row max
    logits = jnp.where(col < c, logits, -1e30)
    m = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    onehot = (y_ref[:] == col).astype(jnp.float32)
    r = ((p - onehot) * wm_ref[:]).astype(jnp.bfloat16)  # [bm, cp], VMEM-only

    @pl.when(i == 0)
    def _init():
        g_ref[:] = jnp.zeros_like(g_ref)

    g_ref[:] += jax.lax.dot_general(
        a,
        r,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("c", "bm", "interpret"))
def masked_softmax_grad(Ab, W, y2, wm, *, c: int, bm: int = 256, interpret: bool = False):
    """G = A^T @ (w * (softmax(A @ W) - Y)) for ONE (trial, split) lane.

    The generic (non-packed) drivers' masked gradient as a fused kernel:
    fold mask applied in-kernel, probabilities never materialized in HBM,
    Gram product in bf16 with f32 reduction. Composes with ``jax.vmap``
    (the engine's trials x splits batching adds grid dimensions).

    Ab [n_pad, dpp] bf16 (n_pad % bm == 0; pad rows must carry wm == 0)
    W  [dpp, cp]    bf16 (classes zero-padded to cp; cols >= c are ignored)
    y2 [n_pad, 1]   i32
    wm [n_pad, 1]   f32
    returns G [dpp, cp] f32 (cols >= c are zero)
    """
    n_pad, dpp = Ab.shape
    cp = W.shape[1]
    assert n_pad % bm == 0, (n_pad, bm)
    return pl.pallas_call(
        functools.partial(_masked_grad_kernel, c=c),
        grid=(n_pad // bm,),
        in_specs=[
            pl.BlockSpec((bm, dpp), lambda i: (i, 0)),
            pl.BlockSpec((dpp, cp), lambda i: (0, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((dpp, cp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((dpp, cp), jnp.float32),
        interpret=interpret,
    )(Ab, W, y2, wm)


def masked_softmax_grad_reference(Ab, W, y2, wm, *, c: int):
    """Pure-XLA reference of ``masked_softmax_grad`` (same padded layout).

    This is also the *fused-mask formulation* the solver uses on non-TPU
    backends: the fold weight folds into the softmax normalizer
    (``w * softmax(z) == exp(z - max) * (w / den)``), so a masked
    iteration replaces softmax's [n, c] divide with an [n, 1] divide and
    an [n, c] multiply — never costlier than an unmasked gradient, and no
    masked copy of the probabilities is ever materialized as a separate
    elementwise pass.
    """
    A = Ab.astype(jnp.float32)
    cp = W.shape[1]
    Z = A @ W.astype(jnp.float32)
    col = jnp.arange(cp)[None, :]
    Z = jnp.where(col < c, Z, -1e30)
    e = jnp.exp(Z - jnp.max(Z, axis=-1, keepdims=True))
    Pw = e * (wm / jnp.sum(e, axis=-1, keepdims=True))
    WY = jnp.where(y2 == col, wm, 0.0)
    return A.T @ (Pw - WY)


def packed_softmax_grad_reference(Ab, W3, y2, WSP, *, c: int, S: int, Tw: int = TRIAL_BLOCK):
    """Pure-XLA reference of the kernel (same packing), for parity tests."""
    n_pad, dpp = Ab.shape
    n_wb, _, NB = W3.shape
    B = S * Tw
    A = Ab.astype(jnp.float32)
    y = y2[:, 0]

    def one_block(W):  # [dpp, NB]
        logits = A @ W  # [n, NB]
        L = logits.reshape(n_pad, c, B)
        P = jax.nn.softmax(L, axis=1)
        onehot = jax.nn.one_hot(y, c, dtype=jnp.float32)  # [n, c]
        wexp = jnp.repeat(WSP, Tw, axis=1)  # [n, B] (split-major blocks)
        R = (P - onehot[:, :, None]) * wexp[:, None, :]
        return jnp.einsum("nd,ncb->dcb", A, R).reshape(dpp, NB)

    return jax.vmap(one_block)(W3)
