"""Fused Pallas MLP training: the whole Adam minibatch epoch on-chip.

Capability target: BASELINE config 5 (MLPClassifier RandomizedSearchCV,
sklearn-MLP semantics — the reference worker fits `MLPClassifier`,
``aws-prod/worker/worker.py:36-57``). The generic vmapped fit
(models/mlp.py) is Adam-STATE-bandwidth bound, not compute bound: at
sklearn's batch-size semantics (<=256 rows/step) every step streams
params + both moments through HBM (~20 B/param/step/lane) while the
step's matmuls only touch ``batch_size`` rows — measured 7.3% MFU at
MNIST scale (VERDICT r3 #4).

This kernel breaks that floor by keeping (params, m, v) RESIDENT in VMEM
across all of an epoch's steps:

- grid = (lane_groups, n_batches), step-minor: the state blocks' index
  maps ignore the step axis, so Mosaic keeps them in VMEM across every
  step of a lane group — HBM state traffic collapses from per-STEP to
  per-EPOCH (``n_batches``x less);
- k lanes (trial x CV-split instances) are packed per grid step: they
  share the epoch-shuffled batch block (every lane of a bucket shares
  the shuffle stream — sklearn seeds it from ``random_state``, which is
  static per bucket), so the [bs, d] activations load once per k fits
  and the 3x2xk matmuls fill the MXU pipeline between batch copies;
- the epoch loop (lax.scan in models/mlp.py) re-shuffles rows in XLA
  (one gather) and re-enters the kernel with the carried state.

Semantics match models/mlp.py's scan step exactly — same Glorot init,
same permutation stream, same bf16 matmuls with f32 accumulation, same
loss scaling (mean weighted batch loss + alpha/2 * ||W||^2 / batch
weight) — with one deliberate upgrade: the first moment stays f32 (the
generic path stores it bf16 purely to cut the HBM traffic this kernel
does not pay).
"""

from __future__ import annotations

import functools
import os
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B1 = 0.9
B2 = 0.999
EPS = 1e-8
_LOG_B1 = float(np.log(B1))
_LOG_B2 = float(np.log(B2))


def _act_and_grad(name: str):
    """(activation, derivative-from-(z, a)) pair for hidden layers."""
    if name == "relu":
        return (lambda z: jnp.maximum(z, 0.0),
                lambda z, a: (z > 0.0).astype(jnp.float32))
    if name == "tanh":
        return jnp.tanh, lambda z, a: 1.0 - a * a
    if name == "logistic":
        return jax.nn.sigmoid, lambda z, a: a * (1.0 - a)
    return (lambda z: z), (lambda z, a: jnp.ones_like(a))


def _dot(a, b, dims, *, interpret: bool = False):
    # bf16 operands, f32 accumulation — the MXU's native mode, matching the
    # generic fit's matmul precision. The CPU interpreter (test coverage)
    # lacks the mixed bf16->f32 dot, so it computes in f32.
    dt = jnp.float32 if interpret else jnp.bfloat16
    return jax.lax.dot_general(
        a.astype(dt), b.astype(dt),
        (dims, ((), ())), preferred_element_type=jnp.float32,
    )


def _epoch_kernel(
    x_ref, y_ref, w_ref, lr_ref, alpha_ref, t0_ref, *state,
    act: str, k: int, n_layers: int, classification: bool,
    solver: str = "adam", momentum: float = 0.9, nesterov: bool = True,
    track_loss: bool = False, interpret: bool = False,
):
    """One grid step = one solver minibatch update for k packed lanes.

    ``state`` = (inputs..., outputs...): per layer, [k-block] slabs of
    (pW, pB, mW, mB, vW, vB) for adam or (pW, pB, velW, velB) for sgd
    (sklearn SGDOptimizer: velocity momentum, optionally Nesterov) —
    plus, when ``track_loss``, one trailing [k, 8, 128] per-lane
    epoch-loss accumulator slab (the adaptive-lr schedule's signal).
    Outputs are initialized from the inputs at step 0 and updated in
    place; their blocks revisit (index maps ignore the step axis) so they
    stay in VMEM until the lane group changes.

    Biases are carried as [k, 8, out] slabs of 8 IDENTICAL sublane rows:
    Mosaic cannot relayout the [1, out] vectors a scalar bias row would
    produce ("non-singleton logical dimension is replicated" compile
    error), so bias broadcast/reduction ride two tiny ones-matmuls
    ([bs, 8] x [8, out] and [8, bs] x [bs, out]) that keep every
    intermediate in a native 2-D layout. Elementwise updates preserve the
    row-identical invariant.
    """
    per_layer = 6 if solver == "adam" else 4
    n_half = per_layer * n_layers + (1 if track_loss else 0)
    ins, outs = state[:n_half], state[n_half:]
    step = pl.program_id(1)
    act_f, act_g = _act_and_grad(act)

    @pl.when(step == 0)
    def _init():
        for o, i_ in zip(outs, ins):
            o[...] = i_[...]

    t = (t0_ref[0, 0] + step + 1).astype(jnp.float32)
    bc1 = 1.0 - jnp.exp(t * _LOG_B1)
    bc2 = 1.0 - jnp.exp(t * _LOG_B2)

    xb = x_ref[...]
    yb = y_ref[...].astype(jnp.float32)
    bs = xb.shape[0]
    ones_b = jnp.full((bs, 8), 0.125, jnp.float32)  # bias broadcast operand
    ones_r = jnp.ones((8, bs), jnp.float32)  # bias reduction operand
    wv = w_ref[...]  # [bs, n_lanes] f32 split weights, shuffled like rows
    lrv = lr_ref[...]  # [n_lanes, 1]
    alv = alpha_ref[...]
    n_lanes = wv.shape[1]
    lane_iota_row = jax.lax.broadcasted_iota(jnp.int32, (1, n_lanes), 1)
    lane_iota_col = jax.lax.broadcasted_iota(jnp.int32, (n_lanes, 1), 0)
    lg = pl.program_id(0)

    def refs(li):
        return outs[per_layer * li : per_layer * (li + 1)]

    for i in range(k):
        # per-lane scalars/vectors via masked reduce (TPU block-shape rules
        # disallow k-row blocks narrower than a sublane, and the full
        # [bs, n_lanes] / [n_lanes, 1] operands are tiny)
        lane = lg * k + i
        lr = jnp.sum(jnp.where(lane_iota_col == lane, lrv, 0.0))
        alpha = jnp.sum(jnp.where(lane_iota_col == lane, alv, 0.0))
        # keepdims: 1-D [bs] vectors hit the same Mosaic replicated-dim
        # relayout error as scalar bias rows — stay 2-D throughout
        wb = jnp.sum(jnp.where(lane_iota_row == lane, wv, 0.0), axis=1,
                     keepdims=True)  # [bs, 1]
        bw = jnp.maximum(jnp.sum(wb), 1e-12)

        # ---- forward ----
        h = xb
        zs, acts = [], [xb]
        for li in range(n_layers):
            pW, pB = refs(li)[0], refs(li)[1]
            z = _dot(h, pW[i], ((1,), (0,)), interpret=interpret)
            z = z + _dot(ones_b, pB[i], ((1,), (0,)), interpret=interpret)
            a = act_f(z) if li < n_layers - 1 else z
            zs.append(z)
            acts.append(a)
            h = a

        # ---- output-layer gradient of the mean weighted loss ----
        if classification:
            p = jax.nn.softmax(acts[-1], axis=-1)
            dz = (p - yb) * (wb / bw)
        else:
            dz = (acts[-1] - yb) * (wb / bw)

        if track_loss:
            # per-batch DATA loss (the adaptive schedule's improvement
            # signal; the L2 term is added host-side per epoch)
            if classification:
                logp = jnp.log(jnp.maximum(p, 1e-12))
                batch_loss = -jnp.sum(yb * logp * wb) / bw
            else:
                batch_loss = 0.5 * jnp.sum(
                    (acts[-1] - yb) ** 2 * wb
                ) / bw
            loss_ref = outs[-1]
            loss_ref[i] = loss_ref[i] + batch_loss

        # ---- backward + in-place update, last layer first ----
        for li in range(n_layers - 1, -1, -1):
            slabs = refs(li)
            pW, pB = slabs[0], slabs[1]
            gW = _dot(acts[li], dz, ((0,), (0,)), interpret=interpret) + (alpha / bw) * pW[i]
            gB = _dot(ones_r, dz, ((1,), (0,)), interpret=interpret)
            if li > 0:
                da = _dot(dz, pW[i], ((1,), (1,)), interpret=interpret)
                dz = da * act_g(zs[li - 1], acts[li])

            if solver == "adam":
                _, _, mW, mB, vW, vB = slabs
                m = B1 * mW[i] + (1.0 - B1) * gW
                v = B2 * vW[i] + (1.0 - B2) * gW * gW
                mW[i], vW[i] = m, v
                pW[i] = pW[i] - lr * (m / bc1) / (jnp.sqrt(v / bc2) + EPS)

                mb = B1 * mB[i] + (1.0 - B1) * gB
                vb = B2 * vB[i] + (1.0 - B2) * gB * gB
                mB[i], vB[i] = mb, vb
                pB[i] = pB[i] - lr * (mb / bc1) / (jnp.sqrt(vb / bc2) + EPS)
            else:  # sgd: sklearn velocity momentum (+ Nesterov look-ahead)
                _, _, velW, velB = slabs
                vw = momentum * velW[i] - lr * gW
                vb = momentum * velB[i] - lr * gB
                velW[i], velB[i] = vw, vb
                if nesterov:
                    pW[i] = pW[i] + momentum * vw - lr * gW
                    pB[i] = pB[i] + momentum * vb - lr * gB
                else:
                    pW[i] = pW[i] + vw
                    pB[i] = pB[i] + vb


def vmem_lane_bytes(dims: Sequence[int], bs: int, solver: str = "adam") -> int:
    """Per-lane VMEM working set: 2x (in+out blocks) state slabs (3x f32
    for adam's params+moments, 2x for sgd's params+velocity) plus the
    step's live activations — the k-chooser's denominator."""
    params = sum(a * b + b for a, b in zip(dims[:-1], dims[1:]))
    acts = bs * (2 * sum(dims) + max(dims))
    per_layer = 12 if solver == "adam" else 8
    return 2 * per_layer * params + 4 * acts


def pick_k(dims: Sequence[int], bs: int, budget_bytes: int = 48 * 2**20,
           solver: str = "adam") -> int:
    """Largest k in {16,8,4,2,1} whose packed state fits the VMEM budget.

    The budget tracks the raised per-kernel vmem limit (the pallas_call
    passes compiler_params vmem_limit_bytes=100 MB), less headroom for
    the double-buffered batch blocks. k=16 (r5) opt-in via
    CS230_MLP_K16=1 — measured NEUTRAL on config 5 (same 23 s steady):
    at MNIST dims the kernel is batch-copy-bound, not lane-bound."""
    per = max(vmem_lane_bytes(dims, bs, solver), 1)
    ks = (16, 8, 4, 2, 1) if os.environ.get("CS230_MLP_K16") == "1" else (8, 4, 2, 1)
    for k in ks:
        if k * per <= budget_bytes:
            return k
    return 1


def build_epoch_fn(
    dims: Tuple[int, ...],
    act: str,
    bs: int,
    n_batches: int,
    n_lanes: int,
    k: int,
    classification: bool,
    solver: str = "adam",
    momentum: float = 0.9,
    nesterov: bool = True,
    track_loss: bool = False,
    interpret: bool = False,
):
    """fn(Xs, Ys, Wlane, lr, alpha, t0, state) -> state.

    ``Xs`` [n_batches*bs, d] bf16 and ``Ys`` [n_batches*bs, c] are the
    epoch-shuffled rows/targets; ``Wlane`` [n_batches*bs, n_lanes] f32 the
    per-lane split weights in the same shuffled row order (lane-minor so
    batch-step blocks satisfy TPU block-shape rules); ``lr``/``alpha``
    [n_lanes, 1]; ``t0`` [1, 1] int32 (completed step count). ``state`` is
    the flat per-layer list of [n_lanes, ...] — (pW, pB, mW, mB, vW, vB)
    for adam, (pW, pB, velW, velB) for sgd — plus, when ``track_loss``, a
    trailing [n_lanes, 8, 128] epoch-loss accumulator (zeroed at step 0,
    read back at [:, 0, 0]); biases are carried [n_lanes, 8, out] with
    identical sublane rows (see the kernel docstring).
    ``n_lanes`` must be a multiple of ``k``; ``bs`` a multiple of 8.
    """
    assert n_lanes % k == 0, (n_lanes, k)
    n_layers = len(dims) - 1
    grid = (n_lanes // k, n_batches)

    def lane_spec(shape):
        return pl.BlockSpec(
            (k,) + tuple(shape[1:]),
            lambda lg, s, _nd=len(shape): (lg,) + (0,) * (_nd - 1),
        )

    kern = functools.partial(
        _epoch_kernel, act=act, k=k, n_layers=n_layers,
        classification=classification, solver=solver, momentum=momentum,
        nesterov=nesterov, track_loss=track_loss, interpret=interpret,
    )

    def fn(Xs, Ys, Wlane, lr, alpha, t0, state):
        in_specs = [
            pl.BlockSpec((bs, dims[0]), lambda lg, s: (s, 0)),
            pl.BlockSpec((bs, dims[-1]), lambda lg, s: (s, 0)),
            pl.BlockSpec((bs, n_lanes), lambda lg, s: (s, 0)),
            pl.BlockSpec((n_lanes, 1), lambda lg, s: (0, 0)),
            pl.BlockSpec((n_lanes, 1), lambda lg, s: (0, 0)),
            pl.BlockSpec((1, 1), lambda lg, s: (0, 0)),
        ] + [lane_spec(a.shape) for a in state]
        out_specs = [lane_spec(a.shape) for a in state]
        out_shape = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in state]
        kwargs = {}
        if not interpret:
            # the packed lane state overflows the default 16 MB scoped-vmem
            # budget by design — residency is the point; v5e has 128 MB
            kwargs["compiler_params"] = pltpu.CompilerParams(
                vmem_limit_bytes=100 * 2**20,
            )
        return list(
            pl.pallas_call(
                kern,
                grid=grid,
                in_specs=in_specs,
                out_specs=out_specs,
                out_shape=out_shape,
                interpret=interpret,
                **kwargs,
            )(Xs, Ys, Wlane, lr, alpha, t0, *state)
        )

    return fn
