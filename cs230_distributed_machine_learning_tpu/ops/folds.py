"""Static-shape cross-validation: splits as weight masks.

The reference worker runs, per subtask, one ``train_test_split`` fit + eval
and a 5-fold ``cross_val_score`` on the full data — i.e. K+1 fits per trial
(``aws-prod/worker/worker.py:302-349``). On TPU, data-dependent subset shapes
would defeat XLA, so every split is expressed as a pair of {0,1} weight
vectors over the *full* (static-shape) dataset:

  row k of ``train_w`` selects the fit subset of split k,
  row k of ``eval_w``  selects the scoring subset of split k,

and kernels use weighted losses/metrics. Because sklearn's regularized
objectives are sums (not means) over samples, 0/1-weighting reproduces
fitting on the subset exactly.

Fold assignment itself is computed host-side with sklearn's own splitters so
fold boundaries (and therefore CV scores and ``best_params_``) match sklearn
bit-for-bit: StratifiedKFold for classifiers, KFold for regressors — the
same defaults ``cross_val_score(cv=5)`` uses.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class SplitPlan:
    """K+1 splits over n samples. Split 0 is the train/test holdout split
    (eval = test set); splits 1..K are the CV folds (eval = held-out fold)."""

    train_w: np.ndarray  # [K+1, n] float32 {0,1}
    eval_w: np.ndarray   # [K+1, n] float32 {0,1}
    n_folds: int
    #: content identity: (task, n, n_folds, test_size, random_state).
    #: Plans are deterministic in these, so equal signatures mean equal
    #: masks — the trial engine keys its device-staging cache on this
    #: (re-uploading fold tensors per job costs real seconds on a
    #: tunneled link). None (e.g. hand-built test plans) disables caching.
    signature: tuple | None = None

    @property
    def n_splits(self) -> int:
        return self.train_w.shape[0]

    @property
    def n_samples(self) -> int:
        return self.train_w.shape[1]


def build_split_plan(
    y: np.ndarray,
    *,
    task: str,
    n_folds: int = 5,
    test_size: float = 0.2,
    random_state: int | None = 42,
) -> SplitPlan:
    """Build the K+1 split masks for one dataset.

    task: "classification" uses stratified folds + stratify-free holdout,
    "regression" uses plain KFold — matching sklearn's cross_val_score
    defaults and the reference worker's train_test_split usage (with its
    positional-arg bug fixed, see SURVEY.md §2.4).
    """
    from sklearn.model_selection import KFold, StratifiedKFold, train_test_split

    n = len(y)
    idx = np.arange(n)
    train_idx, test_idx = train_test_split(
        idx, test_size=test_size, random_state=random_state
    )

    rows_train = [_mask(n, train_idx)]
    rows_eval = [_mask(n, test_idx)]

    if n_folds and n_folds >= 2:
        if task == "classification":
            splitter = StratifiedKFold(n_splits=n_folds)
            split_iter = splitter.split(np.zeros(n), y)
        else:
            splitter = KFold(n_splits=n_folds)
            split_iter = splitter.split(np.zeros(n))
        for fold_train, fold_eval in split_iter:
            rows_train.append(_mask(n, fold_train))
            rows_eval.append(_mask(n, fold_eval))

    return SplitPlan(
        train_w=np.stack(rows_train).astype(np.float32),
        eval_w=np.stack(rows_eval).astype(np.float32),
        n_folds=n_folds or 0,
        signature=(task, n, n_folds or 0, float(test_size), random_state),
    )


def _mask(n: int, idx: np.ndarray) -> np.ndarray:
    m = np.zeros(n, dtype=np.float32)
    m[idx] = 1.0
    return m
