"""Weighted evaluation metrics (jittable).

Parity targets: the reference worker scores classifiers with accuracy and
regressors with r2 + MSE (``aws-prod/worker/worker.py:320-349``), and ranks
trials by ``mean_cv_score``. All metrics here take a {0,1} sample-weight
vector so they evaluate a masked subset of a static-shape array (see
ops/folds.py).
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12


def weighted_accuracy(y_true, y_pred, w):
    w = w.astype(jnp.float32)
    correct = (y_true == y_pred).astype(jnp.float32)
    return jnp.sum(correct * w) / jnp.maximum(jnp.sum(w), _EPS)


def weighted_mse(y_true, y_pred, w):
    w = w.astype(jnp.float32)
    err = (y_true - y_pred) ** 2
    return jnp.sum(err * w) / jnp.maximum(jnp.sum(w), _EPS)


def weighted_r2(y_true, y_pred, w):
    w = w.astype(jnp.float32)
    wsum = jnp.maximum(jnp.sum(w), _EPS)
    ybar = jnp.sum(y_true * w) / wsum
    ss_res = jnp.sum(w * (y_true - y_pred) ** 2)
    ss_tot = jnp.maximum(jnp.sum(w * (y_true - ybar) ** 2), _EPS)
    return 1.0 - ss_res / ss_tot
