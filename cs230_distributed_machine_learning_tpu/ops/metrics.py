"""Weighted evaluation metrics (jittable) + the scoring registry.

Parity targets: the reference worker scores classifiers with accuracy and
regressors with r2 + MSE (``aws-prod/worker/worker.py:320-349``), and ranks
trials by ``mean_cv_score``. The reference *client* also captures a custom
``scoring`` from search wrappers (``DistributedLibrary/src/distributed_ml/
core.py:135-138``) but its worker silently drops it — trials are always
accuracy/r2-ranked. Here ``scoring`` is honored end-to-end: the registry
below maps sklearn scorer names to jittable weighted metrics, and the trial
engine ranks ``mean_cv_score`` by the requested scorer (greater-is-better,
matching sklearn's ``neg_*`` convention for error metrics).

All metrics take a {0,1} sample-weight vector so they evaluate a masked
subset of a static-shape array (see ops/folds.py).

CONTRACT — ``w`` is a binary keep-mask, not a general sample weight. The
averaging metrics happen to generalize to real-valued weights, but the
RANKING metrics (``weighted_average_precision``, ``weighted_roc_auc_*``)
use ``w`` only to exclude rows from their count tables and would silently
ignore weight magnitudes. Callers passing fractional weights get wrong
scores; the CV engine only ever passes fold masks.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12


def weighted_accuracy(y_true, y_pred, w):
    w = w.astype(jnp.float32)
    correct = (y_true == y_pred).astype(jnp.float32)
    return jnp.sum(correct * w) / jnp.maximum(jnp.sum(w), _EPS)


def weighted_mse(y_true, y_pred, w):
    w = w.astype(jnp.float32)
    err = (y_true - y_pred) ** 2
    return jnp.sum(err * w) / jnp.maximum(jnp.sum(w), _EPS)


def weighted_r2(y_true, y_pred, w):
    w = w.astype(jnp.float32)
    wsum = jnp.maximum(jnp.sum(w), _EPS)
    ybar = jnp.sum(y_true * w) / wsum
    ss_res = jnp.sum(w * (y_true - y_pred) ** 2)
    ss_tot = jnp.maximum(jnp.sum(w * (y_true - ybar) ** 2), _EPS)
    return 1.0 - ss_res / ss_tot


def weighted_mae(y_true, y_pred, w):
    w = w.astype(jnp.float32)
    return jnp.sum(jnp.abs(y_true - y_pred) * w) / jnp.maximum(jnp.sum(w), _EPS)


def weighted_explained_variance(y_true, y_pred, w):
    """sklearn explained_variance_score: 1 - Var(y - p) / Var(y), both
    variances weighted over kept rows (differs from r2 by tolerating a
    constant prediction offset)."""
    w = w.astype(jnp.float32)
    wsum = jnp.maximum(jnp.sum(w), _EPS)
    err = y_true - y_pred
    err_mean = jnp.sum(err * w) / wsum
    var_err = jnp.sum(w * (err - err_mean) ** 2) / wsum
    ybar = jnp.sum(y_true * w) / wsum
    var_y = jnp.maximum(jnp.sum(w * (y_true - ybar) ** 2) / wsum, _EPS)
    return 1.0 - var_err / var_y


def weighted_max_error(y_true, y_pred, w):
    err = jnp.abs(y_true - y_pred)
    return jnp.max(jnp.where(w > 0, err, 0.0))


def _class_counts(y_true, y_pred, w, n_classes):
    """Weighted per-class (tp, pred_count, true_count) over kept rows."""
    w = w.astype(jnp.float32)
    classes = jnp.arange(n_classes)
    true_oh = (y_true[:, None] == classes[None, :]).astype(jnp.float32) * w[:, None]
    pred_oh = (y_pred[:, None] == classes[None, :]).astype(jnp.float32) * w[:, None]
    tp = jnp.sum(true_oh * pred_oh, axis=0)
    pred_c = jnp.sum(pred_oh, axis=0)
    true_c = jnp.sum(true_oh, axis=0)
    return tp, pred_c, true_c


def _prf(y_true, y_pred, w, n_classes, stat, average):
    """sklearn precision/recall/f1 with average in macro|micro|weighted|binary.

    Per sklearn's zero_division default, an undefined per-class stat is 0;
    macro averages over labels present in y_true ∪ y_pred (sklearn's
    labels=None behavior), weighted averages by true support.
    """
    tp, pred_c, true_c = _class_counts(y_true, y_pred, w, n_classes)
    if average == "micro":
        TP, PC, TC = jnp.sum(tp), jnp.sum(pred_c), jnp.sum(true_c)
        if stat == "precision":
            return TP / jnp.maximum(PC, _EPS)
        if stat == "recall":
            return TP / jnp.maximum(TC, _EPS)
        return 2 * TP / jnp.maximum(PC + TC, _EPS)
    prec = tp / jnp.maximum(pred_c, _EPS)
    rec = tp / jnp.maximum(true_c, _EPS)
    per_class = {
        "precision": prec,
        "recall": rec,
        "f1": 2 * prec * rec / jnp.maximum(prec + rec, _EPS),
    }[stat]
    if average == "binary":  # pos_label=1, sklearn's default for 2-class
        return per_class[1]
    if average == "weighted":
        return jnp.sum(per_class * true_c) / jnp.maximum(jnp.sum(true_c), _EPS)
    present = ((true_c + pred_c) > 0).astype(jnp.float32)
    return jnp.sum(per_class * present) / jnp.maximum(jnp.sum(present), _EPS)


def weighted_balanced_accuracy(y_true, y_pred, w, n_classes):
    """Mean recall over classes with true support (sklearn drops absent
    classes from the average and warns; we drop silently)."""
    tp, _, true_c = _class_counts(y_true, y_pred, w, n_classes)
    present = (true_c > 0).astype(jnp.float32)
    rec = tp / jnp.maximum(true_c, _EPS)
    return jnp.sum(rec * present) / jnp.maximum(jnp.sum(present), _EPS)


def weighted_log_loss(y_true, proba, w, n_classes):
    """log_loss over kept rows: -mean log p(true class), with f32-eps
    probability clipping and NO renormalization — sklearn >= 1.5 order
    (clip only; non-normalized rows merely warn there). For normalized
    f32 probabilities this is EXACT parity with
    ``sklearn.metrics.log_loss`` on the same f32 input, including
    saturated rows (an exact 0 clips to eps, an exact 1 to 1-eps —
    pinned in tests/test_scoring.py); the old clip-then-renormalize
    order diverged by O(eps) exactly there (ADVICE r5 #4)."""
    w = w.astype(jnp.float32)
    eps = jnp.finfo(jnp.float32).eps
    p = jnp.clip(proba, eps, 1.0 - eps)
    classes = jnp.arange(n_classes)
    oh = (y_true[:, None] == classes[None, :]).astype(jnp.float32)
    ll = -jnp.sum(oh * jnp.log(p), axis=1)
    return jnp.sum(ll * w) / jnp.maximum(jnp.sum(w), _EPS)


def weighted_average_precision(y_true, score, w):
    """Binary average precision from a continuous score, tie-exact.

    ``w`` is a {0,1} KEEP-MASK only (module contract above): rows with
    w==0 are excluded from the count tables; a fractional weight would be
    treated as kept with weight 1.

    AP = sum over positive rows of precision-at-their-threshold / n_pos,
    where precision at threshold t counts ALL rows with score >= t (the
    whole tie group) — identical to sklearn's step-wise
    average_precision_score. Masked rows are pushed to -inf in the count
    tables so searchsorted never counts them (the same trick as
    weighted_roc_auc_binary)."""
    keep = w > 0
    s_all = jnp.sort(jnp.where(keep, score, -jnp.inf))
    s_pos = jnp.sort(jnp.where(keep & (y_true == 1), score, -jnp.inf))
    n_total = score.shape[0]
    n_below_all = jnp.searchsorted(s_all, score, side="left")
    n_below_pos = jnp.searchsorted(s_pos, score, side="left")
    n_ge = (n_total - n_below_all).astype(jnp.float32)   # kept rows >= s_i
    tp_ge = (n_total - n_below_pos).astype(jnp.float32)  # kept pos >= s_i
    prec = tp_ge / jnp.maximum(n_ge, 1.0)
    pos_w = (keep & (y_true == 1)).astype(jnp.float32)
    n_pos = jnp.sum(pos_w)
    return jnp.sum(prec * pos_w) / jnp.maximum(n_pos, _EPS)


def weighted_roc_auc_ovr(y_true, proba, w, n_classes):
    """Multiclass one-vs-rest ROC-AUC, macro over classes with positive
    support (sklearn's roc_auc_score(..., multi_class='ovr')). Each class's
    binary AUC uses its probability column as the score."""
    def one(c):
        return weighted_roc_auc_binary(
            (y_true == c).astype(jnp.int32), proba[:, c], w
        )

    aucs = jnp.stack([one(c) for c in range(n_classes)])
    w32 = w.astype(jnp.float32)
    support = jnp.stack([
        jnp.sum((y_true == c).astype(jnp.float32) * w32)
        for c in range(n_classes)
    ])
    present = (support > 0).astype(jnp.float32)
    return jnp.sum(aucs * present) / jnp.maximum(jnp.sum(present), _EPS)


def weighted_roc_auc_ovo(y_true, proba, w, n_classes):
    """Multiclass one-vs-one ROC-AUC (sklearn multi_class='ovo', macro):
    mean over unordered class pairs (a, b) of
    [AUC(a as pos, score p_a, rows in {a,b}) + AUC(b as pos, p_b)] / 2.
    Pairs where either class has no kept support are EXCLUDED from the
    mean (the binary AUC there is a degenerate 0 that would corrupt the
    score; sklearn raises — excluding mirrors the OVR absent-class mask)."""
    w32 = w.astype(jnp.float32)
    support = jnp.stack([
        jnp.sum((y_true == c).astype(jnp.float32) * w32)
        for c in range(n_classes)
    ])

    def pair(a, b):
        in_pair = ((y_true == a) | (y_true == b)).astype(w.dtype) * w
        auc_a = weighted_roc_auc_binary(
            (y_true == a).astype(jnp.int32), proba[:, a], in_pair
        )
        auc_b = weighted_roc_auc_binary(
            (y_true == b).astype(jnp.int32), proba[:, b], in_pair
        )
        return 0.5 * (auc_a + auc_b)

    pairs = [(a, b) for a in range(n_classes) for b in range(a + 1, n_classes)]
    vals = jnp.stack([pair(a, b) for a, b in pairs])
    ok = jnp.stack([
        (support[a] > 0) & (support[b] > 0) for a, b in pairs
    ]).astype(jnp.float32)
    return jnp.sum(vals * ok) / jnp.maximum(jnp.sum(ok), _EPS)


def weighted_roc_auc_binary(y_true, margin, w):
    """Binary ROC-AUC from a continuous decision score, via the average-rank
    formula (ties counted half) — identical to sklearn's trapezoidal
    roc_auc_score for binary targets. ``w`` is a {0,1} keep-mask (module
    contract above): masked rows are pushed to +inf in the negative-score
    table so searchsorted never counts them; weight magnitudes are
    ignored."""
    keep = w > 0
    neg_scores = jnp.where(keep & (y_true == 0), margin, jnp.inf)
    sorted_neg = jnp.sort(neg_scores)
    n_less = jnp.searchsorted(sorted_neg, margin, side="left")
    n_leq = jnp.searchsorted(sorted_neg, margin, side="right")
    pair_wins = n_less.astype(jnp.float32) + 0.5 * (n_leq - n_less).astype(jnp.float32)
    pos_w = (keep & (y_true == 1)).astype(jnp.float32)
    P = jnp.sum(pos_w)
    N = jnp.sum((keep & (y_true == 0)).astype(jnp.float32))
    return jnp.sum(pair_wins * pos_w) / jnp.maximum(P * N, _EPS)


# ---------------------------------------------------------------------------
# Scoring registry: sklearn scorer-name -> jittable weighted metric.
# All entries are greater-is-better (sklearn's neg_* convention), so
# mean_cv_score ranking (argmax) is scorer-agnostic.
# ---------------------------------------------------------------------------

_CLS_LABEL_SCORERS = {
    "accuracy": lambda y, p, w, k: weighted_accuracy(y, p, w),
    "balanced_accuracy": weighted_balanced_accuracy,
    "f1": lambda y, p, w, k: _prf(y, p, w, k, "f1", "binary"),
    "f1_macro": lambda y, p, w, k: _prf(y, p, w, k, "f1", "macro"),
    "f1_micro": lambda y, p, w, k: _prf(y, p, w, k, "f1", "micro"),
    "f1_weighted": lambda y, p, w, k: _prf(y, p, w, k, "f1", "weighted"),
    "precision": lambda y, p, w, k: _prf(y, p, w, k, "precision", "binary"),
    "precision_macro": lambda y, p, w, k: _prf(y, p, w, k, "precision", "macro"),
    "precision_micro": lambda y, p, w, k: _prf(y, p, w, k, "precision", "micro"),
    "precision_weighted": lambda y, p, w, k: _prf(y, p, w, k, "precision", "weighted"),
    "recall": lambda y, p, w, k: _prf(y, p, w, k, "recall", "binary"),
    "recall_macro": lambda y, p, w, k: _prf(y, p, w, k, "recall", "macro"),
    "recall_micro": lambda y, p, w, k: _prf(y, p, w, k, "recall", "micro"),
    "recall_weighted": lambda y, p, w, k: _prf(y, p, w, k, "recall", "weighted"),
}

_CLS_MARGIN_SCORERS = {
    "roc_auc": weighted_roc_auc_binary,
    "average_precision": weighted_average_precision,
}

#: scorers evaluated on the predicted class-probability matrix [n, k]
_CLS_PROBA_SCORERS = {
    "neg_log_loss": lambda y, p, w, k: -weighted_log_loss(y, p, w, k),
    "roc_auc_ovr": weighted_roc_auc_ovr,
    "roc_auc_ovo": weighted_roc_auc_ovo,
}

_REG_SCORERS = {
    "r2": weighted_r2,
    "neg_mean_squared_error": lambda y, p, w: -weighted_mse(y, p, w),
    "neg_root_mean_squared_error": lambda y, p, w: -jnp.sqrt(weighted_mse(y, p, w)),
    "neg_mean_absolute_error": lambda y, p, w: -weighted_mae(y, p, w),
    "max_error": lambda y, p, w: -weighted_max_error(y, p, w),
    "explained_variance": weighted_explained_variance,
}


_BINARY_ONLY_SCORERS = frozenset(
    {"f1", "precision", "recall", "roc_auc", "average_precision"}
)


def validate_scoring(scoring, task: str, n_classes: int = 0, kernel=None) -> None:
    """Raise ValueError for a scoring this engine cannot honor — at job
    submission, not deep inside a trace (the reference silently *dropped*
    custom scoring, worker.py:320-349; failing loudly beats that). With
    ``n_classes``/``kernel`` provided, also rejects what sklearn rejects
    (binary-average scorers on multiclass targets) and what it can't know
    (margin scorers on kernels without a decision margin)."""
    if scoring is None:
        return
    if callable(scoring) and not isinstance(scoring, str):
        # callable scorers take the host-side fallback path (executor
        # fits per fold on device, exports an sklearn estimator, calls
        # the scorer on host) — nothing to validate here beyond arity
        return
    if not isinstance(scoring, str):
        raise ValueError(
            f"scoring must be a sklearn scorer name or a callable "
            f"scorer(estimator, X, y) (got {type(scoring).__name__})"
        )
    if task == "classification":
        known = (
            set(_CLS_LABEL_SCORERS) | set(_CLS_MARGIN_SCORERS)
            | set(_CLS_PROBA_SCORERS)
        )
    elif task == "regression":
        known = set(_REG_SCORERS)
    else:
        raise ValueError(f"scoring={scoring!r} is not applicable to task {task!r}")
    if scoring not in known:
        raise ValueError(
            f"unsupported scoring {scoring!r} for {task} (supported: {sorted(known)})"
        )
    if scoring in _BINARY_ONLY_SCORERS and n_classes > 2:
        raise ValueError(
            f"scoring={scoring!r} is binary-only but the target has "
            f"{n_classes} classes (sklearn raises here too; use the "
            f"_macro/_micro/_weighted average variants)"
        )
    if scoring in _CLS_MARGIN_SCORERS and kernel is not None:
        # a kernel supports margin scorers iff it overrides predict_margin
        from ..models.base import ModelKernel

        if type(kernel).predict_margin is ModelKernel.predict_margin:
            raise ValueError(
                f"scoring={scoring!r} needs a decision margin, which the "
                f"{kernel.name} kernel does not expose"
            )
    if scoring in _CLS_PROBA_SCORERS and kernel is not None:
        from ..models.base import ModelKernel

        if type(kernel).predict_proba is ModelKernel.predict_proba:
            raise ValueError(
                f"scoring={scoring!r} needs class probabilities, which the "
                f"{kernel.name} kernel does not expose"
            )


def scoring_needs_margin(scoring) -> bool:
    return isinstance(scoring, str) and scoring in _CLS_MARGIN_SCORERS


def scoring_needs_proba(scoring) -> bool:
    return isinstance(scoring, str) and scoring in _CLS_PROBA_SCORERS


def proba_score(scoring, y_true, proba, w, n_classes):
    return _CLS_PROBA_SCORERS[scoring](
        y_true, proba, w, max(int(n_classes), 2)
    )


def classification_score(scoring, y_true, y_pred, w, n_classes):
    """Label-based classification score for the requested scorer (default
    accuracy). ``scoring`` is a static Python string — dispatch happens at
    trace time."""
    if scoring in (None, "accuracy"):
        return weighted_accuracy(y_true, y_pred, w)
    if scoring in _CLS_MARGIN_SCORERS:
        raise ValueError(
            f"scoring={scoring!r} needs a decision margin; this kernel's "
            "evaluation path only produces labels"
        )
    return _CLS_LABEL_SCORERS[scoring](y_true, y_pred, w, max(int(n_classes), 2))


def margin_score(scoring, y_true, margin, w):
    return _CLS_MARGIN_SCORERS[scoring](y_true, margin, w)


def regression_score(scoring, y_true, y_pred, w):
    if scoring in (None, "r2"):
        return weighted_r2(y_true, y_pred, w)
    return _REG_SCORERS[scoring](y_true, y_pred, w)
