"""Fused level-histogram kernels for the chunked tree protocol.

The tree families' per-level cost is dominated by the histogram
contraction ``H[m, f, b, k] = sum_r onehot(node)[r, m] * SC[r, k] *
onehot(bin)[r, f, b]`` (52% of the per-level budget at the production
shape, benchmarks/deep_profile.py). The XLA one-hot matmul form
(``ops/trees.py:_level_histogram_multi``) materializes BOTH 0/1 operands
in HBM between the elementwise one-hot construction and the dot — the
``T1 = onehot(node) ⊗ SC`` tensor ([row_chunk, n_nodes*kk], ~1 GB/level
of write+read traffic per lane at W=1024) is the measured dominant
memory-traffic term.

Two replacements, selected by the ``CS230_HIST_KERNEL`` valve in
ops/trees.py:

- ``level_histogram_pallas`` — a Pallas TPU kernel that builds both
  one-hot operands as VMEM intermediates inside the grid step and feeds
  them straight to the MXU: the [bm, Mb*kk] and [bm, d*n_bins] 0/1 tiles
  never exist in HBM, and the [Mb*kk, d*n_bins] accumulator page stays
  resident in VMEM across all row tiles of a node block. Bin-and-scatter
  semantics, MXU execution (true per-row scatters serialize ~10-30x on
  TPU — measured, see ops/trees.py).
- ``level_histogram_scatter`` — the literal bin-and-scatter formulation
  (one segment-sum per feature): O(n*d*kk) adds instead of the matmul's
  O(n*W*kk*d*n_bins) MACs. This is the fast form on scatter-friendly
  backends (CPU: the one-hot matmul's W-fold arithmetic redundancy is
  catastrophic without an MXU to hide it — measured ~13x at W=64, see
  benchmarks/DEEP_PROFILE_HIST_{BEFORE,AFTER}.json).

Both reproduce the matmul form exactly for integer-valued stats (every
product is exact; f32/s32 accumulation of integers < 2^24), and to f32
summation-order tolerance for float stats. Parity is pinned on CPU by
tests/test_pallas_hist.py (the Pallas kernel through its interpreter).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: rows per grid step / node-block width of the Pallas kernel. Mb * kk
#: one-hot columns per tile keeps T1 at [256, 512] and the accumulator
#: page at [512, d*n_bins] — a few MB of VMEM at covertype shapes.
ROW_TILE = 256
NODE_BLOCK = 64


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _hist_kernel(lb_ref, xb_ref, sc_ref, h_ref, *, Mb: int, kk: int, d: int,
                 n_bins: int, xpad: int, op_dt):
    """One (node-block, row-tile) grid step.

    lb_ref [bm, 1]   i32  per-row node id (rows outside this block no-op)
    xb_ref [bm, d]   i32  per-row bin codes
    sc_ref [bm, kk]  f32  per-row stats (pad rows must carry zeros)
    h_ref  [1, kk*Mb, xpad] f32 accumulator page, revisited across row
           tiles; rows are k-major (row = k*Mb + m), cols feature-major
           (col = f*n_bins + b, zero-padded to xpad).
    """
    nb = pl.program_id(0)
    i = pl.program_id(1)
    bm = lb_ref.shape[0]
    base = nb * Mb

    lb = lb_ref[:]  # [bm, 1]
    node_col = jax.lax.broadcasted_iota(jnp.int32, (bm, Mb), 1) + base
    N = (lb == node_col).astype(op_dt)  # [bm, Mb] block-local one-hot
    sc = sc_ref[:].astype(op_dt)

    # T1 = one_hot(node) ⊗ SC, k-major columns — built in VMEM, never HBM
    t1_parts = [N * sc[:, j : j + 1] for j in range(kk)]
    T1 = jnp.concatenate(t1_parts, axis=1)  # [bm, kk*Mb]

    # bin one-hot, feature-major columns, zero-padded to the tile width
    xb = xb_ref[:]
    bin_col = jax.lax.broadcasted_iota(jnp.int32, (bm, n_bins), 1)
    b_parts = [
        (xb[:, f : f + 1] == bin_col).astype(op_dt) for f in range(d)
    ]
    if xpad > d * n_bins:
        b_parts.append(jnp.zeros((bm, xpad - d * n_bins), op_dt))
    B = jnp.concatenate(b_parts, axis=1)  # [bm, xpad]

    @pl.when(i == 0)
    def _init():
        h_ref[0] = jnp.zeros_like(h_ref[0])

    h_ref[0] += jax.lax.dot_general(
        T1,
        B,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins", "bm", "Mb", "integer_stats", "interpret"),
)
def level_histogram_pallas(local, xb, SC, n_nodes: int, n_bins: int, *,
                           bm: int = ROW_TILE, Mb: int = NODE_BLOCK,
                           integer_stats: bool = False,
                           interpret: bool = False):
    """[n_nodes, d, n_bins, kk] level histogram (same contract as
    ``ops/trees.py:_level_histogram``) as a fused Pallas kernel.

    ``integer_stats`` selects bf16 one-hot/stat operands (exact: every
    product is a single stat value < 2^8 picked by 0/1 factors, summed in
    f32); float stats use f32 operands. The interpreter path (CPU test
    coverage) always computes in f32.
    """
    n, d = xb.shape
    kk = SC.shape[1]
    Mb = min(Mb, _ceil_to(max(n_nodes, 8), 8))
    n_pad = _ceil_to(n, bm)
    if n_pad != n:
        # pad rows carry zero stats — wherever their node id lands, the
        # contribution is zero
        local = jnp.pad(local, (0, n_pad - n))
        xb = jnp.pad(xb, ((0, n_pad - n), (0, 0)))
        SC = jnp.pad(SC, ((0, n_pad - n), (0, 0)))
    NBk = pl.cdiv(n_nodes, Mb)
    xpad = _ceil_to(d * n_bins, 128)
    op_dt = jnp.float32 if (interpret or not integer_stats) else jnp.bfloat16

    kernel = functools.partial(
        _hist_kernel, Mb=Mb, kk=kk, d=d, n_bins=n_bins, xpad=xpad, op_dt=op_dt
    )
    out = pl.pallas_call(
        kernel,
        grid=(NBk, n_pad // bm),
        in_specs=[
            pl.BlockSpec((bm, 1), lambda nb, i: (i, 0)),
            pl.BlockSpec((bm, d), lambda nb, i: (i, 0)),
            pl.BlockSpec((bm, kk), lambda nb, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, kk * Mb, xpad), lambda nb, i: (nb, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((NBk, kk * Mb, xpad), jnp.float32),
        interpret=interpret,
    )(local[:, None].astype(jnp.int32), xb.astype(jnp.int32),
      SC.astype(jnp.float32))

    # [NBk, kk, Mb, d, n_bins] -> [NBk*Mb, d, n_bins, kk] -> [n_nodes, ...]
    H = out[:, :, : d * n_bins].reshape(NBk, kk, Mb, d, n_bins)
    return H.transpose(0, 2, 3, 4, 1).reshape(NBk * Mb, d, n_bins, kk)[:n_nodes]


def pallas_hist_applicable(d: int, n_bins: int, kk: int) -> bool:
    """Static shape gate: the accumulator page + one-hot tiles must fit
    the VMEM budget (~6 MB at the defaults)."""
    return d * n_bins <= 4096 and kk <= 16 and n_bins <= 256


def level_histogram_scatter(local, xb, SC, n_nodes: int, n_bins: int):
    """The literal bin-and-scatter form: one segment-sum per feature.

    O(n * d * kk) scatter-adds; exact f32 accumulation (bit-identical to
    the matmul form for integer stats, summation-order ulps for floats).
    Rows whose node id falls outside [0, n_nodes) are dropped — the same
    dead-row semantics as the one-hot forms.
    """
    n, d = xb.shape
    local = local.astype(jnp.int32)
    seg = n_nodes * n_bins
    valid = (local >= 0) & (local < n_nodes)
    base = jnp.where(valid, local, n_nodes) * n_bins  # invalid -> dropped
    cols = []
    for f in range(d):
        idx = jnp.where(valid, base + xb[:, f], seg)
        cols.append(
            jax.ops.segment_sum(SC, idx, num_segments=seg).reshape(
                n_nodes, n_bins, SC.shape[1]
            )
        )
    return jnp.stack(cols, axis=1)  # [n_nodes, d, n_bins, kk]
