from .manager import MLTaskManager

__all__ = ["MLTaskManager"]
