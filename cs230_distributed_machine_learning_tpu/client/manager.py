"""MLTaskManager: the user-facing client API.

Method-for-method parity with the reference SDK
(``DistributedLibrary/src/distributed_ml/core.py:15-213``): sessions are
created at construction; ``check_data`` / ``download_data`` / ``preprocess``
manage datasets; ``train`` accepts a live sklearn estimator or
GridSearchCV/RandomizedSearchCV wrapper plus ``train_params`` and optionally
blocks with progress; ``check_job_status`` returns per-trial metrics;
``download_best_model`` fetches the winning artifact.

Two transports:
- **local** (default, ``url=None``): talks directly to an in-process
  Coordinator — the idiomatic single-host TPU deployment (no HTTP at all).
- **remote** (``url=...``): REST against a coordinator server
  (runtime/server.py), wire-compatible with the reference master's routes.

Reference client quirks fixed, not copied (SURVEY.md §2.1): the broken
status-code check (core.py:31), train() posting to the SSE endpoint but
polling /metrics (core.py:169,178), and the 60 s default timeout.
"""

from __future__ import annotations

import json
import random
import time
import uuid
from typing import Any, Dict, Optional

from ..obs import TRACE_HEADER, activate, new_trace_id, span
from ..runtime.store import TERMINAL_STATUSES
from ..utils.config import get_config
from ..utils.serialization import json_safe
from .introspection import extract_model_details


class MLTaskManager:
    def __init__(
        self,
        url: Optional[str] = None,
        coordinator=None,
        priority: int = 0,
    ):
        """``priority`` is this session's QoS lane (docs/ARCHITECTURE.md
        "QoS priority lanes"): subtasks of its jobs dispatch ahead of
        lower lanes when the fleet is backlogged. Default 0 keeps the
        legacy FIFO behavior."""
        self.api_url = url.rstrip("/") if url else None
        self.priority = int(priority)
        if self.api_url is None:
            if coordinator is None:
                from ..runtime.coordinator import Coordinator

                coordinator = Coordinator()
            self._coordinator = coordinator
        else:
            self._coordinator = None
        self.session_id = self._create_session()
        self.job_id: Optional[str] = None
        self.result: Optional[Dict[str, Any]] = None
        #: trace id of the most recent train() — minted client-side and
        #: propagated to the coordinator (X-Trace-Id header on REST, trace
        #: context in local mode); read GET /trace/<job_id> with it
        self.trace_id: Optional[str] = None

    # ------------- session -------------

    def _create_session(self) -> str:
        if self._coordinator is not None:
            return self._coordinator.create_session(
                priority=self.priority
            )
        resp = self._request(
            "post", "create_session",
            json={"priority": self.priority} if self.priority else None,
        )
        return resp["session_id"]

    # ------------- data management -------------

    def check_data(self, data_name: str) -> Dict[str, Any]:
        if self._coordinator is not None:
            return self._coordinator.check_data(self.session_id, data_name)
        return self._request(
            "get", f"check_data/{self.session_id}", params={"dataset_name": data_name}
        )

    def download_data(self, data_link: str, data_name: str, data_type: str) -> Dict[str, Any]:
        if self._coordinator is not None:
            return self._coordinator.download_data(
                self.session_id, data_link, data_name, data_type
            )
        return self._request(
            "post",
            f"download_data/{self.session_id}",
            json={
                "dataset_url": data_link,
                "dataset_name": data_name,
                "dataset_type": data_type,
            },
        )

    def preprocess(self, dataset_id: str, config: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        if self._coordinator is not None:
            return self._coordinator.preprocess(self.session_id, dataset_id, config)
        return self._request(
            "post",
            f"preprocess/{self.session_id}",
            json={"dataset_id": dataset_id, "config": config},
        )

    # ------------- training -------------

    def train(
        self,
        estimator: Any,
        dataset_id: Optional[str] = None,
        train_params: Optional[Dict[str, Any]] = None,
        wait_for_completion: bool = True,
        timeout: Optional[float] = None,
        show_progress: bool = True,
        *,
        dataset_name: Optional[str] = None,
        stream: bool = False,
        search_params: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Submit a training / hyperparameter-search job.

        train_params: {test_size=0.2, random_state=42, cv=5} — the plain-
        estimator default test_size matches the reference (core.py:160-163).
        ``dataset_name=`` is accepted as an alias for ``dataset_id`` — the
        reference README's examples use that keyword (README.md:70-76).

        ``search_params=`` opts the job into adaptive search
        (docs/SEARCH.md): ``{"type": "asha" | "hyperband", "eta": 3,
        "min_resource": r, "max_resource": R, "n_iter": n,
        "stop_score": s, "max_brackets": b}``. The estimator's
        param grid/distributions supply the trial configurations (a
        RandomizedSearchCV wrapper works as-is); the rung controller owns
        the resource knob (max_iter / n_estimators) and stops doomed
        trials early with the ``pruned`` terminal status. Progress events
        then carry ``tasks_pruned`` and a per-rung ``search`` summary.

        ``stream=True`` (with ``wait_for_completion``) follows the job by
        CONSUMING the server-sent-event stream instead of polling: remote
        mode posts to ``/train_status`` and reads its SSE body (the
        reference client posted there and then ignored the stream,
        core.py:169 — fixed, not copied); local mode consumes the
        coordinator's ``stream_status`` generator. Progress events update
        the progress bar; the final event carries ``job_result``.
        """
        if dataset_name is not None:
            if dataset_id is not None and dataset_id != dataset_name:
                raise TypeError(
                    f"conflicting dataset_id={dataset_id!r} and "
                    f"dataset_name={dataset_name!r} — pass one"
                )
            dataset_id = dataset_name
        if dataset_id is None:
            raise TypeError("train() requires a dataset id (dataset_id= or dataset_name=)")
        model_details = extract_model_details(estimator)
        if search_params:
            sp = dict(search_params)
            stype = sp.pop("type", "asha")
            if stype not in ("asha", "hyperband"):
                raise ValueError(
                    f"search_params['type'] must be 'asha' or 'hyperband', "
                    f"got {stype!r}"
                )
            model_details["search_type"] = stype
            for key in ("n_iter", "random_state"):
                if key in sp:
                    model_details[key] = sp.pop(key)
            model_details["asha"] = sp
        train_params = dict(train_params or {})
        train_params.setdefault("test_size", get_config().execution.default_test_size)
        self.job_id = str(uuid.uuid4())
        payload = {
            "job_id": self.job_id,
            "session_id": self.session_id,
            "dataset_id": dataset_id,
            "model_details": model_details,
            "train_params": train_params,
            "timestamp": time.time(),
        }
        self.trace_id = new_trace_id()
        if self._coordinator is not None:
            # local mode: the job trace starts here — activate the id so
            # submit_train (same process) adopts it, bracketed by a
            # client-side span
            with activate(self.trace_id):
                with span("client.train", trace_id=self.trace_id,
                          job_id=self.job_id, dataset_id=dataset_id):
                    submit = self._coordinator.submit_train(
                        self.session_id, payload
                    )
        else:
            scoring = (model_details.get("cv_params") or {}).get("scoring")
            if callable(scoring) and not isinstance(scoring, str):
                # json_safe would stringify the function into an
                # unsupported-scorer name server-side — fail HERE with the
                # real reason instead (callables work in local mode, where
                # the object reaches the executor's host-side fallback)
                raise ValueError(
                    "callable scoring cannot be sent over the REST "
                    "transport (it is not JSON-serializable); use a scorer "
                    "name, or a local-mode MLTaskManager for callable "
                    "scorers"
                )
            if stream and wait_for_completion:
                # /train_status both submits AND streams: one request
                return self._train_stream(
                    payload, timeout=timeout, show_progress=show_progress
                )
            # idempotent: the payload carries the client-minted job_id and
            # the coordinator dedupes resubmits on it, so a retried POST
            # (coordinator restart, 429 backoff) can never double-expand
            submit = self._request(
                "post", f"train/{self.session_id}", json=json_safe(payload),
                headers={TRACE_HEADER: self.trace_id}, idempotent=True,
            )
        # adopt the CANONICAL job id: a sharded coordinator stamps the
        # client-minted id with its shard (``s<k>-``) so any front end
        # routes follow-up status/SSE/model requests without a lookup
        # (runtime/sharding.py); unsharded coordinators echo the id back
        self.job_id = submit.get("job_id") or self.job_id
        if not wait_for_completion:
            return submit
        if stream and self._coordinator is not None:
            return self._stream_local(timeout=timeout, show_progress=show_progress)
        return self._wait_for_completion(timeout=timeout, show_progress=show_progress)

    def _wait_for_completion(
        self, timeout: Optional[float] = None, show_progress: bool = True
    ) -> Dict[str, Any]:
        cfg = get_config().service
        timeout = timeout or cfg.client_timeout_s
        poll = cfg.client_poll_s if self._coordinator is None else 0.1
        bar = self._progress_bar(show_progress)
        deadline = time.time() + timeout
        try:
            while time.time() < deadline:
                if self._coordinator is not None:
                    # event-driven: wake on finalize (or after `poll` to
                    # refresh the progress bar), never a blind sleep
                    self._coordinator.store.wait_job(
                        self.session_id,
                        self.job_id,
                        timeout=(min(poll, deadline - time.time()) if bar is not None
                                 else deadline - time.time()),
                    )
                status = self.check_status()
                job_status = status.get("job_status")
                if bar is not None:
                    bar.n = int(_pct(job_status))
                    _bar_postfix(bar, status)
                    bar.refresh()
                if job_status in TERMINAL_STATUSES:
                    self.result = status.get("job_result")
                    return status
                if self._coordinator is None:
                    time.sleep(poll)
        finally:
            if bar is not None:
                bar.close()
        raise TimeoutError(f"Job {self.job_id} did not complete within {timeout}s")

    # ------------- SSE streaming (stream=True) -------------

    @staticmethod
    def _progress_bar(show_progress: bool):
        if not show_progress:
            return None
        try:
            from tqdm import tqdm

            # disable=None: auto-off when stderr is not a tty (piped
            # logs otherwise get one bar line per poll tick)
            return tqdm(total=100, desc="job", unit="%", disable=None)
        except ImportError:
            return None

    def _finish_stream(self, last: Optional[Dict[str, Any]], timeout: float):
        if last is None or last.get("job_status") not in TERMINAL_STATUSES:
            raise TimeoutError(
                f"Job {self.job_id} stream ended without completion "
                f"(timeout {timeout}s)"
            )
        self.result = last.get("job_result")
        return last

    def _stream_local(
        self, timeout: Optional[float] = None, show_progress: bool = True
    ) -> Dict[str, Any]:
        """Local-mode stream consumption: iterate the coordinator's
        ``stream_status`` generator (the SSE body source) to completion."""
        timeout = timeout or get_config().service.client_timeout_s
        deadline = time.time() + timeout
        bar = self._progress_bar(show_progress)
        last: Optional[Dict[str, Any]] = None
        try:
            for progress in self._coordinator.stream_status(
                self.session_id, self.job_id
            ):
                if progress.get("kind") == "curve":
                    # interleaved learning-curve event (trial telemetry
                    # plane) — not a progress snapshot; read via curves()
                    continue
                last = progress
                if bar is not None:
                    bar.n = int(_pct(progress.get("job_status")))
                    _bar_postfix(bar, progress)
                    bar.refresh()
                if progress.get("job_status") in TERMINAL_STATUSES:
                    break
                if time.time() > deadline:
                    raise TimeoutError(
                        f"Job {self.job_id} did not complete within {timeout}s"
                    )
        finally:
            if bar is not None:
                bar.close()
        return self._finish_stream(last, timeout)

    def _train_stream(
        self,
        payload: Dict[str, Any],
        timeout: Optional[float] = None,
        show_progress: bool = True,
    ) -> Dict[str, Any]:
        """Remote-mode stream consumption: POST the job to ``/train_status``
        and read the SSE events off the response body (one request submits
        and follows). Events arrive every ``sse_tick_s``; a read stalled
        well past that cadence — or the overall deadline — raises.

        A DROPPED stream (coordinator restart, broken connection) is
        resumed, not raised: the payload carries the client-minted job_id
        and the coordinator dedupes resubmits on it, so re-POSTing the same
        body re-attaches to the SAME job's stream and progress continues
        from the last seen event (each SSE event is a full progress
        snapshot — nothing between drop and resume is lost). 429/503
        responses back off per their ``Retry-After``."""
        import requests

        cfg = get_config().service
        timeout = timeout or cfg.client_timeout_s
        start = time.time()
        deadline = start + timeout
        retry_window = max(cfg.request_retry_s, 0.0)
        read_timeout = max(10.0, 8 * cfg.sse_tick_s)
        bar = self._progress_bar(show_progress)
        last: Optional[Dict[str, Any]] = None
        attempt = 0
        established = False  # a stream was successfully opened at least once
        try:
            while time.time() < deadline:
                try:
                    resp = requests.post(
                        f"{self.api_url}/train_status/{self.session_id}",
                        json=json_safe(payload),
                        headers={TRACE_HEADER: self.trace_id}
                        if self.trace_id else None,
                        stream=True,
                        timeout=(10, read_timeout),
                    )
                except (requests.ConnectionError, requests.Timeout):
                    # an endpoint that NEVER answered is a config error,
                    # not a drop: surface it within the retry window
                    # instead of spinning to the job deadline
                    # (request_retry_s=0 restores raise-immediately)
                    if not established and time.time() - start > retry_window:
                        raise
                    attempt += 1
                    time.sleep(_retry_delay(attempt))
                    continue
                if resp.status_code in (429, 503) and retry_window > 0:
                    retry_after = resp.headers.get("Retry-After")
                    resp.close()
                    attempt += 1
                    time.sleep(_retry_delay(attempt, retry_after))
                    continue
                try:
                    # fatal HTTP errors (bad session/payload) raise NOW —
                    # only drops of an ESTABLISHED stream are resumed
                    resp.raise_for_status()
                except requests.HTTPError:
                    resp.close()
                    raise
                established = True
                try:
                    for raw in resp.iter_lines():
                        if not raw:
                            continue
                        line = raw.decode() if isinstance(raw, bytes) else raw
                        if not line.startswith("data: "):
                            continue
                        try:
                            event = json.loads(line[len("data: "):])
                        except ValueError:
                            # a torn event (connection died mid-write):
                            # the stream is about to end — resume path
                            continue
                        if event.get("kind") == "curve":
                            # interleaved learning-curve SSE event — skip
                            # (progress bars want snapshots; curves())
                            attempt = 0
                            continue
                        last = event
                        attempt = 0  # real progress resets the backoff
                        # progress events carry the canonical (shard-
                        # stamped) job id — adopt it so post-stream
                        # status/model calls route through any front end
                        if event.get("job_id"):
                            self.job_id = event["job_id"]
                        if bar is not None:
                            bar.n = int(_pct(event.get("job_status")))
                            _bar_postfix(bar, event)
                            bar.refresh()
                        if event.get("job_status") in TERMINAL_STATUSES:
                            return self._finish_stream(last, timeout)
                        if time.time() > deadline:
                            raise TimeoutError(
                                f"Job {self.job_id} did not complete "
                                f"within {timeout}s"
                            )
                except requests.RequestException:
                    # stream dropped mid-job: resume by re-POSTing the
                    # deduped submit instead of raising (the loop)
                    attempt += 1
                    time.sleep(_retry_delay(attempt))
                finally:
                    resp.close()
                # a stream that ENDED without a terminal event (graceful
                # server shutdown mid-job) resumes exactly like a drop —
                # paced at the SSE tick so a flapping server isn't hammered
                time.sleep(min(1.0, max(cfg.sse_tick_s, 0.1)))
            return self._finish_stream(last, timeout)
        finally:
            if bar is not None:
                bar.close()

    # ------------- status / results -------------

    def check_status(self, job_id: Optional[str] = None) -> Dict[str, Any]:
        jid = job_id or self.job_id
        if self._coordinator is not None:
            return self._coordinator.check_status(self.session_id, jid)
        return self._request("get", f"check_status/{self.session_id}/{jid}")

    def check_job_status(self, job_id: Optional[str] = None):
        """Per-trial metrics array (the reference binds this to /metrics,
        core.py:176-178 — kept for API parity)."""
        jid = job_id or self.job_id
        if self._coordinator is not None:
            return self._coordinator.job_metrics(self.session_id, jid)
        return self._request("get", f"metrics/{self.session_id}/{jid}")

    def explain(
        self, job_id: Optional[str] = None, subtask_id: Optional[str] = None
    ) -> Dict[str, Any]:
        """Flight-recorder timeline for one subtask of a job — every
        scheduling decision in order: placement with its score breakdown,
        lease grant/reclaim, attempts/retries with reasons and backoff,
        speculation, and the terminal result (docs/OBSERVABILITY.md
        "Flight recorder"). ``job_id`` defaults to the latest ``train()``;
        raises KeyError when the coordinator has no recorded events for
        the pair (unknown ids or a run under ``CS230_OBS=0``)."""
        jid = job_id or self.job_id
        if jid is None or subtask_id is None:
            raise TypeError(
                "explain() requires a job id (or a prior train()) and a "
                "subtask_id"
            )
        if self._coordinator is not None:
            return self._coordinator.explain(jid, subtask_id)
        import requests

        try:
            return self._request("get", f"explain/{jid}/{subtask_id}")
        except requests.HTTPError as e:
            if e.response is not None and e.response.status_code == 404:
                # same contract as local mode: absence is a KeyError, not
                # a transport error
                raise KeyError(
                    f"no recorded events for subtask {subtask_id!r} of "
                    f"job {jid!r}"
                ) from e
            raise

    def critical_path(
        self, job_id: Optional[str] = None, compare: Optional[str] = None
    ) -> Dict[str, Any]:
        """Exact wall-clock decomposition of one job: the critical-path
        report (docs/OBSERVABILITY.md "Critical path & trace export") —
        segments that tile submit→aggregate (gaps labeled ``untraced``),
        the dominant segment, and retry/speculation attribution. Pass
        ``compare=<baseline_job_id>`` to attach a per-segment diff
        against another job (``report["diff"]``). ``job_id`` defaults to
        the latest ``train()``; raises KeyError when the coordinator has
        no trace bound for the job (unknown id or ``CS230_OBS=0``)."""
        jid = job_id or self.job_id
        if jid is None:
            raise TypeError(
                "critical_path() requires a job id (or a prior train())"
            )
        if self._coordinator is not None:
            report = self._coordinator.critical_path(jid)
            if report is None:
                raise KeyError(f"no critical path for job {jid!r}")
            if compare is not None:
                from ..obs.critpath import compare as _compare

                base = self._coordinator.critical_path(compare)
                if base is None:
                    raise KeyError(f"no critical path for job {compare!r}")
                report["diff"] = _compare(base, report)
            return report
        import requests

        try:
            return self._request(
                "get", f"critical_path/{jid}",
                params={"compare": compare} if compare is not None else None,
            )
        except requests.HTTPError as e:
            if e.response is not None and e.response.status_code == 404:
                raise KeyError(f"no critical path for job {jid!r}") from e
            raise

    def curves(
        self, job_id: Optional[str] = None, subtask_id: Optional[str] = None
    ) -> Dict[str, Any]:
        """Learning curves captured in-fit for a job — or one trial when
        ``subtask_id`` is given (docs/OBSERVABILITY.md "Trial telemetry
        plane"). Each entry carries the downsampled per-split trace
        (loss / score / grad-norm channels), its rung/attempt, and the
        numerical-health watchdog's ``diverged`` flag. ``job_id``
        defaults to the latest ``train()``; raises KeyError when the
        coordinator has no curves for the pair (unknown ids, or a run
        under ``CS230_CURVES=0`` returns an empty job-level list but a
        404/KeyError for a subtask)."""
        jid = job_id or self.job_id
        if jid is None:
            raise TypeError("curves() requires a job id (or a prior train())")
        if self._coordinator is not None:
            if subtask_id is not None:
                return self._coordinator.subtask_curves(jid, subtask_id)
            out = self._coordinator.job_curves(jid)
            if out is None:
                raise KeyError(f"no job {jid!r}")
            return out
        import requests

        path = f"curves/{jid}" if subtask_id is None else (
            f"curves/{jid}/{subtask_id}"
        )
        try:
            return self._request("get", path)
        except requests.HTTPError as e:
            if e.response is not None and e.response.status_code == 404:
                # same contract as local mode: absence is a KeyError
                raise KeyError(
                    f"no curves for job {jid!r}"
                    + (f" subtask {subtask_id!r}" if subtask_id else "")
                ) from e
            raise

    def best_result(self, job_id: Optional[str] = None) -> Optional[Dict[str, Any]]:
        status = self.check_status(job_id)
        result = status.get("job_result") or {}
        return result.get("best_result")

    def download_best_model(self, job_id: Optional[str] = None, output_path: Optional[str] = None) -> str:
        jid = job_id or self.job_id
        if self._coordinator is not None:
            path = self._coordinator.best_model_path(self.session_id, jid)
            if path is None:
                raise FileNotFoundError("No best model artifact for this job")
            if output_path:
                import shutil

                shutil.copy(path, output_path)
                return output_path
            return path
        out = output_path or f"{jid}_best_model.pkl"
        import requests

        r = requests.get(
            f"{self.api_url}/download_model/{self.session_id}/{jid}", timeout=60
        )
        r.raise_for_status()
        with open(out, "wb") as f:
            f.write(r.content)
        return out

    def load_best_model(self, job_id: Optional[str] = None, as_sklearn: bool = True):
        """Download the winning artifact and load it — by default as a real
        fitted sklearn estimator (state-injected; runtime/sklearn_export.py),
        matching the reference's serve-a-sklearn-pickle contract
        (worker.py:352-356, master.py:270-291). ``as_sklearn=False`` returns
        the raw kernel artifact dict for ``predict_with_artifact``."""
        from ..runtime.artifacts import load_artifact, to_sklearn

        path = self.download_best_model(job_id)
        artifact = load_artifact(path)
        return to_sklearn(artifact) if as_sklearn else artifact

    # ------------- REST plumbing -------------

    def _request(
        self,
        method: str,
        endpoint: str,
        json=None,
        params=None,
        headers=None,
        idempotent: Optional[bool] = None,
    ) -> Dict[str, Any]:
        """One REST call with transport resilience (docs/ROBUSTNESS.md
        "Reconnecting edges"): 429/503 responses are retried after their
        ``Retry-After`` (capped, jittered — the admission-control contract),
        and connection errors are retried with capped jittered exponential
        backoff for IDEMPOTENT requests (GETs by default; ``train`` submits
        opt in because the coordinator dedupes on the client-minted
        job_id). The retry window is ``service.request_retry_s`` (0
        disables — every error raises immediately, the legacy behavior)."""
        import requests

        url = f"{self.api_url}/{endpoint.lstrip('/')}"
        if idempotent is None:
            idempotent = method.lower() == "get"
        retry_window = get_config().service.request_retry_s
        deadline = time.time() + max(retry_window, 0.0)
        attempt = 0
        while True:
            try:
                resp = requests.request(
                    method, url,
                    json=json_safe(json) if json is not None else None,
                    params=params, headers=headers, timeout=600,
                )
            except (requests.ConnectionError, requests.Timeout):
                if not idempotent or time.time() >= deadline:
                    raise
                attempt += 1
                time.sleep(_retry_delay(attempt))
                continue
            if resp.status_code in (429, 503) and time.time() < deadline:
                # the request was NOT processed (admission rejection or a
                # recovering coordinator): safe to retry any method
                attempt += 1
                time.sleep(
                    _retry_delay(attempt, resp.headers.get("Retry-After"))
                )
                continue
            resp.raise_for_status()
            return resp.json()


def _retry_delay(attempt: int, retry_after=None, cap: float = 30.0) -> float:
    """Capped jittered backoff. A server-sent ``Retry-After`` is the
    floor (don't come back sooner), padded with up to 25% jitter so a
    rejected fleet doesn't return in lockstep; otherwise exponential from
    0.5 s with full jitter."""
    if retry_after is not None:
        try:
            # jitter first, cap last — the cap is a real ceiling
            return min(float(retry_after) * (1.0 + 0.25 * random.random()), cap)
        except (TypeError, ValueError):
            pass
    return min(10.0, 0.5 * 2 ** min(attempt - 1, 5)) * (0.5 + random.random())


def _bar_postfix(bar, progress: Dict[str, Any]) -> None:
    """Adaptive-search progress decoration (docs/SEARCH.md): pruned count
    and the highest active rung ride the tqdm postfix so a user watching
    the bar sees the controller working, not just percent-done."""
    pruned = progress.get("tasks_pruned")
    diverged = progress.get("tasks_diverged")
    search = progress.get("search")
    if not pruned and not diverged and not search:
        return
    post = {}
    if pruned:
        post["pruned"] = pruned
    if diverged:
        post["diverged"] = diverged
    if isinstance(search, dict):
        rungs = [
            r
            for b in (search.get("brackets") or [search])
            for r in (b.get("rungs") or [])
            if r.get("reported")
        ]
        if rungs:
            post["rung"] = max(r["rung"] for r in rungs)
    try:
        bar.set_postfix(post, refresh=False)
    except Exception:  # noqa: BLE001 — cosmetic only
        pass


def _pct(job_status) -> float:
    if job_status in ("completed", "completed_with_failures"):
        return 100.0
    if isinstance(job_status, str) and job_status.endswith("%"):
        try:
            return float(job_status[:-1])
        except ValueError:
            return 0.0
    return 0.0
