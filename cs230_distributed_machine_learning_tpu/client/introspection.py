"""sklearn estimator introspection -> JSON model_details.

API parity with the reference client's ``_extract_model_details``
(``DistributedLibrary/src/distributed_ml/core.py:96-150``): accepts a live
sklearn estimator or a GridSearchCV/RandomizedSearchCV wrapper and produces
the job payload's ``model_details`` dict:

  {model_type, search_type?, base_estimator_params,
   param_grid | param_distributions + n_iter + random_state, cv_params}

Unlike the reference we also carry the search wrapper's ``random_state`` so
RandomizedSearchCV sampling is reproducible server-side (needed for
``best_params_`` parity — SURVEY.md §7 hard parts).
"""

from __future__ import annotations

from typing import Any, Dict


def extract_model_details(estimator: Any) -> Dict[str, Any]:
    try:
        from sklearn.model_selection import GridSearchCV, RandomizedSearchCV
    except ImportError:
        GridSearchCV = RandomizedSearchCV = ()  # type: ignore[assignment]

    if isinstance(estimator, dict):
        return dict(estimator)  # already a model_details payload

    if GridSearchCV and isinstance(estimator, (GridSearchCV, RandomizedSearchCV)):
        base = estimator.estimator
        details: Dict[str, Any] = {
            "model_type": type(base).__name__,
            "base_estimator_params": _clean_params(base.get_params(deep=False)),
            "cv_params": {
                "cv": estimator.cv if estimator.cv is not None else 5,
                "scoring": estimator.scoring,
            },
        }
        if isinstance(estimator, GridSearchCV):
            details["search_type"] = "GridSearchCV"
            details["param_grid"] = _jsonable_grid(estimator.param_grid)
        else:
            details["search_type"] = "RandomizedSearchCV"
            details["param_distributions"] = _jsonable_grid(estimator.param_distributions)
            details["n_iter"] = estimator.n_iter
            details["random_state"] = estimator.random_state
        return details

    # plain estimator (or anything with get_params)
    if hasattr(estimator, "get_params"):
        return {
            "model_type": type(estimator).__name__,
            "search_type": None,
            "base_estimator_params": _clean_params(estimator.get_params(deep=False)),
        }
    raise TypeError(f"Cannot extract model details from {type(estimator).__name__}")


def _clean_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Keep only JSON-representable, non-default-ish values the kernels
    understand; drop callables/objects."""
    out = {}
    for k, v in params.items():
        if v is None or isinstance(v, (bool, int, float, str)):
            out[k] = v
        elif isinstance(v, (list, tuple)):
            out[k] = list(v)
    return out


def _jsonable_grid(grid: Any) -> Any:
    """Param grids may contain scipy distributions (rv_frozen) for
    RandomizedSearchCV — keep them as live objects in local mode; REST mode
    serializes list-valued grids only."""
    if isinstance(grid, list):
        return [_jsonable_grid(g) for g in grid]
    if isinstance(grid, dict):
        return {k: (list(v) if isinstance(v, (list, tuple)) else v) for k, v in grid.items()}
    return grid
