from .config import FrameworkConfig, get_config, set_config
from .logging import get_logger
from .serialization import json_safe, clean_nans

__all__ = [
    "FrameworkConfig",
    "get_config",
    "set_config",
    "get_logger",
    "json_safe",
    "clean_nans",
]
