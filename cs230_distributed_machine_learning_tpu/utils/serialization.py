"""JSON-safe serialization of numpy / JAX / pandas values.

Capability parity with the client-side serializer + NaN scrubber in the
reference (``DistributedLibrary/src/distributed_ml/core.py:60-80``), extended
to JAX arrays and used across the whole control plane (client payloads, job
journal, REST responses).
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np


def json_safe(obj: Any) -> Any:
    """Recursively convert a value into plain JSON-compatible Python types."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return None if (math.isnan(obj) or math.isinf(obj)) else obj
    if isinstance(obj, (np.floating,)):
        f = float(obj)
        return None if (math.isnan(f) or math.isinf(f)) else f
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, np.ndarray):
        return [json_safe(v) for v in obj.tolist()]
    if isinstance(obj, dict):
        return {str(k): json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [json_safe(v) for v in obj]
    # jax.Array and pandas objects without importing them eagerly
    if hasattr(obj, "tolist"):
        return json_safe(np.asarray(obj))
    if hasattr(obj, "to_dict"):
        return json_safe(obj.to_dict())
    return str(obj)


def clean_nans(data: Any) -> Any:
    """Recursively replace NaN/Inf floats with None (reference
    ``core.py:71-80`` behavior)."""
    if isinstance(data, dict):
        return {k: clean_nans(v) for k, v in data.items()}
    if isinstance(data, list):
        return [clean_nans(v) for v in data]
    if isinstance(data, float) and (math.isnan(data) or math.isinf(data)):
        return None
    return data
