"""Process-level JAX tuning applied once by framework entry points.

Persistent XLA compilation cache: trial-engine executables are keyed by
bucket shapes that recur across processes (bench runs, agent restarts), so
caching compiles on disk removes the 5-40 s first-compile cost from every
fresh process — important for the round-trip driver runs and for elastic
agents joining mid-job.
"""

from __future__ import annotations

import os

_done = False


def setup_jax(cache_dir: str | None = None) -> None:
    global _done
    if _done:
        return
    _done = True
    import jax

    # TPUML_PLATFORM=cpu|tpu pins the backend for THIS process before first
    # backend touch. Needed by supervised child agents in tests/CI (the
    # parent owns the only chip) and by fleets where some executors should
    # run host-side; a plain JAX_PLATFORMS env is overridden by the axon
    # plugin's sitecustomize, the config update is not.
    platform = os.environ.get("TPUML_PLATFORM")
    if platform:
        try:
            jax.config.update("jax_platforms", platform)
        except Exception:  # noqa: BLE001
            pass

    if platform == "cpu" and cache_dir is None:
        # No persistent compile cache for CPU-pinned processes: reloading a
        # serialized XLA:CPU executable has been observed to SIGSEGV in this
        # environment (cpu_aot_loader feature-mismatch path — the entry
        # embeds compile-machine pseudo-features like +prefer-no-scatter
        # that host detection never reports). CPU compiles are cheap; the
        # cache's value is the TPU path, which keeps it.
        return

    if cache_dir is None:
        # partition the persistent cache by compilation context: XLA:CPU
        # cache entries embed target machine features that vary with the
        # process's XLA flags/platform (e.g. +prefer-no-scatter under the
        # axon plugin's TPU process vs a plain CPU agent); loading an entry
        # compiled in a different context can SIGILL (cpu_aot_loader
        # feature-mismatch warning). Identical launch contexts share a
        # subdirectory; different ones never see each other's binaries.
        import hashlib

        ctx = "|".join((
            os.environ.get("XLA_FLAGS", ""),
            os.environ.get("JAX_PLATFORMS", ""),
            platform or "",
        ))
        sig = hashlib.sha256(ctx.encode()).hexdigest()[:10]
        cache_dir = os.path.join(
            os.path.expanduser("~/.tpuml"), "jax_compilation_cache", sig
        )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every compile: even sub-second compiles cost a backend RPC
        # round trip per fresh process (large on tunneled/remote devices)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # noqa: BLE001 — older jax or read-only fs: run uncached
        pass
