"""Process-level JAX tuning applied once by framework entry points.

Persistent XLA compilation cache: trial-engine executables are keyed by
bucket shapes that recur across processes (bench runs, agent restarts), so
caching compiles on disk removes the 5-40 s first-compile cost from every
fresh process — important for the round-trip driver runs and for elastic
agents joining mid-job.
"""

from __future__ import annotations

import os

_done = False


def setup_jax(cache_dir: str | None = None) -> None:
    global _done
    if _done:
        return
    _done = True
    import jax

    cache_dir = cache_dir or os.path.join(
        os.path.expanduser("~/.tpuml"), "jax_compilation_cache"
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every compile: even sub-second compiles cost a backend RPC
        # round trip per fresh process (large on tunneled/remote devices)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # noqa: BLE001 — older jax or read-only fs: run uncached
        pass
