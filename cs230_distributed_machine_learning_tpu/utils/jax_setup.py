"""Process-level JAX tuning applied once by framework entry points.

Persistent XLA compilation cache: trial-engine executables are keyed by
bucket shapes that recur across processes (bench runs, agent restarts), so
caching compiles on disk removes the 5-40 s first-compile cost from every
fresh process — important for the round-trip driver runs and for elastic
agents joining mid-job.
"""

from __future__ import annotations

import functools
import os

_done = False


@functools.lru_cache(maxsize=1)
def host_fingerprint() -> str:
    """Stable fingerprint of THIS host's CPU capabilities.

    XLA:CPU AOT/cache entries embed the compile machine's feature set; a
    shared cache root across heterogeneous hosts (the deploy/ fleet story —
    NFS home dirs, identical env vars, different EC2 instance types) would
    otherwise let host B load host A's binary and SIGILL. Partitioning the
    cache directory by (machine, cpu-flag set) makes a feature mismatch
    structurally impossible: hosts with different ISAs never share a
    subdirectory. The reference has no analog (pure-Python workers); this
    hazard is specific to compiled-executable caching.
    """
    import hashlib
    import platform as _platform

    parts = [_platform.machine(), _platform.system()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags") or line.startswith("Features"):
                    # flags are a stable, unordered capability set per host
                    parts.append(" ".join(sorted(line.split(":", 1)[1].split())))
                    break
    except OSError:
        # non-Linux: fall back to the processor string (coarser, still
        # machine-specific enough to split x86 from arm etc.)
        parts.append(_platform.processor())
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def setup_jax(cache_dir: str | None = None) -> None:
    global _done
    if _done:
        return
    _done = True
    import jax

    # TPUML_PLATFORM=cpu|tpu pins the backend for THIS process before first
    # backend touch. Needed by supervised child agents in tests/CI (the
    # parent owns the only chip) and by fleets where some executors should
    # run host-side; a plain JAX_PLATFORMS env is overridden by the axon
    # plugin's sitecustomize, the config update is not.
    platform = os.environ.get("TPUML_PLATFORM")
    if platform:
        try:
            jax.config.update("jax_platforms", platform)
        except Exception:  # noqa: BLE001
            pass

    # No persistent compile cache for CPU-resolved processes, however the
    # pin arrived (TPUML_PLATFORM, JAX_PLATFORMS env, or an earlier
    # jax.config.update as in tests/driver dryruns): reloading a serialized
    # XLA:CPU executable has been observed to SIGSEGV in this environment,
    # and even same-host reloads always log cpu_aot_loader feature-mismatch
    # errors (the entry embeds compile-machine pseudo-features like
    # +prefer-no-scatter that host detection never reports). CPU compiles
    # are cheap; the cache's value is the TPU path, which keeps it.
    try:
        configured = jax.config.jax_platforms or ""
    except AttributeError:
        configured = ""
    resolved = platform or configured or os.environ.get("JAX_PLATFORMS", "")
    if not resolved and cache_dir is None:
        # no pin anywhere: ask the backend (this initializes it, but only
        # on plugin-less machines — pinned/plugin processes resolve above
        # without the touch, and the axon sitecustomize always pins)
        try:
            resolved = jax.default_backend()
        except Exception:  # noqa: BLE001 — no backend at all: run uncached
            return
    # only the FIRST entry is the default backend: the axon plugin pins
    # "axon,cpu" (cpu as fallback only), which must keep the TPU cache
    if str(resolved).split(",")[0].strip() == "cpu" and cache_dir is None:
        return

    if cache_dir is None:
        # partition the persistent cache by compilation context: XLA:CPU
        # cache entries embed target machine features that vary with the
        # process's XLA flags/platform (e.g. +prefer-no-scatter under the
        # axon plugin's TPU process vs a plain CPU agent); loading an entry
        # compiled in a different context can SIGILL (cpu_aot_loader
        # feature-mismatch warning). Identical launch contexts share a
        # subdirectory; different ones never see each other's binaries.
        import hashlib

        # NO host CPU fingerprint here, mirroring aot_cache._generation():
        # only accelerator-resolved processes reach this point (CPU-resolved
        # ones returned above, uncached), and accelerator executables are
        # device code — folding the host CPU into their cache signature
        # would make TPU hosts with heterogeneous CPUs sharing a storage
        # root re-pay the 5-40 s first-compile each (ADVICE r5 #2).
        # Residual exposure, accepted with that (performance-only-rated)
        # ADVICE trade: an accelerator process's host-fast-path buckets
        # (trial_map host_exec) compile on the XLA CPU backend into this
        # same shared dir, so heterogeneous hosts can see each other's
        # CPU-lowered entries. Observed behavior in this environment is
        # the cpu_aot_loader feature-mismatch error + fresh recompile
        # (same-host reloads always false-mismatch, see the comment
        # above); the harder SIGILL outcome documented for mismatched CPU
        # entries has not been observed for these, but a fleet hitting it
        # should re-partition by setting CS230_AOT_DIR/cache_dir per host
        # class.
        ctx = "|".join((
            os.environ.get("XLA_FLAGS", ""),
            os.environ.get("JAX_PLATFORMS", ""),
            platform or "",
        ))
        sig = hashlib.sha256(ctx.encode()).hexdigest()[:10]
        cache_dir = os.path.join(
            os.path.expanduser("~/.tpuml"), "jax_compilation_cache", sig
        )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every compile: even sub-second compiles cost a backend RPC
        # round trip per fresh process (large on tunneled/remote devices)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # noqa: BLE001 — older jax or read-only fs: run uncached
        pass
