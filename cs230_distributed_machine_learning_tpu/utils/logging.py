"""Shared logging setup.

One implementation instead of the reference's three copy-pasted
``logger_util.py`` files (``aws-prod/master/logger_util.py:1-29``): console +
optional daily-rotating file handler with 7-day retention, funcName in format.

``CS230_LOG_JSON=1`` opts into structured JSON lines (one object per
record) stamped with the active ``trace_id``/``span_id`` from the obs
context — so logs, metrics, and traces join on one id
(docs/OBSERVABILITY.md "Structured logs"). The env var is read when a
logger is first configured; already-configured loggers keep their format.
"""

from __future__ import annotations

import json
import logging
import os
import time
from logging.handlers import TimedRotatingFileHandler

_FORMAT = "%(asctime)s %(levelname)s %(name)s:%(funcName)s - %(message)s"
_configured: set = set()


def _json_logs_enabled() -> bool:
    return os.environ.get("CS230_LOG_JSON", "0") == "1"


class JsonFormatter(logging.Formatter):
    """One JSON object per record. Keys: ``ts`` (epoch seconds), ``level``,
    ``logger``, ``func``, ``msg``, plus ``trace_id``/``span_id`` when a
    trace is active in the emitting context (the obs contextvar — handlers
    run on the emitting thread, so the ids are the caller's) and ``exc``
    for records carrying exception info."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "func": record.funcName,
            "msg": record.getMessage(),
        }
        # lazy import: utils.logging must stay importable before obs (and
        # obs logs through here) — no import cycle at module load
        try:
            from ..obs.tracing import current_span_id, current_trace_id

            tid = current_trace_id()
            if tid:
                out["trace_id"] = tid
            sid = current_span_id()
            if sid:
                out["span_id"] = sid
        except Exception:  # noqa: BLE001 — a log line must never raise
            pass
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)

    def formatTime(self, record, datefmt=None):  # pragma: no cover - unused
        return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))


def _make_formatter() -> logging.Formatter:
    if _json_logs_enabled():
        return JsonFormatter()
    return logging.Formatter(_FORMAT)


def get_logger(name: str = "tpuml", log_dir: str | None = None) -> logging.Logger:
    logger = logging.getLogger(name)
    if name in _configured:
        return logger
    logger.setLevel(logging.INFO)
    logger.propagate = False
    fmt = _make_formatter()
    console = logging.StreamHandler()
    console.setFormatter(fmt)
    logger.addHandler(console)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        fh = TimedRotatingFileHandler(
            os.path.join(log_dir, "app.log"), when="midnight", backupCount=7
        )
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    _configured.add(name)
    return logger
