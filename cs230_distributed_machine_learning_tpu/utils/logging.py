"""Shared logging setup.

One implementation instead of the reference's three copy-pasted
``logger_util.py`` files (``aws-prod/master/logger_util.py:1-29``): console +
optional daily-rotating file handler with 7-day retention, funcName in format.
"""

from __future__ import annotations

import logging
import os
from logging.handlers import TimedRotatingFileHandler

_FORMAT = "%(asctime)s %(levelname)s %(name)s:%(funcName)s - %(message)s"
_configured: set = set()


def get_logger(name: str = "tpuml", log_dir: str | None = None) -> logging.Logger:
    logger = logging.getLogger(name)
    if name in _configured:
        return logger
    logger.setLevel(logging.INFO)
    logger.propagate = False
    fmt = logging.Formatter(_FORMAT)
    console = logging.StreamHandler()
    console.setFormatter(fmt)
    logger.addHandler(console)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        fh = TimedRotatingFileHandler(
            os.path.join(log_dir, "app.log"), when="midnight", backupCount=7
        )
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    _configured.add(name)
    return logger
