"""Disk cache of exported (AOT) trial executables.

The XLA persistent compilation cache (utils/jax_setup.py) removes the
*compile* cost from fresh processes, but each process still pays Python
tracing for every trial-engine executable (seconds for the larger model
kernels). `jax.export` serializes the traced StableHLO module; deserializing
it in a later process skips tracing entirely, and its compile hits the XLA
persistent cache — together they take a fresh-process dispatch from
~3-12 s of trace+compile down to ~a second of (cached) executable load.

This is the TPU-framework counterpart of the reference scheduler persisting
its learned runtime model across restarts (scheduler_service.py:44-46): warm
state survives process boundaries so the steady-state cost, not the cold
cost, is what jobs pay.

Entries are keyed by the executable identity (kernel/static/shapes/splits/
chunk — trial_map._aot_key, which also folds in the transfer-layer knobs:
the packed-output flag and the staging dtype, plus the staged leaves' own
shape/dtype signature, so bf16/int8-staged and packed/per-leaf executables
never collide with their f32/dict counterparts), the lowering platform, the
jax version, and a content fingerprint of this package's compute-path
sources — a code change invalidates every blob, so a stale cache can never
resurrect old kernel behavior. Any failure to export/serialize/deserialize
falls back silently to the traced path (CS230_AOT_CACHE=0 disables the
cache outright).
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Optional, Sequence, Tuple

_FINGERPRINT: Optional[str] = None
_LOCK = threading.Lock()

# compute-path packages whose source content keys the cache
_CODE_DIRS = ("models", "ops", "parallel")


def cache_dir() -> str:
    override = os.environ.get("CS230_AOT_DIR")
    if override:
        return override
    from .config import get_config

    return os.path.join(get_config().storage.root, "aot_cache")


def enabled() -> bool:
    """On by default on accelerator backends; OFF on CPU. Executing a
    deserialized CPU export has been observed to SIGSEGV in this
    environment (same machine, same context — jaxlib CPU AOT path), and the
    cache's payoff is the TPU fleet anyway (tests use per-test cache dirs,
    so CPU deserialize was never a tested path). ``CS230_AOT_CACHE=force``
    overrides; ``0`` disables everywhere."""
    flag = os.environ.get("CS230_AOT_CACHE", "1")
    if flag == "0":
        return False
    if flag == "force":
        return True
    import jax

    return jax.default_backend() != "cpu"


def _code_fingerprint() -> str:
    """sha256 over the compute-path sources (content, not mtime: rebuilds
    and checkouts must not produce false hits or misses)."""
    global _FINGERPRINT
    if _FINGERPRINT is not None:
        return _FINGERPRINT
    with _LOCK:
        if _FINGERPRINT is not None:
            return _FINGERPRINT
        h = hashlib.sha256()
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for sub in _CODE_DIRS:
            root = os.path.join(pkg_root, sub)
            for dirpath, _, files in sorted(os.walk(root)):
                for name in sorted(files):
                    if name.endswith(".py"):
                        path = os.path.join(dirpath, name)
                        h.update(name.encode())
                        with open(path, "rb") as f:
                            h.update(f.read())
        _FINGERPRINT = h.hexdigest()
        return _FINGERPRINT


def _generation() -> str:
    """Cache generation: code fingerprint + jax version + host CPU
    fingerprint. Blobs live in a per-generation subdirectory so superseded
    generations are prunable. The host fingerprint keeps heterogeneous
    machines sharing a storage root (the deploy/ fleet story) from loading
    each other's machine-feature-specific binaries (SIGILL hazard flagged
    by the cpu_aot_loader)."""
    import jax

    host = ""
    if jax.default_backend() == "cpu":
        # only CPU-lowered exports embed host machine features; TPU blobs
        # are device code and MUST stay shared across a heterogeneous-CPU
        # fleet (the whole payoff of a shared storage root)
        from .jax_setup import host_fingerprint

        host = host_fingerprint()
    return hashlib.sha256(
        (_code_fingerprint() + jax.__version__ + host).encode()
    ).hexdigest()[:16]


_PRUNE_AGE_S = 7 * 24 * 3600
_PRUNED = False


def _prune_stale_generations(root: str, keep: str) -> None:
    """Drop superseded generation dirs, but only ones untouched for
    _PRUNE_AGE_S and only once per process: two live processes on different
    code/jax versions sharing a storage root must not delete each other's
    active caches on every write (they'd silently degrade both to
    re-tracing, and could race a sibling's in-flight tmp file)."""
    import shutil
    import time

    global _PRUNED
    if _PRUNED:
        return
    _PRUNED = True
    now = time.time()
    try:
        for name in os.listdir(root):
            path = os.path.join(root, name)
            if name == keep or not os.path.isdir(path):
                continue
            try:
                ages = [os.path.getmtime(path)]
                with os.scandir(path) as it:
                    ages += [e.stat().st_mtime for e in it]
            except OSError:
                continue
            if now - max(ages) > _PRUNE_AGE_S:
                shutil.rmtree(path, ignore_errors=True)
    except OSError:
        pass


def _blob_path(key_parts: Sequence[Any]) -> str:
    import jax

    platform = jax.default_backend()
    ident = repr(tuple(key_parts)) + platform
    digest = hashlib.sha256(ident.encode()).hexdigest()
    return os.path.join(cache_dir(), _generation(), f"{digest}.jaxexport")


def generation_inventory() -> dict:
    """Blob count/bytes of the CURRENT cache generation — what a prewarm
    pass (runtime/prewarm.py) can load without tracing. One cheap
    directory scan; zeros when the cache is disabled or empty."""
    out = {"n_blobs": 0, "bytes": 0, "dir": None}
    try:
        if not enabled():
            return out
        gen_dir = os.path.join(cache_dir(), _generation())
        out["dir"] = gen_dir
        with os.scandir(gen_dir) as it:
            for entry in it:
                if entry.name.endswith(".jaxexport"):
                    out["n_blobs"] += 1
                    out["bytes"] += entry.stat().st_size
    except OSError:
        pass
    return out


def aot_jit(fn, key_parts: Sequence[Any], example_args: Tuple[Any, ...]):
    """Return (callable, source) where source is "aot" (deserialized, no
    tracing) or "traced". The callable has the same signature as ``fn`` and
    is jit-compiled either way.

    ``example_args`` are only inspected for shape/dtype (avals); on the cold
    path they drive one ``jax.export`` trace that doubles as the live
    executable, so tracing happens at most once per process either way.
    """
    import jax

    if not enabled():
        return jax.jit(fn), "traced"

    from jax import export as jex

    from ..obs import counter_inc

    path = _blob_path(key_parts)
    if os.path.exists(path):
        try:
            with open(path, "rb") as f:
                exp = jex.deserialize(f.read())
            counter_inc("tpuml_aot_cache_hits_total")
            return jax.jit(exp.call), "aot"
        except Exception:  # noqa: BLE001 — stale/corrupt blob: re-trace
            try:
                os.remove(path)
            except OSError:
                pass
    counter_inc("tpuml_aot_cache_misses_total")

    try:
        # Pallas kernels lower to Mosaic custom calls, which jax.export
        # flags as non-stable across versions; the generation directory
        # already keys on jax version + code content, so replay of a
        # same-generation blob is safe — disable the stability check.
        kwargs = {}
        try:
            kwargs["disabled_checks"] = [
                jex.DisabledSafetyCheck.custom_call("tpu_custom_call"),
                jex.DisabledSafetyCheck.custom_call("Mosaic"),
            ]
        except AttributeError:
            pass
        exp = jex.export(jax.jit(fn), **kwargs)(*example_args)
        blob = exp.serialize()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _prune_stale_generations(cache_dir(), _generation())
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)  # atomic: concurrent executors race safely
        return jax.jit(exp.call), "traced"
    except Exception:  # noqa: BLE001 — unexportable (e.g. exotic custom
        # calls) or read-only fs: plain traced jit
        return jax.jit(fn), "traced"
