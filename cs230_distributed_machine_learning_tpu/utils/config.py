"""Typed, layered configuration system.

Replaces the reference's three copy-pasted per-service ``config.py`` constant
files and env-var sprinkling (reference: ``aws-prod/master/config.py:1-18``,
``aws-prod/scheduler/scheduler.py:59-65``, ``aws-prod/worker/config.py``) with
one dataclass hierarchy resolved as: defaults <- config file (JSON/YAML) <-
environment variables <- explicit overrides.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any, Optional

_ENV_PREFIX = "TPUML_"


@dataclasses.dataclass
class StorageConfig:
    """Filesystem layout. Mirrors the reference's /mnt/efs shared-volume layout
    (``aws-prod/master/config.py:11-12``) but defaults to a repo-local root."""

    root: str = os.path.expanduser("~/.tpuml")

    @property
    def datasets_dir(self) -> str:
        return os.path.join(self.root, "datasets")

    @property
    def configs_dir(self) -> str:
        return os.path.join(self.root, "configs")

    @property
    def models_dir(self) -> str:
        return os.path.join(self.root, "models")

    @property
    def journal_dir(self) -> str:
        return os.path.join(self.root, "journal")

    @property
    def runtime_model_path(self) -> str:
        return os.path.join(self.root, "runtime_predictor.joblib")


@dataclasses.dataclass
class SchedulerConfig:
    """Placement-engine knobs. Values mirror the reference's operational
    constants (``worker.py:33``, ``scheduler_service.py:25,31,209-216``)."""

    heartbeat_interval_s: float = 5.0
    dead_after_s: float = 10.0
    sweep_interval_s: float = 15.0
    predictor_refit_batch: int = 10
    default_mem_capacity_mb: float = 16000.0
    speed_ema_alpha: float = 0.2
    speed_factor_min: float = 0.2
    speed_factor_max: float = 5.0
    algo_weights: dict = dataclasses.field(default_factory=dict)
    # ---- per-worker health telemetry (docs/OBSERVABILITY.md) ----
    # EWMA smoothing for a worker's batch wall time
    health_ema_alpha: float = 0.2
    # a worker is a straggler when its batch EWMA exceeds factor x the
    # median EWMA of its peers (each judged against the OTHERS' median, so
    # two-worker pools can flag too), after its EWMA has absorbed at least
    # min_batches BATCHES (outcomes arrive per subtask — counting them
    # would let one cold multi-subtask batch satisfy the guard)
    straggler_factor: float = 3.0
    straggler_min_batches: int = 2
    # advisory placement-score penalty (seconds) added to flagged
    # stragglers — eligibility and fallback semantics are untouched
    straggler_penalty_s: float = 30.0
    # ---- fault-tolerance layer (docs/ROBUSTNESS.md) ----
    # every placed subtask carries a lease: deadline = now +
    # max(lease_floor_s, lease_factor x predicted completion time on the
    # chosen worker, queue wait included). The sweep reclaims and requeues
    # expired leases from LIVE but hung workers. factor <= 0 disables.
    lease_factor: float = 4.0
    lease_floor_s: float = 30.0
    # total execution attempts per subtask before quarantine (failed or
    # lease-reclaimed executions both consume the budget)
    retry_max_attempts: int = 3
    # per-attempt exponential backoff before a failure retry:
    # retry_backoff_s x 2^(failures-1), capped at retry_backoff_max_s
    retry_backoff_s: float = 0.5
    retry_backoff_max_s: float = 10.0
    # a subtask that killed this many worker backends (DeviceLostError
    # correlation) is poisoned and quarantined immediately
    poison_kill_threshold: int = 2
    # speculative execution (MapReduce backup tasks): when a subtask's
    # in-flight time exceeds straggler_factor x the peer-median batch EWMA
    # (floored at speculative_min_inflight_s) and an idle worker exists,
    # launch ONE duplicate there; first terminal result wins
    speculative_enabled: bool = True
    speculative_min_inflight_s: float = 10.0
    # worker circuit breaker: trip to half-open (probe tasks only) when
    # failed/total outcomes since the last transition reaches the ratio
    # over at least min_outcomes; evict after max_trips trips. ratio <= 0
    # disables.
    breaker_failure_ratio: float = 0.5
    breaker_min_outcomes: int = 4
    breaker_max_trips: int = 3
    # ---- QoS lane aging (docs/ARCHITECTURE.md "QoS priority lanes") ----
    # strict-priority dispatch queues promote a waiting message one lane
    # per qos_aging_s seconds of queue age, so a sustained high-priority
    # flood cannot starve low lanes forever. <= 0 disables (pure strict
    # priority).
    qos_aging_s: float = 30.0


@dataclasses.dataclass
class ExecutionConfig:
    """Trial-execution knobs for the TPU compute path."""

    # mesh axis names
    trial_axis: str = "trials"
    data_axis: str = "data"
    # max trials fused into one vmapped super-batch per dispatch
    max_trials_per_batch: int = 256
    # default dtype for fitting kernels (MXU-friendly accumulate in f32)
    compute_dtype: str = "float32"
    # cv defaults matching sklearn cross_val_score(cv=5)
    default_cv_folds: int = 5
    default_test_size: float = 0.2
    # donate buffers / profiler toggles
    enable_profiler: bool = False
    profiler_dir: str = "/tmp/tpuml_traces"


@dataclasses.dataclass
class ServiceConfig:
    """Control-plane endpoints (coordinator REST server + SSE cadence).
    SSE tick mirrors the reference's 1.5 s stream loop (``master.py:266``)."""

    host: str = "0.0.0.0"
    port: int = 5001
    sse_tick_s: float = 1.5
    client_poll_s: float = 1.0
    client_timeout_s: float = 600.0  # reference default of 60 s is too small
    # ---- admission control / overload survival (docs/ROBUSTNESS.md
    # "Coordinator recovery and overload survival") ----
    # hard caps on ACCEPTED work: a submit beyond any of them is rejected
    # with 429 + Retry-After instead of queueing the coordinator to death.
    # <= 0 disables the corresponding cap.
    max_inflight_jobs: int = 64
    max_inflight_jobs_per_session: int = 16
    # total PENDING subtasks across all unfinished jobs — the queue-depth
    # watermark (a 10-trial job and a 10k-trial job are not the same load)
    admission_queue_watermark: int = 50000
    # Retry-After seconds sent with 429 (admission) and 503 (recovering)
    admission_retry_after_s: float = 5.0
    # soft watermark: above this fraction of any enabled cap the engine
    # sheds optional work first (speculative duplicates, prewarm hints)
    # before admission starts rejecting
    shed_fraction: float = 0.8
    # client-side transport resilience: how long MLTaskManager keeps
    # retrying an idempotent request through 429/503/connection errors
    # (capped jittered backoff, Retry-After honored). 0 disables retries.
    request_retry_s: float = 60.0
    # ---- fleet health plane (docs/OBSERVABILITY.md "Fleet health
    # plane"): capacity signals (obs/signals.py) + SLO alert rules
    # (obs/slo.py) ----
    # evaluation floors: the engine sweep, /metrics/prom scrapes, and
    # /alerts //autoscale reads all drive evaluation — the throttle keeps
    # the drivers from multi-evaluating
    autoscale_interval_s: float = 5.0
    alert_eval_interval_s: float = 5.0
    # drain-time target: desired_workers is sized so the predictor-priced
    # backlog drains within this horizon (also the rejection-rate window
    # of the pressure probe)
    autoscale_horizon_s: float = 120.0
    autoscale_min_workers: int = 1
    autoscale_max_workers: int = 256
    # desired_shards targets this fill fraction of the admission caps
    autoscale_target_fill: float = 0.7
    # scale-down hysteresis: a below-live signal must hold this long (and
    # idle workers must exist to drain through the lease/evict path)
    # before the published gauge actually drops
    autoscale_downscale_hold_s: float = 180.0
    # SLO targets the default alert rules evaluate (obs/slo.py)
    route_p99_slo_s: float = 2.0
    sse_lag_slo_s: float = 5.0
    alert_admission_reject_per_s: float = 0.2
    # ---- cross-shard rebalancing (docs/ROBUSTNESS.md "Shard
    # rebalancing"): job migration + work stealing, driven by the
    # per-shard pressure signal (obs/signals.py tpuml_shard_pressure) ----
    # master valve: even with peers wired (server --peers) a shard takes
    # no rebalancing ACTION unless enabled (the peer endpoints still
    # answer, so a mixed fleet degrades to one-sided stealing)
    rebalance_enabled: bool = False
    # floor between rebalance passes (each pass does peer HTTP probes,
    # so it must not run at sweep/scrape cadence)
    rebalance_interval_s: float = 10.0
    # a shard at/above this tpuml_shard_pressure is HOT: it offers steal
    # candidates and looks for a cold peer to migrate a job to
    rebalance_hot_pressure: float = 2.0
    # a peer at/below this pressure is drainable-COLD: eligible migration
    # destination; a shard at/below it with idle workers turns thief
    rebalance_cold_pressure: float = 0.5
    # hot/cold pressure ratio floor: migration only fires when the skew
    # is real (keeps balanced fleets from ping-ponging jobs)
    rebalance_imbalance_ratio: float = 3.0
    # how long the donor keeps replaying-forward late results for a
    # migrated job (at-least-once across the handoff)
    rebalance_forward_s: float = 120.0
    # max queued subtasks one steal grant hands a thief shard
    steal_max_tasks: int = 8
    # donor-side steal lease: a tombstone older than this with no result
    # from the thief is reclaimed (fresh attempt fences the thief)
    steal_lease_s: float = 120.0
    # ---- trial telemetry plane (docs/OBSERVABILITY.md "Trial telemetry
    # plane"): numerical-health watchdog threshold. A trial whose curve
    # tail (loss or grad-norm) exceeds this factor x the median of its
    # own early trace — or contains any non-finite sample — is marked
    # diverged and its in-flight attempt is cooperatively cancelled.
    # <= 0 disables the ratio rule (non-finite still trips).
    curve_divergence_factor: float = 1e3


@dataclasses.dataclass
class FrameworkConfig:
    storage: StorageConfig = dataclasses.field(default_factory=StorageConfig)
    scheduler: SchedulerConfig = dataclasses.field(default_factory=SchedulerConfig)
    execution: ExecutionConfig = dataclasses.field(default_factory=ExecutionConfig)
    service: ServiceConfig = dataclasses.field(default_factory=ServiceConfig)

    @classmethod
    def load(
        cls,
        path: Optional[str] = None,
        env: Optional[dict] = None,
        **overrides: Any,
    ) -> "FrameworkConfig":
        cfg = cls()
        if path:
            cfg = cfg.merged(_read_config_file(path))
        cfg = cfg.merged(_env_overrides(env if env is not None else os.environ))
        if overrides:
            cfg = cfg.merged(overrides)
        return cfg

    def merged(self, updates: dict) -> "FrameworkConfig":
        return _merge_dataclass(self, updates)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _merge_dataclass(obj, updates: dict):
    if not dataclasses.is_dataclass(obj):
        return updates
    kwargs = {}
    for f in dataclasses.fields(obj):
        cur = getattr(obj, f.name)
        if f.name in updates:
            upd = updates[f.name]
            if dataclasses.is_dataclass(cur) and isinstance(upd, dict):
                kwargs[f.name] = _merge_dataclass(cur, upd)
            else:
                kwargs[f.name] = upd
        else:
            kwargs[f.name] = cur
    return type(obj)(**kwargs)


def _read_config_file(path: str) -> dict:
    text = Path(path).read_text()
    if path.endswith((".yaml", ".yml")):
        import yaml

        return yaml.safe_load(text) or {}
    return json.loads(text)


def _env_overrides(env) -> dict:
    """TPUML_SECTION__FIELD=value -> {"section": {"field": parsed}}."""
    out: dict = {}
    for key, raw in env.items():
        if not key.startswith(_ENV_PREFIX):
            continue
        parts = key[len(_ENV_PREFIX):].lower().split("__")
        if len(parts) != 2:
            continue
        section, field = parts
        try:
            value: Any = json.loads(raw)
        except (json.JSONDecodeError, ValueError):
            value = raw
        out.setdefault(section, {})[field] = value
    return out


_GLOBAL_CONFIG: Optional[FrameworkConfig] = None


def get_config() -> FrameworkConfig:
    global _GLOBAL_CONFIG
    if _GLOBAL_CONFIG is None:
        _GLOBAL_CONFIG = FrameworkConfig.load()
    return _GLOBAL_CONFIG


def set_config(cfg: FrameworkConfig) -> None:
    global _GLOBAL_CONFIG
    _GLOBAL_CONFIG = cfg
