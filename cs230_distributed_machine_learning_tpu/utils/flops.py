"""Analytical FLOP accounting + MFU for the benchmark harnesses.

The reference has no utilization measurement at all (SURVEY.md §6); the
round-1 verdict flagged "is it actually fast for the silicon" as
unanswerable. Kernels publish ``macs_estimate(n, d, static)`` — the
model-analytical multiply-accumulate count of ONE (trial, split) fit — and
the harnesses combine it with wall-clock and the device's peak rate:

    mfu = (2 * macs * n_splits * n_trials) / wall_s / peak_flops

This is *model* FLOP utilization: only the FLOPs the model semantically
requires count, not implementation overheads (padding, recompute, masked
lanes), so it is comparable across implementations.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

#: peak dense bf16 FLOP/s by device kind substring (per published specs)
_PEAKS = (
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6e", 918e12),
    ("v6 lite", 918e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops() -> Optional[float]:
    """Peak bf16 FLOP/s of device 0, or None when unknown/CPU (MFU is not a
    meaningful metric for host execution)."""
    import jax

    try:
        dev = jax.devices()[0]
    except Exception:  # noqa: BLE001
        return None
    if dev.platform == "cpu":
        return None
    kind = str(getattr(dev, "device_kind", "")).lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return 197e12 if dev.platform == "tpu" else None


def device_memory_stats() -> Dict[str, Any]:
    """``memory_stats()`` of local device 0, ``{}`` when the backend
    exposes none (CPU) or no device is reachable. The one shared reader
    behind the HBM gauge, ``TrialRunResult.hbm_peak_bytes``, and
    ``GET /healthz`` — key names and the device-0 policy live here only."""
    import jax

    try:
        return dict(jax.local_devices()[0].memory_stats() or {})
    except Exception:  # noqa: BLE001 — stats are best-effort everywhere
        return {}


def analytical_flops(
    kernel: Any,
    static: Dict[str, Any],
    n: int,
    d: int,
    n_splits: int,
    n_trials: int,
) -> Optional[float]:
    """Total model FLOPs of a job: 2 * per-(trial,split) MACs * splits *
    trials. None when the kernel has no analytical estimate."""
    if not hasattr(kernel, "macs_estimate"):
        return None
    per = float(kernel.macs_estimate(n, d, static))
    return 2.0 * per * max(n_splits, 1) * max(n_trials, 1)


def stratified_by(population, key_fn, n_samples: int):
    """Evenly spaced quantile positions of ``population`` sorted by
    ``key_fn`` — the harnesses' shared subsampling for extrapolated sklearn
    denominators (per-trial cost varies strongly with e.g. C under
    loguniform, so random draws under-represent the tails)."""
    import numpy as np

    srt = sorted(population, key=key_fn)
    pos = (
        np.linspace(0, len(srt) - 1, min(n_samples, len(srt))).round().astype(int)
    )
    return [srt[i] for i in pos]


def mfu(
    flops: Optional[float], wall_s: float, n_devices: int = 1
) -> Optional[float]:
    """Achieved fraction of device peak; None off-accelerator or without an
    analytical FLOPs figure. ``n_devices`` scales the peak for work that
    ran across a mesh — whole-mesh FLOPs over a single chip's peak would
    report N x reality."""
    peak = device_peak_flops()
    if flops is None or peak is None or wall_s <= 0:
        return None
    return flops / wall_s / (peak * max(int(n_devices), 1))
