"""TPU-native distributed ML training & hyperparameter-search framework.

A ground-up JAX/XLA re-design of the capabilities of
``sanjita2911/CS230-distributed-machine-learning`` (see SURVEY.md): a client
(`MLTaskManager`) submits sklearn-style training / GridSearchCV /
RandomizedSearchCV jobs; a coordinator expands them into per-trial subtasks; a
placement engine schedules trial *batches* onto chips of a TPU mesh; jitted
model kernels fit all trials of a batch in parallel (vmap over trials, sharded
over the mesh ``trials`` axis); cross-trial/cross-fold aggregation happens
on-device with XLA collectives instead of broker round-trips.

Reference architecture being matched (not copied): client SDK
(``DistributedLibrary/src/distributed_ml/core.py``), master
(``aws-prod/master/master.py``), scheduler (``aws-prod/scheduler/``), worker
(``aws-prod/worker/worker.py``) — Kafka/Redis/Flask replaced by an in-process
async queue, an in-memory journaled store, and ICI collectives.
"""

from .version import __version__
from .client.manager import MLTaskManager

__all__ = ["MLTaskManager", "__version__"]
