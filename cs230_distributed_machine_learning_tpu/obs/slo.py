"""Declarative SLO alert rules over the embedded time-series rings.

Nothing in this repo ever *consumed* its telemetry: RED metrics, the
flight recorder, and the per-series history rings all existed, but no
alert fired and no operator was paged. This module closes that loop
coordinator-side (fleets here often run with no external Prometheus or
Alertmanager at all — the same reasoning that put the time-series store
in-process, obs/timeseries.py):

- :class:`AlertRule` — one declarative rule against a counter/gauge
  family sampled into ``obs.timeseries.TIMESERIES``. Three kinds:

  * ``threshold``   — latest gauge value (max across matching series,
    stale series ignored) compared against ``threshold``;
  * ``burn_rate``   — multi-window burn rate (SRE workbook ch. 5): the
    counter's per-second rate over a SHORT and a LONG window must BOTH
    breach — the short window proves the burn is current, the long one
    proves it is significant, so a single blip neither fires nor does a
    sustained burn hide behind an old quiet period;
  * ``increase``    — any counter increase above ``threshold`` within
    one window (never-silent counters like
    ``tpuml_stage_cache_overflow_total`` whose doc row says "Alert on
    this counter").

- :class:`AlertEngine` — evaluates the rule set (throttled; the engine
  sweep, every ``/metrics/prom`` scrape, and ``GET /alerts`` all drive
  it), runs the ok -> pending(``for_s``) -> firing -> ok state machine,
  and journals every transition as an ``alert.fire`` / ``alert.resolve``
  flight-recorder event plus ``tpuml_alert_firing{rule=}`` /
  ``tpuml_alerts_fired_total`` metrics, so an incident is reconstructable
  from the same ``/events`` feed as everything else.

- :func:`default_rules` — the shipped ruleset: admission 429 rate, route
  p99 SLO, SSE delivery lag, worker breaker trips, and stage-budget
  overflow (docs/OBSERVABILITY.md "Fleet health plane").

Because rules read the RINGS (not the live registry), they can only
target counter/gauge families — which is exactly what the rings sample;
histogram-derived SLOs ride the derived gauges the scrape refreshes
(``tpuml_http_route_p99_seconds``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .metrics import REGISTRY
from .recorder import record_event
from .timeseries import TIMESERIES, timeseries_sample
from .tracing import _enabled

__all__ = [
    "AlertRule",
    "AlertEngine",
    "default_rules",
    "windowed_increase",
    "windowed_rate",
    "latest_value",
]


# ---------------- ring primitives ----------------


def _match(labels: Dict[str, str], want: Optional[Dict[str, Any]]) -> bool:
    """Subset match: every wanted key must be present; a wanted value may
    be a single string or a collection of acceptable strings."""
    if not want:
        return True
    for k, v in want.items():
        got = labels.get(k)
        if isinstance(v, (list, tuple, set, frozenset)):
            if got not in v:
                return False
        elif got != v:
            return False
    return True


def _series(
    name: str, labels: Optional[Dict[str, Any]] = None, store=None
) -> List[List[Tuple[float, float]]]:
    store = store or TIMESERIES
    out = []
    for s in store.history(name):
        if not _match(s.get("labels") or {}, labels):
            continue
        if s.get("samples"):
            out.append([(ts, v) for ts, v in s["samples"]])
    return out


def latest_value(
    name: str,
    labels: Optional[Dict[str, Any]] = None,
    *,
    now: Optional[float] = None,
    max_age_s: Optional[float] = None,
    store=None,
) -> Optional[float]:
    """Max over matching series' newest samples. ``max_age_s`` drops
    STALE series — a gauge cell the registry already removed (an evicted
    worker's breaker state) keeps its old samples in the ring forever,
    and an alert must not stay pinned to a worker that no longer
    exists."""
    now = time.time() if now is None else now
    best: Optional[float] = None
    for samples in _series(name, labels, store=store):
        ts, v = samples[-1]
        if max_age_s is not None and now - ts > max_age_s:
            continue
        best = v if best is None else max(best, v)
    return best


def windowed_increase(
    name: str,
    window_s: float,
    *,
    now: Optional[float] = None,
    labels: Optional[Dict[str, Any]] = None,
    store=None,
) -> Tuple[Optional[float], float]:
    """Summed counter increase over the trailing window across matching
    series, reset-clamped (a restart's drop to zero counts the new value,
    never a negative delta). Returns ``(increase, coverage_s)`` where
    coverage is how much of the window the samples actually span — young
    series (the flood that JUST started) get rated over the real elapsed
    time, not diluted across an empty window. ``(None, 0)`` when no
    matching series has any sample."""
    now = time.time() if now is None else now
    cutoff = now - window_s
    total: Optional[float] = None
    coverage = 0.0
    for samples in _series(name, labels, store=store):
        prior = None
        inwin: List[Tuple[float, float]] = []
        for ts, v in samples:
            if ts < cutoff:
                prior = (ts, v)
            else:
                inwin.append((ts, v))
        if prior is None and not inwin:
            continue
        # baseline: the last pre-window sample; absent one, the series was
        # born inside the window and counters are born at zero
        prev = prior[1] if prior is not None else 0.0
        inc = 0.0
        for _, v in inwin:
            inc += (v - prev) if v >= prev else v
            prev = v
        total = inc if total is None else total + inc
        first_ts = prior[0] if prior is not None else (
            inwin[0][0] if inwin else now
        )
        coverage = max(coverage, min(now - first_ts, window_s))
    return total, coverage


def windowed_rate(
    name: str,
    window_s: float,
    *,
    now: Optional[float] = None,
    labels: Optional[Dict[str, Any]] = None,
    store=None,
) -> Optional[float]:
    """Per-second counter rate over the trailing window (see
    :func:`windowed_increase` for partial-window semantics)."""
    inc, coverage = windowed_increase(
        name, window_s, now=now, labels=labels, store=store
    )
    if inc is None:
        return None
    return inc / max(coverage, 1.0)


# ---------------- rules ----------------


@dataclasses.dataclass
class AlertRule:
    """One declarative rule. ``labels`` filters series (subset match;
    values may be collections of acceptable strings). ``for_s`` delays
    firing until the breach has held that long (pending state).
    ``windows_s``: (short, long) for ``burn_rate``, (window,) for
    ``increase``; ignored by ``threshold``. ``max_age_s`` is the
    staleness cutoff for ``threshold`` rules (see latest_value)."""

    name: str
    metric: str
    kind: str = "threshold"  # threshold | burn_rate | increase
    threshold: float = 0.0
    cmp: str = ">"  # > | >= | < | <=
    windows_s: Sequence[float] = (60.0, 300.0)
    for_s: float = 0.0
    labels: Optional[Dict[str, Any]] = None
    max_age_s: float = 120.0
    severity: str = "page"  # page | warn
    description: str = ""

    def value(self, now: float, store=None) -> Optional[float]:
        """The rule's current evaluated value (None = no data, never a
        breach). burn_rate returns the SHORT-window rate but only breaches
        when both windows do (see breached)."""
        if self.kind == "threshold":
            return latest_value(
                self.metric, self.labels, now=now,
                max_age_s=self.max_age_s, store=store,
            )
        if self.kind == "increase":
            inc, _ = windowed_increase(
                self.metric, float(self.windows_s[0]), now=now,
                labels=self.labels, store=store,
            )
            return inc
        if self.kind == "burn_rate":
            return windowed_rate(
                self.metric, float(self.windows_s[0]), now=now,
                labels=self.labels, store=store,
            )
        raise ValueError(f"unknown rule kind {self.kind!r}")

    def _cmp(self, v: float) -> bool:
        if self.cmp == ">":
            return v > self.threshold
        if self.cmp == ">=":
            return v >= self.threshold
        if self.cmp == "<":
            return v < self.threshold
        if self.cmp == "<=":
            return v <= self.threshold
        raise ValueError(f"unknown cmp {self.cmp!r}")

    def breached(self, now: float, store=None) -> Tuple[bool, Optional[float]]:
        v = self.value(now, store=store)
        if v is None:
            return False, None
        if not self._cmp(v):
            return False, v
        if self.kind == "burn_rate" and len(self.windows_s) > 1:
            # multi-window: the long window must burn too
            long_rate = windowed_rate(
                self.metric, float(self.windows_s[1]), now=now,
                labels=self.labels, store=store,
            )
            if long_rate is None or not self._cmp(long_rate):
                return False, v
        return True, v

    def spec(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out["windows_s"] = list(self.windows_s)
        return out


class AlertEngine:
    """Evaluates a rule set against the rings; journals transitions.

    State machine per rule: ok -> (breach) -> pending [for_s] -> firing
    -> (clear) -> ok. Fire and resolve transitions emit ``alert.fire`` /
    ``alert.resolve`` flight-recorder events (journaled with everything
    else), bump ``tpuml_alerts_fired_total`` / ``_resolved_total``, and
    drive the ``tpuml_alert_firing{rule=}`` gauge the rings then sample —
    an alert's own history is inspectable like any other series."""

    def __init__(
        self, rules: Iterable[AlertRule], *, interval_s: float = 5.0
    ):
        self.rules: List[AlertRule] = list(rules)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._state: Dict[str, Dict[str, Any]] = {
            r.name: {"state": "ok", "since": None, "value": None}
            for r in self.rules
        }
        self._last_eval = 0.0
        self._store = None  # test injection point (defaults to TIMESERIES)

    # ---------------- evaluation ----------------

    def evaluate(
        self, *, now: Optional[float] = None, force: bool = False
    ) -> bool:
        """One evaluation pass. Throttled by ``interval_s`` so the sweep,
        the scrape, and /alerts reads don't triple-evaluate; returns
        whether a pass actually ran."""
        wall = time.time()
        now = wall if now is None else now
        with self._lock:
            if not force and wall - self._last_eval < self.interval_s:
                return False
            self._last_eval = wall
        if _enabled():
            # rules read the rings: make sure this instant is sampled
            # (itself throttled — a no-op when the sweep just sampled)
            timeseries_sample()
        for rule in self.rules:
            try:
                breach, value = rule.breached(now, store=self._store)
            except Exception:  # noqa: BLE001 — one bad rule must not mute the rest
                continue
            self._transition(rule, breach, value, now)
        return True

    def _transition(
        self, rule: AlertRule, breach: bool, value: Optional[float],
        now: float,
    ) -> None:
        with self._lock:
            st = self._state[rule.name]
            st["value"] = value
            prev = st["state"]
            if breach:
                if prev == "ok":
                    if rule.for_s > 0:
                        st["state"], st["since"] = "pending", now
                        return
                    self._fire(rule, st, value, now)
                elif prev == "pending":
                    if now - (st["since"] or now) >= rule.for_s:
                        self._fire(rule, st, value, now)
                # firing stays firing (value refreshed above)
            else:
                if prev == "firing":
                    self._resolve(rule, st, value, now)
                elif prev == "pending":
                    st["state"], st["since"] = "ok", None

    def _fire(
        self, rule: AlertRule, st: Dict[str, Any], value, now: float
    ) -> None:
        st["state"], st["since"] = "firing", now
        if _enabled():
            REGISTRY.gauge("tpuml_alert_firing").set(1.0, rule=rule.name)
            REGISTRY.counter("tpuml_alerts_fired_total").inc(rule=rule.name)
        record_event(
            "alert.fire", rule=rule.name, severity=rule.severity,
            metric=rule.metric, rule_kind=rule.kind,
            value=None if value is None else round(float(value), 6),
            threshold=rule.threshold, description=rule.description,
        )

    def _resolve(
        self, rule: AlertRule, st: Dict[str, Any], value, now: float
    ) -> None:
        fired_at = st["since"]
        st["state"], st["since"] = "ok", None
        if _enabled():
            REGISTRY.gauge("tpuml_alert_firing").set(0.0, rule=rule.name)
            REGISTRY.counter("tpuml_alerts_resolved_total").inc(
                rule=rule.name
            )
        record_event(
            "alert.resolve", rule=rule.name, severity=rule.severity,
            metric=rule.metric,
            value=None if value is None else round(float(value), 6),
            firing_s=(
                None if fired_at is None else round(now - fired_at, 3)
            ),
        )

    # ---------------- reading ----------------

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(
                name for name, st in self._state.items()
                if st["state"] == "firing"
            )

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /alerts`` body: one entry per rule with its live
        state, plus the firing shortlist."""
        now = time.time()
        alerts = []
        with self._lock:
            for rule in self.rules:
                st = self._state[rule.name]
                alerts.append({
                    "rule": rule.name,
                    "state": st["state"],
                    "value": st["value"],
                    "threshold": rule.threshold,
                    "cmp": rule.cmp,
                    "metric": rule.metric,
                    "kind": rule.kind,
                    "windows_s": list(rule.windows_s),
                    "severity": rule.severity,
                    "since": st["since"],
                    "for_s": (
                        None if st["since"] is None
                        else round(now - st["since"], 3)
                    ),
                    "description": rule.description,
                })
        firing = [a["rule"] for a in alerts if a["state"] == "firing"]
        return {
            "status": "firing" if firing else "ok",
            "n_rules": len(alerts),
            "firing": firing,
            "alerts": alerts,
            "ts": now,
        }


#: poll/submit routes the control-plane p99 SLO covers — NOT the
#: deliberately-blocking ones (long-poll /next_tasks, SSE /train_status,
#: ?wait= holds on /metrics, bulk /dataset /download_* transfers), whose
#: latency is their contract, not a breach
_SLO_ROUTES = (
    "health", "healthz", "check_status", "jobs", "workers", "queues",
    "create_session", "train", "subscribe", "heartbeat", "events",
)


def default_rules(config=None) -> List[AlertRule]:
    """The shipped ruleset (docs/OBSERVABILITY.md "Fleet health plane").
    Thresholds come from ``ServiceConfig`` so a deployment tunes SLOs in
    config, not code."""
    if config is None:
        from ..utils.config import get_config

        config = get_config()
    svc = config.service
    return [
        AlertRule(
            name="admission_reject_rate",
            metric="tpuml_jobs_rejected_total",
            kind="burn_rate",
            threshold=svc.alert_admission_reject_per_s,
            windows_s=(30.0, 120.0),
            severity="page",
            description="Admission control is rejecting submits (429) "
                        "faster than the SLO burn budget on both the "
                        "30 s and 120 s windows — the fleet is saturated "
                        "or a client is flooding.",
        ),
        AlertRule(
            name="route_p99_slo",
            metric="tpuml_http_route_p99_seconds",
            kind="threshold",
            threshold=svc.route_p99_slo_s,
            labels={"route": list(_SLO_ROUTES)},
            for_s=10.0,
            severity="page",
            description="Control-plane p99 latency above the SLO on a "
                        "poll/submit route (blocking routes excluded).",
        ),
        AlertRule(
            name="sse_lag",
            metric="tpuml_sse_lag_seconds",
            kind="threshold",
            threshold=svc.sse_lag_slo_s,
            for_s=10.0,
            severity="warn",
            description="SSE progress events are delivered late beyond "
                        "the stream's tick cadence.",
        ),
        AlertRule(
            name="worker_breaker_trips",
            metric="tpuml_worker_breaker_state",
            kind="threshold",
            threshold=0.5,
            cmp=">=",
            severity="warn",
            description="At least one worker's circuit breaker is "
                        "half-open (failure ratio above the trip "
                        "threshold) — capacity is degraded while it "
                        "proves itself or gets evicted.",
        ),
        AlertRule(
            name="stage_cache_overflow",
            metric="tpuml_stage_cache_overflow_total",
            kind="increase",
            threshold=0.0,
            windows_s=(300.0,),
            severity="page",
            description="The stage cache overflowed its device-memory "
                        "budget (every LRU survivor pinned, or "
                        "CS230_STAGE_STRICT refused an upload) within "
                        "the last 5 minutes — the never-silent OOM "
                        "counter docs tell operators to alert on.",
        ),
    ]
