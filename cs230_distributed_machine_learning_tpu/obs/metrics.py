"""Thread-safe in-process metrics registry with Prometheus text exposition.

The permanent replacement for the one-off benchmark harnesses VERDICT
weaknesses 1/4/5 were diagnosed with: counters, gauges, and fixed-bucket
histograms that every runtime layer (coordinator, scheduler, executor,
trial engine, REST server) increments in place, scraped as standard
Prometheus text format at ``GET /metrics/prom`` (runtime/server.py).

Design constraints:

- **Thread-safe**: the coordinator's job threads, the cluster's worker
  loops, and the werkzeug request threads all write concurrently. Each
  metric guards its label-keyed cells with one lock; increments are
  dict-op cheap.
- **Near-free when disabled**: callers go through the ``obs`` facade
  (``obs/__init__.py``), which checks the ``CS230_OBS`` valve before ever
  touching the registry — a disabled increment is one env read.
- **Stable catalog**: metric families are registered eagerly at import
  (``obs/__init__.py``), so ``/metrics/prom`` exposes every family (at
  zero) from the first scrape — scrapers and the parsing test never see a
  name flicker into existence.

Exposition follows the Prometheus text format v0.0.4: ``# HELP``/``# TYPE``
per family; histograms emit cumulative ``_bucket{le=...}`` series plus
``_sum``/``_count``.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: default latency buckets (seconds) — spans sub-ms placement decisions
#: through multi-minute compiles
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

#: finer buckets for the placement decision (lock + min over workers:
#: microseconds on small pools)
PLACEMENT_BUCKETS: Tuple[float, ...] = (
    1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0,
)

#: control-plane request-latency buckets (seconds) — finer sub-ms low end
#: than DEFAULT_BUCKETS (health polls and queue reads sit there), topping
#: out at 30 s (an SSE stream's first byte under a slow job)
HTTP_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

#: dimensionless relative-error buckets for predictor calibration
#: (|predicted - actual| / actual): 0.05 = within 5%, 10 = off by 10x —
#: the range spans a well-calibrated predictor through a cold-started one
CALIBRATION_BUCKETS: Tuple[float, ...] = (
    0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 1.0, 1.5, 2.0, 3.0, 5.0, 10.0,
    30.0, 100.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline) — a label value fed from a wire message (e.g. a remote
    agent's ``algo``) must not be able to break the whole scrape."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: LabelKey, extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(key) + ([extra] if extra else [])
    if not items:
        return ""
    body = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in items
    )
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class Counter:
    """Monotonic counter, optionally labeled. Values are floats (Prometheus
    counters are)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def cells(self) -> List[Tuple[Dict[str, str], float]]:
        """Snapshot of every labeled cell as (labels, value) — the
        time-series sampler's read path (obs/timeseries.py)."""
        with self._lock:
            return [(dict(key), v) for key, v in self._values.items()]

    def render(self) -> List[str]:
        out = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} counter",
        ]
        with self._lock:
            cells = sorted(self._values.items()) or [((), 0.0)]
        for key, v in cells:
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}")
        return out


class Gauge:
    """Last-written value, optionally labeled."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def remove(self, **labels: str) -> None:
        """Drop one labeled cell — a gauge keyed by worker id must not keep
        exposing a dead/unsubscribed worker forever."""
        with self._lock:
            self._values.pop(_label_key(labels), None)

    def labelsets(self) -> List[Dict[str, str]]:
        """Current label sets with a live cell (introspection/tests)."""
        with self._lock:
            return [dict(key) for key in self._values]

    def cells(self) -> List[Tuple[Dict[str, str], float]]:
        """Snapshot of every labeled cell as (labels, value) — the
        time-series sampler's read path (obs/timeseries.py)."""
        with self._lock:
            return [(dict(key), v) for key, v in self._values.items()]

    def render(self) -> List[str]:
        out = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
        ]
        with self._lock:
            cells = sorted(self._values.items()) or [((), 0.0)]
        for key, v in cells:
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}")
        return out


class Histogram:
    """Fixed-bucket histogram. Buckets are upper bounds (seconds for the
    latency families); observations land in every bucket whose bound is
    >= the value — the cumulative Prometheus semantics are computed at
    render so the hot path is one bisect + two adds."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.help = help
        self.buckets: Tuple[float, ...] = tuple(sorted(float(b) for b in buckets))
        self._lock = threading.Lock()
        # per label-set: ([per-bucket non-cumulative counts] + [overflow],
        #                 sum, count)
        self._cells: Dict[LabelKey, List] = {}

    def observe(self, value: float, **labels: str) -> None:
        import bisect

        value = float(value)
        key = _label_key(labels)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._cells[key] = cell
            cell[0][i] += 1
            cell[1] += value
            cell[2] += 1

    def count(self, **labels: str) -> int:
        with self._lock:
            cell = self._cells.get(_label_key(labels))
            return cell[2] if cell else 0

    def _interpolate(self, counts: List[int], n: int, q: float) -> float:
        """Bucket-interpolated quantile (the standard Prometheus
        ``histogram_quantile`` semantics, computed in-process): find the
        bucket the q-th observation falls in and interpolate linearly
        inside it. Observations above the top bound clamp to it (the
        +Inf bucket has no interpolable width)."""
        rank = min(max(float(q), 0.0), 1.0) * n
        cum = 0
        for i, cnt in enumerate(counts[: len(self.buckets)]):
            prev = cum
            cum += cnt
            if cum >= rank and cnt > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                return lo + (hi - lo) * (rank - prev) / cnt
        return float(self.buckets[-1])

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """Quantile estimate for one exact label set; None when empty."""
        with self._lock:
            cell = self._cells.get(_label_key(labels))
            if cell is None or cell[2] == 0:
                return None
            counts, n = list(cell[0]), cell[2]
        return self._interpolate(counts, n, q)

    def quantile_where(self, q: float, **match: str) -> Optional[float]:
        """Quantile over the MERGE of every cell whose labels include
        ``match`` — e.g. ``quantile_where(0.99, route="health")`` pools
        methods and status codes into one per-route estimate (the SLO
        layer's route-p99 gauge refresh). None when nothing matches."""
        want = set((str(k), str(v)) for k, v in match.items())
        merged: Optional[List[int]] = None
        n = 0
        with self._lock:
            for key, (counts, _s, c) in self._cells.items():
                if not want <= set(key):
                    continue
                if merged is None:
                    merged = list(counts)
                else:
                    merged = [a + b for a, b in zip(merged, counts)]
                n += c
        if merged is None or n == 0:
            return None
        return self._interpolate(merged, n, q)

    def labelsets(self) -> List[Dict[str, str]]:
        """Label sets with a live cell — the route-p99 refresh walks
        these to know which routes have observations."""
        with self._lock:
            return [dict(key) for key in self._cells]

    def sum(self, **labels: str) -> float:
        with self._lock:
            cell = self._cells.get(_label_key(labels))
            return cell[1] if cell else 0.0

    def render(self) -> List[str]:
        out = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            cells = {
                key: ([*counts], s, c)
                for key, (counts, s, c) in sorted(self._cells.items())
            } or {(): ([0] * (len(self.buckets) + 1), 0.0, 0)}
        for key, (counts, total, n) in cells.items():
            cum = 0
            for bound, cnt in zip(self.buckets, counts):
                cum += cnt
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(key, ('le', _fmt_value(bound)))} {cum}"
                )
            out.append(
                f"{self.name}_bucket{_fmt_labels(key, ('le', '+Inf'))} {n}"
            )
            out.append(f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(total)}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {n}")
        return out


class MetricsRegistry:
    """Name -> metric. ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent, so call sites need no registration
    ceremony); re-registering with a different kind raises."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "", buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def render(self) -> str:
        """Full Prometheus text exposition (v0.0.4), families in name order."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


#: the process-global registry every runtime layer writes to
REGISTRY = MetricsRegistry()
