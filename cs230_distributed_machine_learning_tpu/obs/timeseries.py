"""Embedded metrics time-series: a bounded in-memory ring per series.

The Prometheus exposition (``GET /metrics/prom``) is point-in-time: a
question like "was the retry rate climbing before the breaker tripped" or
"how deep did worker-1's queue get during the incident" needs HISTORY,
and fleets in this repo's deployments often run with no external
Prometheus at all (Monarch-style in-memory time series, PAPERS.md). So
the runtime keeps its own short history:

- :func:`sample` walks the registry's counters and gauges and appends
  ``(ts, value)`` to a ring per (name, label-set) series. It is driven by
  the placement engine's sweep loop (one sample per sweep — the cadence
  every other periodic decision already runs on) and by each
  ``/metrics/prom`` scrape, throttled by ``min_interval_s`` so the two
  drivers don't double-sample.
- ``GET /metrics/history?name=&since=`` serves a series' samples;
  ``/dashboard`` draws rate/sparkline panels from it (queue depth,
  retries/s, breaker states, MFU).

Bounds: ``max_samples`` per series (ring), ``max_series`` series total
(least-recently-written evicted). Histograms are not sampled — per-bucket
series would multiply the series count for little explanatory power; the
``_count``/``_sum`` of interest already exist as derived counters on the
exposition side.

Valve-gated by ``CS230_OBS`` like everything else in ``obs/``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .metrics import REGISTRY, Counter, Gauge
from .tracing import _enabled

#: samples kept per series (at the default 15 s sweep cadence: ~2 h)
_MAX_SAMPLES = 512
#: distinct (name, labels) series kept
_MAX_SERIES = 1024
#: floor between samples — the sweep and the scrape both drive sample()
_MIN_INTERVAL_S = 1.0

SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]


class TimeSeriesStore:
    def __init__(
        self,
        *,
        max_samples: int = _MAX_SAMPLES,
        max_series: int = _MAX_SERIES,
        min_interval_s: float = _MIN_INTERVAL_S,
    ):
        self._lock = threading.Lock()
        self._series: "collections.OrderedDict[SeriesKey, collections.deque]" = (
            collections.OrderedDict()
        )
        self._max_samples = max_samples
        self._max_series = max_series
        self.min_interval_s = min_interval_s
        self._last_sample = 0.0

    # ---------------- writing ----------------

    def sample(self, registry=None, *, now: Optional[float] = None, force: bool = False) -> int:
        """Record one sample of every counter/gauge cell in ``registry``.
        Returns how many series were touched (0 when disabled or
        throttled). ``force=True`` bypasses the throttle (tests and
        explicit operator refreshes)."""
        if not _enabled():
            return 0
        registry = registry or REGISTRY
        now = time.time() if now is None else now
        with self._lock:
            if not force and now - self._last_sample < self.min_interval_s:
                return 0
            self._last_sample = now
        n = 0
        for name in registry.names():
            metric = registry.get(name)
            if not isinstance(metric, (Counter, Gauge)):
                continue
            for labels, value in metric.cells():
                self._append(name, labels, now, value)
                n += 1
        return n

    def _append(
        self, name: str, labels: Dict[str, str], ts: float, value: float
    ) -> None:
        key: SeriesKey = (name, tuple(sorted(labels.items())))
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                ring = collections.deque(maxlen=self._max_samples)
                self._series[key] = ring
                while len(self._series) > self._max_series:
                    self._series.popitem(last=False)
            else:
                self._series.move_to_end(key)
            ring.append((ts, value))

    # ---------------- reading ----------------

    def history(
        self, name: str, since: float = 0.0
    ) -> List[Dict[str, Any]]:
        """All series of family ``name``: [{labels, samples: [[ts, v]...]}]
        with samples newer than ``since`` (epoch seconds). Unknown names
        return an empty list — an unsampled family is absence of data, not
        an error."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for (n, labelkey), ring in self._series.items():
                if n != name:
                    continue
                samples = [[ts, v] for ts, v in ring if ts > since]
                out.append({"labels": dict(labelkey), "samples": samples})
        out.sort(key=lambda s: sorted(s["labels"].items()))
        return out

    def names(self) -> List[str]:
        """Sampled family names (the /metrics/history discovery list)."""
        with self._lock:
            return sorted({name for name, _ in self._series})

    def n_series(self) -> int:
        with self._lock:
            return len(self._series)


#: the process-global store the sweep/scrape sample into
TIMESERIES = TimeSeriesStore()


def timeseries_sample(force: bool = False) -> int:
    """Sample the global registry into the global store (valve-gated,
    throttled). The placement-engine sweep and the /metrics/prom handler
    both call this."""
    return TIMESERIES.sample(REGISTRY, force=force)
