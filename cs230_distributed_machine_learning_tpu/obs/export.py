"""Trace export: Perfetto Chrome-JSON and OTLP-shaped JSON documents.

The tracer's native formats (the in-process ring behind
``GET /trace/<job_id>`` and the ``spans.jsonl`` journal) are bespoke —
no external tool opens them. This module converts a trace's span list
into the two interchange formats that matter:

- **Perfetto / Chrome trace JSON** (``format=perfetto``): the
  ``traceEvents`` array of complete ("ph": "X") events that
  https://ui.perfetto.dev and chrome://tracing load directly. One
  Perfetto *process* per recording process tag (coordinator pid, each
  agent pid, the front end), spans laid out on depth-based tracks.
- **OTLP-shaped JSON** (``format=otlp``): the ``resourceSpans`` →
  ``scopeSpans`` → ``spans`` shape of the OpenTelemetry protobuf JSON
  encoding, with ids padded to OTLP widths (32-hex trace / 16-hex span)
  and times in unix nanoseconds — paste-ready for any OTLP ingest.

``export_trace`` writes the document under the journal dir
(``trace_<trace_id>.<format>.json``) and returns it, which is what
``GET /trace/<job_id>/export?format=`` serves
(docs/OBSERVABILITY.md "Critical path & trace export").
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from .tracing import journal_dir

FORMATS = ("perfetto", "otlp")


def _f(v: Any, default: float = 0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def _safe_attrs(attrs: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    out = {}
    for k, v in (attrs or {}).items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[str(k)] = v
        else:
            out[str(k)] = str(v)
    return out


def _depth(span: Dict[str, Any], by_id: Dict[str, Dict[str, Any]]) -> int:
    """Ancestor count, cycle-guarded (a malformed parent chain must not
    hang the exporter)."""
    d, seen = 0, set()
    cur = span
    while True:
        pid = cur.get("parent_id")
        if not pid or pid in seen or pid not in by_id:
            return d
        seen.add(pid)
        cur = by_id[pid]
        d += 1


def to_perfetto(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace JSON ("JSON Array Format" with the object wrapper):
    ``{"traceEvents": [...], "displayTimeUnit": "ms"}``. Timestamps are
    microseconds relative to the earliest span start (Chrome renders
    relative time; absolute epoch-µs values also load but read poorly)."""
    by_id = {s.get("span_id"): s for s in spans if s.get("span_id")}
    t0 = min((_f(s.get("start")) for s in spans), default=0.0)
    procs: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for s in sorted(spans, key=lambda s: _f(s.get("start"))):
        proc = str(s.get("process") or "unknown")
        if proc not in procs:
            pid = len(procs) + 1
            procs[proc] = pid
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": proc},
            })
        start = _f(s.get("start"))
        dur = max(_f(s.get("end")) - start, 0.0)
        events.append({
            "ph": "X",
            "name": str(s.get("name") or "span"),
            "cat": "tpuml",
            "pid": procs[proc],
            "tid": _depth(s, by_id),
            "ts": round((start - t0) * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "args": {
                "trace_id": s.get("trace_id"),
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                **_safe_attrs(s.get("attrs")),
            },
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "tpuml", "t0_epoch_s": t0},
    }


def _otlp_id(hexid: Optional[str], width: int) -> str:
    h = str(hexid or "")
    return h.ljust(width, "0")[:width]


def _otlp_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def to_otlp(spans: List[Dict[str, Any]],
            service_name: str = "tpuml") -> Dict[str, Any]:
    """OTLP/JSON-shaped document: one ``resourceSpans`` entry per
    recording process, ids padded to the OTLP hex widths."""
    by_proc: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        by_proc.setdefault(str(s.get("process") or "unknown"), []).append(s)
    resource_spans = []
    for proc in sorted(by_proc):
        otlp_spans = []
        for s in sorted(by_proc[proc], key=lambda s: _f(s.get("start"))):
            start_ns = int(_f(s.get("start")) * 1e9)
            end_ns = max(int(_f(s.get("end")) * 1e9), start_ns)
            entry = {
                "traceId": _otlp_id(s.get("trace_id"), 32),
                "spanId": _otlp_id(s.get("span_id"), 16),
                "name": str(s.get("name") or "span"),
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(start_ns),
                "endTimeUnixNano": str(end_ns),
                "attributes": [
                    {"key": k, "value": _otlp_value(v)}
                    for k, v in _safe_attrs(s.get("attrs")).items()
                    if v is not None
                ],
            }
            if s.get("parent_id"):
                entry["parentSpanId"] = _otlp_id(s.get("parent_id"), 16)
            otlp_spans.append(entry)
        resource_spans.append({
            "resource": {
                "attributes": [
                    {"key": "service.name",
                     "value": {"stringValue": service_name}},
                    {"key": "tpuml.process",
                     "value": {"stringValue": proc}},
                ]
            },
            "scopeSpans": [{
                "scope": {"name": "tpuml.tracing"},
                "spans": otlp_spans,
            }],
        })
    return {"resourceSpans": resource_spans}


def export_trace(
    trace_id: str,
    spans: List[Dict[str, Any]],
    fmt: str = "perfetto",
    *,
    job_id: Optional[str] = None,
) -> Dict[str, Any]:
    """Render ``spans`` in ``fmt`` and write the document under the
    journal dir as ``trace_<trace_id>.<fmt>.json``. Returns
    ``{format, path, trace_id, job_id, n_spans, document}``; raises
    ValueError on an unknown format (the route's 400). A filesystem
    failure leaves ``path`` None — the document is still returned, so
    the caller can relay it even on a read-only journal dir."""
    if fmt not in FORMATS:
        raise ValueError(
            f"unknown export format {fmt!r} (one of {', '.join(FORMATS)})"
        )
    doc = to_perfetto(spans) if fmt == "perfetto" else to_otlp(spans)
    path: Optional[str] = None
    try:
        d = journal_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"trace_{trace_id}.{fmt}.json")
        with open(path, "w") as f:
            json.dump(doc, f)
    except OSError:
        path = None
    return {
        "format": fmt,
        "path": path,
        "trace_id": trace_id,
        "job_id": job_id,
        "n_spans": len(spans),
        "document": doc,
    }
