"""Critical-path engine: exact end-to-end wall-clock decomposition of a job.

Five observability layers collect — spans (tracing.py), device cost
(devprof.py), the flight recorder (recorder.py), phase-attributed
device-seconds, fleet alerts — but none of them *analyzes*: nothing
answers "this job took 40 s wall — which 40 s?". This module does, by
joining a job's span tree with its flight-recorder timelines and tiling
the measured wall [t0, t1] with labeled segments:

    frontend.proxy → submit → expand → queue.wait → place →
    executor.{compile,stage,dispatch,fetch} → result.ingest → aggregate

The tiling is EXACT by construction: candidate intervals (spans, plus
intervals derived from recorder events — queue wait before the first
placement, the lease-reclaim wait of a hung attempt, the gap between a
batch finishing and its result ingesting) are swept over the window and
the most-specific candidate wins each slice; slices nothing covers are
labeled ``untraced`` rather than silently absorbed, so
``sum(segment durations) == wall`` always holds and the untraced
fraction is an honest data-quality signal.

Retried and speculative attempts charge only their on-critical-path
portion: the engine picks the *critical subtask* (the one whose terminal
result the aggregate waited on last) and, within it, the *winning
attempt* (the attempt stamped on the accepted result) — a speculative
loser's executor spans and a superseded attempt's phases never enter the
candidate set, while the reclaim wait that preceded a re-place does
(it was real wall time the job spent hung).

``compare(a, b)`` diffs two reports segment-by-segment and attributes
the wall-clock delta — the interpretability layer for perf-observatory
A/B runs and before/after benchmark pairs.

Pure functions over plain dicts: the coordinator feeds it
``TRACER.spans_for(tid)`` + ``RECORDER.timeline(...)`` per subtask
(runtime/coordinator.py ``critical_path``); tests feed synthetic spans.
Served at ``GET /critical_path/<job_id>`` (docs/OBSERVABILITY.md
"Critical path & trace export").
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

#: span names that can open a job's wall-clock window, most-upstream
#: first — the earliest of these that exists anchors t0
_ROOT_NAMES = ("frontend.proxy", "http.train", "http.train_status",
               "client.train", "job.submit")
#: span names that can close the window — the latest end wins
_TAIL_NAMES = ("job.aggregate", "job.execute", "job.submit")

#: terminal result statuses (the event the aggregate waited on)
_TERMINAL = {"completed", "failed", "pruned"}

#: synthesized per-phase executor spans (children of executor.batch)
_PHASE_NAMES = ("executor.compile", "executor.stage",
                "executor.dispatch", "executor.fetch")


def _f(v: Any, default: float = 0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


class _Candidate:
    __slots__ = ("start", "end", "name", "prio", "detail")

    def __init__(self, start: float, end: float, name: str, prio: int,
                 detail: Optional[Dict[str, Any]] = None):
        self.start = start
        self.end = end
        self.name = name
        self.prio = prio
        self.detail = detail or {}


def _pick_critical_subtask(
    timelines: Dict[str, List[Dict[str, Any]]]
) -> Tuple[Optional[str], Optional[Dict[str, Any]]]:
    """The subtask whose terminal result landed LAST — the one the
    aggregate barrier actually waited on. Returns (subtask_id, its
    terminal result event)."""
    best_stid, best_ev = None, None
    for stid, events in timelines.items():
        for ev in events or []:
            if ev.get("kind") != "result":
                continue
            if (ev.get("data") or {}).get("status") not in _TERMINAL:
                continue
            if best_ev is None or _f(ev.get("ts")) > _f(best_ev.get("ts")):
                best_stid, best_ev = stid, ev
    return best_stid, best_ev


def _span_window(spans: List[Dict[str, Any]]) -> Tuple[float, float]:
    starts = {n: min(_f(s.get("start")) for s in spans if s["name"] == n)
              for n in {s["name"] for s in spans}}
    ends = {n: max(_f(s.get("end")) for s in spans if s["name"] == n)
            for n in {s["name"] for s in spans}}
    t0 = None
    for name in _ROOT_NAMES:
        if name in starts:
            t0 = starts[name] if t0 is None else min(t0, starts[name])
    if t0 is None:
        t0 = min(_f(s.get("start")) for s in spans)
    t1 = None
    for name in _TAIL_NAMES:
        if name in ends:
            t1 = ends[name] if t1 is None else max(t1, ends[name])
    if t1 is None:
        t1 = max(_f(s.get("end")) for s in spans)
    return t0, max(t1, t0)


def critical_path(
    job_id: str,
    *,
    trace_id: Optional[str],
    spans: List[Dict[str, Any]],
    timelines: Optional[Dict[str, List[Dict[str, Any]]]] = None,
    job_wall_s: Optional[float] = None,
) -> Optional[Dict[str, Any]]:
    """Decompose one job's wall clock into labeled critical-path segments.

    ``spans`` is the job trace (TRACER.spans_for), ``timelines`` maps
    subtask_id -> flight-recorder events (RECORDER.timeline);
    ``job_wall_s`` is the store-measured wall (created_at ->
    completion_time) reported alongside for cross-checking. Returns None
    when there are no spans at all (nothing to decompose)."""
    if not spans:
        return None
    timelines = timelines or {}
    t0, t1 = _span_window(spans)
    wall = t1 - t0

    cands: List[_Candidate] = []

    def add(start, end, name, prio, **detail):
        start, end = _f(start), _f(end)
        # clamp to the window; degenerate intervals never tile anything
        start, end = max(start, t0), min(end, t1)
        if end > start:
            cands.append(_Candidate(start, end, name, prio, detail))

    # ---- span-derived candidates (control-plane skeleton) ----
    for s in spans:
        name, st, en = s["name"], s.get("start"), s.get("end")
        attrs = s.get("attrs") or {}
        if name == "frontend.proxy":
            add(st, en, "frontend.proxy", 1, route=attrs.get("route"))
        elif name in ("http.train", "http.train_status"):
            add(st, en, "submit.http", 2)
        elif name == "job.submit":
            add(st, en, "submit", 3)
        elif name == "job.expand":
            add(st, en, "expand", 4)
        elif name == "job.aggregate":
            add(st, en, "aggregate", 4)

    # ---- critical subtask: pick it, then walk its attempts ----
    crit_stid, result_ev = _pick_critical_subtask(timelines)
    crit_events = timelines.get(crit_stid) or [] if crit_stid else []
    win_attempt = int(result_ev.get("attempt") or 0) if result_ev else None
    win_worker = result_ev.get("worker_id") if result_ev else None
    result_ts = _f(result_ev.get("ts")) if result_ev else None
    placements = [e for e in crit_events if e.get("kind") == "placement"]
    reclaims = [e for e in crit_events if e.get("kind") == "lease.reclaim"]
    spec_wins = [e for e in crit_events if e.get("kind") == "speculate.win"]

    exec_start = next(
        (_f(s.get("start")) for s in spans if s["name"] == "job.execute"),
        None,
    )
    if placements:
        first_place = min(_f(e.get("ts")) for e in placements)
        q0 = exec_start if exec_start is not None else t0
        add(q0, first_place, "queue.wait", 2,
            subtask_id=crit_stid)

    # placement decisions themselves (back-dated schedule.place spans)
    for s in spans:
        if s["name"] != "schedule.place":
            continue
        attrs = s.get("attrs") or {}
        if crit_stid and attrs.get("subtask_id") == crit_stid:
            add(s.get("start"), s.get("end"), "place", 5,
                worker=attrs.get("worker"), attempt=attrs.get("attempt"))

    # the reclaim wait of every superseded attempt IS critical-path time:
    # the job sat hung from that attempt's placement until the sweeper
    # reclaimed the lease and re-placed
    for rec in reclaims:
        r_attempt = int(rec.get("attempt") or 0)
        p_ts = max(
            (_f(p.get("ts")) for p in placements
             if int(p.get("attempt") or 0) == r_attempt),
            default=None,
        )
        if p_ts is not None:
            add(p_ts, _f(rec.get("ts")), "reclaim.wait", 4,
                attempt=r_attempt, worker=rec.get("worker_id"),
                overdue_s=(rec.get("data") or {}).get("overdue_s"))

    # ---- winning attempt's executor window (only the winner charges) ----
    win_place_ts = None
    if placements and win_attempt is not None:
        win_place_ts = max(
            (_f(p.get("ts")) for p in placements
             if int(p.get("attempt") or 0) == win_attempt),
            default=None,
        )
    batch_end = None
    if win_worker and result_ts is not None:
        lo = win_place_ts if win_place_ts is not None else t0
        batch_windows: Dict[Any, Tuple[float, float]] = {}
        for s in spans:
            if s["name"] != "executor.batch":
                continue
            if (s.get("attrs") or {}).get("worker") != win_worker:
                continue
            b0, b1 = _f(s.get("start")), _f(s.get("end"))
            # the winner's batch overlaps [placement, result]; a
            # speculative loser or stale attempt ran elsewhere/elsewhen.
            # Only the portion up to the result event is on the critical
            # path — a batch tail past its own result (other subtasks
            # still in the batch) belongs to them, not this job's wall.
            if b1 < lo or b0 > result_ts:
                continue
            b1 = min(b1, result_ts)
            add(b0, b1, "execute", 6, worker=win_worker)
            batch_windows[s.get("span_id")] = (b0, b1)
            batch_end = b1 if batch_end is None else max(batch_end, b1)
        for s in spans:
            win = batch_windows.get(s.get("parent_id"))
            if s["name"] in _PHASE_NAMES and win is not None:
                # synthesized phases carry exact DURATIONS but indicative
                # offsets (laid sequentially from batch start while real
                # phases overlap — executor._record_batch_phases): clamp
                # to the parent batch envelope so an overrunning phase
                # estimate can never eat into post-batch segments
                # (result.ingest, aggregate)
                add(max(_f(s.get("start")), win[0]),
                    min(_f(s.get("end")), win[1]), s["name"], 7)
        if batch_end is not None and result_ts > batch_end:
            add(batch_end, result_ts, "result.ingest", 3,
                subtask_id=crit_stid)

    # ---- sweep: most-specific candidate wins each elementary slice ----
    bounds = sorted({t0, t1, *(c.start for c in cands),
                     *(c.end for c in cands)})
    segments: List[Dict[str, Any]] = []
    for lo, hi in zip(bounds, bounds[1:]):
        if hi <= lo:
            continue
        best: Optional[_Candidate] = None
        for c in cands:
            if c.start <= lo and c.end >= hi:
                if best is None or c.prio > best.prio:
                    best = c
        name = best.name if best is not None else "untraced"
        detail = best.detail if best is not None else {}
        if segments and segments[-1]["name"] == name:
            segments[-1]["end"] = hi
        else:
            segments.append({"name": name, "start": lo, "end": hi,
                             "detail": detail})

    totals: Dict[str, float] = {}
    for seg in segments:
        seg["duration_s"] = seg["end"] - seg["start"]
        seg["fraction"] = seg["duration_s"] / wall if wall > 0 else 0.0
        totals[seg["name"]] = totals.get(seg["name"], 0.0) + seg["duration_s"]
    untraced_s = totals.get("untraced", 0.0)

    return {
        "job_id": job_id,
        "trace_id": trace_id,
        "t0": t0,
        "t1": t1,
        "wall_s": wall,
        "job_wall_s": job_wall_s,
        "critical_subtask": crit_stid,
        "winning_attempt": win_attempt,
        "winning_worker": win_worker,
        "n_attempts": (max((int(p.get("attempt") or 0)
                            for p in placements), default=-1) + 1),
        "n_reclaims": len(reclaims),
        "speculated": bool(spec_wins),
        "segments": segments,
        "n_segments": len(segments),
        "totals": {k: totals[k] for k in sorted(totals)},
        # per-segment ranking, biggest consumer first — "which 40 s?"
        "dominant": sorted(totals, key=lambda k: -totals[k]),
        "untraced_s": untraced_s,
        "coverage": (wall - untraced_s) / wall if wall > 0 else 1.0,
    }


def compare(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Attribute the wall-clock delta between two critical-path reports
    to segments. ``b`` is the candidate/after run, ``a`` the baseline:
    a positive ``delta_s`` means ``b`` spent longer there. Segment rows
    are ranked by absolute delta; ``dominant_segment`` names the largest
    positive contributor (the slowdown's home) and ``share_of_delta`` is
    each segment's fraction of the total wall delta."""
    totals_a = a.get("totals") or {}
    totals_b = b.get("totals") or {}
    delta_wall = _f(b.get("wall_s")) - _f(a.get("wall_s"))
    rows = []
    for name in sorted(set(totals_a) | set(totals_b)):
        da = _f(totals_a.get(name))
        db = _f(totals_b.get(name))
        delta = db - da
        rows.append({
            "name": name,
            "a_s": da,
            "b_s": db,
            "delta_s": delta,
            "share_of_delta": (delta / delta_wall) if delta_wall else None,
        })
    rows.sort(key=lambda r: -abs(r["delta_s"]))
    slower = [r for r in rows if r["delta_s"] > 0]
    return {
        "job_a": a.get("job_id"),
        "job_b": b.get("job_id"),
        "wall_a_s": _f(a.get("wall_s")),
        "wall_b_s": _f(b.get("wall_s")),
        "delta_wall_s": delta_wall,
        "segments": rows,
        "dominant_segment": slower[0]["name"] if slower else None,
    }
